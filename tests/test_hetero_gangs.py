"""Heterogeneous-member affinity groups (the PP scheduling analog).

The reference exercises a gang whose members have different device counts
(group9, a 7-GPU + 5-GPU pod pair: hived_algorithm_test.go:93-95, with
totalPodNums keyed by leaf-cell count at types.go:141). These tests drive
the same shape through the full lifecycle — schedule -> bind -> recovery
replay -> delete — plus the recovery-disambiguation case the advisor
flagged (two same-sized pods of one gang on one node).
"""

import logging

from hivedscheduler_tpu import common
from hivedscheduler_tpu.scheduler.types import SchedulingPhase

from .test_core import Sim, make_pod

common.init_logging(logging.ERROR)


def hetero_gang(name):
    """One 4-chip pod + two 2-chip pods: a driver stage and two worker
    stages of a pipeline job."""
    return {
        "name": name,
        "members": [
            {"podNumber": 1, "leafCellNumber": 4},
            {"podNumber": 2, "leafCellNumber": 2},
        ],
    }


def schedule_hetero(sim, vc="VC2", leaf_type="v5e-chip", priority=0):
    g = hetero_gang("pp-gang")
    pods = [
        make_pod("pp-a", "u-a", vc, priority, leaf_type, 4, group=g),
        make_pod("pp-b", "u-b", vc, priority, leaf_type, 2, group=g),
        make_pod("pp-c", "u-c", vc, priority, leaf_type, 2, group=g),
    ]
    return pods, [sim.schedule_and_bind(p) for p in pods]


def test_hetero_gang_schedule_bind_delete():
    sim = Sim()
    pods, bound = schedule_hetero(sim)

    status = sim.core.get_affinity_group("pp-gang")["status"]
    assert status["state"] == "Allocated"
    assert sorted(status["allocatedPods"]) == ["u-a", "u-b", "u-c"]
    # 4 + 2 + 2 chips placed in total.
    placed = [i for chips in status["physicalPlacement"].values() for i in chips]
    assert len(placed) == 8

    g = sim.core.affinity_groups["pp-gang"]
    assert g.total_pod_nums == {4: 1, 2: 2}
    assert [p is not None for p in g.allocated_pods[4]] == [True]
    assert [p is not None for p in g.allocated_pods[2]] == [True, True]

    # Deleting only the 4-chip member keeps the group alive; slots empty
    # correctly per member size.
    sim.delete(pods[0])
    g = sim.core.affinity_groups["pp-gang"]
    assert g.allocated_pods[4] == [None]
    assert sorted(
        p.uid for p in g.allocated_pods[2] if p is not None
    ) == ["u-b", "u-c"]

    sim.delete(pods[1])
    sim.delete(pods[2])
    assert "pp-gang" not in sim.core.affinity_groups


def test_hetero_gang_recovery_replay():
    sim = Sim()
    pods, bound = schedule_hetero(sim)
    want = sim.core.get_affinity_group("pp-gang")["status"]

    # Scheduler restart: a fresh core sees only the informer replay of the
    # bound pods (in an arbitrary order).
    fresh = Sim()
    for bp in [bound[2], bound[0], bound[1]]:
        fresh.core.add_allocated_pod(bp)
        fresh.bound[bp.uid] = bp

    got = fresh.core.get_affinity_group("pp-gang")["status"]
    assert got["physicalPlacement"] == want["physicalPlacement"]
    assert got["virtualPlacement"] == want["virtualPlacement"]
    assert sorted(got["allocatedPods"]) == sorted(want["allocatedPods"])
    g = fresh.core.affinity_groups["pp-gang"]
    # Every slot of every member size recovered exactly one pod.
    assert [p is not None for p in g.allocated_pods[4]] == [True]
    assert [p is not None for p in g.allocated_pods[2]] == [True, True]

    # The recovered state must be fully releasable (no leaked cells).
    for p in pods:
        fresh.delete(p)
    assert "pp-gang" not in fresh.core.affinity_groups
    for chain, ccl in fresh.core.full_cell_list.items():
        for cell in ccl[ccl.top_level]:
            # VC2 shares the tree with live VC1 state in other tests; here
            # nothing else was ever allocated.
            assert cell.state.value == "Free", (chain, cell.address)


def test_same_size_members_same_node_recovery_no_alias():
    """Two same-sized pods of one gang landing on ONE node: recovery must
    map each to its own slot by chip indices, not alias both to slot 0
    (advisor finding on get_allocated_pod_index, core.py:107-122)."""
    sim = Sim()
    g = {"name": "twins", "members": [{"podNumber": 2, "leafCellNumber": 2}]}
    pods = [
        make_pod(
            "tw-0", "u-tw0", "VC2", 0, "v5e-chip", 2, group=g,
            ignore_suggested=False,
        ),
        make_pod(
            "tw-1", "u-tw1", "VC2", 0, "v5e-chip", 2, group=g,
            ignore_suggested=False,
        ),
    ]
    # The v5e-solo host (2+2 chips with nonstandard indices) forces both
    # sub-host pods onto the same node.
    bound = [
        sim.schedule_and_bind(p, suggested=["v5e-solo"]) for p in pods
    ]
    assert bound[0].node_name == bound[1].node_name == "v5e-solo"
    chips0 = sim.bound["u-tw0"].annotations[
        "hivedscheduler.tpu.io/pod-leaf-cell-isolation"
    ]
    chips1 = sim.bound["u-tw1"].annotations[
        "hivedscheduler.tpu.io/pod-leaf-cell-isolation"
    ]
    assert chips0 != chips1

    fresh = Sim()
    for bp in bound:
        fresh.core.add_allocated_pod(bp)
        fresh.bound[bp.uid] = bp
    g2 = fresh.core.affinity_groups["twins"]
    recovered = [p.uid for p in g2.allocated_pods[2] if p is not None]
    assert sorted(recovered) == ["u-tw0", "u-tw1"], recovered

    for p in pods:
        fresh.delete(p)
    assert "twins" not in fresh.core.affinity_groups


def test_hetero_gang_preemption_and_insufficiency():
    """A low-priority hetero gang is preempted by a high-priority one; a
    gang too large for the VC quota fails cleanly."""
    sim = Sim()
    pods, bound = schedule_hetero(sim, priority=0)

    # VC2 has one v5e-16 (16 chips) + one v5e-host (4 chips); the hetero
    # gang took 8 chips of something. A 16-chip high-priority gang on the
    # v5e chain must be able to preempt the low one if placements overlap.
    big = {"name": "big", "members": [{"podNumber": 4, "leafCellNumber": 4}]}
    big_pods = [
        make_pod(f"big-{i}", f"u-big{i}", "VC2", 10, "v5e-chip", 4, group=big)
        for i in range(4)
    ]
    results = [
        sim.schedule(p, phase=SchedulingPhase.PREEMPTING) for p in big_pods
    ]
    # Either it fits in free space (bind infos) or it preempts the gang.
    victims = {
        v.uid
        for r in results
        if r.pod_preempt_info is not None
        for v in r.pod_preempt_info.victim_pods
    }
    binds = [r for r in results if r.pod_bind_info is not None]
    assert victims or len(binds) == len(big_pods)
    if victims:
        assert victims <= {"u-a", "u-b", "u-c"}
