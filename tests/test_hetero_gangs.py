"""Heterogeneous-member affinity groups (the PP scheduling analog).

The reference exercises a gang whose members have different device counts
(group9, a 7-GPU + 5-GPU pod pair: hived_algorithm_test.go:93-95, with
totalPodNums keyed by leaf-cell count at types.go:141). These tests drive
the same shape through the full lifecycle — schedule -> bind -> recovery
replay -> delete — plus the recovery-disambiguation case the advisor
flagged (two same-sized pods of one gang on one node).
"""

import logging

from hivedscheduler_tpu import common
from hivedscheduler_tpu.scheduler.types import SchedulingPhase

from .test_core import Sim, make_pod

common.init_logging(logging.ERROR)


def hetero_gang(name):
    """One 4-chip pod + two 2-chip pods: a driver stage and two worker
    stages of a pipeline job."""
    return {
        "name": name,
        "members": [
            {"podNumber": 1, "leafCellNumber": 4},
            {"podNumber": 2, "leafCellNumber": 2},
        ],
    }


def schedule_hetero(sim, vc="VC2", leaf_type="v5e-chip", priority=0):
    g = hetero_gang("pp-gang")
    pods = [
        make_pod("pp-a", "u-a", vc, priority, leaf_type, 4, group=g),
        make_pod("pp-b", "u-b", vc, priority, leaf_type, 2, group=g),
        make_pod("pp-c", "u-c", vc, priority, leaf_type, 2, group=g),
    ]
    return pods, [sim.schedule_and_bind(p) for p in pods]


def test_hetero_gang_schedule_bind_delete():
    sim = Sim()
    pods, bound = schedule_hetero(sim)

    status = sim.core.get_affinity_group("pp-gang")["status"]
    assert status["state"] == "Allocated"
    assert sorted(status["allocatedPods"]) == ["u-a", "u-b", "u-c"]
    # 4 + 2 + 2 chips placed in total.
    placed = [i for chips in status["physicalPlacement"].values() for i in chips]
    assert len(placed) == 8

    g = sim.core.affinity_groups["pp-gang"]
    assert g.total_pod_nums == {4: 1, 2: 2}
    assert [p is not None for p in g.allocated_pods[4]] == [True]
    assert [p is not None for p in g.allocated_pods[2]] == [True, True]

    # Deleting only the 4-chip member keeps the group alive; slots empty
    # correctly per member size.
    sim.delete(pods[0])
    g = sim.core.affinity_groups["pp-gang"]
    assert g.allocated_pods[4] == [None]
    assert sorted(
        p.uid for p in g.allocated_pods[2] if p is not None
    ) == ["u-b", "u-c"]

    sim.delete(pods[1])
    sim.delete(pods[2])
    assert "pp-gang" not in sim.core.affinity_groups


def test_hetero_gang_recovery_replay():
    sim = Sim()
    pods, bound = schedule_hetero(sim)
    want = sim.core.get_affinity_group("pp-gang")["status"]

    # Scheduler restart: a fresh core sees only the informer replay of the
    # bound pods (in an arbitrary order).
    fresh = Sim()
    for bp in [bound[2], bound[0], bound[1]]:
        fresh.core.add_allocated_pod(bp)
        fresh.bound[bp.uid] = bp

    got = fresh.core.get_affinity_group("pp-gang")["status"]
    assert got["physicalPlacement"] == want["physicalPlacement"]
    assert got["virtualPlacement"] == want["virtualPlacement"]
    assert sorted(got["allocatedPods"]) == sorted(want["allocatedPods"])
    g = fresh.core.affinity_groups["pp-gang"]
    # Every slot of every member size recovered exactly one pod.
    assert [p is not None for p in g.allocated_pods[4]] == [True]
    assert [p is not None for p in g.allocated_pods[2]] == [True, True]

    # The recovered state must be fully releasable (no leaked cells).
    for p in pods:
        fresh.delete(p)
    assert "pp-gang" not in fresh.core.affinity_groups
    for chain, ccl in fresh.core.full_cell_list.items():
        for cell in ccl[ccl.top_level]:
            # VC2 shares the tree with live VC1 state in other tests; here
            # nothing else was ever allocated.
            assert cell.state.value == "Free", (chain, cell.address)


def test_same_size_members_same_node_recovery_no_alias():
    """Two same-sized pods of one gang landing on ONE node: recovery must
    map each to its own slot by chip indices, not alias both to slot 0
    (advisor finding on get_allocated_pod_index, core.py:107-122)."""
    sim = Sim()
    g = {"name": "twins", "members": [{"podNumber": 2, "leafCellNumber": 2}]}
    pods = [
        make_pod(
            "tw-0", "u-tw0", "VC2", 0, "v5e-chip", 2, group=g,
            ignore_suggested=False,
        ),
        make_pod(
            "tw-1", "u-tw1", "VC2", 0, "v5e-chip", 2, group=g,
            ignore_suggested=False,
        ),
    ]
    # The v5e-solo host (2+2 chips with nonstandard indices) forces both
    # sub-host pods onto the same node.
    bound = [
        sim.schedule_and_bind(p, suggested=["v5e-solo"]) for p in pods
    ]
    assert bound[0].node_name == bound[1].node_name == "v5e-solo"
    chips0 = sim.bound["u-tw0"].annotations[
        "hivedscheduler.tpu.io/pod-leaf-cell-isolation"
    ]
    chips1 = sim.bound["u-tw1"].annotations[
        "hivedscheduler.tpu.io/pod-leaf-cell-isolation"
    ]
    assert chips0 != chips1

    fresh = Sim()
    for bp in bound:
        fresh.core.add_allocated_pod(bp)
        fresh.bound[bp.uid] = bp
    g2 = fresh.core.affinity_groups["twins"]
    recovered = [p.uid for p in g2.allocated_pods[2] if p is not None]
    assert sorted(recovered) == ["u-tw0", "u-tw1"], recovered

    for p in pods:
        fresh.delete(p)
    assert "twins" not in fresh.core.affinity_groups


def test_hetero_gang_preemption_and_insufficiency():
    """A low-priority hetero gang is preempted by a high-priority one; a
    gang too large for the VC quota fails cleanly."""
    sim = Sim()
    pods, bound = schedule_hetero(sim, priority=0)

    # VC2 has one v5e-16 (16 chips) + one v5e-host (4 chips); the hetero
    # gang took 8 chips of something. A 16-chip high-priority gang on the
    # v5e chain must be able to preempt the low one if placements overlap.
    big = {"name": "big", "members": [{"podNumber": 4, "leafCellNumber": 4}]}
    big_pods = [
        make_pod(f"big-{i}", f"u-big{i}", "VC2", 10, "v5e-chip", 4, group=big)
        for i in range(4)
    ]
    results = [
        sim.schedule(p, phase=SchedulingPhase.PREEMPTING) for p in big_pods
    ]
    # Either it fits in free space (bind infos) or it preempts the gang.
    victims = {
        v.uid
        for r in results
        if r.pod_preempt_info is not None
        for v in r.pod_preempt_info.victim_pods
    }
    binds = [r for r in results if r.pod_bind_info is not None]
    assert victims or len(binds) == len(big_pods)
    if victims:
        assert victims <= {"u-a", "u-b", "u-c"}


def test_pp_gang_members_land_on_whole_v5p16s():
    """The llama-pp example's shape (example/request/llama-pp.yaml): a
    2-member gang, 4 pods x 4 chips each, on a v5p-64. Every member must
    occupy the 4 hosts of exactly ONE v5p-16 sub-cell (its stage's ICI
    domain), and the two members must take different v5p-16s."""
    from hivedscheduler_tpu.api.config import Config
    from hivedscheduler_tpu.api import extender as ei
    from hivedscheduler_tpu.scheduler.framework import (
        HivedScheduler, NullKubeClient,
    )
    from hivedscheduler_tpu.scheduler.types import Node
    from hivedscheduler_tpu.tpu import topology

    cell_types = topology.v5p_cell_types(max_hosts=16)
    hosts = [f"v5p-w{i}" for i in range(16)]
    config = Config.from_dict(
        {
            "physicalCluster": {
                "cellTypes": {
                    n: {
                        "childCellType": s.child_cell_type,
                        "childCellNumber": s.child_cell_number,
                        "isNodeLevel": s.is_node_level,
                    }
                    for n, s in cell_types.items()
                },
                "physicalCells": [
                    topology.make_physical_cell(
                        "v5p-64", hosts, cell_types
                    ).to_dict()
                ],
            },
            "virtualClusters": {
                "prod": {"virtualCells": [{"cellType": "v5p-64",
                                           "cellNumber": 1}]},
            },
        }
    )
    sched = HivedScheduler(config, kube_client=NullKubeClient())
    for n in sched.core.configured_node_names():
        sched.add_node(Node(name=n))

    group = {
        "name": "prod/llama-pp",
        "members": [
            {"podNumber": 4, "leafCellNumber": 4},
            {"podNumber": 4, "leafCellNumber": 4},
        ],
    }
    nodes_by_pod = {}
    for i in range(8):
        uid = f"pp-{i}"
        pod = make_pod(uid, uid, "prod", 0, "v5p-chip", 4, group=group)
        sched.add_pod(pod)
        r = sched.filter_routine(
            ei.ExtenderArgs(pod=pod, node_names=list(hosts))
        )
        assert r.node_names, (i, r.error, r.failed_nodes)
        nodes_by_pod[uid] = r.node_names[0]

    # 8 distinct whole hosts (4 chips each).
    used = list(nodes_by_pod.values())
    assert len(set(used)) == 8

    # Partition the used hosts by v5p-16 membership (make_physical_cell
    # assigns children in order: v5p-16 #j = hosts 4j..4j+3).
    def sub16(host):
        return int(host.split("w")[1]) // 4

    placement = sched.core.get_affinity_group("prod/llama-pp")["status"][
        "physicalPlacement"
    ]
    groups_hit = {}
    for host in placement:
        groups_hit.setdefault(sub16(host), set()).add(host)
    # Exactly two v5p-16s, each fully occupied (4 hosts).
    assert len(groups_hit) == 2, groups_hit
    for g, hs in groups_hit.items():
        assert len(hs) == 4, (g, hs)

    # The per-STAGE guarantee: identical-shape members are interchangeable
    # to the scheduler, so stage membership is derived from the env
    # contract's worker order (tpu/env.py natural sort). Workers 0-3 must
    # share one quad and workers 4-7 the other — i.e. the worker-ordered
    # host list groups quads contiguously.
    import yaml

    from hivedscheduler_tpu.api import constants

    any_pod = sched.pod_schedule_statuses["pp-0"].pod
    block = yaml.safe_load(
        any_pod.annotations[constants.ANNOTATION_POD_TPU_ENV]
    )
    roster = block["TPU_WORKER_HOSTNAMES"].split(",")
    assert len(roster) == 8
    assert len({sub16(h) for h in roster[:4]}) == 1, roster
    assert len({sub16(h) for h in roster[4:]}) == 1, roster
    assert sub16(roster[0]) != sub16(roster[4])
