"""Machine-proof of the jax.distributed env contract (SURVEY §7.4 part 5).

Schedules a real gang through the core, takes each binding pod's emitted
``pod-tpu-env`` annotation, spawns that many OS processes, and has every
process boot ``jax.distributed`` from its own block and run one collective.
This closes the loop the unit tests in test_tpu_env.py only inspect: the
worker-id/coordinator assignment derived independently by each pod actually
forms a working distributed runtime.
"""

import json
import logging
import os

import yaml

from hivedscheduler_tpu import common
from hivedscheduler_tpu.api import constants

from ._multiproc import free_port, run_workers
from .test_core import Sim, make_pod

common.init_logging(logging.ERROR)

GANG_SIZE = 2


def test_gang_env_blocks_boot_a_real_jax_distributed_runtime():
    sim = Sim()
    gang = {
        "name": "mp-gang",
        "members": [{"podNumber": GANG_SIZE, "leafCellNumber": 4}],
    }
    bound = [
        sim.schedule_and_bind(
            make_pod(f"mp-{i}", f"mpu{i}", "VC1", 0, "v5e-chip", 4, group=gang)
        )
        for i in range(GANG_SIZE)
    ]
    envs = [
        yaml.safe_load(bp.annotations[constants.ANNOTATION_POD_TPU_ENV])
        for bp in bound
    ]
    # Independently-bound pods must agree on the coordinator and the count.
    assert len({e["JAX_COORDINATOR_ADDRESS"] for e in envs}) == 1
    assert all(int(e["JAX_NUM_PROCESSES"]) == GANG_SIZE for e in envs)

    port = free_port()
    worker = os.path.join(os.path.dirname(__file__), "_env_contract_worker.py")
    outs = run_workers(worker, [[json.dumps(e), str(port)] for e in envs])

    roster = list(range(GANG_SIZE))
    assert sorted(o["pid"] for o in outs) == roster
    assert all(o["roster"] == roster for o in outs)
