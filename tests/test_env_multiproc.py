"""Machine-proof of the jax.distributed env contract (SURVEY §7.4 part 5).

Schedules a real gang through the core, takes each binding pod's emitted
``pod-tpu-env`` annotation, spawns that many OS processes, and has every
process boot ``jax.distributed`` from its own block and run one collective.
This closes the loop the unit tests in test_tpu_env.py only inspect: the
worker-id/coordinator assignment derived independently by each pod actually
forms a working distributed runtime.
"""

import json
import logging
import os
import socket
import subprocess
import sys

import yaml

from hivedscheduler_tpu import common
from hivedscheduler_tpu.api import constants

from .test_core import Sim, make_pod

common.init_logging(logging.ERROR)

GANG_SIZE = 2


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_gang_env_blocks_boot_a_real_jax_distributed_runtime():
    sim = Sim()
    gang = {
        "name": "mp-gang",
        "members": [{"podNumber": GANG_SIZE, "leafCellNumber": 4}],
    }
    bound = [
        sim.schedule_and_bind(
            make_pod(f"mp-{i}", f"mpu{i}", "VC1", 0, "v5e-chip", 4, group=gang)
        )
        for i in range(GANG_SIZE)
    ]
    envs = [
        yaml.safe_load(bp.annotations[constants.ANNOTATION_POD_TPU_ENV])
        for bp in bound
    ]
    # Independently-bound pods must agree on the coordinator and the count.
    assert len({e["JAX_COORDINATOR_ADDRESS"] for e in envs}) == 1
    assert all(int(e["JAX_NUM_PROCESSES"]) == GANG_SIZE for e in envs)

    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "_env_contract_worker.py")
    # A clean env per process: the conftest's 8-device virtual mesh must not
    # leak in (each worker is one process = one CPU device).
    child_env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, worker, json.dumps(e), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=child_env,
        )
        for e in envs
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=180)
            assert p.returncode == 0, (p.returncode, err[-2000:])
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        # One worker failing leaves its peers blocked inside
        # jax.distributed.initialize — reap them or they outlive the test.
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()

    roster = list(range(GANG_SIZE))
    assert sorted(o["pid"] for o in outs) == roster
    assert all(o["roster"] == roster for o in outs)
