"""The black-box plane (ISSUE 15; doc/observability.md "The black-box
plane"): production flight recorder + deterministic incident replay +
always-on live invariant auditor.

Covers the acceptance surface:

- a recording captured from a LIVE 432-host bench run (gang churn,
  faults, at least one preemption) replays through the what-if-fork
  restore + ``TraceDriver.replay_recording`` with a placement
  fingerprint IDENTICAL to the live run's;
- the sensitivity meta-test: injected free-list and doomed-counter
  corruption is caught by the LIVE auditor within one cadence, counted,
  journaled, and answered by the black-box artifact bundle — while the
  scheduler keeps serving; and a NO-OP'd auditor is itself caught
  (mirroring the ``test_nooped_*`` precedent: the test's teeth are
  themselves tested);
- the ``/v1/inspect/flightrecorder`` endpoint and the window re-anchor
  discipline (bounded ring, fresh snapshot anchor, replay still
  identical);
- causal cross-shard trace stitching: worker filter traces commit with
  the frontend's trace id as ``parentTraceId`` and the merged
  ``/v1/inspect/traces`` nests them as children, wall-time ordered —
  the PR-8 round-robin-interleave deviation is retired.
"""

import json
import logging
import os
import urllib.request

import pytest

from hivedscheduler_tpu import common
from hivedscheduler_tpu.algorithm.cell import LOWEST_LEVEL
from hivedscheduler_tpu.api import constants, extender as ei
from hivedscheduler_tpu.scheduler import audit as audit_mod
from hivedscheduler_tpu.scheduler import recorder as recorder_mod
from hivedscheduler_tpu.scheduler.framework import (
    HivedScheduler,
    NullKubeClient,
)
from hivedscheduler_tpu.scheduler.types import Node
from hivedscheduler_tpu.sim.driver import TraceDriver, build_fleet_config
from hivedscheduler_tpu.sim.trace import TraceShape, generate_trace

from .test_core import make_pod
from .test_observability import gang, two_host_config

common.init_logging(logging.ERROR)


# --------------------------------------------------------------------- #
# Deterministic incident replay (the 432-host acceptance)
# --------------------------------------------------------------------- #


def _bench_recording(hosts=432, gangs=140, seed=3, capacity=1 << 18):
    """A live bench-fleet run with the recorder armed: burst load, fault
    injection, preemption pressure — the acceptance workload. The env
    hatch is pinned so an ambient HIVED_FLIGHT_RECORDER=0 cannot blank
    the capture mid-suite."""
    saved = os.environ.pop(recorder_mod.FLIGHT_RECORDER_ENV, None)
    try:
        return _bench_recording_inner(hosts, gangs, seed, capacity)
    finally:
        if saved is not None:
            os.environ[recorder_mod.FLIGHT_RECORDER_ENV] = saved


def _bench_recording_inner(hosts, gangs, seed, capacity):
    shape = TraceShape(
        hosts=hosts,
        gangs=gangs,
        duration_s=1800.0,
        pattern="burst",
        burst_fraction=0.6,
        opportunistic_fraction=0.4,
        mean_runtime_s=700.0,
        fault_events=12,
    )
    trace = generate_trace(seed, shape)
    config, actual_hosts = build_fleet_config(hosts)
    config.flight_recorder_capacity = capacity
    driver = TraceDriver(config)
    driver.sched.recorder.hosts = actual_hosts
    report = driver.run(trace)
    report["hosts"] = actual_hosts
    recording = driver.sched.recorder.recording()
    driver.close()
    return report, recording


def test_recording_replays_fingerprint_identical_at_432_hosts():
    """ISSUE 15 acceptance: capture from a live 432-host bench run (gang
    churn + faults + >= 1 preemption), replay through
    --replay-recording's engine, assert the placement fingerprints are
    identical."""
    report, recording = _bench_recording()
    counts = report["counts"]
    assert counts["preemptionEvents"] >= 1, counts
    assert counts["faultsApplied"] >= 1, counts
    assert counts["boundGangs"] > 0
    assert recording["truncated"] is False
    assert recording["hosts"] == report["hosts"]

    result = recorder_mod.replay_recording(
        recording, build_fleet_config(432)[0]
    )
    assert result["identical"] is True, (
        result["liveFingerprint"], result["replayFingerprint"],
    )
    assert result["events"]["_errors"] == 0
    assert result["events"].get("filter", 0) > 0
    assert result["events"].get("preempt", 0) >= 1


def test_reanchored_window_still_replays_identically():
    """A bounded ring that wrapped mid-run re-anchors on a fresh
    snapshot export; the (non-pristine) window must restore through the
    what-if fork path and still replay fingerprint-identically."""
    report, recording = _bench_recording(
        hosts=104, gangs=110, seed=5, capacity=300
    )
    assert recording["meta"]["reanchors"] >= 1, recording["meta"]
    assert recording["anchor"]["pristine"] is False
    assert recording["truncated"] is False
    result = recorder_mod.replay_recording(
        recording, build_fleet_config(104)[0]
    )
    assert result["identical"] is True, (
        result["liveFingerprint"], result["replayFingerprint"],
    )


def test_truncated_recording_is_refused_for_replay():
    rec = {
        "kind": "flightRecording", "truncated": True,
        "anchor": {"pristine": True}, "events": [],
    }
    with pytest.raises(ValueError):
        recorder_mod.build_replay_subject(
            rec, build_fleet_config(104)[0]
        )


def test_config_fingerprint_mismatch_is_refused():
    _report, recording = _bench_recording(hosts=104, gangs=20, seed=1)
    recording["configFingerprint"] = "deadbeef" * 8
    with pytest.raises(ValueError):
        recorder_mod.build_replay_subject(
            recording, build_fleet_config(104)[0]
        )


# --------------------------------------------------------------------- #
# Live invariant auditor: sensitivity meta-test
# --------------------------------------------------------------------- #


def _audited_scheduler(tmp_path, monkeypatch):
    monkeypatch.setenv(audit_mod.AUDIT_ARTIFACT_DIR_ENV, str(tmp_path))
    cfg = two_host_config()
    cfg.audit_interval_ticks = 1  # every mutating verb audits
    sched = HivedScheduler(
        cfg, kube_client=NullKubeClient(), trace_sample=0.0
    )
    for name in sched.core.configured_node_names():
        sched.add_node(Node(name=name))
    assert sched.live_auditor is not None
    assert sched.live_auditor.violation_count == 0
    return sched


def _drive_one_verb(sched, tag):
    """One harmless mutating verb — the cadence clock the auditor rides."""
    sched.health_tick()


def test_live_auditor_catches_free_list_corruption(tmp_path, monkeypatch):
    """Corrupt a free list under the test hook: the LIVE auditor must
    catch it within one cadence, increment the violation counter, dump
    the artifact bundle — and the scheduler must keep serving."""
    sched = _audited_scheduler(tmp_path, monkeypatch)
    core = sched.core
    chain = sorted(core.free_cell_list)[0]
    ccl = core.free_cell_list[chain]
    top = ccl.top_level
    cell = ccl[top][0]
    ccl.remove(cell, top)  # the corruption: a free cell vanishes
    _drive_one_verb(sched, "after-corruption")
    aud = sched.live_auditor
    assert aud.violation_count >= 1, "auditor missed free-list corruption"
    assert sched.get_metrics()["auditViolationCount"] >= 1
    # The bundle landed, with the black-box contents.
    assert aud.last_artifact and os.path.exists(aud.last_artifact)
    payload = json.loads(open(aud.last_artifact).read())
    assert "decisions" in payload and "metrics" in payload
    assert "flightRecording" in payload and "traces" in payload
    # Journaled under the synthetic _audit pod key.
    journal = [
        d for d in sched.get_decisions()["items"]
        if d.get("pod") == "_audit"
    ]
    assert journal and journal[-1]["verdict"] == "error"
    # Degrade gracefully: the scheduler still serves (un-corrupt first so
    # placement is sane, then filter must succeed).
    core.free_cell_list[chain][top].append(cell)
    pod = make_pod("a0-0", "ua0", "A", -1, "v5e-chip", 1,
                   group=gang("ga", 1, 1))
    sched.add_pod(pod)
    r = sched.filter_routine(
        ei.ExtenderArgs(pod=pod, node_names=sorted(sched.nodes))
    )
    assert r.node_names or r.failed_nodes  # served, not crashed


def test_live_auditor_catches_doomed_counter_corruption(
    tmp_path, monkeypatch
):
    sched = _audited_scheduler(tmp_path, monkeypatch)
    core = sched.core
    chain = sorted(core.full_cell_list)[0]
    # The corruption: a phantom doomed-bad cell count with no doomed list
    # entry behind it (invariant 2).
    core.all_vc_doomed_bad_cell_num.setdefault(chain, {})
    core.all_vc_doomed_bad_cell_num[chain][LOWEST_LEVEL] = (
        core.all_vc_doomed_bad_cell_num[chain].get(LOWEST_LEVEL, 0) + 1
    )
    before = sched.live_auditor.violation_count
    _drive_one_verb(sched, "after-doom-corruption")
    assert sched.live_auditor.violation_count > before, (
        "auditor missed doomed-counter corruption"
    )


def test_nooped_live_auditor_is_caught(tmp_path, monkeypatch):
    """The meta-test's teeth: with audit_invariants no-op'd, the SAME
    corruption goes uncaught — proving the catch above is the auditor's
    doing, not an accident of some other assertion (the test_nooped_*
    precedent)."""
    sched = _audited_scheduler(tmp_path, monkeypatch)
    monkeypatch.setattr(
        audit_mod, "audit_invariants", lambda s, ctx="": None
    )
    core = sched.core
    chain = sorted(core.free_cell_list)[0]
    ccl = core.free_cell_list[chain]
    top = ccl.top_level
    ccl.remove(ccl[top][0], top)
    _drive_one_verb(sched, "after-corruption-nooped")
    assert sched.live_auditor.violation_count == 0, (
        "no-op'd auditor still reported a violation — the sensitivity "
        "test is not actually exercising audit_invariants"
    )


def test_auditor_hatch_and_cadence_knobs(monkeypatch):
    monkeypatch.setenv(audit_mod.LIVE_AUDIT_ENV, "0")
    sched = HivedScheduler(
        two_host_config(), kube_client=NullKubeClient(), trace_sample=0.0
    )
    assert sched.live_auditor is None
    monkeypatch.delenv(audit_mod.LIVE_AUDIT_ENV)
    monkeypatch.setenv(audit_mod.AUDIT_INTERVAL_ENV, "7")
    sched2 = HivedScheduler(
        two_host_config(), kube_client=NullKubeClient(), trace_sample=0.0
    )
    assert sched2.live_auditor is not None
    assert sched2.live_auditor.interval_ticks == 7
    monkeypatch.setenv(recorder_mod.FLIGHT_RECORDER_ENV, "0")
    sched3 = HivedScheduler(
        two_host_config(), kube_client=NullKubeClient(), trace_sample=0.0
    )
    assert sched3.recorder is None
    # Golden metrics keys stay present while disabled.
    m = sched3.get_metrics()
    assert m["flightRecorderEventCount"] == 0
    assert m["auditViolationCount"] == 0


# --------------------------------------------------------------------- #
# /v1/inspect/flightrecorder + decision filters over HTTP
# --------------------------------------------------------------------- #


def test_flightrecorder_endpoint_and_decision_filters():
    from hivedscheduler_tpu.webserver.server import WebServer

    sched = HivedScheduler(
        two_host_config(), kube_client=NullKubeClient(), trace_sample=0.0
    )
    for name in sched.core.configured_node_names():
        sched.add_node(Node(name=name))
    ws = WebServer(sched, address="127.0.0.1:0")
    ws.start()
    try:
        pod = make_pod("f0-0", "uf0", "A", 0, "v5e-chip", 4,
                       group=gang("gf", 1, 4))
        sched.add_pod(pod)
        assert sched.filter_routine(
            ei.ExtenderArgs(pod=pod, node_names=sorted(sched.nodes))
        ).node_names
        # A quota-blocked waiter for the ?verdict=wait&gate=vcQuota slice.
        waiter = make_pod("f1-0", "uf1", "A", 0, "v5e-chip", 4,
                          group=gang("gw", 2, 4))
        sched.add_pod(waiter)
        sched.filter_routine(
            ei.ExtenderArgs(pod=waiter, node_names=sorted(sched.nodes))
        )

        def get(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{ws.port}{path}", timeout=10
            ) as r:
                return json.loads(r.read())

        fr = get(constants.FLIGHTRECORDER_PATH)
        assert fr["enabled"] is True
        assert fr["windowEvents"] > 0
        assert "eventKinds" in fr and fr["eventKinds"].get("filter")
        full = get(constants.FLIGHTRECORDER_PATH + "?full=1")
        assert full["kind"] == "flightRecording"
        assert full["events"] and full["pods"]
        # ?verdict= / ?gate= slice the journal server-side.
        binds = get(constants.DECISIONS_PATH + "?verdict=bind")["items"]
        assert binds and all(d["verdict"] == "bind" for d in binds)
        waits = get(
            constants.DECISIONS_PATH + "?verdict=wait&gate=vcQuota"
        )["items"]
        assert waits and all(d["verdict"] == "wait" for d in waits)
        assert get(
            constants.DECISIONS_PATH + "?verdict=preempt"
        )["items"] == []
        assert len(get(
            constants.DECISIONS_PATH + "?verdict=bind&n=1"
        )["items"]) == 1
    finally:
        ws.stop()


# --------------------------------------------------------------------- #
# Causal cross-shard trace stitching
# --------------------------------------------------------------------- #


def test_sharded_traces_are_causally_stitched(monkeypatch):
    """Worker filter traces must commit as children of the frontend's
    trace (parentTraceId over the pipe protocol) and the merged ring
    must nest them — retiring the PR-8 round-robin interleave."""
    monkeypatch.setenv("HIVED_TRACE_SAMPLE", "1")
    import bench
    from hivedscheduler_tpu.scheduler.shards import ShardedScheduler

    front = ShardedScheduler(
        bench.build_concurrent_config(2, 8),
        kube_client=NullKubeClient(),
        n_shards=2,
        transport="local",
        auto_admit=True,
    )
    try:
        nodes = front.configured_node_names()
        for n in nodes:
            front.add_node(Node(name=n))
        pod = make_pod(
            "st0-0", "ust0", "vc0", 0, "cc0-chip", 1,
            group=gang("gst", 1, 1),
        )
        front.add_pod(pod)
        r = front.filter_routine(
            ei.ExtenderArgs(pod=pod, node_names=nodes)
        )
        assert r.node_names
        merged = front.get_traces()
        items = merged["items"]
        fronts = [
            t for t in items
            if t.get("shard") == "frontend" and t["name"] == "filter"
        ]
        assert fronts, items
        parent = fronts[-1]
        children = parent.get("children") or []
        assert children, "worker trace did not stitch under the frontend"
        for child in children:
            assert child["parentTraceId"] == parent["traceId"]
            assert child["shard"] != "frontend"
            assert child["name"] == "filter"
        # Every top-level item carries the cross-process wall stamp and
        # the list is recency-ordered on it.
        stamps = [t.get("wallTime") for t in items]
        assert all(s is not None for s in stamps)
        assert stamps == sorted(stamps)
        # No stitched child is ALSO duplicated at top level.
        child_ids = {
            (c["shard"], c["traceId"])
            for t in items for c in (t.get("children") or [])
        }
        top_ids = {(t.get("shard"), t["traceId"]) for t in items}
        assert not (child_ids & top_ids)
    finally:
        front.close()
