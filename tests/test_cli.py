"""End-to-end CLI test: `python -m hivedscheduler_tpu --standalone` serves
the example config over HTTP and exits on config change (restart-based
reconfiguration, reference: api/config.go:202-217)."""

import json
import pathlib
import shutil
import subprocess
import sys
import time
import urllib.error
import urllib.request

REPO = pathlib.Path(__file__).resolve().parents[1]
PORT = 19473  # unlikely-to-collide test port


def wait_http(url, timeout=20.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=1) as resp:
                return json.loads(resp.read())
        except (urllib.error.URLError, OSError):
            time.sleep(0.2)
    raise TimeoutError(url)


def test_cli_standalone_serves_and_restarts_on_config_change(tmp_path):
    config_path = tmp_path / "hivedscheduler.yaml"
    text = (REPO / "example/config/hivedscheduler.yaml").read_text()
    config_path.write_text(text.replace('":9096"', f'"127.0.0.1:{PORT}"'))

    proc = subprocess.Popen(
        [sys.executable, "-m", "hivedscheduler_tpu", "--standalone",
         "--config", str(config_path)],
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )
    try:
        status = wait_http(f"http://127.0.0.1:{PORT}/v1/inspect/clusterstatus")
        assert set(status["virtualClusters"]) == {"prod", "research"}
        # 2 v5p-64 + 2 v5e-16 + 1 v5e host + 2 cpu hosts
        assert len(status["physicalCluster"]) == 7

        version = wait_http(f"http://127.0.0.1:{PORT}/v1")
        assert version["component"] == "hivedscheduler-tpu"

        # Touching the config with new content must make the process exit
        # (the supervisor then restarts it into recovery).
        config_path.write_text(config_path.read_text() + "\n# changed\n")
        assert proc.wait(timeout=30) == 1
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def test_validate_config_mode(tmp_path, capsys):
    """--validate-config compiles the config and exits 0/1 with a verdict
    line — the pre-deploy lint."""
    from hivedscheduler_tpu.__main__ import main

    good = REPO / "example/config/hivedscheduler.yaml"
    assert main(["--validate-config", "--config", str(good)]) == 0
    assert capsys.readouterr().out.startswith("OK: ")

    bad = tmp_path / "bad.yaml"
    bad.write_text(
        "physicalCluster:\n"
        "  cellTypes:\n"
        "    v5e-host: {childCellType: v5e-chip, childCellNumber: 4,"
        " isNodeLevel: true}\n"
        "  physicalCells:\n"
        "    - cellType: v5e-host\n"
        "      cellAddress: host-a\n"
        "virtualClusters:\n"
        "  vc1:\n"
        "    virtualCells:\n"
        "      - cellType: v5e-host\n"
        "        cellNumber: 5\n"
    )
    assert main(["--validate-config", "--config", str(bad)]) == 1
    # The rejection must be the quota-vs-capacity check this fixture
    # targets — not a YAML typo or a missing file.
    out = capsys.readouterr().out
    assert out.startswith("INVALID: ")
    assert "Insufficient physical cells" in out
