"""Tests for checkpoint/resume (orbax) and the data pipeline."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hivedscheduler_tpu.models import checkpoint, train, transformer
from hivedscheduler_tpu.parallel import mesh as pmesh, sharding
from hivedscheduler_tpu.utils.data import TokenFileDataset, prefetch_to_mesh


def test_checkpoint_roundtrip_sharded(tmp_path):
    config = transformer.tiny()
    mesh = pmesh.make_mesh(pmesh.MeshConfig(fsdp=4, tp=2), devices=jax.devices())
    optimizer = train.make_optimizer()
    params, opt_state, param_sh, opt_sh = train.init_sharded(
        config, mesh, jax.random.PRNGKey(0), optimizer
    )

    ckpt = checkpoint.TrainCheckpointer(str(tmp_path / "ckpt"))
    ckpt.save(7, params, opt_state)
    ckpt.wait()
    assert ckpt.latest_step() == 7

    # Restore into the same shardings; every leaf matches bit-for-bit.
    r_params, r_opt, step = ckpt.restore(params, opt_state)
    assert step == 7
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(r_params)):
        np.testing.assert_array_equal(np.array(a), np.array(b))
        assert a.sharding == b.sharding

    # Params-only restore (the serving path): same bits, DIFFERENT target
    # shardings (a serving mesh need not match the trainer's), optimizer
    # items never touched.
    smesh = pmesh.make_mesh(
        pmesh.MeshConfig(fsdp=2, tp=4), devices=jax.devices()
    )
    s_sh = sharding.tree_shardings(smesh, transformer.logical_axes(config))
    p_like = jax.tree.map(
        lambda a, shd: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=shd),
        params, s_sh,
    )
    s_params, s_step = ckpt.restore_params(p_like)
    assert s_step == 7
    for a, b, like in zip(
        jax.tree.leaves(params),
        jax.tree.leaves(s_params),
        jax.tree.leaves(p_like),
    ):
        np.testing.assert_array_equal(np.array(a), np.array(b))
        assert b.sharding == like.sharding
    ckpt.close()


def test_token_dataset_and_prefetch(tmp_path):
    tokens = np.arange(1000, dtype=np.uint16) % 511
    path = tmp_path / "tokens.bin"
    tokens.tofile(path)

    ds = TokenFileDataset(str(path), seq_len=32)
    assert ds.n_samples == (1000 - 1) // 32

    mesh = pmesh.make_mesh(pmesh.MeshConfig(fsdp=8), devices=jax.devices())
    got = []
    for batch in prefetch_to_mesh(ds.batches(8, epochs=1), mesh):
        assert batch.shape == (8, 33)
        assert batch.dtype == jnp.int32
        got.append(batch)
    assert len(got) == ds.n_samples // 8
    # Batches are device-resident and sharded over the batch axis.
    assert len(got[0].sharding.device_set) == 8


def test_dataset_shuffles_deterministically(tmp_path):
    tokens = (np.arange(4096, dtype=np.uint16) * 7) % 500
    path = tmp_path / "tokens.bin"
    tokens.tofile(path)
    ds = TokenFileDataset(str(path), seq_len=64)
    a = [b.copy() for b in ds.batches(4, seed=1, epochs=1)]
    b = [b.copy() for b in ds.batches(4, seed=1, epochs=1)]
    c = [b.copy() for b in ds.batches(4, seed=2, epochs=1)]
    np.testing.assert_array_equal(np.concatenate(a), np.concatenate(b))
    assert not np.array_equal(np.concatenate(a), np.concatenate(c))


@pytest.mark.parametrize(
    "chunk_of_vocab",
    [lambda v: v // 4,        # even split
     lambda v: v // 4 + 7],   # non-divisor: exercises the remainder step
)
def test_fused_chunked_loss_matches_reference(chunk_of_vocab):
    """The vocab-chunked logsumexp loss must equal the materialized
    log_softmax path exactly (values and gradients), including when the
    chunk does not divide the vocab."""
    from hivedscheduler_tpu.models import train, transformer

    config = transformer.tiny()
    params = transformer.init(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 32), 0, config.vocab_size
    )
    chunk = chunk_of_vocab(config.vocab_size)

    ref = train.next_token_loss(params, tokens, config, fused=False)
    fused = train.next_token_loss(params, tokens, config, fused=True,
                                  chunk=chunk)
    assert abs(float(ref) - float(fused)) < 1e-5, (ref, fused)

    gr = jax.grad(
        lambda p: train.next_token_loss(p, tokens, config, fused=False)
    )(params)
    gf = jax.grad(
        lambda p: train.next_token_loss(p, tokens, config, fused=True,
                                        chunk=chunk)
    )(params)
    for a, b in zip(jax.tree.leaves(gr), jax.tree.leaves(gf)):
        scale = float(jnp.max(jnp.abs(a))) + 1e-8
        assert float(jnp.max(jnp.abs(a - b))) / scale < 1e-4


def test_fused_loss_engages_and_matches_on_fsdp_mesh():
    """dp/fsdp-only meshes leave the vocab unsharded, so the fused path is
    the default there too; it must match the unfused loss under the mesh."""
    from hivedscheduler_tpu.models import train, transformer
    from hivedscheduler_tpu.parallel import mesh as pmesh, sharding

    config = transformer.tiny()
    params = transformer.init(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (8, 32), 0, config.vocab_size
    )
    mesh = pmesh.make_mesh(pmesh.MeshConfig(fsdp=8), devices=jax.devices())
    param_sh = sharding.tree_shardings(mesh, transformer.logical_axes(config))
    sp = jax.device_put(params, param_sh)
    st = sharding.shard_batch(tokens, mesh)
    ref = train.next_token_loss(params, tokens, config, fused=False)
    fused = jax.jit(
        lambda p, t: train.next_token_loss(
            p, t, config, mesh=mesh, fused=True,
            chunk=config.vocab_size // 4,
        )
    )(sp, st)
    assert abs(float(ref) - float(fused)) < 1e-4, (ref, fused)


def test_checkpoint_restores_across_mesh_layouts(tmp_path):
    """Elastic resume: a checkpoint written under one parallelism layout
    must restore bit-exactly into a different mesh (here fsdp=4 x tp=2 ->
    pp=2 x fsdp=2 x tp=2, a layout the pipelined train step supports) with
    the new layout's shardings — what a rescheduled gang does when the
    scheduler lands it on a different slice shape."""
    config = transformer.tiny()
    optimizer = train.make_optimizer()
    mesh_a = pmesh.make_mesh(
        pmesh.MeshConfig(fsdp=4, tp=2), devices=jax.devices()
    )
    params, opt_state, _, _ = train.init_sharded(
        config, mesh_a, jax.random.PRNGKey(0), optimizer
    )
    ckpt = checkpoint.TrainCheckpointer(str(tmp_path / "ckpt"))
    ckpt.save(3, params, opt_state)
    ckpt.wait()

    mesh_b = pmesh.make_mesh(
        pmesh.MeshConfig(pp=2, fsdp=2, tp=2), devices=jax.devices()
    )
    p2, o2, psh_b, osh_b = train.init_sharded(
        config, mesh_b, jax.random.PRNGKey(1), optimizer
    )
    r_params, r_opt, step = ckpt.restore(p2, o2)
    assert step == 3
    # Params AND optimizer state (the larger, more reshard-prone tree)
    # restore bit-exactly...
    for saved, restored in (
        (params, r_params),
        (opt_state, r_opt),
    ):
        for a, b in zip(jax.tree.leaves(saved), jax.tree.leaves(restored)):
            np.testing.assert_array_equal(np.array(a), np.array(b))
    # ...and every restored leaf carries the NEW layout's shardings.
    for restored, want_sh in ((r_params, psh_b), (r_opt, osh_b)):
        for leaf, want in zip(
            jax.tree.leaves(restored), jax.tree.leaves(want_sh)
        ):
            assert leaf.sharding == want
    ckpt.close()


def test_sharded_batches_single_process(tmp_path):
    """pc=1 degenerate: sharded_batches must yield the same token content
    as the plain batches() iterator, as a mesh-sharded global jax.Array."""
    import numpy as np

    from hivedscheduler_tpu.parallel import mesh as pmesh
    from hivedscheduler_tpu.utils import data

    path = tmp_path / "tokens.bin"
    rng = np.random.default_rng(0)
    rng.integers(0, 1000, size=4096, dtype=np.uint16).tofile(path)
    ds = data.TokenFileDataset(str(path), seq_len=32)
    mesh = pmesh.make_mesh(pmesh.MeshConfig(fsdp=8), devices=jax.devices())

    plain = list(ds.batches(8, seed=3, epochs=1))
    shard = list(data.sharded_batches(ds, 8, mesh, seed=3, epochs=1))
    assert len(plain) == len(shard) and len(plain) > 0
    for a, b in zip(plain, shard):
        assert b.shape == (8, 33)
        np.testing.assert_array_equal(a, np.array(b))


def test_sharded_batches_across_real_processes(tmp_path):
    """2 real OS processes x 4 virtual devices: each process materializes
    only its rows; the assembled global arrays must match the single-host
    reference batches ROW FOR ROW (positional per-row sums — content at
    the wrong global position would pass a permutation-invariant total)."""
    import os

    import numpy as np

    from hivedscheduler_tpu.utils import data

    from ._multiproc import free_port, run_workers

    path = tmp_path / "tokens.bin"
    rng = np.random.default_rng(1)
    rng.integers(0, 500, size=2048, dtype=np.uint16).tofile(path)

    port = free_port()
    worker = os.path.join(os.path.dirname(__file__), "_sharded_data_worker.py")
    outs = run_workers(
        worker,
        [[str(pid), "2", str(port), str(path), "4", "fsdp"]
         for pid in range(2)],
    )

    assert all(o["shape"] == [8, 17] for o in outs)
    # Both processes assembled the SAME global arrays...
    assert outs[0]["row_sums"] == outs[1]["row_sums"]
    # ...whose rows sit at exactly the shared-seed reference positions.
    ds = data.TokenFileDataset(str(path), seq_len=16)
    expect = [
        b.astype(np.int64).sum(axis=1).tolist()
        for b in ds.batches(8, seed=7, epochs=1)
    ]
    assert outs[0]["row_sums"] == expect and len(expect) > 0


def test_sharded_batches_when_seq_axis_crosses_processes(tmp_path):
    """4 processes x 1 device on an fsdp=2 x sp=2 mesh: each process's
    addressable region is a QUARTER box (half the rows x half the seq
    columns). sharded_batches must derive that box from the sharding —
    the assumed-contiguous-rows formulation cannot serve this layout —
    and the assembled global arrays must still match the reference row
    for row."""
    import os

    import numpy as np

    from hivedscheduler_tpu.utils import data

    from ._multiproc import free_port, run_workers

    path = tmp_path / "tokens.bin"
    rng = np.random.default_rng(2)
    rng.integers(0, 500, size=2048, dtype=np.uint16).tofile(path)

    port = free_port()
    worker = os.path.join(os.path.dirname(__file__), "_sharded_data_worker.py")
    outs = run_workers(
        worker,
        # seq_len 15 -> sample width 16, divisible by sp=2 (the +1 target
        # column is part of the sharded width).
        [[str(pid), "4", str(port), str(path), "1", "fsdp_sp", "15"]
         for pid in range(4)],
        timeout=240,
    )

    assert all(o["shape"] == [8, 16] for o in outs)
    assert all(o["row_sums"] == outs[0]["row_sums"] for o in outs)
    ds = data.TokenFileDataset(str(path), seq_len=15)
    expect = [
        b.astype(np.int64).sum(axis=1).tolist()
        for b in ds.batches(8, seed=7, epochs=1)
    ]
    assert outs[0]["row_sums"] == expect and len(expect) > 0
