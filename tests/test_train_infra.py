"""Tests for checkpoint/resume (orbax) and the data pipeline."""

import jax
import jax.numpy as jnp
import numpy as np

from hivedscheduler_tpu.models import checkpoint, train, transformer
from hivedscheduler_tpu.parallel import mesh as pmesh, sharding
from hivedscheduler_tpu.utils.data import TokenFileDataset, prefetch_to_mesh


def test_checkpoint_roundtrip_sharded(tmp_path):
    config = transformer.tiny()
    mesh = pmesh.make_mesh(pmesh.MeshConfig(fsdp=4, tp=2), devices=jax.devices())
    optimizer = train.make_optimizer()
    params, opt_state, param_sh, opt_sh = train.init_sharded(
        config, mesh, jax.random.PRNGKey(0), optimizer
    )

    ckpt = checkpoint.TrainCheckpointer(str(tmp_path / "ckpt"))
    ckpt.save(7, params, opt_state)
    ckpt.wait()
    assert ckpt.latest_step() == 7

    # Restore into the same shardings; every leaf matches bit-for-bit.
    r_params, r_opt, step = ckpt.restore(params, opt_state)
    assert step == 7
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(r_params)):
        np.testing.assert_array_equal(np.array(a), np.array(b))
        assert a.sharding == b.sharding
    ckpt.close()


def test_token_dataset_and_prefetch(tmp_path):
    tokens = np.arange(1000, dtype=np.uint16) % 511
    path = tmp_path / "tokens.bin"
    tokens.tofile(path)

    ds = TokenFileDataset(str(path), seq_len=32)
    assert ds.n_samples == (1000 - 1) // 32

    mesh = pmesh.make_mesh(pmesh.MeshConfig(fsdp=8), devices=jax.devices())
    got = []
    for batch in prefetch_to_mesh(ds.batches(8, epochs=1), mesh):
        assert batch.shape == (8, 33)
        assert batch.dtype == jnp.int32
        got.append(batch)
    assert len(got) == ds.n_samples // 8
    # Batches are device-resident and sharded over the batch axis.
    assert len(got[0].sharding.device_set) == 8


def test_dataset_shuffles_deterministically(tmp_path):
    tokens = (np.arange(4096, dtype=np.uint16) * 7) % 500
    path = tmp_path / "tokens.bin"
    tokens.tofile(path)
    ds = TokenFileDataset(str(path), seq_len=64)
    a = [b.copy() for b in ds.batches(4, seed=1, epochs=1)]
    b = [b.copy() for b in ds.batches(4, seed=1, epochs=1)]
    c = [b.copy() for b in ds.batches(4, seed=2, epochs=1)]
    np.testing.assert_array_equal(np.concatenate(a), np.concatenate(b))
    assert not np.array_equal(np.concatenate(a), np.concatenate(c))
