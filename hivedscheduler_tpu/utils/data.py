"""Input pipeline: token datasets with async host->device prefetch.

Keeps the MXU fed: while step N computes, batch N+1 is already being
device_put onto the mesh (double buffering). Sources are memory-mapped
token files (np.memmap — zero-copy reads, no framework dependency) or any
iterator of numpy arrays.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh

from ..parallel import sharding


class TokenFileDataset:
    """Fixed-length sample view over a flat token file (dtype uint16/32).

    ``path`` is a binary file of token ids; sample i is the half-open
    window [i*seq_len, (i+1)*seq_len + 1) — the +1 provides the shifted
    next-token target inside the same sample.
    """

    def __init__(self, path: str, seq_len: int, dtype=np.uint16):
        self.tokens = np.memmap(path, dtype=dtype, mode="r")
        self.seq_len = seq_len
        self.n_samples = (len(self.tokens) - 1) // seq_len
        if self.n_samples <= 0:
            raise ValueError(
                f"{path}: {len(self.tokens)} tokens < one sample of "
                f"{seq_len + 1}"
            )

    def batches(
        self, batch_size: int, seed: int = 0, epochs: Optional[int] = None
    ) -> Iterator[np.ndarray]:
        """Yield [batch, seq_len+1] int32 batches, shuffled per epoch."""
        rng = np.random.default_rng(seed)
        epoch = 0
        while epochs is None or epoch < epochs:
            order = rng.permutation(self.n_samples)
            for start in range(0, self.n_samples - batch_size + 1, batch_size):
                idx = order[start:start + batch_size]
                batch = np.stack(
                    [
                        self.tokens[i * self.seq_len:(i + 1) * self.seq_len + 1]
                        for i in idx
                    ]
                )
                yield batch.astype(np.int32)
            epoch += 1


def prefetch_to_mesh(
    batches: Iterable[Any],
    mesh: Mesh,
    buffer_size: int = 2,
    put: Optional[Callable[[Any, Mesh], Any]] = None,
) -> Iterator[Any]:
    """Async device transfer: a background thread device_puts up to
    ``buffer_size`` batches ahead onto the mesh (batch/seq sharding by
    default), so the transfer overlaps the previous step's compute."""
    put = put or sharding.shard_batch
    q: "queue.Queue" = queue.Queue(maxsize=buffer_size)
    END = object()
    stop = threading.Event()

    def _enqueue(item) -> bool:
        # Bounded put that notices consumer abandonment: without this, a
        # consumer that breaks out early leaves the worker blocked forever,
        # pinning device-resident batches in HBM.
        while not stop.is_set():
            try:
                q.put(item, timeout=0.5)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for batch in batches:
                if not _enqueue(put(batch, mesh)):
                    return
            _enqueue(END)
        except BaseException as e:  # noqa: BLE001
            # Surface data-source / transfer failures to the consumer —
            # never let a broken pipeline look like a clean end-of-data.
            _enqueue(e)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        # GeneratorExit (early consumer break) or error: release the worker
        # and drop any buffered device batches.
        stop.set()
        while not q.empty():
            try:
                q.get_nowait()
            except queue.Empty:
                break
