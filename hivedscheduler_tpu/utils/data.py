"""Input pipeline: token datasets with async host->device prefetch.

Keeps the MXU fed: while step N computes, batch N+1 is already being
device_put onto the mesh (double buffering). Sources are memory-mapped
token files (np.memmap — zero-copy reads, no framework dependency) or any
iterator of numpy arrays.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from ..parallel import sharding


class TokenFileDataset:
    """Fixed-length sample view over a flat token file (dtype uint16/32).

    ``path`` is a binary file of token ids; sample i is the half-open
    window [i*seq_len, (i+1)*seq_len + 1) — the +1 provides the shifted
    next-token target inside the same sample.
    """

    def __init__(self, path: str, seq_len: int, dtype=np.uint16):
        self.tokens = np.memmap(path, dtype=dtype, mode="r")
        self.seq_len = seq_len
        self.n_samples = (len(self.tokens) - 1) // seq_len
        if self.n_samples <= 0:
            raise ValueError(
                f"{path}: {len(self.tokens)} tokens < one sample of "
                f"{seq_len + 1}"
            )

    def sample_indices(
        self, batch_size: int, seed: int = 0, epochs: Optional[int] = None
    ) -> Iterator[np.ndarray]:
        """Yield per-batch sample-index arrays, shuffled per epoch.
        Deterministic in ``seed``: every process of a gang derives the
        identical order (the basis of ``sharded_batches``)."""
        if batch_size > self.n_samples:
            # Would otherwise yield nothing and, with epochs=None, spin
            # forever re-permuting — fail fast with the actual cause.
            raise ValueError(
                f"batch_size={batch_size} > {self.n_samples} samples in "
                "the dataset"
            )
        rng = np.random.default_rng(seed)
        epoch = 0
        while epochs is None or epoch < epochs:
            order = rng.permutation(self.n_samples)
            for start in range(0, self.n_samples - batch_size + 1, batch_size):
                yield order[start:start + batch_size]
            epoch += 1

    def gather(self, idx: np.ndarray) -> np.ndarray:
        """Materialize the [len(idx), seq_len+1] int32 rows for ``idx``."""
        return np.stack(
            [
                self.tokens[i * self.seq_len:(i + 1) * self.seq_len + 1]
                for i in idx
            ]
        ).astype(np.int32)

    def batches(
        self, batch_size: int, seed: int = 0, epochs: Optional[int] = None
    ) -> Iterator[np.ndarray]:
        """Yield [batch, seq_len+1] int32 batches, shuffled per epoch."""
        for idx in self.sample_indices(batch_size, seed, epochs):
            yield self.gather(idx)


def _addressable_box(
    ns: NamedSharding, global_shape: tuple
) -> tuple:
    """This process's addressable region of a 2-D NamedSharding as one
    contiguous box ((row_lo, row_hi), (col_lo, col_hi)).

    Derived from the sharding itself (``devices_indices_map``), never from
    an assumed process->rows mapping: in multi-host meshes a process's
    devices can sit at ANY batch block, and the sequence axis (sp) can
    cross process boundaries too. Raises when the addressable region is
    not a box (a layout interleaving this process's devices
    non-contiguously), which per-process materialization cannot serve."""
    imap = ns.devices_indices_map(global_shape)
    rows, cols = set(), set()
    for d in ns.addressable_devices:
        r, c = imap[d]
        rows.add((r.start or 0,
                  global_shape[0] if r.stop is None else r.stop))
        cols.add((c.start or 0,
                  global_shape[1] if c.stop is None else c.stop))

    def _contiguous(spans, what):
        spans = sorted(spans)
        for (a0, b0), (a1, b1) in zip(spans, spans[1:]):
            if b0 != a1:
                raise ValueError(
                    f"process-addressable {what} spans {spans} are not "
                    "contiguous; choose a process-aligned mesh layout for "
                    "sharded_batches"
                )
        return spans[0][0], spans[-1][1]

    if len(rows) * len(cols) != len(
        {imap[d] for d in ns.addressable_devices}
    ):
        raise ValueError(
            "process-addressable shards do not form a box; choose a "
            "process-aligned mesh layout for sharded_batches"
        )
    return _contiguous(rows, "rows"), _contiguous(cols, "cols")


def sharded_batches(
    dataset: TokenFileDataset,
    global_batch: int,
    mesh: Mesh,
    seed: int = 0,
    epochs: Optional[int] = None,
) -> Iterator[jax.Array]:
    """Multi-host input pipeline: yield GLOBAL [global_batch, seq+1]
    jax.Arrays of which this process materializes only its own region.

    Every process draws the same deterministic sample order (shared
    ``seed`` — the scheduler's bind-time env guarantees gang members can
    agree on one without coordination) and materializes exactly its
    ADDRESSABLE box of the global array — the batch rows its devices own
    (any block, not an assumed contiguous range) and, when the sequence
    axis is sharded across processes too (sp spanning hosts), only that
    column range; the global array is assembled with
    ``jax.make_array_from_process_local_data``, so no host reads from
    disk or holds more than its region. Single-process degenerates to a
    device_put of the full batch. The reference has no input pipeline at
    all (it schedules; workloads bring their own) — this is the
    TPU-native equivalent of per-rank dataset sharding in its example
    workloads' TF parameter-server jobs."""
    ns = NamedSharding(mesh, sharding.spec_for(("batch", "seq")))
    global_shape = (global_batch, dataset.seq_len + 1)
    (row_lo, row_hi), (col_lo, col_hi) = _addressable_box(ns, global_shape)
    for idx in dataset.sample_indices(global_batch, seed, epochs):
        # Slice the shared order FIRST: only this process's region is ever
        # read from the memmap or held in host memory.
        local_rows = dataset.gather(idx[row_lo:row_hi])[:, col_lo:col_hi]
        yield jax.make_array_from_process_local_data(
            ns, local_rows, global_shape
        )


def prefetch_to_mesh(
    batches: Iterable[Any],
    mesh: Mesh,
    buffer_size: int = 2,
    put: Optional[Callable[[Any, Mesh], Any]] = None,
) -> Iterator[Any]:
    """Async device transfer: a background thread device_puts up to
    ``buffer_size`` batches ahead onto the mesh (batch/seq sharding by
    default), so the transfer overlaps the previous step's compute."""
    put = put or sharding.shard_batch
    q: "queue.Queue" = queue.Queue(maxsize=buffer_size)
    END = object()
    stop = threading.Event()

    def _enqueue(item) -> bool:
        # Bounded put that notices consumer abandonment: without this, a
        # consumer that breaks out early leaves the worker blocked forever,
        # pinning device-resident batches in HBM.
        while not stop.is_set():
            try:
                q.put(item, timeout=0.5)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for batch in batches:
                if not _enqueue(put(batch, mesh)):
                    return
            _enqueue(END)
        except BaseException as e:  # noqa: BLE001
            # Surface data-source / transfer failures to the consumer —
            # never let a broken pipeline look like a clean end-of-data.
            _enqueue(e)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        # GeneratorExit (early consumer break) or error: release the worker
        # and drop any buffered device batches.
        stop.set()
        while not q.empty():
            try:
                q.get_nowait()
            except queue.Empty:
                break
