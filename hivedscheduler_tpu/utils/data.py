"""Input pipeline: token datasets with async host->device prefetch.

Keeps the MXU fed: while step N computes, batch N+1 is already being
device_put onto the mesh (double buffering). Sources are memory-mapped
token files (np.memmap — zero-copy reads, no framework dependency) or any
iterator of numpy arrays.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from ..parallel import sharding


class TokenFileDataset:
    """Fixed-length sample view over a flat token file (dtype uint16/32).

    ``path`` is a binary file of token ids; sample i is the half-open
    window [i*seq_len, (i+1)*seq_len + 1) — the +1 provides the shifted
    next-token target inside the same sample.
    """

    def __init__(self, path: str, seq_len: int, dtype=np.uint16):
        self.tokens = np.memmap(path, dtype=dtype, mode="r")
        self.seq_len = seq_len
        self.n_samples = (len(self.tokens) - 1) // seq_len
        if self.n_samples <= 0:
            raise ValueError(
                f"{path}: {len(self.tokens)} tokens < one sample of "
                f"{seq_len + 1}"
            )

    def sample_indices(
        self, batch_size: int, seed: int = 0, epochs: Optional[int] = None
    ) -> Iterator[np.ndarray]:
        """Yield per-batch sample-index arrays, shuffled per epoch.
        Deterministic in ``seed``: every process of a gang derives the
        identical order (the basis of ``sharded_batches``)."""
        rng = np.random.default_rng(seed)
        epoch = 0
        while epochs is None or epoch < epochs:
            order = rng.permutation(self.n_samples)
            for start in range(0, self.n_samples - batch_size + 1, batch_size):
                yield order[start:start + batch_size]
            epoch += 1

    def gather(self, idx: np.ndarray) -> np.ndarray:
        """Materialize the [len(idx), seq_len+1] int32 rows for ``idx``."""
        return np.stack(
            [
                self.tokens[i * self.seq_len:(i + 1) * self.seq_len + 1]
                for i in idx
            ]
        ).astype(np.int32)

    def batches(
        self, batch_size: int, seed: int = 0, epochs: Optional[int] = None
    ) -> Iterator[np.ndarray]:
        """Yield [batch, seq_len+1] int32 batches, shuffled per epoch."""
        for idx in self.sample_indices(batch_size, seed, epochs):
            yield self.gather(idx)


def sharded_batches(
    dataset: TokenFileDataset,
    global_batch: int,
    mesh: Mesh,
    seed: int = 0,
    epochs: Optional[int] = None,
) -> Iterator[jax.Array]:
    """Multi-host input pipeline: yield GLOBAL [global_batch, seq+1]
    jax.Arrays of which this process materializes only its own rows.

    Every process draws the same deterministic sample order (shared
    ``seed`` — the scheduler's bind-time env guarantees gang members can
    agree on one without coordination) and slices its contiguous
    ``global_batch / process_count`` row range; the global array is
    assembled with ``jax.make_array_from_process_local_data``, so no host
    ever holds (or reads from disk) more than its shard. Single-process
    degenerates to a device_put of the full batch. The reference has no
    input pipeline at all (it schedules; workloads bring their own) — this
    is the TPU-native equivalent of per-rank dataset sharding in its
    example workloads' TF parameter-server jobs.

    The process layout comes strictly from the live runtime
    (``jax.process_index/process_count``): it must agree with what
    ``make_array_from_process_local_data`` uses to place the rows, so it
    is not overridable."""
    pi = jax.process_index()
    pc = jax.process_count()
    if global_batch % pc != 0:
        raise ValueError(
            f"global_batch={global_batch} not divisible by "
            f"process_count={pc}"
        )
    local = global_batch // pc
    ns = NamedSharding(mesh, sharding.spec_for(("batch", "seq")))
    global_shape = (global_batch, dataset.seq_len + 1)
    for idx in dataset.sample_indices(global_batch, seed, epochs):
        # Slice the shared order FIRST: only this process's rows are ever
        # read from the memmap or held in host memory.
        local_rows = dataset.gather(idx[pi * local:(pi + 1) * local])
        yield jax.make_array_from_process_local_data(
            ns, local_rows, global_shape
        )


def prefetch_to_mesh(
    batches: Iterable[Any],
    mesh: Mesh,
    buffer_size: int = 2,
    put: Optional[Callable[[Any, Mesh], Any]] = None,
) -> Iterator[Any]:
    """Async device transfer: a background thread device_puts up to
    ``buffer_size`` batches ahead onto the mesh (batch/seq sharding by
    default), so the transfer overlaps the previous step's compute."""
    put = put or sharding.shard_batch
    q: "queue.Queue" = queue.Queue(maxsize=buffer_size)
    END = object()
    stop = threading.Event()

    def _enqueue(item) -> bool:
        # Bounded put that notices consumer abandonment: without this, a
        # consumer that breaks out early leaves the worker blocked forever,
        # pinning device-resident batches in HBM.
        while not stop.is_set():
            try:
                q.put(item, timeout=0.5)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for batch in batches:
                if not _enqueue(put(batch, mesh)):
                    return
            _enqueue(END)
        except BaseException as e:  # noqa: BLE001
            # Surface data-source / transfer failures to the consumer —
            # never let a broken pipeline look like a clean end-of-data.
            _enqueue(e)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        # GeneratorExit (early consumer break) or error: release the worker
        # and drop any buffered device batches.
        stop.set()
        while not q.empty():
            try:
                q.get_nowait()
            except queue.Empty:
                break
