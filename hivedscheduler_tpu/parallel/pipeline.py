"""Pipeline parallelism: GPipe microbatching over a ``pp`` mesh axis.

The workload-level half of the reference's pipeline story: at the scheduler
level PP is a heterogeneous-member gang (SURVEY.md §2.2, reference test
`pkg/algorithm/hived_algorithm_test.go:93-95`); here the placed workload
actually splits the layer stack across stages. TPU-first formulation:

  - The stacked layer params ``[L, ...]`` shard their leading dim over
    ``pp`` (logical axis name "layers" in parallel/sharding.DEFAULT_RULES),
    so each stage holds L/P contiguous layers — the memory win that lets a
    model deeper than one slice's HBM train at all.
  - One ``shard_map`` manual over ONLY the pp axis (``axis_names={"pp"}``);
    dp/fsdp/tp stay auto, so the per-stage computation keeps its GSPMD
    shardings and collectives. Sequence parallelism composes by joining the
    manual region (``seq_axis="sp"``): activations enter seq-sharded and the
    block runs ring attention's manual collectives directly (the SP
    backends' own shard_map cannot nest inside an already-manual axis).
  - The schedule is a ``lax.scan`` over M + P - 1 ticks. Each tick: every
    stage ppermutes its activation to the next stage, stage 0 injects the
    next microbatch, every stage applies its local layers (a nested scan).
    Static shapes, no data-dependent control flow — one XLA program.
  - Backward is just ``jax.grad`` through the scan: ppermute transposes to
    the reverse rotation, giving the symmetric reverse schedule. Remat
    composes per-block exactly as in the unpipelined path.

The GPipe bubble is (P-1)/(M+P-1) of each stage's time; raise
``n_microbatches`` to amortize it (at B/M >= 1 per microbatch).

Scope: blocks whose scan body returns (x, None) — the dense transformer.
MoE blocks scale their router statistics (capacity, load-balancing aux)
with the visible batch, so microbatching them changes those semantics;
MoE models parallelize over ``ep`` instead (models/mixtral.py).

Composition: dp/fsdp/tp stay auto alongside pp. Sequence parallelism
composes via ``seq_axis`` — the sp axis joins the manual region and the
blocks dispatch through ``sharding.sp_attention_manual`` (ring ppermute
loop or Ulysses all_to_alls, both manual-friendly); verified fwd+bwd
against the single-device reference for BOTH backends in
tests/test_models.py::test_pp_x_sp_matches_single_device and the dryrun
gate's "pp-x-sp" check.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .sharding import axes_size

BlockFn = Callable[[jax.Array, Any], tuple]


def pipeline_blocks(
    layers: Any,  # pytree of [L, ...] stacked layer params
    x: jax.Array,  # [B, S, D] activations entering the layer stack
    mesh: Mesh,
    block_fn: BlockFn,  # (x, layer) -> (x, _), the lax.scan body
    n_microbatches: Optional[int] = None,
    axis: str = "pp",
    seq_axis: Optional[str] = None,
) -> jax.Array:
    """Apply all L stacked layers to x, pipelined over the ``axis`` stages.

    Drop-in replacement for ``x, _ = lax.scan(block_fn, x, layers)`` when
    the mesh has pp > 1 (falls back to exactly that when pp == 1). The
    result is bitwise the same computation per microbatch; only the
    schedule differs.

    ``seq_axis``: also make that axis manual in the shard_map and keep the
    activations sequence-sharded over it through the pipeline. The caller's
    ``block_fn`` must then be manual-region aware: run attention via the
    SP backends' local collectives (``sharding.sp_attention_manual``) and
    offset positional encodings by ``axis_index(seq_axis)`` — see
    models/transformer._block(sp_manual=True).
    """
    p = axes_size(axis, mesh)
    if p <= 1:
        out, _ = jax.lax.scan(block_fn, x, layers)
        return out

    n_layers = jax.tree.leaves(layers)[0].shape[0]
    if n_layers % p != 0:
        raise ValueError(f"n_layers={n_layers} not divisible by pp={p}")
    b = x.shape[0]
    if n_microbatches is not None:
        m = n_microbatches
        if b % m != 0:
            raise ValueError(f"batch={b} not divisible by n_microbatches={m}")
    else:
        # Largest divisor of b not exceeding 2*p: deepest legal pipeline
        # fill without rejecting awkward batch sizes (worst case m=1, which
        # degenerates to sequential stages but stays correct).
        m = max(d for d in range(1, min(b, 2 * p) + 1) if b % d == 0)

    def stage_apply(stage_layers, h):
        out, _ = jax.lax.scan(block_fn, h, stage_layers)
        return out

    def local(stage_layers, x_full):
        # stage_layers: this stage's [L/P, ...] slice; x_full: the whole
        # [B, S, D] batch (replicated over pp; still sharded over the auto
        # axes). Only stage 0 reads it, only stage P-1's outputs survive.
        s_idx = jax.lax.axis_index(axis)
        mb = x_full.reshape(m, b // m, *x_full.shape[1:])
        fwd = [(i, i + 1) for i in range(p - 1)]

        def tick(state, t):
            recv = jax.lax.ppermute(state, axis, fwd)
            inject = jax.lax.dynamic_index_in_dim(
                mb, jnp.minimum(t, m - 1), keepdims=False
            )
            cur = jnp.where(s_idx == 0, inject, recv)
            out = stage_apply(stage_layers, cur)
            return out, out

        # The carry is varying over pp (each stage holds a different
        # activation); the zeros init must carry that type too (shard_map
        # scan vma typing).
        init = jax.lax.pcast(jnp.zeros_like(mb[0]), (axis,), to="varying")
        _, ys = jax.lax.scan(tick, init, jnp.arange(m + p - 1))
        # Microbatch i exits the last stage at tick i + p - 1; every other
        # stage's ys rows are bubble garbage. Mask + psum broadcasts the
        # last stage's rows to all pp ranks without an all_gather's x P
        # memory spike.
        outs = jnp.where(s_idx == p - 1, ys[p - 1 :], 0)
        outs = jax.lax.psum(outs, axis)
        return outs.reshape(x_full.shape)

    manual_axes = {axis} | ({seq_axis} if seq_axis else set())
    x_spec = P(None, seq_axis) if seq_axis else P()
    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), x_spec),
        out_specs=x_spec,
        axis_names=manual_axes,
    )(layers, x)
