"""Ulysses-style sequence parallelism: all-to-all head/sequence re-shard.

The second sequence-parallel backend next to ring attention (parallel/
ring.py), per the build goal's "ring attention or all-to-all sequence/
context parallelism" — this repo ships both because they win in different
regimes. The reference scheduler has no compute path at all (SURVEY.md
§2.2); its enabler is contiguous-slice placement, which is exactly what
makes these ICI collectives fast.

Mechanics (DeepSpeed-Ulysses / GSPMD all-to-all pattern): Q/K/V arrive
sequence-sharded over ``sp``. One ``all_to_all`` per tensor trades the head
dimension for the sequence dimension — each device ends up holding the FULL
sequence for H/sp of the heads — then attention runs entirely locally, and
one ``all_to_all`` on the output restores sequence sharding. Attention is
embarrassingly parallel over heads, so the local step is exact.

vs ring attention:
  - Ulysses moves Q/K/V/O once each (4 all-to-alls of the *shard*, i.e.
    O(S/p·d) bytes per device per tensor); ring moves K/V p-1 times
    (2·(p-1) ppermutes). For long sequences with enough heads, Ulysses is
    the lower-traffic schedule.
  - The local attention is a single full-sequence call, so the Pallas
    flash kernels (ops/attention.py) apply unchanged — ring's streaming
    inner step cannot use them (it never sees the full sequence).
  - The catch: parallelism is capped by heads — needs sp | H (and sp | Hkv
    for GQA, else K/V heads are replicated first). Ring has no head
    requirement, which is why it stays the fallback (``can_ulysses``).

Memory per device is O(S·H/p·d) — same total as ring, laid out
head-sharded instead of sequence-sharded.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import attention as att
from .sharding import axes_size




def can_ulysses(
    mesh: Mesh,
    n_heads: int,
    n_kv_heads: int,
    seq_len: int,
    seq_axis: str = "sp",
    head_axis: str = "tp",
) -> bool:
    """Whether the all-to-all schedule applies: every device must receive a
    whole number of (tp-local) Q heads, and the sequence must re-assemble
    evenly. K/V heads only need tp-divisibility — ``_ulysses_local``
    expands GQA K/V heads to the Q head count when sp does not divide
    them, which needs the usual GQA condition (Q heads a multiple of KV
    heads) to hold per tp shard."""
    sp = axes_size(seq_axis, mesh)
    tp = axes_size(head_axis, mesh)
    if sp <= 1:
        return False
    if not (
        n_heads % (tp * sp) == 0
        and n_kv_heads % tp == 0
        and seq_len % sp == 0
    ):
        return False
    hq_tp = n_heads // tp
    hkv_tp = n_kv_heads // tp
    return hkv_tp % sp == 0 or hq_tp % hkv_tp == 0


def _ulysses_local(
    q: jax.Array,  # [b, S/sp, H_tp, D] this device's shards
    k: jax.Array,  # [b, S/sp, Hkv_tp, D]
    v: jax.Array,
    axis_name: str,
    causal: bool,
    sm_scale: Optional[float],
    head_shard_factor: int = 1,
    use_pallas: Optional[bool] = None,
) -> jax.Array:
    """Runs under shard_map. all_to_all to full-sequence/sharded-heads,
    local (flash-dispatched) attention, all_to_all back.

    ``head_shard_factor``: number of AUTO shards still dividing the head
    axis. 1 when every mesh axis is manual (ulysses_attention's own
    shard_map). Inside a partially-manual region (the pp x sp pipeline,
    where tp stays auto) the traced head dim is the pre-tp global count,
    so the GQA-repeat decision below must divide it out to see the real
    per-device head count.

    ``use_pallas``: forwarded to the local ``att.mha``. Partial-manual
    callers pass False: a ``pallas_call`` cannot sit on operands GSPMD
    still shards (batch over dp/fsdp, heads over tp) — the XLA reference
    path partitions fine."""
    sp = jax.lax.psum(1, axis_name)
    hq, hkv = q.shape[2], k.shape[2]
    if (hkv // head_shard_factor) % sp != 0:
        # GQA with fewer KV heads than the sp degree: expand K/V to the Q
        # head count first so both all_to_alls split identically and every
        # device's Q-head subset travels with exactly its own GQA group —
        # splitting the raw hkv heads would pair local head j with kv head
        # j instead of j // group. Costs (hq/hkv)x the minimal KV traffic;
        # only the hkv % sp != 0 fallback pays it.
        k = jnp.repeat(k, hq // hkv, axis=2)
        v = jnp.repeat(v, hq // hkv, axis=2)
    # Trade heads for sequence: [b, S/sp, h, D] -> [b, S, h/sp, D].
    k = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    v = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    q = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    # Full sequence locally: the Pallas flash kernels dispatch when on TPU
    # (unless the caller disabled them — see use_pallas above).
    o = att.mha(q, k, v, causal=causal, sm_scale=sm_scale,
                use_pallas=use_pallas)
    # Back to sequence-sharded: [b, S, H_tp/sp, D] -> [b, S/sp, H_tp, D].
    return jax.lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jax.Array,  # [B, S, H, D] globally; S sharded over `sp`
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    batch_axes=("dp", "fsdp"),
    seq_axis: str = "sp",
    head_axis: str = "tp",
) -> jax.Array:
    """Exact attention with the sequence sharded over ``seq_axis``, computed
    by all-to-all head re-sharding. Same signature/spec contract as
    ``ring.ring_attention`` so callers can switch per ``can_ulysses``."""
    if not can_ulysses(
        mesh, q.shape[2], k.shape[2], q.shape[1], seq_axis, head_axis
    ):
        raise ValueError(
            f"ulysses_attention needs sp|heads and sp|seq: heads={q.shape[2]} "
            f"kv_heads={k.shape[2]} seq={q.shape[1]} mesh={dict(mesh.shape)}"
        )
    spec = P(batch_axes, seq_axis, head_axis, None)
    fn = functools.partial(
        _ulysses_local,
        axis_name=seq_axis,
        causal=causal,
        sm_scale=sm_scale,
    )
    return jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)
