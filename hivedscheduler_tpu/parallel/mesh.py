"""Device meshes and jax.distributed bootstrap from the scheduler's env.

This is the workload side of the scheduler's bind-time contract
(``tpu/env.py``): a HiveD-placed gang boots multi-host JAX with
:func:`initialize_from_env`, then lays out computation over a
:func:`make_mesh` mesh. Axes follow the scaling-book recipe: shard over a
named mesh, annotate, and let XLA insert the collectives (psum /
all-gather / reduce-scatter over ICI).

Axis conventions used across models/:

  - ``dp``:   pure data parallelism (batch) — DCN-friendly, outermost.
  - ``fsdp``: data parallelism with sharded params/optimizer (ZeRO-3 style);
              ICI, second-outermost.
  - ``sp``:   sequence/context parallelism (Ulysses all-to-all or ring
              attention; parallel/sharding.sp_attention picks) — ICI.
  - ``tp``:   tensor parallelism (megatron-style) — innermost, ICI-adjacent.
  - ``ep``:   expert parallelism for MoE models (aliases fsdp capacity).
  - ``pp``:   pipeline parallelism (GPipe microbatching over layer stages;
              parallel/pipeline.py) — outermost after dp: stage hops move
              one activation per tick, the lightest traffic, so they can
              ride DCN.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

MESH_AXES = ("dp", "pp", "fsdp", "ep", "sp", "tp")


def initialize_from_env(env: Optional[Dict[str, str]] = None) -> None:
    """Boot ``jax.distributed`` from the env block the scheduler injected at
    bind time (tpu/env.py). No-op for single-process jobs.

    The scheduler guarantees every gang member independently derives the same
    coordinator/rank assignment, so this needs zero external coordination —
    the TPU analog of reading ``NVIDIA_VISIBLE_DEVICES``
    (reference: doc/user-manual.md:159-192).
    """
    e = os.environ if env is None else env
    num = int(e.get("JAX_NUM_PROCESSES", "1"))
    if num <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=e["JAX_COORDINATOR_ADDRESS"],
        num_processes=num,
        process_id=int(e["JAX_PROCESS_ID"]),
    )


@dataclass(frozen=True)
class MeshConfig:
    """Logical parallelism layout. Sizes must multiply to the device count;
    size 1 axes are kept in the mesh (zero-cost) so PartitionSpecs are stable
    across layouts."""

    dp: int = 1
    pp: int = 1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    @property
    def axis_sizes(self) -> Tuple[int, ...]:
        return (self.dp, self.pp, self.fsdp, self.ep, self.sp, self.tp)

    def total(self) -> int:
        return int(np.prod(self.axis_sizes))


def make_mesh(
    config: MeshConfig,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the named device mesh.

    Axis order (dp, pp, fsdp, ep, sp, tp) places tp on the most-adjacent devices
    (fastest-varying => nearest in the ICI torus for TPU slices, since
    jax device order follows the torus), dp on the least — collectives that
    move the most bytes per step ride the shortest links.
    """
    devs = list(devices if devices is not None else jax.devices())
    if config.total() != len(devs):
        raise ValueError(
            f"MeshConfig {config.axis_sizes} needs {config.total()} devices, "
            f"got {len(devs)}"
        )
    dev_array = np.array(devs).reshape(config.axis_sizes)
    return Mesh(dev_array, MESH_AXES)


def single_device_mesh() -> Mesh:
    """A 1-device mesh with the standard axes (for single-chip runs the
    PartitionSpecs degenerate to replication)."""
    return make_mesh(MeshConfig(), devices=jax.devices()[:1])


def infer_mesh_config(
    n_devices: int,
    tp: int = 1,
    sp: int = 1,
    ep: int = 1,
    pp: int = 1,
    fsdp: Optional[int] = None,
) -> MeshConfig:
    """Fill the leftover factor into fsdp (or dp when fsdp is pinned)."""
    inner = tp * sp * ep * pp
    if n_devices % inner != 0:
        raise ValueError(
            f"{n_devices} devices not divisible by tp*sp*ep*pp={inner}"
        )
    rest = n_devices // inner
    if fsdp is None:
        return MeshConfig(dp=1, pp=pp, fsdp=rest, ep=ep, sp=sp, tp=tp)
    if rest % fsdp != 0:
        raise ValueError(f"residual {rest} not divisible by fsdp={fsdp}")
    return MeshConfig(dp=rest // fsdp, pp=pp, fsdp=fsdp, ep=ep, sp=sp, tp=tp)
