"""Logical-axis sharding rules: name every tensor dimension once, map names
to mesh axes in one table, and derive NamedShardings for whole pytrees.

This is the "annotate and let XLA do the rest" half of the scaling-book
recipe: models label their params/activations with logical axis names
(``("embed", "mlp")``), and a rule table decides which mesh axis each name
shards over. Changing the parallelism layout = changing the table, not the
model.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical dim name -> mesh axis (or None = replicate). The default table
# implements: batch over (dp, fsdp), sequence over sp (ring attention),
# megatron-style tp over heads/mlp, fsdp-sharded embed (ZeRO-3), experts
# over ep.
DEFAULT_RULES: Dict[str, Any] = {
    "batch": ("dp", "fsdp"),
    "seq": "sp",
    # Param embed dim shards over fsdp (ZeRO-3); the activation residual
    # stream replicates its feature dim (batch already covers fsdp).
    "embed": "fsdp",
    "act_embed": None,
    "heads": "tp",
    "kv_heads": "tp",
    "head_dim": None,
    "mlp": "tp",
    "vocab": "tp",
    "expert": "ep",
    "layers": None,
}


def spec_for(
    logical_axes: Sequence[Optional[str]],
    rules: Optional[Dict[str, Any]] = None,
) -> P:
    """PartitionSpec for one tensor's logical axis names."""
    table = DEFAULT_RULES if rules is None else rules
    return P(*[table.get(name) if name else None for name in logical_axes])


def tree_shardings(
    mesh: Mesh,
    logical_tree: Any,
    rules: Optional[Dict[str, Any]] = None,
) -> Any:
    """NamedSharding pytree from a pytree of logical-axis tuples (the tree
    structure mirrors the param tree; leaves are tuples of names)."""
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, spec_for(axes, rules)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def constrain(x: jax.Array, *logical_axes: Optional[str], rules=None) -> jax.Array:
    """Sharding constraint by logical names; no-op outside a mesh context."""
    try:
        return jax.lax.with_sharding_constraint(x, spec_for(logical_axes, rules))
    except (ValueError, RuntimeError):
        return x


def shard_batch(batch: Any, mesh: Mesh, rules=None) -> Any:
    """Device-put a host batch with (batch, seq, ...) layout onto the mesh."""
    table = DEFAULT_RULES if rules is None else rules

    def put(x):
        axes: Tuple[Optional[str], ...] = ("batch", "seq")[: x.ndim] + (None,) * max(
            0, x.ndim - 2
        )
        return jax.device_put(x, NamedSharding(mesh, spec_for(axes, table)))

    return jax.tree.map(put, batch)
