"""Logical-axis sharding rules: name every tensor dimension once, map names
to mesh axes in one table, and derive NamedShardings for whole pytrees.

This is the "annotate and let XLA do the rest" half of the scaling-book
recipe: models label their params/activations with logical axis names
(``("embed", "mlp")``), and a rule table decides which mesh axis each name
shards over. Changing the parallelism layout = changing the table, not the
model.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical dim name -> mesh axis (or None = replicate). The default table
# implements: batch over (dp, fsdp), sequence over sp (ring attention),
# megatron-style tp over heads/mlp, fsdp-sharded embed (ZeRO-3), experts
# over ep.
DEFAULT_RULES: Dict[str, Any] = {
    "batch": ("dp", "fsdp"),
    "seq": "sp",
    # Param embed dim shards over fsdp (ZeRO-3); the activation residual
    # stream replicates its feature dim (batch already covers fsdp).
    "embed": "fsdp",
    "act_embed": None,
    "heads": "tp",
    "kv_heads": "tp",
    "head_dim": None,
    "mlp": "tp",
    "vocab": "tp",
    "expert": "ep",
    # Stacked-layer leading dim shards over pp (pipeline stages). All
    # meshes carry a pp axis (size 1 without pipelining — MeshConfig keeps
    # every axis), so this is replication unless pp > 1.
    "layers": "pp",
}

# Sequence-parallel backends accepted by sp_attention and the model
# configs' sp_mode fields (validated eagerly via validate_sp_mode).
SP_MODES = ("auto", "ring", "ulysses")


def validate_sp_mode(sp_mode: str) -> None:
    if sp_mode not in SP_MODES:
        raise ValueError(
            f"unknown sp_mode {sp_mode!r}; one of {'/'.join(SP_MODES)}"
        )


def axes_size(axis, mesh: Optional[Mesh]) -> int:
    """Total device count over a mesh-axis spec (None, a name, or a tuple
    of names — the shapes logical-axis rules produce)."""
    if axis is None or mesh is None:
        return 1
    axes = axis if isinstance(axis, tuple) else (axis,)
    n = 1
    for a in axes:
        n *= mesh.shape.get(a, 1)
    return n


def spec_for(
    logical_axes: Sequence[Optional[str]],
    rules: Optional[Dict[str, Any]] = None,
) -> P:
    """PartitionSpec for one tensor's logical axis names."""
    table = DEFAULT_RULES if rules is None else rules
    return P(*[table.get(name) if name else None for name in logical_axes])


def tree_shardings(
    mesh: Mesh,
    logical_tree: Any,
    rules: Optional[Dict[str, Any]] = None,
) -> Any:
    """NamedSharding pytree from a pytree of logical-axis tuples (the tree
    structure mirrors the param tree; leaves are tuples of names)."""
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, spec_for(axes, rules)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def constrain(x: jax.Array, *logical_axes: Optional[str], rules=None) -> jax.Array:
    """Sharding constraint by logical names; no-op outside a mesh context."""
    try:
        return jax.lax.with_sharding_constraint(x, spec_for(logical_axes, rules))
    except (ValueError, RuntimeError):
        return x


def embed_lookup(
    table: jax.Array,
    tokens: jax.Array,
    mesh: Optional[Mesh],
    rules: Optional[Dict[str, Any]] = None,
) -> jax.Array:
    """Embedding lookup, vocab-parallel when the mesh/shapes allow it.

    shard_map needs every sharded dim evenly divisible by its mesh axes;
    when that doesn't hold (tiny test configs, odd batch sizes), fall back
    to the plain gather, which GSPMD handles (at the cost of the
    involuntary-remat replication this path exists to avoid).
    """
    table_rules = DEFAULT_RULES if rules is None else rules

    def _size(name):
        return axes_size(table_rules.get(name), mesh)

    divisible = (
        mesh is not None
        and table.shape[0] % _size("vocab") == 0
        and table.shape[1] % _size("embed") == 0
        and tokens.shape[0] % _size("batch") == 0
        and tokens.shape[1] % _size("seq") == 0
    )
    if mesh is not None and mesh.size > 1 and divisible:
        return vocab_parallel_embed(table, tokens, mesh, rules)
    return table[tokens]


def vocab_parallel_embed(
    table: jax.Array,  # [V, D], sharded (vocab->tp, embed->fsdp)
    tokens: jax.Array,  # [B, S] int, sharded (batch, seq)
    mesh: Mesh,
    rules: Optional[Dict[str, Any]] = None,
) -> jax.Array:
    """Megatron-style vocab-parallel embedding lookup.

    A plain ``table[tokens]`` on a tp-sharded table makes XLA's SPMD
    partitioner replicate the gathered tensor ("involuntary full
    rematerialization"), because it cannot reshard through a gather. Instead,
    each tp shard gathers only the rows it owns (out-of-range indices masked
    to zero) and a ``psum`` over tp combines them; the embed dim is then
    all-gathered over fsdp. Output is [B, S, D] sharded (batch, seq, -),
    exactly what the first block consumes.
    """
    table_rules = DEFAULT_RULES if rules is None else rules

    def _axes(name):
        ax = table_rules.get(name)
        return ax if isinstance(ax, tuple) or ax is None else (ax,)

    vocab_ax = _axes("vocab")
    embed_ax = _axes("embed")
    batch_ax = _axes("batch")
    seq_ax = _axes("seq")

    def lookup(local_table, local_tokens):
        # Unshard the embed dim FIRST (the usual ZeRO-3 param all-gather).
        # It must not happen after the lookup: batch shards over fsdp too,
        # so post-lookup rows differ across fsdp peers and combining their
        # embed shards would mix different tokens' embeddings.
        if embed_ax:
            local_table = jax.lax.all_gather(
                local_table, embed_ax, axis=-1, tiled=True
            )
        vshard = local_table.shape[0]
        lo = jnp.int32(0)
        for name in vocab_ax or ():
            lo = lo * mesh.shape[name] + jax.lax.axis_index(name)
        lo = lo * vshard
        local = local_tokens - lo
        ok = (local >= 0) & (local < vshard)
        out = local_table[jnp.clip(local, 0, vshard - 1)]
        out = jnp.where(ok[..., None], out, jnp.zeros((), out.dtype))
        if vocab_ax:
            out = jax.lax.psum(out, vocab_ax)
        return out

    return jax.shard_map(
        lookup,
        mesh=mesh,
        in_specs=(P(vocab_ax, embed_ax), P(batch_ax, seq_ax)),
        out_specs=P(batch_ax, seq_ax, None),
    )(table, tokens)


def sharded_mha(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,
    mesh: Optional[Mesh],
    causal: bool = True,
    rules: Optional[Dict[str, Any]] = None,
) -> jax.Array:
    """Attention through the TPU flash-kernel dispatcher, shard_map-wrapped
    when a multi-device mesh is active.

    GSPMD cannot partition a ``pallas_call``; attention is embarrassingly
    parallel over batch and heads, so an explicit shard_map over
    (batch, heads) makes the kernel run per-shard. Requires batch/heads
    divisible by their mesh axes and tp | kv_heads (so each shard keeps
    whole GQA groups); otherwise falls back to the XLA reference path,
    which GSPMD partitions itself.
    """
    from ..ops import attention as att

    table = DEFAULT_RULES if rules is None else rules

    def _size(name):
        return axes_size(table.get(name), mesh)

    if mesh is None or mesh.size == 1:
        return att.mha(q, k, v, causal=causal)

    divisible = (
        q.shape[0] % _size("batch") == 0
        and q.shape[2] % _size("heads") == 0
        and k.shape[2] % _size("kv_heads") == 0
        and _size("heads") == _size("kv_heads")
        and _size("seq") == 1  # sp>1 goes through ring attention instead
    )
    if not divisible:
        return att.mha_reference(q, k, v, causal=causal)

    spec_q = spec_for(("batch", None, "heads", None), table)
    spec_kv = spec_for(("batch", None, "kv_heads", None), table)
    return jax.shard_map(
        lambda a, b, c: att.mha(a, b, c, causal=causal),
        mesh=mesh,
        in_specs=(spec_q, spec_kv, spec_kv),
        out_specs=spec_q,
    )(q, k, v)


def shard_batch(batch: Any, mesh: Mesh, rules=None) -> Any:
    """Device-put a host batch with (batch, seq, ...) layout onto the mesh."""
    table = DEFAULT_RULES if rules is None else rules

    def put(x):
        axes: Tuple[Optional[str], ...] = ("batch", "seq")[: x.ndim] + (None,) * max(
            0, x.ndim - 2
        )
        return jax.device_put(x, NamedSharding(mesh, spec_for(axes, table)))

    return jax.tree.map(put, batch)


def sp_attention(
    q: jax.Array,  # [B, S, H, D] globally; S sharded over `sp`
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    sp_mode: str = "auto",
) -> jax.Array:
    """Sequence-parallel attention dispatcher — the single place that picks
    between the two SP backends, shared by every model:

      - "ulysses" (parallel/ulysses.py): all-to-all head/sequence re-shard;
        lower traffic and the local full-sequence call uses the Pallas
        flash kernels. Requires the head counts to divide the mesh.
      - "ring" (parallel/ring.py): K/V rotation with a streaming softmax;
        no head requirement, and local memory is O(chunk) by construction.

    "auto" picks Ulysses only when it is both legal (``can_ulysses``) AND
    its local attention would run the flash kernels — without the kernels
    the local step falls back to the O(S^2)-memory XLA reference, while
    ring keeps its score tile bounded, so ring is the safer default there
    (e.g. HIVED_DISABLE_PALLAS=1, non-TPU backends, gate-rejected shapes).
    An explicit sp_mode overrides that heuristic either way.
    """
    from ..ops import attention as att
    from . import ring, ulysses

    validate_sp_mode(sp_mode)
    h, hkv, s = q.shape[2], k.shape[2], q.shape[1]
    legal = _ulysses_legal_or_raise(mesh, h, hkv, s, sp_mode)
    use_ulysses = sp_mode == "ulysses" or (
        sp_mode == "auto"
        and legal
        and att.pallas_wanted()
        and att.pallas_shape_ok(s, s)
    )
    if use_ulysses:
        return ulysses.ulysses_attention(
            q, k, v, mesh, causal=causal, sm_scale=sm_scale
        )
    return ring.ring_attention(q, k, v, mesh, causal=causal, sm_scale=sm_scale)


def _ulysses_legal_or_raise(
    mesh: Mesh, h: int, hkv: int, s_global: int, sp_mode: str
) -> bool:
    """Shared legality gate of both sp_attention dispatchers: an explicit
    sp_mode='ulysses' on an incompatible mesh is a user error."""
    from . import ulysses

    legal = ulysses.can_ulysses(mesh, h, hkv, s_global)
    if sp_mode == "ulysses" and not legal:
        raise ValueError(
            f"sp_mode='ulysses' but heads/seq do not divide the mesh: "
            f"heads={h} kv_heads={hkv} seq={s_global} "
            f"mesh={dict(mesh.shape)}"
        )
    return legal


def sp_attention_manual(
    q: jax.Array,  # [B, S/sp, H, D]: the LOCAL seq shard; heads still auto
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    sp_mode: str = "auto",
) -> jax.Array:
    """``sp_attention``'s twin for callers ALREADY inside a shard_map that
    is manual over the sp axis (the pp x sp pipeline,
    parallel/pipeline.py seq_axis): dispatches straight to the backends'
    local bodies — the ring ppermute loop or the Ulysses all_to_alls —
    since nesting another shard_map over sp would be illegal.

    The backend heuristic deliberately differs from ``sp_attention``:
    batch (dp/fsdp) and heads (tp) stay GSPMD-auto inside the region, and
    a ``pallas_call`` cannot sit on auto-sharded operands, so Ulysses's
    usual advantage (flash kernels on the local full sequence) is void
    here — "auto" therefore always picks ring (whose streaming XLA ops
    partition fine and keep O(chunk) memory). An explicit
    sp_mode='ulysses' still runs, with the XLA-reference local attention
    (exact, partitionable, O(S^2) score memory)."""
    from . import ring, ulysses

    validate_sp_mode(sp_mode)
    sp = axes_size("sp", mesh)
    h, hkv = q.shape[2], k.shape[2]
    s_global = q.shape[1] * sp  # q holds the local shard here
    _ulysses_legal_or_raise(mesh, h, hkv, s_global, sp_mode)
    if sp_mode == "ulysses":
        return ulysses._ulysses_local(
            q, k, v, "sp", causal, sm_scale,
            head_shard_factor=axes_size("tp", mesh),
            use_pallas=False,
        )
    return ring._ring_attention_local(q, k, v, "sp", causal, sm_scale)
