"""Ring attention: exact attention over sequence shards with ppermute.

Long-context sequence/context parallelism (first-class per the build goal;
the reference's enabler is merely large contiguous slice allocation,
SURVEY.md §2.2). Each ``sp`` device holds a contiguous sequence shard of
Q/K/V; K/V blocks rotate around the ring via ``lax.ppermute`` while every
device maintains a streaming-softmax accumulator — flash attention at
inter-chip granularity, overlapping the ICI transfer of the next block with
the matmuls of the current one (XLA pipelines the ppermute).

Memory per device is O(S/p · d) instead of O(S · d); the S×S score matrix
never exists anywhere.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.attention import NEG_INF


# Per-(batch, head) score-matrix budget for one local update: chunk the
# query dim so a ring step's scores stay <= ~4M f32 elements per head,
# keeping local memory O(cq * sk) however long the shard is.
_SCORE_BUDGET = 4 * 1024 * 1024


def _q_chunk_size(sq: int, sk: int, q_chunk: Optional[int]) -> int:
    if q_chunk is not None and q_chunk <= 0:
        raise ValueError(f"q_chunk must be positive, got {q_chunk}")
    if q_chunk is not None and sq % q_chunk == 0:
        return q_chunk
    if q_chunk is None and sq * sk <= _SCORE_BUDGET:
        return sq
    # Auto-size (or repair a non-divisor request): largest divisor of sq
    # not exceeding the target — never silently fall back to unchunked.
    target = (
        q_chunk if q_chunk is not None else max(1, _SCORE_BUDGET // sk)
    )
    best = 1
    c = 1
    while c * c <= sq:
        if sq % c == 0:
            if c <= target:
                best = max(best, c)
            if sq // c <= target:
                best = max(best, sq // c)
        c += 1
    return best


def _ring_attention_local(
    q: jax.Array,  # [B, Sq, H, D] this device's query shard
    k: jax.Array,  # [B, Sk, Hkv, D] this device's key shard (rotates)
    v: jax.Array,
    axis_name: str,
    causal: bool,
    sm_scale: Optional[float],
    q_chunk: Optional[int] = None,
) -> jax.Array:
    """Runs under shard_map; exact attention over the full sequence.

    The local update is q-chunked (``_q_chunk_size``): one ring step's
    score tile is [B, H, cq, Sk] instead of [B, H, Sq, Sk], so local
    memory stays bounded however long the per-device shard grows — the
    host-level analog of the flash kernels' O(block) VMEM."""
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, sq, hq, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    if hq != hkv:
        k = jnp.repeat(k, hq // hkv, axis=2)
        v = jnp.repeat(v, hq // hkv, axis=2)
    scale = 1.0 / math.sqrt(d) if sm_scale is None else sm_scale
    cq = _q_chunk_size(sq, sk, q_chunk)
    nc = sq // cq

    # Chunk-shaped layout throughout the ring loop (one relayout before, one
    # after, instead of slice/stitch per step): q/o are [nc, B, cq, H, D],
    # m/l are [nc, B, H, cq].
    q32 = (
        q.astype(jnp.float32)
        .reshape(b, nc, cq, hq, d)
        .transpose(1, 0, 2, 3, 4)
    )
    # Derive the accumulators from q so they carry the same varying-manual
    # axes type as the loop outputs (required by shard_map's scan typing;
    # the *0 folds away after fusion).
    zero_ml = jnp.sum(q32, axis=4).transpose(0, 1, 3, 2) * 0.0  # [nc,B,H,cq]
    m0 = zero_ml + NEG_INF
    l0 = zero_ml
    o0 = q32 * 0.0
    # Absolute position of each chunk's first query row.
    pos0 = my_idx * sq + jnp.arange(nc, dtype=jnp.int32) * cq  # [nc]

    def update(qc, oc, mc, lc, k_blk, v_blk, q_pos0, k_idx):
        """Streaming-softmax update of one q chunk against one K/V block.
        qc/oc: [B, cq, H, D]; mc/lc: [B, H, cq]."""
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", qc, k_blk.astype(jnp.float32)
        ) * scale  # [B, H, cq, sk]
        if causal:
            q_pos = q_pos0 + jax.lax.broadcasted_iota(jnp.int32, (cq, sk), 0)
            k_pos = k_idx * sk + jax.lax.broadcasted_iota(
                jnp.int32, (cq, sk), 1
            )
            s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
        m_cur = jnp.maximum(mc, jnp.max(s, axis=-1))
        # Guard fully-masked rows (future-only blocks): exp(NEG_INF-NEG_INF)
        # must not become 1.
        safe_m = jnp.where(m_cur <= NEG_INF / 2, 0.0, m_cur)
        p_blk = jnp.exp(
            jnp.where(s <= NEG_INF / 2, NEG_INF, s) - safe_m[..., None]
        )
        alpha = jnp.where(
            mc <= NEG_INF / 2, jnp.zeros_like(mc), jnp.exp(mc - safe_m)
        )
        l_cur = lc * alpha + jnp.sum(p_blk, axis=-1)
        o_cur = oc * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p_blk, v_blk.astype(jnp.float32)
        )
        return o_cur, m_cur, l_cur

    # Remat the chunk update: without it, differentiating the chunk map
    # stores every chunk's p_blk ([B, H, cq, sk] each) and the backward
    # pass reconstitutes the full O(Sq*Sk) score matrix the chunking
    # exists to avoid. Recomputing s/p per chunk keeps the bound under AD.
    update_ck = jax.checkpoint(update)

    def step(i, carry):
        o, m, l, k_blk, v_blk = carry
        # Shard i steps behind on the ring: block j = (my_idx - i) mod p.
        k_idx = jax.lax.rem(my_idx - i + axis_size, axis_size)

        def chunk(args):
            qc, oc, mc, lc, p0 = args
            return update_ck(qc, oc, mc, lc, k_blk, v_blk, p0, k_idx)

        o, m, l = jax.lax.map(chunk, (q32, o, m, l, pos0))
        # Rotate K/V to the next device; the transfer overlaps the next
        # step's compute.
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        return o, m, l, k_nxt, v_nxt

    o, m, l, _, _ = jax.lax.fori_loop(0, axis_size, step, (o0, m0, l0, k, v))
    l = jnp.maximum(l, 1e-20)
    # Back to [B, Sq, H, D] / [B, H, Sq] once, after the loop.
    o = o.transpose(1, 0, 2, 3, 4).reshape(b, sq, hq, d)
    l = l.transpose(1, 2, 0, 3).reshape(b, hq, sq)
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def ring_attention(
    q: jax.Array,  # [B, S, H, D] globally; S sharded over `sp`
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    batch_axes=("dp", "fsdp"),
    seq_axis: str = "sp",
    head_axis: str = "tp",
    q_chunk: Optional[int] = None,
) -> jax.Array:
    """Exact attention with the sequence dimension sharded over ``seq_axis``.

    Composable under jit: shard_map with explicit ppermute inside, XLA
    collectives outside. Heads additionally shard over tp; batch over
    dp/fsdp. ``q_chunk`` bounds the local score tile (auto-sized from the
    shard length by default; see ``_q_chunk_size``).
    """
    spec = P(batch_axes, seq_axis, head_axis, None)
    fn = functools.partial(
        _ring_attention_local,
        axis_name=seq_axis,
        causal=causal,
        sm_scale=sm_scale,
        q_chunk=q_chunk,
    )
    return jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)
