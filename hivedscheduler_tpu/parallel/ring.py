"""Ring attention: exact attention over sequence shards with ppermute.

Long-context sequence/context parallelism (first-class per the build goal;
the reference's enabler is merely large contiguous slice allocation,
SURVEY.md §2.2). Each ``sp`` device holds a contiguous sequence shard of
Q/K/V; K/V blocks rotate around the ring via ``lax.ppermute`` while every
device maintains a streaming-softmax accumulator — flash attention at
inter-chip granularity, overlapping the ICI transfer of the next block with
the matmuls of the current one (XLA pipelines the ppermute).

Memory per device is O(S/p · d) instead of O(S · d); the S×S score matrix
never exists anywhere.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.attention import NEG_INF


def _ring_attention_local(
    q: jax.Array,  # [B, Sq, H, D] this device's query shard
    k: jax.Array,  # [B, Sk, Hkv, D] this device's key shard (rotates)
    v: jax.Array,
    axis_name: str,
    causal: bool,
    sm_scale: Optional[float],
) -> jax.Array:
    """Runs under shard_map; exact attention over the full sequence."""
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, sq, hq, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    if hq != hkv:
        k = jnp.repeat(k, hq // hkv, axis=2)
        v = jnp.repeat(v, hq // hkv, axis=2)
    scale = 1.0 / math.sqrt(d) if sm_scale is None else sm_scale

    q32 = q.astype(jnp.float32)
    # Derive the accumulators from q so they carry the same varying-manual
    # axes type as the loop outputs (required by shard_map's scan typing;
    # the *0 folds away after fusion).
    zero_bhq = jnp.sum(q32, axis=3).transpose(0, 2, 1) * 0.0  # [B, H, Sq]
    m0 = zero_bhq + NEG_INF
    l0 = zero_bhq
    o0 = q32 * 0.0

    def step(i, carry):
        o, m, l, k_blk, v_blk = carry
        # Shard i steps behind on the ring: block j = (my_idx - i) mod p.
        k_idx = jax.lax.rem(my_idx - i + axis_size, axis_size)
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q32, k_blk.astype(jnp.float32)
        ) * scale
        if causal:
            q_pos = my_idx * sq + jax.lax.broadcasted_iota(
                jnp.int32, (sq, sk), 0
            )
            k_pos = k_idx * sk + jax.lax.broadcasted_iota(
                jnp.int32, (sq, sk), 1
            )
            s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
        m_cur = jnp.maximum(m, jnp.max(s, axis=-1))
        # Guard fully-masked rows (future-only blocks): exp(NEG_INF-NEG_INF)
        # must not become 1.
        safe_m = jnp.where(m_cur <= NEG_INF / 2, 0.0, m_cur)
        p_blk = jnp.exp(jnp.where(s <= NEG_INF / 2, NEG_INF, s) - safe_m[..., None])
        alpha = jnp.where(
            m <= NEG_INF / 2, jnp.zeros_like(m), jnp.exp(m - safe_m)
        )
        l_cur = l * alpha + jnp.sum(p_blk, axis=-1)
        o_cur = o * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p_blk, v_blk.astype(jnp.float32)
        )
        # Rotate K/V to the next device; the transfer overlaps the next
        # step's compute.
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        return o_cur, m_cur, l_cur, k_nxt, v_nxt

    o, m, l, _, _ = jax.lax.fori_loop(0, axis_size, step, (o0, m0, l0, k, v))
    l = jnp.maximum(l, 1e-20)
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def ring_attention(
    q: jax.Array,  # [B, S, H, D] globally; S sharded over `sp`
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    batch_axes=("dp", "fsdp"),
    seq_axis: str = "sp",
    head_axis: str = "tp",
) -> jax.Array:
    """Exact attention with the sequence dimension sharded over ``seq_axis``.

    Composable under jit: shard_map with explicit ppermute inside, XLA
    collectives outside. Heads additionally shard over tp; batch over
    dp/fsdp.
    """
    spec = P(batch_axes, seq_axis, head_axis, None)
    fn = functools.partial(
        _ring_attention_local,
        axis_name=seq_axis,
        causal=causal,
        sm_scale=sm_scale,
    )
    return jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)
