"""CLI entry point: ``python -m hivedscheduler_tpu [--config path]``.

Production equivalent of the reference's ``cmd/hivedscheduler/main.go``:
init logging, load config, recover from the cluster (or start empty in
--standalone mode), serve the extender + inspect API, and exit(1) when the
config file changes so the supervisor restarts us into the work-preserving
recovery path (reference: api/config.go:202-217 WatchConfig).
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
import time

from . import common
from .api.config import config_fingerprint, load_config
from .scheduler.framework import HivedScheduler
from .scheduler.types import Node
from .webserver.server import WebServer

CONFIG_POLL_SECONDS = 2.0


def validate_config(path: str) -> int:
    """Compile the config exactly as startup would (YAML -> Config ->
    HivedAlgorithm cell trees, including the VC-quota-fits-capacity
    checks) and report. Exit 0 on a valid config, 1 with the rejection
    reason otherwise — usable as a pre-deploy lint."""
    try:
        config = load_config(path)
        scheduler = HivedScheduler(config)
    except Exception as exc:  # noqa: BLE001 — any rejection is the answer
        print(f"INVALID: {type(exc).__name__}: {exc}")
        return 1
    chains = scheduler.core.full_cell_list
    n_nodes = len(scheduler.core.configured_node_names())
    print(
        f"OK: {len(chains)} chains, {n_nodes} nodes, "
        f"{len(config.virtual_clusters)} VCs "
        f"({', '.join(sorted(config.virtual_clusters))})"
    )
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="hivedscheduler-tpu")
    parser.add_argument(
        "--config",
        default=os.environ.get("CONFIG", "./hivedscheduler.yaml"),
        help="scheduler config YAML (default: $CONFIG or ./hivedscheduler.yaml)",
    )
    parser.add_argument(
        "--standalone",
        action="store_true",
        help="no kube apiserver: mark all configured nodes healthy and serve "
        "(for simulation/e2e harnesses)",
    )
    parser.add_argument(
        "--ha",
        action="store_true",
        default=os.environ.get("HIVED_HA", "") == "1",
        help="active-standby mode: hold off on a coordination.k8s.io Lease, "
        "recover and serve only while leading; /readyz is 503 on the "
        "standby (doc/fault-model.md 'HA and snapshot recovery plane')",
    )
    parser.add_argument(
        "--validate-config",
        action="store_true",
        help="compile the config (cell chains, physical cells, VC quotas "
        "vs capacity) and exit: 0 = valid, 1 = rejected — a pre-deploy "
        "lint for CI",
    )
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)

    if args.validate_config:
        # Lint mode: constructor chatter (init marks every node bad until
        # informed, so doomed-binding warnings always fire) would drown
        # the verdict line.
        common.init_logging(
            logging.DEBUG if args.verbose else logging.ERROR
        )
        return validate_config(args.config)
    common.init_logging(logging.DEBUG if args.verbose else logging.INFO)
    config = load_config(args.config)
    # Multi-process scheduling core (doc/hot-path.md "The multi-process
    # contract"): HIVED_PROC_SHARDS=N (or the procShards config knob)
    # shards the core by chain family into N worker processes behind this
    # webserver; 0 — the default — serves the in-process sharded
    # scheduler exactly as before.
    procs = int(
        os.environ.get("HIVED_PROC_SHARDS", "") or config.proc_shards or 0
    )
    # Standalone has no informer, so filter-time auto-admission stands in
    # for pod events.
    if procs > 0:
        from .scheduler.shards import ShardedScheduler

        scheduler = ShardedScheduler(
            config, n_shards=procs, auto_admit=args.standalone
        )
        common.log.info(
            "multi-process core: %d shard worker(s), chain plan %s",
            len(scheduler.shards),
            {b.shard_id: list(b.owned_chains) for b in scheduler.shards},
        )
        # Shard supervision plane (doc/fault-model.md): heartbeat
        # liveness checks + hot resurrection of crashed/hung workers.
        scheduler.supervisor.start()
    else:
        scheduler = HivedScheduler(config, auto_admit=args.standalone)

    if args.standalone:
        # The constructor already defaulted kube_client to a NullKubeClient.
        for name in scheduler.configured_node_names() if procs > 0 else (
            scheduler.core.configured_node_names()
        ):
            scheduler.add_node(Node(name=name))
    else:
        from .scheduler.kube import (
            InformerLoop,
            KubeAPIClient,
            RetryingKubeClient,
        )

        apiserver = config.kube_apiserver_address or os.environ.get(
            "KUBE_APISERVER_ADDRESS", "https://kubernetes.default.svc"
        )
        client = KubeAPIClient(apiserver)
        # Durable-state plane v2: an optional object-store backend for the
        # snapshot envelope (snapshotStoreBackend: file). None keeps the
        # ConfigMap chunk family default.
        from .scheduler.scrub import SnapshotScrubber
        from .scheduler.store import make_snapshot_store

        snapshot_store = make_snapshot_store(config)
        if snapshot_store is not None:
            common.log.info(
                "snapshot store backend: %s (GC keeps last %d generations)",
                snapshot_store.name, config.snapshot_store_gc_generations,
            )
        # Write path goes through the fault absorber: transient apiserver
        # errors are retried with backoff; terminal 404/409 failures release
        # the assume-bind allocation (doc/fault-model.md).
        scheduler.kube_client = RetryingKubeClient(
            client, scheduler=scheduler, snapshot_store=snapshot_store
        )
        # Continuous integrity scrubber: rides the flusher beats on the
        # leader and the standby beats on a hot standby;
        # HIVED_SNAPSHOT_SCRUB=0 is the emergency hatch. Single-process
        # only — the sharded frontend's per-shard partition slots carry
        # their own per-slot checksums (scheduler.shards).
        if isinstance(scheduler, HivedScheduler):
            scheduler.scrubber = SnapshotScrubber(
                scheduler,
                interval_beats=config.snapshot_scrub_interval_beats,
            )
        informer = InformerLoop(scheduler, client)
        if args.ha:
            from .scheduler.ha import LeaderElector, StandbyLoop

            # Epoch-seconds clock: the Lease's acquire/renew MicroTimes
            # must be comparable across processes, so wall clock — not
            # monotonic (kube.KubeAPIClient translates to/from MicroTime).
            elector = LeaderElector(
                scheduler.kube_client,
                identity=os.environ.get("HOSTNAME") or f"hived-{os.getpid()}",
                duration_s=config.lease_duration_seconds,
                renew_s=config.lease_renew_seconds,
                clock=time.time,
            )
            scheduler.leadership = elector

            def on_started_leading() -> None:
                # Recovery (snapshot + delta replay via the informer's
                # initial relist) runs at the moment of acquisition;
                # /readyz flips 200 only after it completes AND we lead.
                informer.start()
                scheduler.start_snapshot_flusher()

            def on_stopped_leading() -> None:
                # Deposed: the framework already fences bind writes; exit
                # so the supervisor restarts us into a clean standby
                # (half-recovered state must not linger).
                common.log.error(
                    "leadership lost; exiting for restart into standby"
                )
                os._exit(1)

            def on_standby_beat() -> None:
                # Hot standby: decode AND restore the latest snapshot into
                # this process's core on every idle beat, so takeover skips
                # both the JSON decode and the projection restore — the
                # failover blackout is just the delta replay.
                scheduler.prefetch_snapshot(apply=True)
                # Anti-entropy: fingerprint the pre-applied projection
                # against the durable envelope every few beats; rot is
                # discarded and re-prefetched (scheduler.scrub).
                scrub = getattr(scheduler, "scrubber", None)
                if scrub is not None:
                    scrub.tick()

            StandbyLoop(
                elector,
                on_started_leading,
                on_stopped_leading,
                on_standby_beat=on_standby_beat,
            ).start()
        else:
            # Recovery completes before we accept scheduling requests
            # (reference: scheduler.go:200-212); /readyz turns 200 when the
            # informer's initial replay is done.
            informer.start()
            scheduler.start_snapshot_flusher()

    server = WebServer(scheduler)
    server.start()

    # Restart-based reconfiguration: exit on config change; the supervisor
    # (K8s) restarts us and recovery replays allocated pods against the new
    # config (reference semantics: api/config.go:202-217).
    fingerprint = config_fingerprint(args.config)
    try:
        while True:
            time.sleep(CONFIG_POLL_SECONDS)
            try:
                current = config_fingerprint(args.config)
            except OSError:
                continue
            if current != fingerprint:
                common.log.warning(
                    "Config file %s changed; exiting for work-preserving "
                    "restart", args.config,
                )
                return 1
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
