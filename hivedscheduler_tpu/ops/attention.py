"""Attention ops: XLA reference path + a Pallas flash-attention TPU kernel.

The reference repo has no compute ops at all (it is a scheduler;
SURVEY.md §2.2) — these ops exist for the BASELINE workloads the scheduler
places (ResNet/BERT/Llama/Mixtral). Design per the TPU playbook:

  - The training path uses the XLA implementation: scores/softmax/PV all fuse
    onto MXU+VPU, XLA derives the backward pass, and bf16 keeps the MXU fed.
  - The Pallas kernel is the forward flash attention (streaming softmax, no
    S×S materialization in HBM) for long-context inference where the S×S
    intermediate would blow HBM; it falls back to XLA off-TPU.

GQA is supported by repeating KV heads; head_dim should be a multiple of 128
on TPU for lane alignment (pallas_guide.md tiling constraints).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def mha_reference(
    q: jax.Array,  # [B, Sq, Hq, D]
    k: jax.Array,  # [B, Sk, Hkv, D]
    v: jax.Array,  # [B, Sk, Hkv, D]
    causal: bool = True,
    sm_scale: Optional[float] = None,
    q_offset: int = 0,
    kv_offset: int = 0,
) -> jax.Array:
    """Plain-XLA multi-head attention with f32 softmax accumulation.

    ``q_offset``/``kv_offset`` are the absolute sequence positions of the
    first query/key — that is what makes this same function the per-block
    inner step of ring attention (parallel/ring.py), where each device holds
    a rotating sequence shard.
    """
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    if hq != hkv:
        assert hq % hkv == 0, (hq, hkv)
        k = jnp.repeat(k, hq // hkv, axis=2)
        v = jnp.repeat(v, hq // hkv, axis=2)
    scale = 1.0 / math.sqrt(d) if sm_scale is None else sm_scale
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        q_pos = q_offset + jnp.arange(sq)[:, None]
        k_pos = kv_offset + jnp.arange(sk)[None, :]
        scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, sm_scale, causal,
                      q_block, seq_len):
    """One (batch*head, q-block) program: stream K/V blocks through VMEM with
    an online softmax (m, l running stats), never materializing S×S."""
    import jax.experimental.pallas as pl

    q_idx = pl.program_id(1)
    q = q_ref[...]  # [block_q, d]
    block_q = q.shape[0]
    d = q.shape[-1]

    m = jnp.full((block_q,), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((block_q,), dtype=jnp.float32)
    acc = jnp.zeros((block_q, d), dtype=jnp.float32)

    q_pos = q_idx * q_block + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(start_k, carry):
        m_prev, l_prev, acc_prev = carry
        k_blk = pl.load(k_ref, (pl.dslice(start_k * block_k, block_k), slice(None)))
        v_blk = pl.load(v_ref, (pl.dslice(start_k * block_k, block_k), slice(None)))
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # [block_q, block_k]
        if causal:
            k_pos = start_k * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_cur[:, None])
        alpha = jnp.exp(m_prev - m_cur)
        l_cur = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_cur = acc_prev * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_cur, l_cur, acc_cur

    num_k_blocks = seq_len // block_k
    if causal:
        # Only blocks at or before this q block contribute.
        upper = jax.lax.div(
            (q_idx + 1) * q_block + block_k - 1, jnp.int32(block_k)
        )
        upper = jnp.minimum(upper, num_k_blocks)
    else:
        upper = num_k_blocks
    m, l, acc = jax.lax.fori_loop(0, upper, body, (m, l, acc))
    o_ref[...] = (acc / l[:, None]).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "sm_scale", "block_q", "block_k")
)
def flash_attention_tpu(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = 256,
    block_k: int = 256,
) -> jax.Array:
    """Pallas flash-attention forward. Requires S % block == 0 and TPU."""
    import jax.experimental.pallas as pl

    b, s, h, d = q.shape
    hkv = k.shape[2]
    if hkv != h:
        k = jnp.repeat(k, h // hkv, axis=2)
        v = jnp.repeat(v, h // hkv, axis=2)
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    assert s % block_q == 0 and s % block_k == 0, (s, block_q, block_k)
    scale = 1.0 / math.sqrt(d) if sm_scale is None else sm_scale

    # [B, S, H, D] -> [B*H, S, D] so the grid is (batch*head, q-block).
    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    qt, kt, vt = to_bh(q), to_bh(k), to_bh(v)
    kernel = functools.partial(
        _flash_fwd_kernel,
        block_k=block_k,
        sm_scale=scale,
        causal=causal,
        q_block=block_q,
        seq_len=s,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, s // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, s, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
    )(qt, kt, vt)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def mha(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    use_pallas: Optional[bool] = None,
) -> jax.Array:
    """Dispatch: Pallas flash forward on TPU (inference-shaped calls), XLA
    reference elsewhere and for training (XLA autodiffs + fuses it)."""
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    s = q.shape[1]
    if use_pallas and s >= 256 and s % 256 == 0 and s == k.shape[1]:
        return flash_attention_tpu(q, k, v, causal=causal, sm_scale=sm_scale)
    return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)
