"""Attention ops: XLA reference path + Pallas flash-attention TPU kernels
(forward AND backward, wired through custom_vjp).

The reference repo has no compute ops at all (it is a scheduler;
SURVEY.md §2.2) — these ops exist for the BASELINE workloads the scheduler
places. Design per the TPU playbook:

  - Flash forward: streaming softmax over K/V blocks in VMEM; the S×S score
    matrix never exists in HBM. Saves the per-row logsumexp for backward.
  - Flash backward: two kernels — dK/dV per key-block (sweeping query
    blocks) and dQ per query-block (sweeping key blocks) — recomputing P
    from Q,K and the saved LSE instead of storing it (remat: FLOPs for HBM,
    the usual TPU trade).
  - Off-TPU (and for short sequences) everything falls back to the XLA
    implementation, which fuses fine and autodiffs itself.

GQA is supported by repeating KV heads; head_dim should be a multiple of
128 on TPU for lane alignment (pallas_guide.md tiling constraints).
Set ``attention.INTERPRET = True`` to run the kernels in interpreter mode
(hermetic CPU tests do this).
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

NEG_INF = -1e30

# Default Pallas block sizes, env-tunable for on-chip sweeps
# (hack/mfu_sweep.py) without code edits; the shape gate below adapts to
# whatever is configured. 512x1024 is the measured optimum on v5e at the
# bench shape (seq 8192, head_dim 128): MFU 0.541 vs 0.329 at 256x256 in
# the same sweep session (remat=flash both); 1024x1024 collapses (VMEM),
# 2048-wide K is flat — see doc/perf.md.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024
BLOCK_Q = int(os.environ.get("HIVED_FLASH_BLOCK_Q", str(DEFAULT_BLOCK_Q)))
BLOCK_K = int(os.environ.get("HIVED_FLASH_BLOCK_K", str(DEFAULT_BLOCK_K)))
# The backward kernels are tunable separately, but the shipped defaults
# stay uniform with the forward: an isolated fwd+bwd microbench preferred
# square 512x512 backward tiles, yet the full train step measured best
# with 512x1024 everywhere (0.541 MFU vs 0.523 at bwd 512x512) — block
# choices interact with the surrounding step (fusion, scheduling, HBM
# pressure), so the full train step, not an isolated microbench, is the
# ground truth for defaults.
DEFAULT_BLOCK_Q_BWD = DEFAULT_BLOCK_Q
DEFAULT_BLOCK_K_BWD = DEFAULT_BLOCK_K
BLOCK_Q_BWD = int(
    os.environ.get("HIVED_FLASH_BLOCK_Q_BWD", str(DEFAULT_BLOCK_Q_BWD))
)
BLOCK_K_BWD = int(
    os.environ.get("HIVED_FLASH_BLOCK_K_BWD", str(DEFAULT_BLOCK_K_BWD))
)


def block_limits() -> Tuple[int, int, int, int]:
    """Effective (block_q, block_k, block_q_bwd, block_k_bwd) limits,
    resolved at *dispatch* time: a ``HIVED_FLASH_BLOCK_*`` env var set now
    wins over the value captured at import, so env overrides behave the
    same in-process as across processes. The module attributes remain the
    fallback so tests/harnesses may still monkeypatch them directly."""
    def _resolve(env_key: str, attr_value: int) -> int:
        raw = os.environ.get(env_key)
        return int(raw) if raw is not None else attr_value

    return (
        _resolve("HIVED_FLASH_BLOCK_Q", BLOCK_Q),
        _resolve("HIVED_FLASH_BLOCK_K", BLOCK_K),
        _resolve("HIVED_FLASH_BLOCK_Q_BWD", BLOCK_Q_BWD),
        _resolve("HIVED_FLASH_BLOCK_K_BWD", BLOCK_K_BWD),
    )

# Interpreter mode for pallas kernels (CPU tests); real TPU runs leave False.
INTERPRET = False

# Degradation switch: force the XLA path even on TPU (see ``mha``).
DISABLE_PALLAS = False

# Mosaic requires the last two dims of every block to respect the (8, 128)
# tile. Per-row scalars (logsumexp, delta) therefore cannot be rank-1 blocks:
# they are stored broadcast across a 128-wide lane dimension, the same layout
# jax.experimental.pallas.ops.tpu.flash_attention uses (MIN_BLOCK_SIZE).
LANE = 128


def mha_reference(
    q: jax.Array,  # [B, Sq, Hq, D]
    k: jax.Array,  # [B, Sk, Hkv, D]
    v: jax.Array,  # [B, Sk, Hkv, D]
    causal: bool = True,
    sm_scale: Optional[float] = None,
    q_offset: int = 0,
    kv_offset: int = 0,
) -> jax.Array:
    """Plain-XLA multi-head attention with f32 softmax accumulation.

    ``q_offset``/``kv_offset`` are the absolute sequence positions of the
    first query/key — that is what makes this same function the per-block
    inner step of ring attention (parallel/ring.py), where each device holds
    a rotating sequence shard.
    """
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    if hq != hkv:
        assert hq % hkv == 0, (hq, hkv)
        k = jnp.repeat(k, hq // hkv, axis=2)
        v = jnp.repeat(v, hq // hkv, axis=2)
    scale = 1.0 / math.sqrt(d) if sm_scale is None else sm_scale
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        q_pos = q_offset + jnp.arange(sq)[:, None]
        k_pos = kv_offset + jnp.arange(sk)[None, :]
        scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


###############################################################################
# Pallas kernels. All operate on [B*H, S, D] ("bh" layout); the public entry
# reshapes/transposes around them.
###############################################################################


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_sc, m_sc, l_sc, *,
                block_k, sm_scale, causal, block_q):
    """One (batch*head, q-block, k-block) grid step of the streaming-softmax
    forward: update the online max/sum/accumulator in VMEM scratch, flush
    o/lse on the last k step.

    The k sweep is a grid dimension (not an in-kernel loop over full-
    sequence refs), so VMEM holds only (block, d) slabs — the same
    O(block)-VMEM restructuring as the backward kernels, which is what lets
    the sequence length scale to long-context sizes. o/lse out-spec indices
    are constant in the innermost grid dim (Mosaic output revisiting)."""
    import jax.experimental.pallas as pl

    q_idx = pl.program_id(1)
    k_i = pl.program_id(2)

    @pl.when(k_i == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    def compute():
        q = q_ref[...]      # [block_q, d]
        k_blk = k_ref[...]  # [block_k, d]
        v_blk = v_ref[...]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # [block_q, block_k]
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = k_i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_sc[:, 0:1]  # [block_q, 1] (lane-broadcast scratch)
        l_prev = l_sc[:, 0:1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_cur)
        alpha = jnp.exp(m_prev - m_cur)
        l_cur = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_sc[...] = acc_sc[...] * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_sc[...] = jnp.broadcast_to(m_cur, m_sc.shape)
        l_sc[...] = jnp.broadcast_to(l_cur, l_sc.shape)

    if causal:
        # Skip k blocks entirely above the diagonal for this q block.
        @pl.when(k_i * block_k < (q_idx + 1) * block_q)
        def _():
            compute()
    else:
        compute()

    @pl.when(k_i == pl.num_programs(2) - 1)
    def _flush():
        l = jnp.maximum(l_sc[:, 0:1], 1e-30)
        o_ref[...] = (acc_sc[...] / l).astype(o_ref.dtype)
        lse_ref[...] = jnp.broadcast_to(
            m_sc[:, 0:1] + jnp.log(l), (block_q, LANE)
        )


def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, acc_dk, acc_dv, *, block_q, sm_scale,
                     causal, block_k):
    """One (batch*head, k-block, q-block) grid step: accumulate this q
    block's dK/dV contribution into VMEM scratch; flush on the last q step.

    The q sweep is a *grid dimension*, not an in-kernel loop over full-
    sequence refs: only one (block_q, d) slab of q/do and one
    (block_q, LANE) slab of lse/delta is resident at a time, so VMEM stays
    O(block) instead of O(seq) — the fori_loop formulation ran out of
    scoped VMEM at seq 8192 (full-s refs alone are ~12 MB of the 16 MB
    budget). The dk/dv out-spec index is constant in the innermost grid
    dim, which is the Mosaic output-revisiting pattern.
    """
    import jax.experimental.pallas as pl

    k_idx = pl.program_id(1)
    q_i = pl.program_id(2)

    @pl.when(q_i == 0)
    def _init():
        acc_dk[...] = jnp.zeros_like(acc_dk)
        acc_dv[...] = jnp.zeros_like(acc_dv)

    def compute():
        k_blk = k_ref[...]  # [block_k, d]
        v_blk = v_ref[...]
        q = q_ref[...]      # [block_q, d]
        do = do_ref[...]
        # lse/delta are lane-broadcast [block_q, LANE]; lane 0 is the scalar.
        lse = lse_ref[:, 0:1]    # [block_q, 1]
        delta = delta_ref[:, 0:1]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # [block_q, block_k]
        if causal:
            q_pos = q_i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = k_idx * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)  # [block_q, block_k]
        # dV += P^T dO
        acc_dv[...] += jax.lax.dot_general(
            p, do.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dP = dO V^T ; dS = P * (dP - delta)
        dp = jax.lax.dot_general(
            do.astype(jnp.float32), v_blk.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        # dK += dS^T Q * scale
        acc_dk[...] += jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale

    if causal:
        # Skip q blocks strictly above the diagonal for this k block.
        @pl.when((q_i + 1) * block_q > k_idx * block_k)
        def _():
            compute()
    else:
        compute()

    @pl.when(q_i == pl.num_programs(2) - 1)
    def _flush():
        dk_ref[...] = acc_dk[...].astype(dk_ref.dtype)
        dv_ref[...] = acc_dv[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_dq, *, block_k, sm_scale, causal, block_q):
    """One (batch*head, q-block, k-block) grid step: accumulate this k
    block's dQ contribution into VMEM scratch; flush on the last k step.
    Same O(block)-VMEM restructuring as _bwd_dkdv_kernel."""
    import jax.experimental.pallas as pl

    q_idx = pl.program_id(1)
    k_i = pl.program_id(2)

    @pl.when(k_i == 0)
    def _init():
        acc_dq[...] = jnp.zeros_like(acc_dq)

    def compute():
        q = q_ref[...]
        do = do_ref[...]
        lse = lse_ref[:, 0:1]    # lane-broadcast [block_q, LANE]; lane 0
        delta = delta_ref[:, 0:1]
        k_blk = k_ref[...]
        v_blk = v_ref[...]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = k_i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do.astype(jnp.float32), v_blk.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        acc_dq[...] += jax.lax.dot_general(
            ds, k_blk.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale

    if causal:
        # Skip k blocks entirely above the diagonal for this q block.
        @pl.when(k_i * block_k < (q_idx + 1) * block_q)
        def _():
            compute()
    else:
        compute()

    @pl.when(k_i == pl.num_programs(2) - 1)
    def _flush():
        dq_ref[...] = acc_dq[...].astype(dq_ref.dtype)


def _flash_fwd_bh(qt, kt, vt, causal, scale, block_q, block_k):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, s, d = qt.shape
    kernel = functools.partial(
        _fwd_kernel, block_k=block_k, sm_scale=scale, causal=causal,
        block_q=block_q,
    )
    if causal:
        # Clamp above-diagonal k indices to the diagonal block: Mosaic
        # dedups repeated block indices, so the skipped (pl.when-gated)
        # steps re-address the already-resident block instead of DMA-ing
        # K/V blocks the kernel never reads.
        def kv_index(i, j, k):
            return (i, jnp.minimum(k, ((j + 1) * block_q - 1) // block_k), 0)
    else:
        def kv_index(i, j, k):
            return (i, k, 0)
    return pl.pallas_call(
        kernel,
        grid=(bh, s // block_q, s // block_k),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j, k: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), kv_index),
            pl.BlockSpec((None, block_k, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j, k: (i, j, 0)),
            pl.BlockSpec((None, block_q, LANE), lambda i, j, k: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), qt.dtype),
            jax.ShapeDtypeStruct((bh, s, LANE), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, LANE), jnp.float32),
            pltpu.VMEM((block_q, LANE), jnp.float32),
        ],
        interpret=INTERPRET,
    )(qt, kt, vt)


def _flash_bwd_bh(qt, kt, vt, ot, do, lse, causal, scale, block_q, block_k):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, s, d = qt.shape
    # delta = rowsum(dO * O): cheap elementwise, XLA fuses it. Lane-broadcast
    # to [bh, s, LANE] to match the tiled layout the kernels require.
    delta = jnp.sum(
        do.astype(jnp.float32) * ot.astype(jnp.float32), axis=-1
    )
    delta = jnp.broadcast_to(delta[..., None], (bh, s, LANE))

    dkdv = functools.partial(
        _bwd_dkdv_kernel, block_q=block_q, sm_scale=scale, causal=causal,
        block_k=block_k,
    )
    if causal:
        # Below-diagonal q blocks contribute nothing to this k block: clamp
        # their indices to the diagonal so the gated-off steps do not DMA
        # q/do/lse/delta blocks the kernel never reads.
        def q_index(i, j, q):
            return (i, jnp.maximum(q, (j * block_k) // block_q), 0)
    else:
        def q_index(i, j, q):
            return (i, q, 0)
    dk, dv = pl.pallas_call(
        dkdv,
        grid=(bh, s // block_k, s // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), q_index),                    # q
            pl.BlockSpec((None, block_k, d), lambda i, j, q: (i, j, 0)),  # k
            pl.BlockSpec((None, block_k, d), lambda i, j, q: (i, j, 0)),  # v
            pl.BlockSpec((None, block_q, d), q_index),                    # do
            pl.BlockSpec((None, block_q, LANE), q_index),                 # lse
            pl.BlockSpec((None, block_q, LANE), q_index),                 # delta
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda i, j, q: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j, q: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=INTERPRET,
    )(qt, kt, vt, do, lse, delta)

    dqk = functools.partial(
        _bwd_dq_kernel, block_k=block_k, sm_scale=scale, causal=causal,
        block_q=block_q,
    )
    if causal:
        def kv_index(i, j, k):  # clamp above-diagonal k blocks (as fwd)
            return (i, jnp.minimum(k, ((j + 1) * block_q - 1) // block_k), 0)
    else:
        def kv_index(i, j, k):
            return (i, k, 0)
    dq = pl.pallas_call(
        dqk,
        grid=(bh, s // block_q, s // block_k),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j, k: (i, j, 0)),  # q
            pl.BlockSpec((None, block_k, d), kv_index),                   # k
            pl.BlockSpec((None, block_k, d), kv_index),                   # v
            pl.BlockSpec((None, block_q, d), lambda i, j, k: (i, j, 0)),  # do
            pl.BlockSpec((None, block_q, LANE),
                         lambda i, j, k: (i, j, 0)),                      # lse
            pl.BlockSpec((None, block_q, LANE),
                         lambda i, j, k: (i, j, 0)),                    # delta
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j, k: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=INTERPRET,
    )(qt, kt, vt, do, lse, delta)
    return dq, dk, dv


###############################################################################
# Public flash entry: [B, S, H, D] layout, GQA, custom VJP.
###############################################################################


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8)
)
def flash_attention_tpu(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    block_q_bwd: Optional[int] = None,  # None: same as block_q
    block_k_bwd: Optional[int] = None,  # None: same as block_k
) -> jax.Array:
    out, _ = _flash_fwd(
        q, k, v, causal, sm_scale, block_q, block_k, block_q_bwd, block_k_bwd
    )
    return out


def _prep(q, k, v, block_q, block_k, sm_scale):
    b, s, h, d = q.shape
    hkv = k.shape[2]
    groups = h // hkv
    if hkv != h:
        k = jnp.repeat(k, groups, axis=2)
        v = jnp.repeat(v, groups, axis=2)
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    assert s % block_q == 0 and s % block_k == 0, (s, block_q, block_k)
    scale = 1.0 / math.sqrt(d) if sm_scale is None else sm_scale

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    return to_bh(q), to_bh(k), to_bh(v), scale, block_q, block_k, groups


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k,
               block_q_bwd=None, block_k_bwd=None):
    b, s, h, d = q.shape
    qt, kt, vt, scale, bq, bk, groups = _prep(q, k, v, block_q, block_k,
                                              sm_scale)
    ot, lse = _flash_fwd_bh(qt, kt, vt, causal, scale, bq, bk)
    # Name the backward's residuals so a remat policy can pin them
    # (transformer remat_policy="flash": save_only_these_names). With the
    # kernel outputs saved, the rematerialized forward inside backward
    # DCEs the whole pallas_call — the most expensive recompute in the
    # block — while q/k/v are still cheaply recomputed from the carry.
    # Only lane 0 of the lane-broadcast lse is information; save the thin
    # [bh, s, 1] slice and rebroadcast (cheap, recomputed in backward) so
    # the policy pins 1/LANE-th of the f32 array.
    ot = checkpoint_name(ot, "flash_out")
    lse = jnp.broadcast_to(
        checkpoint_name(lse[:, :, :1], "flash_lse"), lse.shape
    )
    out = ot.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    return out, (q, k, v, ot, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, block_q_bwd, block_k_bwd,
               residuals, g):
    q, k, v, ot, lse = residuals
    b, s, h, d = q.shape
    hkv = k.shape[2]
    qt, kt, vt, scale, bq, bk, groups = _prep(
        q, k, v,
        block_q if block_q_bwd is None else block_q_bwd,
        block_k if block_k_bwd is None else block_k_bwd,
        sm_scale,
    )
    do = g.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    dq, dk, dv = _flash_bwd_bh(qt, kt, vt, ot, do, lse, causal, scale, bq, bk)

    def from_bh(x, heads):
        return x.reshape(b, heads, s, d).transpose(0, 2, 1, 3)

    dq = from_bh(dq, h).astype(q.dtype)
    dk = from_bh(dk, h)
    dv = from_bh(dv, h)
    if hkv != h:
        # Sum gradients over the query heads sharing each KV head.
        dk = dk.reshape(b, s, hkv, groups, d).sum(axis=3)
        dv = dv.reshape(b, s, hkv, groups, d).sum(axis=3)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention_tpu.defvjp(_flash_fwd, _flash_bwd)


def mha(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    use_pallas: Optional[bool] = None,
) -> jax.Array:
    """Dispatch: Pallas flash kernels (fwd+bwd) on TPU for long sequences,
    XLA reference elsewhere. ``HIVED_DISABLE_PALLAS=1`` (or setting
    ``attention.DISABLE_PALLAS``) forces the XLA path — the degradation
    switch perf/bench harnesses flip so a kernel regression downgrades the
    throughput number instead of erasing it."""
    if use_pallas is None:
        use_pallas = pallas_wanted()
    if use_pallas and pallas_shape_ok(q.shape[1], k.shape[1]):
        s = q.shape[1]
        bq, bk, bq_bwd, bk_bwd = block_limits()
        return flash_attention_tpu(
            q, k, v, causal, sm_scale,
            fit_block(bq, s, 8), fit_block(bk, s, 128),
            fit_block(bq_bwd, s, 8), fit_block(bk_bwd, s, 128),
        )
    return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)


def pallas_wanted() -> bool:
    """True when the dispatcher would *want* the Pallas path: TPU backend
    and neither kill switch set. Single source of truth for ``mha`` and the
    perf harness's ``pallas_used`` label."""
    import os

    return (
        jax.default_backend() == "tpu"
        and not DISABLE_PALLAS
        and os.environ.get("HIVED_DISABLE_PALLAS", "0") != "1"
    )


def fit_block(limit: int, s: int, align: int) -> int:
    """Largest block <= ``limit`` that divides ``s`` and is a multiple of
    ``align`` (the Mosaic tile constraint for that score-matrix dim), or 0
    when none exists. This is what lets an 8k-tuned BLOCK_K=1024 still run
    the flash kernels at seq 768 (with 768-wide blocks) instead of silently
    demoting every non-multiple-of-1024 length to the O(S^2) XLA path."""
    for b in range(min(limit, s) // align * align, 0, -align):
        if s % b == 0:
            return b
    return 0


def pallas_shape_ok(sq: int, sk: int) -> bool:
    """Shape gate of the Pallas path: long-enough self-attention for which
    some Mosaic-tile-aligned blocks exist under the configured limits
    (``fit_block``; ``mha`` dispatches with exactly those fitted blocks).
    The effective blocks are the last two dims of the in-kernel score
    matrix, hence the (8, 128) alignment requirement — e.g. sq=300 has no
    valid block and must route to the XLA fallback rather than crash in
    lowering."""
    bq, bk, bq_bwd, bk_bwd = block_limits()
    return (
        sq >= 256
        and sq == sk
        and fit_block(bq, sq, 8) > 0
        and fit_block(bk, sq, 128) > 0
        and fit_block(bq_bwd, sq, 8) > 0
        and fit_block(bk_bwd, sq, 128) > 0
    )
