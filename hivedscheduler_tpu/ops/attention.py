"""Attention ops: XLA reference path + Pallas flash-attention TPU kernels
(forward AND backward, wired through custom_vjp).

The reference repo has no compute ops at all (it is a scheduler;
SURVEY.md §2.2) — these ops exist for the BASELINE workloads the scheduler
places. Design per the TPU playbook:

  - Flash forward: streaming softmax over K/V blocks in VMEM; the S×S score
    matrix never exists in HBM. Saves the per-row logsumexp for backward.
  - Flash backward: two kernels — dK/dV per key-block (sweeping query
    blocks) and dQ per query-block (sweeping key blocks) — recomputing P
    from Q,K and the saved LSE instead of storing it (remat: FLOPs for HBM,
    the usual TPU trade).
  - Off-TPU (and for short sequences) everything falls back to the XLA
    implementation, which fuses fine and autodiffs itself.

GQA is supported by repeating KV heads; head_dim should be a multiple of
128 on TPU for lane alignment (pallas_guide.md tiling constraints).
Set ``attention.INTERPRET = True`` to run the kernels in interpreter mode
(hermetic CPU tests do this).
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30

# Interpreter mode for pallas kernels (CPU tests); real TPU runs leave False.
INTERPRET = False


def mha_reference(
    q: jax.Array,  # [B, Sq, Hq, D]
    k: jax.Array,  # [B, Sk, Hkv, D]
    v: jax.Array,  # [B, Sk, Hkv, D]
    causal: bool = True,
    sm_scale: Optional[float] = None,
    q_offset: int = 0,
    kv_offset: int = 0,
) -> jax.Array:
    """Plain-XLA multi-head attention with f32 softmax accumulation.

    ``q_offset``/``kv_offset`` are the absolute sequence positions of the
    first query/key — that is what makes this same function the per-block
    inner step of ring attention (parallel/ring.py), where each device holds
    a rotating sequence shard.
    """
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    if hq != hkv:
        assert hq % hkv == 0, (hq, hkv)
        k = jnp.repeat(k, hq // hkv, axis=2)
        v = jnp.repeat(v, hq // hkv, axis=2)
    scale = 1.0 / math.sqrt(d) if sm_scale is None else sm_scale
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        q_pos = q_offset + jnp.arange(sq)[:, None]
        k_pos = kv_offset + jnp.arange(sk)[None, :]
        scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


###############################################################################
# Pallas kernels. All operate on [B*H, S, D] ("bh" layout); the public entry
# reshapes/transposes around them.
###############################################################################


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k, sm_scale,
                causal, block_q, seq_len):
    import jax.experimental.pallas as pl

    q_idx = pl.program_id(1)
    q = q_ref[...]  # [block_q, d]
    d = q.shape[-1]

    m0 = jnp.full((block_q,), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((block_q,), dtype=jnp.float32)
    acc0 = jnp.zeros((block_q, d), dtype=jnp.float32)

    q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(start_k, carry):
        m_prev, l_prev, acc_prev = carry
        k_blk = k_ref[pl.dslice(start_k * block_k, block_k), :]
        v_blk = v_ref[pl.dslice(start_k * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # [block_q, block_k]
        if causal:
            k_pos = start_k * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_cur[:, None])
        alpha = jnp.exp(m_prev - m_cur)
        l_cur = l_prev * alpha + jnp.sum(p, axis=-1)
        acc_cur = acc_prev * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_cur, l_cur, acc_cur

    num_k_blocks = seq_len // block_k
    if causal:
        upper = jnp.minimum(
            jax.lax.div((q_idx + 1) * block_q + block_k - 1,
                        jnp.int32(block_k)),
            num_k_blocks,
        )
    else:
        upper = num_k_blocks
    m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[...] = (acc / l[:, None]).astype(o_ref.dtype)
    lse_ref[...] = m + jnp.log(l)


def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, *, block_q, sm_scale, causal, block_k,
                     seq_len):
    """One (batch*head, k-block) program: accumulate dK, dV over q blocks."""
    import jax.experimental.pallas as pl

    k_idx = pl.program_id(1)
    k_blk = k_ref[...]  # [block_k, d]
    v_blk = v_ref[...]
    d = k_blk.shape[-1]

    k_pos = k_idx * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )

    def body(q_i, carry):
        dk, dv = carry
        q = q_ref[pl.dslice(q_i * block_q, block_q), :]
        do = do_ref[pl.dslice(q_i * block_q, block_q), :]
        lse = lse_ref[pl.dslice(q_i * block_q, block_q)]
        delta = delta_ref[pl.dslice(q_i * block_q, block_q)]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # [block_q, block_k]
        if causal:
            q_pos = q_i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])  # [block_q, block_k]
        # dV += P^T dO
        dv = dv + jax.lax.dot_general(
            p, do.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dP = dO V^T ; dS = P * (dP - delta)
        dp = jax.lax.dot_general(
            do.astype(jnp.float32), v_blk.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None])
        # dK += dS^T Q * scale
        dk = dk + jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        return dk, dv

    num_q_blocks = seq_len // block_q
    if causal:
        # Only q blocks at or after this k block see it.
        lower = jax.lax.div(k_idx * block_k, jnp.int32(block_q))
    else:
        lower = jnp.int32(0)
    dk0 = jnp.zeros((block_k, d), dtype=jnp.float32)
    dv0 = jnp.zeros((block_k, d), dtype=jnp.float32)
    dk, dv = jax.lax.fori_loop(lower, num_q_blocks, body, (dk0, dv0))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, block_k, sm_scale, causal, block_q, seq_len):
    """One (batch*head, q-block) program: accumulate dQ over k blocks."""
    import jax.experimental.pallas as pl

    q_idx = pl.program_id(1)
    q = q_ref[...]
    do = do_ref[...]
    lse = lse_ref[...]
    delta = delta_ref[...]
    d = q.shape[-1]

    q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(k_i, dq):
        k_blk = k_ref[pl.dslice(k_i * block_k, block_k), :]
        v_blk = v_ref[pl.dslice(k_i * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        if causal:
            k_pos = k_i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do.astype(jnp.float32), v_blk.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None])
        return dq + jax.lax.dot_general(
            ds, k_blk.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale

    num_k_blocks = seq_len // block_k
    if causal:
        upper = jnp.minimum(
            jax.lax.div((q_idx + 1) * block_q + block_k - 1,
                        jnp.int32(block_k)),
            num_k_blocks,
        )
    else:
        upper = num_k_blocks
    dq = jax.lax.fori_loop(
        0, upper, body, jnp.zeros((block_q, d), dtype=jnp.float32)
    )
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _flash_fwd_bh(qt, kt, vt, causal, scale, block_q, block_k):
    import jax.experimental.pallas as pl

    bh, s, d = qt.shape
    kernel = functools.partial(
        _fwd_kernel, block_k=block_k, sm_scale=scale, causal=causal,
        block_q=block_q, seq_len=s,
    )
    return pl.pallas_call(
        kernel,
        grid=(bh, s // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, s, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_q), lambda i, j: (i, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), qt.dtype),
            jax.ShapeDtypeStruct((bh, s), jnp.float32),
        ],
        interpret=INTERPRET,
    )(qt, kt, vt)


def _flash_bwd_bh(qt, kt, vt, ot, do, lse, causal, scale, block_q, block_k):
    import jax.experimental.pallas as pl

    bh, s, d = qt.shape
    # delta = rowsum(dO * O): cheap elementwise, XLA fuses it.
    delta = jnp.sum(
        do.astype(jnp.float32) * ot.astype(jnp.float32), axis=-1
    )  # [bh, s]

    dkdv = functools.partial(
        _bwd_dkdv_kernel, block_q=block_q, sm_scale=scale, causal=causal,
        block_k=block_k, seq_len=s,
    )
    dk, dv = pl.pallas_call(
        dkdv,
        grid=(bh, s // block_k),
        in_specs=[
            pl.BlockSpec((None, s, d), lambda i, j: (i, 0, 0)),      # q
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),  # k
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),  # v
            pl.BlockSpec((None, s, d), lambda i, j: (i, 0, 0)),      # do
            pl.BlockSpec((None, s), lambda i, j: (i, 0)),            # lse
            pl.BlockSpec((None, s), lambda i, j: (i, 0)),            # delta
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
        ],
        interpret=INTERPRET,
    )(qt, kt, vt, do, lse, delta)

    dqk = functools.partial(
        _bwd_dq_kernel, block_k=block_k, sm_scale=scale, causal=causal,
        block_q=block_q, seq_len=s,
    )
    dq = pl.pallas_call(
        dqk,
        grid=(bh, s // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),  # q
            pl.BlockSpec((None, s, d), lambda i, j: (i, 0, 0)),      # k
            pl.BlockSpec((None, s, d), lambda i, j: (i, 0, 0)),      # v
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),  # do
            pl.BlockSpec((None, block_q), lambda i, j: (i, j)),      # lse
            pl.BlockSpec((None, block_q), lambda i, j: (i, j)),      # delta
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
        interpret=INTERPRET,
    )(qt, kt, vt, do, lse, delta)
    return dq, dk, dv


###############################################################################
# Public flash entry: [B, S, H, D] layout, GQA, custom VJP.
###############################################################################


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6)
)
def flash_attention_tpu(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = 256,
    block_k: int = 256,
) -> jax.Array:
    out, _ = _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return out


def _prep(q, k, v, block_q, block_k, sm_scale):
    b, s, h, d = q.shape
    hkv = k.shape[2]
    groups = h // hkv
    if hkv != h:
        k = jnp.repeat(k, groups, axis=2)
        v = jnp.repeat(v, groups, axis=2)
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    assert s % block_q == 0 and s % block_k == 0, (s, block_q, block_k)
    scale = 1.0 / math.sqrt(d) if sm_scale is None else sm_scale

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    return to_bh(q), to_bh(k), to_bh(v), scale, block_q, block_k, groups


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    b, s, h, d = q.shape
    qt, kt, vt, scale, bq, bk, groups = _prep(q, k, v, block_q, block_k,
                                              sm_scale)
    ot, lse = _flash_fwd_bh(qt, kt, vt, causal, scale, bq, bk)
    out = ot.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    return out, (q, k, v, ot, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, residuals, g):
    q, k, v, ot, lse = residuals
    b, s, h, d = q.shape
    hkv = k.shape[2]
    qt, kt, vt, scale, bq, bk, groups = _prep(q, k, v, block_q, block_k,
                                              sm_scale)
    do = g.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    dq, dk, dv = _flash_bwd_bh(qt, kt, vt, ot, do, lse, causal, scale, bq, bk)

    def from_bh(x, heads):
        return x.reshape(b, heads, s, d).transpose(0, 2, 1, 3)

    dq = from_bh(dq, h).astype(q.dtype)
    dk = from_bh(dk, h)
    dv = from_bh(dv, h)
    if hkv != h:
        # Sum gradients over the query heads sharing each KV head.
        dk = dk.reshape(b, s, hkv, groups, d).sum(axis=3)
        dv = dv.reshape(b, s, hkv, groups, d).sum(axis=3)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention_tpu.defvjp(_flash_fwd, _flash_bwd)


def mha(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    use_pallas: Optional[bool] = None,
) -> jax.Array:
    """Dispatch: Pallas flash kernels (fwd+bwd) on TPU for long sequences,
    XLA reference elsewhere."""
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    s = q.shape[1]
    if use_pallas and s >= 256 and s % 256 == 0 and s == k.shape[1]:
        return flash_attention_tpu(q, k, v, causal, sm_scale)
    return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)
