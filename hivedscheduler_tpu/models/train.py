"""Sharded training step: next-token loss, AdamW, declarative parallelism.

The full jax.distributed training loop a HiveD-placed gang runs: params and
optimizer state sharded by the logical-axis rules (ZeRO-3 over ``fsdp``, tp
over heads/mlp), batch sharded over (dp, fsdp) and sequence over sp. Every
collective (gradient psum, fsdp all-gathers, ring-attention ppermute) is
inserted by XLA from the shardings — none is hand-written except the ring.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel import sharding
from . import transformer

Params = Dict[str, Any]


# Vocab sizes at or above this use the fused chunked loss on single-chip
# paths: a [B, S, V] f32 logits tensor at e.g. V=128k, S=8k is multiple GB
# of pure HBM traffic that the chunked online-logsumexp never materializes.
FUSED_LOSS_MIN_VOCAB = 32768
_LOSS_CHUNK = 8192  # vocab elements per chunk


def _chunked_ce(
    x: jax.Array,        # [N, D] compute dtype (final hidden, scored rows)
    head: jax.Array,     # [D, V]
    targets: jax.Array,  # [N] int32
    chunk: int,
) -> jax.Array:
    """Exact mean cross-entropy without materializing [N, V] logits: scan
    vocab chunks with an online logsumexp; each chunk's logits are remat'd
    in backward (jax.checkpoint), so peak memory is O(N * chunk). The
    flash-attention trade (FLOPs for HBM) applied to the LM head. A vocab
    that does not divide the chunk gets one static remainder step."""
    n, d = x.shape
    v = head.shape[1]
    nc, rem = divmod(v, chunk)

    def update(carry, start, w, width):
        m, s, tl = carry
        logits = (x @ w).astype(jnp.float32)  # [N, width]
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1
        )
        local = targets - start
        in_chunk = (local >= 0) & (local < width)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, width - 1)[:, None], axis=1
        )[:, 0]
        tl = jnp.where(in_chunk, picked, tl)
        return m_new, s, tl

    def step(carry, c):
        w = jax.lax.dynamic_slice_in_dim(head, c * chunk, chunk, axis=1)
        return update(carry, c * chunk, w, chunk), None

    carry = (
        jnp.full((n,), -jnp.inf, dtype=jnp.float32),
        jnp.zeros((n,), dtype=jnp.float32),
        jnp.zeros((n,), dtype=jnp.float32),
    )
    if nc:
        carry, _ = jax.lax.scan(
            jax.checkpoint(step), carry, jnp.arange(nc, dtype=jnp.int32)
        )
    if rem:
        w_tail = jax.lax.slice_in_dim(head, nc * chunk, v, axis=1)
        carry = jax.checkpoint(
            lambda cr: update(cr, nc * chunk, w_tail, rem)
        )(carry)
    m, s, tl = carry
    lse = m + jnp.log(s)
    return jnp.mean(lse - tl)


def next_token_loss(
    params: Params,
    tokens: jax.Array,  # [B, S]
    config: transformer.TransformerConfig,
    mesh: Optional[Mesh] = None,
    fused: Optional[bool] = None,
    chunk: int = _LOSS_CHUNK,
) -> jax.Array:
    """Causal LM loss: predict tokens[:, 1:] from tokens[:, :-1]. The whole
    sequence goes through the model (keeps static shapes / sp divisibility);
    the last position's logits are simply not scored.

    ``fused`` selects the vocab-chunked logsumexp path (no [B, S, V]
    logits tensor). Default: on for large vocab whenever the vocab
    dimension is unsharded (single chip, or dp/fsdp/sp-only meshes); off
    when tp shards the vocab — there the chunk slices would fight the
    sharding, and GSPMD's own partitioned softmax handles it well."""
    if fused is None:
        fused = (
            config.vocab_size >= FUSED_LOSS_MIN_VOCAB
            and (mesh is None or mesh.shape.get("tp", 1) == 1)
        )
    targets = tokens[:, 1:]
    if fused:
        x, head = transformer.forward_hidden(params, tokens, config, mesh)
        b, s, d = x.shape
        return _chunked_ce(
            x[:, :-1].reshape(b * (s - 1), d),
            head,
            targets.reshape(-1),
            chunk,
        )
    logits = transformer.forward(params, tokens, config, mesh)  # [B,S,V] f32
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def make_optimizer(
    learning_rate: float = 3e-4, weight_decay: float = 0.1
) -> optax.GradientTransformation:
    return optax.adamw(
        learning_rate=learning_rate,
        b1=0.9,
        b2=0.95,
        weight_decay=weight_decay,
    )


def train_step(
    params: Params,
    opt_state: Any,
    tokens: jax.Array,
    config: transformer.TransformerConfig,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
) -> Tuple[Params, Any, jax.Array]:
    loss, grads = jax.value_and_grad(next_token_loss)(
        params, tokens, config, mesh
    )
    updates, opt_state = optimizer.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss


def shardings_for(
    config: Any,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    model: Any = transformer,
) -> Tuple[Any, Any, Any, Any]:
    """Shape-only sharding plan for a model's train state:
    (param_shardings, opt_shardings, params_shape, opt_shape), computed
    entirely with ``jax.eval_shape`` — nothing is allocated, so this also
    serves compile/lowering gates on shapes far too big for the host
    (the 8B / Mixtral-8x7B virtual-v5p-64 lowering checks). ``model``
    supplies ``init(config, key)`` + ``logical_axes(config)``; the
    flagship transformer by default, ``models.mixtral`` for the MoE
    family."""
    logical = model.logical_axes(config)
    param_sh = sharding.tree_shardings(mesh, logical)

    params_shape = jax.eval_shape(
        functools.partial(model.init, config), jax.random.PRNGKey(0)
    )
    # Optimizer state embeds copies of the param tree (adam mu/nu): any
    # sub-tree structurally identical to the param tree gets the param
    # shardings leaf-for-leaf; every other leaf (counts, scalars) is
    # replicated. Structural matching — unlike shape matching — cannot
    # mis-shard a moment when two params share a shape.
    opt_shape = jax.eval_shape(optimizer.init, params_shape)
    param_treedef = jax.tree.structure(params_shape)

    def _is_param_tree(node):
        return jax.tree.structure(node) == param_treedef

    opt_sh = jax.tree.map(
        lambda node: param_sh if _is_param_tree(node) else NamedSharding(mesh, P()),
        opt_shape,
        is_leaf=_is_param_tree,
    )
    return param_sh, opt_sh, params_shape, opt_shape


def init_sharded(
    config: transformer.TransformerConfig,
    mesh: Mesh,
    key: jax.Array,
    optimizer: optax.GradientTransformation,
) -> Tuple[Params, Any, Any, Any]:
    """Initialize params + optimizer state directly into their shardings
    (jit with out_shardings => no host-side full copy ever exists).

    Returns (params, opt_state, param_shardings, opt_shardings).
    """
    param_sh, opt_sh, _, _ = shardings_for(config, mesh, optimizer)
    params = jax.jit(
        functools.partial(transformer.init, config), out_shardings=param_sh
    )(key)
    opt_state = jax.jit(optimizer.init, out_shardings=opt_sh)(params)
    return params, opt_state, param_sh, opt_sh


def make_train_step(
    config: transformer.TransformerConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    param_sh: Any,
    opt_sh: Any,
) -> Callable:
    """The jitted, fully-sharded train step. Batch arrives sharded over
    (dp, fsdp) x sp (use parallel.sharding.shard_batch)."""
    token_sh = NamedSharding(mesh, sharding.spec_for(("batch", "seq")))

    step = functools.partial(
        train_step, config=config, optimizer=optimizer, mesh=mesh
    )
    return jax.jit(
        step,
        in_shardings=(param_sh, opt_sh, token_sh),
        out_shardings=(param_sh, opt_sh, NamedSharding(mesh, P())),
        donate_argnums=(0, 1),
    )
