"""ResNet-50, TPU-first (BASELINE config 2: single-host v5e-4 data parallel).

Functional JAX implementation: NCHW->NHWC (TPU conv layout), bf16 compute
with f32 batch-norm statistics, ``lax.conv_general_dilated`` so XLA tiles
convs onto the MXU. Parallelism is batch-only (dp/fsdp), matching the
single-host BASELINE config; params replicate.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]

# (blocks per stage) for ResNet-50
STAGES = (3, 4, 6, 3)
STAGE_WIDTHS = (256, 512, 1024, 2048)


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16


def _conv_init(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    return jax.random.normal(key, (kh, kw, cin, cout), dtype=jnp.float32) * (
        2.0 / fan_in
    ) ** 0.5


def _bn_init(c):
    return {
        "scale": jnp.ones((c,), jnp.float32),
        "bias": jnp.zeros((c,), jnp.float32),
    }


def _bn_stats(c):
    return {
        "mean": jnp.zeros((c,), jnp.float32),
        "var": jnp.ones((c,), jnp.float32),
    }


def init(config: ResNetConfig, key: jax.Array) -> Tuple[Params, Params]:
    """Returns (params, batch_stats)."""
    keys = iter(jax.random.split(key, 200))
    params: Params = {
        "stem": {"conv": _conv_init(next(keys), 7, 7, 3, config.width),
                  "bn": _bn_init(config.width)},
        "stages": [],
        "head": jax.random.normal(
            next(keys), (STAGE_WIDTHS[-1], config.num_classes), dtype=jnp.float32
        ) / STAGE_WIDTHS[-1] ** 0.5,
    }
    stats: Params = {"stem": _bn_stats(config.width), "stages": []}
    cin = config.width
    for stage_idx, n_blocks in enumerate(STAGES):
        cout = STAGE_WIDTHS[stage_idx]
        mid = cout // 4
        stage_p, stage_s = [], []
        for b in range(n_blocks):
            block_p = {
                "conv1": _conv_init(next(keys), 1, 1, cin, mid),
                "bn1": _bn_init(mid),
                "conv2": _conv_init(next(keys), 3, 3, mid, mid),
                "bn2": _bn_init(mid),
                "conv3": _conv_init(next(keys), 1, 1, mid, cout),
                "bn3": _bn_init(cout),
            }
            block_s = {
                "bn1": _bn_stats(mid),
                "bn2": _bn_stats(mid),
                "bn3": _bn_stats(cout),
            }
            if b == 0:
                block_p["proj"] = _conv_init(next(keys), 1, 1, cin, cout)
                block_p["bn_proj"] = _bn_init(cout)
                block_s["bn_proj"] = _bn_stats(cout)
            stage_p.append(block_p)
            stage_s.append(block_s)
            cin = cout
        params["stages"].append(stage_p)
        stats["stages"].append(stage_s)
    return params, stats


def _conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x,
        w.astype(x.dtype),
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _bn(x, p, s, train: bool, momentum=0.9, eps=1e-5):
    """Batch norm; returns (y, new_stats). Stats stay f32."""
    if train:
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=(0, 1, 2))
        var = jnp.var(x32, axis=(0, 1, 2))
        new_s = {
            "mean": momentum * s["mean"] + (1 - momentum) * mean,
            "var": momentum * s["var"] + (1 - momentum) * var,
        }
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    inv = jax.lax.rsqrt(var + eps) * p["scale"]
    y = (x.astype(jnp.float32) - mean) * inv + p["bias"]
    return y.astype(x.dtype), new_s


def forward(
    params: Params,
    stats: Params,
    images: jax.Array,  # [B, H, W, 3]
    config: ResNetConfig,
    train: bool = False,
) -> Tuple[jax.Array, Params]:
    """Returns (logits [B, num_classes], new_batch_stats)."""
    x = images.astype(config.dtype)
    x = _conv(x, params["stem"]["conv"], stride=2)
    x, stem_s = _bn(x, params["stem"]["bn"], stats["stem"], train)
    x = jax.nn.relu(x)
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )

    new_stats: Params = {"stem": stem_s, "stages": []}
    for stage_idx, stage in enumerate(params["stages"]):
        stage_stats = []
        for b, block in enumerate(stage):
            stride = 2 if (b == 0 and stage_idx > 0) else 1
            shortcut = x
            y = _conv(x, block["conv1"])
            y, s1 = _bn(y, block["bn1"], stats["stages"][stage_idx][b]["bn1"], train)
            y = jax.nn.relu(y)
            y = _conv(y, block["conv2"], stride=stride)
            y, s2 = _bn(y, block["bn2"], stats["stages"][stage_idx][b]["bn2"], train)
            y = jax.nn.relu(y)
            y = _conv(y, block["conv3"])
            y, s3 = _bn(y, block["bn3"], stats["stages"][stage_idx][b]["bn3"], train)
            bs = {"bn1": s1, "bn2": s2, "bn3": s3}
            if "proj" in block:
                shortcut = _conv(x, block["proj"], stride=stride)
                shortcut, sp = _bn(
                    shortcut,
                    block["bn_proj"],
                    stats["stages"][stage_idx][b]["bn_proj"],
                    train,
                )
                bs["bn_proj"] = sp
            x = jax.nn.relu(y + shortcut)
            stage_stats.append(bs)
        new_stats["stages"].append(stage_stats)

    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))  # global average pool
    logits = x @ params["head"]
    return logits, new_stats


def loss_fn(params, stats, images, labels, config, train=True):
    logits, new_stats = forward(params, stats, images, config, train=train)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, labels[:, None], axis=-1)
    return -jnp.mean(ll), new_stats
