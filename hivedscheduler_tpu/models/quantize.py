"""Int8 weight quantization for the serving path.

Single-chip decode is bound by re-reading the weights from HBM every
token step (doc/perf.md's decode roofline): per-output-channel symmetric
int8 halves that traffic. The quantized tree drops into the existing
KV-cache decode machinery unchanged — ``generate``'s matmuls accept
either a plain array or a ``{"w": int8, "scale": f32}`` leaf and cast at
load, letting XLA fuse the int8→bf16 convert into the matmul's weight
read. Training and the MoE expert weights are out of scope (training
wants full precision; GShard dispatch reads experts per-token anyway).

Accuracy contract (tested): per-channel symmetric int8 keeps every
dequantized weight within one quantization step of the original
(|w - dq(w)| <= scale/2 with scale = max|channel|/127), and the decode
scan remains bit-identical to the stepwise decode under the SAME
quantized weights — the representation changes, the machinery's
exactness does not.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from .transformer import Params

# The decode-path linear weights ([in, out] matmuls re-read every step).
# Norms are vectors, embeddings are gathered by row (not a full-matrix
# read), and rotary has no weights — all stay in the compute dtype.
LAYER_LINEAR_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_weight(w: jax.Array) -> Dict[str, jax.Array]:
    """Per-output-channel symmetric int8 of an [in, out] matrix."""
    # Fail fast at the API boundary: a higher-rank array here means a
    # tree this scheme doesn't model (e.g. MoE expert stacks [E, in,
    # out], where axis-0 max would scale ACROSS experts) — reject with a
    # clear error instead of corrupting silently.
    assert w.ndim == 2, f"expected [in, out] weight, got shape {w.shape}"
    wf = w.astype(jnp.float32)
    scale = jnp.max(jnp.abs(wf), axis=0) / 127.0
    scale = jnp.maximum(scale, 1e-8)  # all-zero channels
    q = jnp.clip(jnp.round(wf / scale), -127, 127)
    return {"w": q.astype(jnp.int8), "scale": scale}


def quantized_matmul(x: jax.Array, w: Any) -> jax.Array:
    """``x @ w`` where ``w`` is a plain array OR a quantized leaf. The
    int8 weights are cast to the activation dtype at load (XLA fuses the
    convert into the matmul read) and the per-output-channel scale is
    applied to the product."""
    if isinstance(w, dict):
        return (x @ w["w"].astype(x.dtype)) * w["scale"].astype(x.dtype)
    return x @ w


def quantize_params(params: Params) -> Params:
    """Quantize the flagship transformer's decode-path linears: the
    stacked per-layer matmuls (vmapped over the layer axis, so the scan
    in ``generate._forward_cached`` unstacks the quantized leaves
    per-layer) and the untied ``lm_head``. Everything else passes
    through unchanged."""
    out = dict(params)
    layers = params["layers"]
    out["layers"] = {
        k: (jax.vmap(quantize_weight)(v) if k in LAYER_LINEAR_KEYS else v)
        for k, v in layers.items()
    }
    if "lm_head" in params:
        out["lm_head"] = quantize_weight(params["lm_head"])
    return out
