"""BERT-style bidirectional encoder for MLM pretraining (BASELINE config 3:
BERT-large on a 4-host v5e-16 gang).

Reuses the decoder's primitives where they coincide (rms_norm is replaced by
classic LayerNorm to match BERT; attention is the same op, non-causal).
Parallelism identical to the decoder: logical axes + the shared rule table,
so the same dp/fsdp/tp layouts apply; sp/ring attention is unnecessary at
BERT sequence lengths.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..parallel import sharding

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    d_model: int = 1024
    n_layers: int = 24
    n_heads: int = 16
    d_ff: int = 4096
    max_seq_len: int = 512
    dtype: Any = jnp.bfloat16
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def bert_large() -> BertConfig:
    return BertConfig()


def tiny(vocab: int = 512) -> BertConfig:
    return BertConfig(
        vocab_size=vocab,
        d_model=64,
        n_layers=2,
        n_heads=4,
        d_ff=128,
        max_seq_len=128,
        dtype=jnp.float32,
        remat=False,
    )


def init(config: BertConfig, key: jax.Array) -> Params:
    c = config
    d, f, L = c.d_model, c.d_ff, c.n_layers
    keys = jax.random.split(key, 8)

    def norm(k, fan_in, shape):
        return jax.random.normal(k, shape, dtype=jnp.float32) / jnp.sqrt(fan_in)

    return {
        "embed": norm(keys[0], 1, (c.vocab_size, d)),
        "pos_embed": norm(keys[1], 1, (c.max_seq_len, d)) * 0.02,
        "layers": {
            "ln1_scale": jnp.ones((L, d), jnp.float32),
            "ln1_bias": jnp.zeros((L, d), jnp.float32),
            "wqkv": norm(keys[2], d, (L, d, 3 * d)),
            "wo": norm(keys[3], d, (L, d, d)),
            "ln2_scale": jnp.ones((L, d), jnp.float32),
            "ln2_bias": jnp.zeros((L, d), jnp.float32),
            "w_up": norm(keys[4], d, (L, d, f)),
            "w_down": norm(keys[5], f, (L, f, d)),
        },
        "ln_f_scale": jnp.ones((d,), jnp.float32),
        "ln_f_bias": jnp.zeros((d,), jnp.float32),
        "mlm_head": norm(keys[6], d, (d, c.vocab_size)),
    }


def logical_axes(config: BertConfig) -> Params:
    return {
        "embed": ("vocab", "embed"),
        "pos_embed": (None, "embed"),
        "layers": {
            "ln1_scale": ("layers", None),
            "ln1_bias": ("layers", None),
            "wqkv": ("layers", "embed", "heads"),
            "wo": ("layers", "heads", "embed"),
            "ln2_scale": ("layers", None),
            "ln2_bias": ("layers", None),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        },
        "ln_f_scale": (None,),
        "ln_f_bias": (None,),
        "mlm_head": ("embed", "vocab"),
    }


def layer_norm(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _block(x, layer, config, mesh):
    c = config
    b, s, d = x.shape
    h = layer_norm(x, layer["ln1_scale"], layer["ln1_bias"])
    qkv = h @ layer["wqkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, s, c.n_heads, c.head_dim)
    k = k.reshape(b, s, c.n_heads, c.head_dim)
    v = v.reshape(b, s, c.n_heads, c.head_dim)
    q = sharding.constrain(q, "batch", "seq", "heads", None)
    attn = sharding.sharded_mha(q, k, v, mesh, causal=False)
    attn = attn.reshape(b, s, d)
    x = x + sharding.constrain(attn @ layer["wo"], "batch", "seq", "act_embed")

    h = layer_norm(x, layer["ln2_scale"], layer["ln2_bias"])
    ffn = jax.nn.gelu(h @ layer["w_up"]) @ layer["w_down"]
    return x + sharding.constrain(ffn, "batch", "seq", "act_embed")


def forward(
    params: Params,
    tokens: jax.Array,  # [B, S]
    config: BertConfig,
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    """MLM logits [B, S, V]."""
    c = config
    params = jax.tree.map(lambda a: a.astype(c.dtype), params)
    s = tokens.shape[1]
    x = params["embed"][tokens] + params["pos_embed"][None, :s]
    x = sharding.constrain(x, "batch", "seq", "act_embed")

    block = lambda x, layer: (_block(x, layer, c, mesh), None)
    if c.remat:
        block = jax.checkpoint(block)
    x, _ = jax.lax.scan(block, x, params["layers"])

    x = layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])
    logits = x @ params["mlm_head"]
    return logits.astype(jnp.float32)


def mlm_loss(
    params: Params,
    tokens: jax.Array,
    targets: jax.Array,  # original token at masked positions, -100 elsewhere
    config: BertConfig,
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    logits = forward(params, tokens, config, mesh)
    logp = jax.nn.log_softmax(logits, axis=-1)
    mask = targets >= 0
    safe_targets = jnp.where(mask, targets, 0)
    ll = jnp.take_along_axis(logp, safe_targets[..., None], axis=-1)[..., 0]
    return -jnp.sum(ll * mask) / jnp.maximum(jnp.sum(mask), 1)
