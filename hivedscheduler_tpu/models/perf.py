"""Single-chip model-performance benchmark: tokens/sec/chip + MFU.

The second half of the BASELINE.json headline metric ("gang-schedule p50
latency; tokens/sec/chip at 8B"): the scheduler's placement guarantee exists
to buy training throughput, so the framework must measure it. This module
runs the flagship transformer's FULL train step (forward + backward + AdamW)
on one chip and reports tokens/sec and model-FLOPs-utilization against the
chip's peak bf16 FLOPs, plus a flash-vs-XLA attention microbenchmark at 8k
sequence (quantifying the Pallas kernel win on hardware).

Run as ``python -m hivedscheduler_tpu.models.perf``; prints one JSON object.
``bench.py`` invokes this in a subprocess with a timeout so a dead TPU
tunnel degrades to a skipped stage, never a hung benchmark.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time

# Peak dense bf16 FLOP/s per chip, keyed by device_kind substring
# (public spec sheets; v5e = 197 TFLOPs, v5p = 459, v4 = 275, v6e = 918).
PEAK_BF16 = [
    ("v6e", 918e12),
    ("trillium", 918e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
]


def peak_flops(device_kind: str) -> float | None:
    kind = device_kind.lower()
    for sub, peak in PEAK_BF16:
        if sub in kind:
            return peak
    return None


def mfu_fields(flops_per_token: float, tokens_per_sec: float,
               device_kind: str) -> dict:
    """MFU against the chip's peak, with the physical-plausibility guard.

    An MFU outside (0, 1] means the timing sync failed (e.g. an environment
    where even the host fetch is faked): refuse to publish the number rather
    than report >100% utilization as a result. Shared by the main harness
    and hack/mfu_sweep.py so no publisher skips the guard."""
    peak = peak_flops(device_kind)
    if peak is None:
        return {}
    fields: dict = {"peak_bf16_flops": peak}
    mfu = flops_per_token * tokens_per_sec / peak
    if 0.0 < mfu <= 1.0:
        fields["mfu"] = round(mfu, 4)
    else:
        fields["mfu"] = None
        fields["mfu_rejected"] = round(mfu, 4)
        fields["mfu_rejected_reason"] = (
            "MFU outside (0, 1] — timing sync not trustworthy"
        )
    return fields


# On-chip model presets (HIVED_PERF_MODEL). "268m" is the historical bench
# shape; "800m" is the largest AdamW-f32-master config that fits a 16 GB
# v5e chip: peak HBM ~= 18 B/param (4+4+4 f32 master/mu/nu + 2 bf16
# compute copy + 4 grads — the grad tree is fully live at the end of the
# backward scan) + ~0.9 GB saved activations at batch 1 x seq 8192 under
# the flash remat policy => 795M x 18 B + 0.9 GB ~= 15.2 GB (doc/perf.md
# memory table). GQA (kv_heads=8 vs 16 heads) trims attention params the
# same way the 8B flagship does (llama3_8b uses 32/8).
MODEL_PRESETS = {
    "268m": dict(d_model=1024, n_layers=12, n_heads=8, n_kv_heads=8,
                 d_ff=4096, default_batch=2),
    "800m": dict(d_model=2048, n_layers=12, n_heads=16, n_kv_heads=8,
                 d_ff=6912, default_batch=1),
}


def bench_config(on_tpu: bool, batch: int | None = None,
                 seq: int | None = None):
    """Flagship bench config, env-selectable (``HIVED_PERF_MODEL``: one of
    MODEL_PRESETS, default "268m") with head_dim=128 for MXU/lane
    alignment; a miniature shape off-TPU so CPU smoke runs finish.
    On TPU, ``HIVED_PERF_BATCH``/``HIVED_PERF_SEQ`` override the shape for
    tuning sweeps without code edits, and explicit ``batch``/``seq``
    arguments (the long-context sweep) take precedence over both; the
    off-TPU smoke branch always uses the miniature shape and ignores all
    overrides."""
    import os

    import jax.numpy as jnp

    from . import transformer

    if on_tpu:
        preset = MODEL_PRESETS[os.environ.get("HIVED_PERF_MODEL", "268m")]
        if batch is None:
            batch = int(
                os.environ.get("HIVED_PERF_BATCH",
                               str(preset["default_batch"]))
            )
        if seq is None:
            seq = int(os.environ.get("HIVED_PERF_SEQ", "8192"))
        return transformer.TransformerConfig(
            vocab_size=32768,
            d_model=preset["d_model"],
            n_layers=preset["n_layers"],
            n_heads=preset["n_heads"],
            n_kv_heads=preset["n_kv_heads"],
            d_ff=preset["d_ff"],
            max_seq_len=seq,
            dtype=jnp.bfloat16,
            remat=True,
            # "flash" (pin the flash kernel residuals, remat the rest)
            # measured 1.24x over full remat on-chip; see doc/perf.md.
            remat_policy=os.environ.get("HIVED_PERF_REMAT", "flash"),
        ), batch, seq
    return transformer.TransformerConfig(
        vocab_size=2048,
        d_model=256,
        n_layers=2,
        n_heads=2,
        n_kv_heads=2,
        d_ff=1024,
        max_seq_len=512,
        dtype=jnp.float32,
        remat=False,
    ), 2, 512


def n_params(params) -> int:
    import jax

    return sum(p.size for p in jax.tree.leaves(params))


def flops_per_token(config, n_param: int, seq: int) -> float:
    """6*N for the matmuls (fwd+bwd) + causal attention term
    6 * L * S * d_model (PaLM-style accounting, halved for causality)."""
    return 6.0 * n_param + 6.0 * config.n_layers * seq * config.d_model


def host_sync(out) -> float:
    """Force completion via a device-to-host scalar fetch.

    ``jax.block_until_ready`` is a no-op through the axon TPU tunnel —
    without a real sync a chained 8192^3 bf16 matmul "measures" 43,652
    TFLOP/s on a 197 TFLOP/s chip. The only trustworthy barrier is a value
    dependency fetched to the host: reduce one output leaf on device, then
    ``float()`` it. Programs execute in enqueue order on the chip, so the
    fetch also fences every previously dispatched step.
    """
    import jax
    import jax.numpy as jnp

    leaf = jax.tree.leaves(out)[0]
    return float(jnp.sum(leaf.astype(jnp.float32)))


def time_steps(fn, args, n_steps: int) -> float:
    """Seconds per call, after the caller has warmed up compilation.
    Synced by host fetch of the last output (see ``host_sync``)."""
    t0 = time.perf_counter()
    out = None
    for _ in range(n_steps):
        out = fn(*args)
    host_sync(out)
    return (time.perf_counter() - t0) / n_steps


def bench_train_step(on_tpu: bool, batch: int | None = None,
                     seq: int | None = None) -> dict:
    import jax
    import jax.numpy as jnp

    from . import train, transformer

    config, batch, seq = bench_config(on_tpu, batch=batch, seq=seq)
    params = jax.jit(lambda k: transformer.init(config, k))(
        jax.random.PRNGKey(0)
    )
    n_param = n_params(params)
    optimizer = train.make_optimizer()
    opt_state = jax.jit(optimizer.init)(params)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq), 0, config.vocab_size
    )

    step = jax.jit(
        lambda p, o, t: train.train_step(p, o, t, config, optimizer),
        donate_argnums=(0, 1),
    )
    # Warm-up: compile + one steady-state step; host fetch is the only sync
    # that works through the tunnel (see host_sync).
    params, opt_state, loss = step(params, opt_state, tokens)
    params, opt_state, loss = step(params, opt_state, tokens)
    warm_loss = host_sync(loss)

    n_steps = 8 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(n_steps):
        params, opt_state, loss = step(params, opt_state, tokens)
    final_loss = host_sync(loss)
    dt = (time.perf_counter() - t0) / n_steps

    tps = batch * seq / dt
    out = {
        "model_params_m": round(n_param / 1e6, 1),
        "batch": batch,
        "seq": seq,
        "step_time_ms": round(dt * 1e3, 2),
        "tokens_per_sec_per_chip": round(tps, 1),
        "flops_per_token": flops_per_token(config, n_param, seq),
        "loss": round(final_loss, 4) if math.isfinite(final_loss) else None,
    }
    if not math.isfinite(final_loss):
        # Keep the JSON strict (no bare NaN/Infinity) and surface the
        # divergence instead of hiding it behind the warm-up value.
        out["loss_nonfinite"] = repr(final_loss)
        out["warmup_loss"] = (
            round(warm_loss, 4) if math.isfinite(warm_loss) else None
        )
    return out


def bench_attention(on_tpu: bool) -> dict:
    """fwd+bwd attention at 8k sequence: Pallas flash vs XLA reference."""
    import jax
    import jax.numpy as jnp

    from ..ops import attention as att

    b, s, h, d = (2, 8192, 8, 128) if on_tpu else (1, 512, 2, 64)
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(ks[0], (b, s, h, d), dtype)
    k = jax.random.normal(ks[1], (b, s, h, d), dtype)
    v = jax.random.normal(ks[2], (b, s, h, d), dtype)

    def loss_of(fn):
        return jax.jit(
            jax.grad(lambda q, k, v: fn(q, k, v).astype(jnp.float32).sum())
        )

    out = {"attention_shape": [b, s, h, d]}
    # 3 iterations suffice (spread < 5%); the XLA reference at 8k costs
    # ~0.5 s per fwd+bwd and the whole stage must fit the bench budget.
    n = 3 if on_tpu else 2
    ref = loss_of(lambda q, k, v: att.mha_reference(q, k, v, causal=True))
    host_sync(ref(q, k, v))  # compile
    out["xla_fwd_bwd_ms"] = round(time_steps(ref, (q, k, v), n) * 1e3, 2)
    try:
        # use_pallas is left None so the dispatcher's kill switches
        # (DISABLE_PALLAS / HIVED_DISABLE_PALLAS) stay effective here too.
        flash = loss_of(lambda q, k, v: att.mha(q, k, v, causal=True))
        host_sync(flash(q, k, v))  # compile
        out["flash_fwd_bwd_ms"] = round(
            time_steps(flash, (q, k, v), n) * 1e3, 2
        )
        out["flash_speedup"] = round(
            out["xla_fwd_bwd_ms"] / out["flash_fwd_bwd_ms"], 2
        )
        out["pallas_used"] = bool(
            att.pallas_wanted() and att.pallas_shape_ok(s, s)
        )
    except Exception as exc:  # degrade, never vanish: XLA number stands
        out["pallas_used"] = False
        out["pallas_error"] = f"{type(exc).__name__}: {exc}"[:300]
    return out


def _env_int_csv(name: str, default: str):
    """Parse a comma-separated integer env knob, yielding ``(value, None)``
    per parseable entry and ``(None, error_row)`` per garbage entry — the
    shared degrade-never-crash rule for the optional sweep stages
    (an unparseable entry becomes an error row, never a crash in a run
    that already paid for the headline benches)."""
    for tok in os.environ.get(name, default).split(","):
        if not tok.strip():
            continue
        try:
            yield int(tok), None
        except ValueError:
            yield None, {"error": f"unparseable entry {tok!r} in {name}"}


def _flagship_params(config):
    """The deterministic flagship-model params used by every serving-side
    stage (zoo decode + decode sweep) — one PRNG convention so the stages
    bench the same weights."""
    import jax

    from . import transformer

    return jax.jit(lambda k: transformer.init(config, k))(
        jax.random.PRNGKey(5)
    )


def bench_long_context(on_tpu: bool) -> list:
    """Optional (HIVED_PERF_LONGCTX=1): train-step rows at 16k and 32k
    tokens of context (batch 1), demonstrating the O(block)-VMEM flash
    kernels hold MFU as sequence grows — the long-context claim measured,
    not asserted. Reuses bench_train_step (explicit batch/seq arguments)
    so every row goes through the identical measurement path.
    HIVED_PERF_LONGCTX_SEQS (comma-separated) overrides the sweep points,
    e.g. "16384,32768,65536" for a 64k row; unparseable entries become
    error rows rather than crashing a run that already paid for the
    headline benches."""
    import jax

    kind = getattr(jax.devices()[0], "device_kind", "")
    rows = []
    for seq, bad in _env_int_csv("HIVED_PERF_LONGCTX_SEQS", "16384,32768"):
        if bad is not None:
            rows.append(bad)
            continue
        try:
            row = bench_train_step(on_tpu, batch=1, seq=seq)
            fields = mfu_fields(
                row["flops_per_token"],
                row["tokens_per_sec_per_chip"],
                kind,
            )
            row.update(fields)
            if fields.get("mfu") is not None:
                # Drop the derivable input only when MFU was actually
                # computed; on an unrecognized device kind the raw
                # flops/token is the only field MFU could later be
                # derived from.
                row.pop("flops_per_token", None)
        except Exception as exc:  # optional: degrade, never crash
            row = {"seq": seq,
                   "error": f"{type(exc).__name__}: {exc}"[:300]}
        rows.append(row)
    return rows


def bench_decode_sweep(on_tpu: bool) -> list:
    """Optional (HIVED_PERF_DECODE=1): serving decode throughput vs batch
    size on the flagship model. Single-chip decode is HBM-bandwidth-bound
    (every token step re-reads all the weights), so aggregate tokens/sec
    should scale near-linearly with batch until KV-cache reads take over —
    this sweep is the measured version of that claim, and the large-batch
    row is the chip's real serving throughput (the zoo's batch-8 row
    mostly measures weight-read amortized over too few requests).

    Methodology: times the one-dispatch ``generate_greedy_scan`` at two
    generation lengths and reports the MARGINAL per-token cost
    ``(t_long - t_short) / (n_long - n_short)`` — the prefill cost and the
    single host dispatch are identical in both and cancel exactly, so the
    row is pure steady-state decode speed even through a high-latency
    tunnel. Each length is timed twice and the min taken (dispatch jitter
    is one-sided). HIVED_PERF_DECODE_BATCHES overrides the sweep points;
    unparseable or failing rows degrade to error rows."""
    import jax

    from . import generate

    config, _, _ = bench_config(on_tpu)
    params = _flagship_params(config)
    prompt_len = 128 if on_tpu else 16
    n_short, n_long = (16, 80) if on_tpu else (2, 6)

    def marginal_row(p, batch, extra=None):
        """One sweep row: marginal steady-state decode cost for ``p``
        (fp or int8 weights) at ``batch``."""
        try:
            prompt = jax.random.randint(
                jax.random.PRNGKey(6), (batch, prompt_len), 0,
                config.vocab_size,
            )
            best = {}
            for n_new in (n_short, n_long):
                seq = generate.generate_greedy_scan(
                    p, prompt, config, max_new_tokens=n_new
                )
                host_sync(seq)  # compile
                for _ in range(2):
                    t0 = time.perf_counter()
                    seq = generate.generate_greedy_scan(
                        p, prompt, config, max_new_tokens=n_new
                    )
                    host_sync(seq)
                    dt = time.perf_counter() - t0
                    best[n_new] = min(best.get(n_new, dt), dt)
            marginal = (best[n_long] - best[n_short]) / (n_long - n_short)
            if marginal <= 0:  # jitter swamped the 64-step delta
                return {"batch": batch,
                        "error": "non-positive marginal step time "
                                 "(host timing jitter)", **(extra or {})}
            return {
                "batch": batch,
                "decode_ms_per_token": round(marginal * 1e3, 3),
                "tokens_per_sec": round(batch / marginal, 1),
                **(extra or {}),
            }
        except Exception as exc:  # optional: degrade, never crash
            return {"batch": batch,
                    "error": f"{type(exc).__name__}: {exc}"[:300],
                    **(extra or {})}

    rows, batches = [], []
    for batch, bad in _env_int_csv("HIVED_PERF_DECODE_BATCHES", "8,32,64"):
        if bad is not None:
            rows.append(bad)
            continue
        batches.append(batch)
        rows.append(marginal_row(params, batch))
    if batches and os.environ.get("HIVED_PERF_DECODE_INT8", "1") == "1":
        # Int8-quantized weights at the largest sweep batch: the
        # weight-HBM half of the decode roofline measured against the fp
        # row above (models/quantize.py).
        from . import quantize

        rows.append(marginal_row(
            quantize.quantize_params(params), max(batches),
            extra={"int8": True},
        ))

    # Time-to-first-token at a long prompt: prefill dispatches its causal
    # self-attention to the flash kernels (generate._block_cached), which
    # measured ~2x on the whole prefill at 8k on-chip vs the einsum path.
    try:
        pbatch, plen = (2, 8192) if on_tpu else (2, 64)
        prompt = jax.random.randint(
            jax.random.PRNGKey(7), (pbatch, plen), 0, config.vocab_size
        )
        best = None
        cache = generate.init_cache(config, pbatch, plen + 64)
        logits, _ = generate.prefill(params, prompt, cache, config)
        host_sync(logits)  # compile + warm
        for _ in range(3):
            cache = generate.init_cache(config, pbatch, plen + 64)
            t0 = time.perf_counter()
            logits, _ = generate.prefill(params, prompt, cache, config)
            host_sync(logits)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        rows.append({
            "batch": pbatch,
            "prefill_len": plen,
            "prefill_ms": round(best * 1e3, 1),
            "prefill_tokens_per_sec": round(pbatch * plen / best, 1),
        })
    except Exception as exc:  # optional: degrade, never crash
        rows.append({"prefill_len": plen,
                     "error": f"{type(exc).__name__}: {exc}"[:300]})
    return rows


def bench_zoo(on_tpu: bool) -> dict:
    """Optional (HIVED_PERF_ZOO=1): one-chip step timings for the other
    model families — BERT-large MLM train step, ResNet-50 train step, and
    flagship decode throughput — evidence the whole zoo runs on hardware,
    not just the flagship."""
    import jax
    import jax.numpy as jnp
    import optax

    out = {}
    n = 4 if on_tpu else 2

    from . import bert as bert_mod

    bconfig = bert_mod.bert_large() if on_tpu else bert_mod.tiny()
    bbatch, bseq = (8, 512) if on_tpu else (2, 64)
    bparams = jax.jit(lambda k: bert_mod.init(bconfig, k))(jax.random.PRNGKey(0))
    bopt = optax.adamw(1e-4)
    bstate = jax.jit(bopt.init)(bparams)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (bbatch, bseq), 0, bconfig.vocab_size
    )
    mask = jax.random.bernoulli(jax.random.PRNGKey(2), 0.15, (bbatch, bseq))

    @jax.jit
    def bert_step(p, s, t, m):
        loss, grads = jax.value_and_grad(bert_mod.mlm_loss)(p, t, m, bconfig)
        updates, s = bopt.update(grads, s, p)
        return optax.apply_updates(p, updates), s, loss

    bparams, bstate, bloss = bert_step(bparams, bstate, tokens, mask)
    host_sync(bloss)
    t0 = time.perf_counter()
    for _ in range(n):
        bparams, bstate, bloss = bert_step(bparams, bstate, tokens, mask)
    host_sync(bloss)
    bdt = (time.perf_counter() - t0) / n
    out["bert_large_step_ms"] = round(bdt * 1e3, 2)
    out["bert_tokens_per_sec"] = round(bbatch * bseq / bdt, 1)

    from . import resnet as resnet_mod

    rconfig = resnet_mod.ResNetConfig()
    rbatch, rsize = (64, 224) if on_tpu else (2, 32)
    rparams, rstats = resnet_mod.init(rconfig, jax.random.PRNGKey(0))
    ropt = optax.sgd(0.1, momentum=0.9)
    rstate = jax.jit(ropt.init)(rparams)
    images = jax.random.normal(
        jax.random.PRNGKey(3), (rbatch, rsize, rsize, 3), jnp.bfloat16
    )
    labels = jax.random.randint(
        jax.random.PRNGKey(4), (rbatch,), 0, rconfig.num_classes
    )

    @jax.jit
    def resnet_step(p, stats, s, x, y):
        (loss, stats), grads = jax.value_and_grad(
            resnet_mod.loss_fn, has_aux=True
        )(p, stats, x, y, rconfig, train=True)
        updates, s = ropt.update(grads, s)
        return optax.apply_updates(p, updates), stats, s, loss

    rparams, rstats, rstate, rloss = resnet_step(
        rparams, rstats, rstate, images, labels
    )
    host_sync(rloss)
    t0 = time.perf_counter()
    for _ in range(n):
        rparams, rstats, rstate, rloss = resnet_step(
            rparams, rstats, rstate, images, labels
        )
    host_sync(rloss)
    rdt = (time.perf_counter() - t0) / n
    out["resnet50_step_ms"] = round(rdt * 1e3, 2)
    out["resnet50_images_per_sec"] = round(rbatch / rdt, 1)

    from . import generate

    gconfig, _, _ = bench_config(on_tpu)
    gparams = _flagship_params(gconfig)
    gbatch, prompt_len, new_tokens = (8, 128, 32) if on_tpu else (2, 16, 8)
    prompt = jax.random.randint(
        jax.random.PRNGKey(6), (gbatch, prompt_len), 0, gconfig.vocab_size
    )
    cache = generate.init_cache(gconfig, gbatch, prompt_len + new_tokens + 1)
    logits, cache = generate.prefill(gparams, prompt, cache, gconfig)
    token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    # Warm the decode_step compile, then time the steady-state loop.
    logits, cache = generate.decode_step(gparams, token, cache, gconfig)
    host_sync(logits)
    t0 = time.perf_counter()
    for _ in range(new_tokens):
        logits, cache = generate.decode_step(gparams, token, cache, gconfig)
    host_sync(logits)
    gdt = (time.perf_counter() - t0) / new_tokens
    out["decode_step_ms"] = round(gdt * 1e3, 2)
    out["decode_tokens_per_sec"] = round(gbatch / gdt, 1)

    # Whole-sequence scan decode: one dispatch for prefill + all steps —
    # isolates per-call dispatch overhead from on-chip decode speed.
    seq = generate.generate_greedy_scan(
        gparams, prompt, gconfig, max_new_tokens=new_tokens
    )
    host_sync(seq)  # compile
    t0 = time.perf_counter()
    seq = generate.generate_greedy_scan(
        gparams, prompt, gconfig, max_new_tokens=new_tokens
    )
    host_sync(seq)
    sdt = (time.perf_counter() - t0) / new_tokens
    out["decode_scan_step_ms"] = round(sdt * 1e3, 2)
    out["decode_scan_tokens_per_sec"] = round(gbatch / sdt, 1)
    return out


def artifact_path(model: str | None = None) -> str:
    """Where successful on-chip runs are persisted. Lives under
    example/logs/ next to the human-readable perf session logs, so the
    provenance chain is one directory. Non-default model presets get
    their own file (perf_last_measured_800m.json) so a sizing run never
    overwrites the headline-shape measurement bench.py re-emits on skip
    — this function is the single owner of that naming rule.

    ``model=None`` resolves the CURRENT run's artifact: the
    ``HIVED_PERF_MODEL`` preset, with ``HIVED_PERF_ARTIFACT`` overriding
    the whole path. An explicit ``model`` names that preset's default
    artifact (a cross-model lookup — e.g. bench.py attaching the 800m
    sizing measurement), which the env override deliberately does NOT
    redirect."""
    import os

    override = os.environ.get("HIVED_PERF_ARTIFACT") if model is None else None
    if override:
        return override
    if model is None:
        model = os.environ.get("HIVED_PERF_MODEL", "268m")
    name = (
        "perf_last_measured.json" if model == "268m"
        else f"perf_last_measured_{model}.json"
    )
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        "example", "logs", name,
    )


# The optional, env-gated measurement stages that persist_result carries
# forward across runs and bench.py re-attaches to live results — ONE
# definition so the artifact's writer and reader can never drift.
CARRY_STAGES = ("long_context", "zoo", "decode_sweep")


def carried_provenance(record: dict, stage: str) -> dict:
    """The TRUE origin provenance for ``stage`` rows in a persisted
    artifact: the artifact's ``carried_forward`` marker when it names the
    stage (tolerating the legacy list format, which recorded only stage
    names, no provenance), else the artifact's top-level provenance.
    Shared by persist_result's carry-forward and bench.py's
    ``_merge_carried`` so the two consumers of the artifact format can
    never diverge. Stdlib-only, like everything at this module's top
    level."""
    marker = record.get("carried_forward")
    if isinstance(marker, dict) and stage in marker:
        return marker[stage]
    return record.get("provenance", {})


def stage_rows_clean(val):
    """The single cleaning rule for an optional stage's value: a list
    keeps only its clean rows (None when none survive); a whole-stage
    error dict is None; anything else is already clean. Both the artifact
    writer (persist_result) and reader (bench's merge) define "stage
    effectively present" through this function."""
    if isinstance(val, list):
        clean = [r for r in val
                 if "error" not in r and "mfu_rejected" not in r]
        return clean or None
    if isinstance(val, dict) and "error" in val:
        return None
    return val


def attach_carried(dst: dict, src: dict, stage: str) -> None:
    """Copy ``src``'s rows for ``stage`` into ``dst`` and record the
    dict-format ``carried_forward`` marker pointing at the TRUE origin's
    provenance (normalizing a legacy list-format marker on ``dst`` away
    rather than crashing on it)."""
    dst[stage] = src[stage]
    cf = dst.get("carried_forward")
    marker = dict(cf) if isinstance(cf, dict) else {}
    marker[stage] = carried_provenance(src, stage)
    dst["carried_forward"] = marker


def persist_result(result: dict, on_tpu: bool) -> None:
    """Persist a successful on-chip measurement (atomically) so bench.py can
    emit it inline as ``last_measured`` whenever the live TPU path is later
    unreachable — four rounds of builder-log-only perf evidence is the gap
    this closes. CPU smoke runs, failed runs, and DEGRADED runs never
    overwrite a real measurement: an XLA-fallback run (in-process fallback
    or the kill switches — e.g. bench.py's HIVED_DISABLE_PALLAS salvage
    retry) or a rejected-MFU run (untrustworthy timing sync) is far off
    the flash numbers and must not replace them as the cached evidence.
    The optional stages degrade PER ROW (error dicts), so they get the
    same treatment at their own granularity: degraded long_context rows /
    a failed zoo are dropped from the new record, carrying forward the
    previous artifact's good rows for that stage instead — a headline
    success with a failed sweep must not destroy cached sweep evidence.
    Best-effort: persistence failure must not fail the run."""
    import os
    import subprocess

    from ..ops import attention as att

    if not on_tpu or "tokens_per_sec_per_chip" not in result:
        return
    if (
        "attention_fallback" in result
        or "mfu_rejected" in result
        or not att.pallas_wanted()
    ):
        return
    try:
        commit = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        ).stdout.strip() or None
    except Exception:
        commit = None
    path = artifact_path()
    try:
        with open(path) as f:
            prev = json.load(f)
    except (OSError, json.JSONDecodeError):
        prev = {}
    record = {
        **result,
        "provenance": {
            "measured_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "git_commit": commit,
            "recorded_by": "hivedscheduler_tpu.models.perf",
            "env_overrides": {
                k: v for k, v in os.environ.items()
                if k.startswith(("HIVED_PERF_", "HIVED_FLASH_",
                                 "HIVED_DISABLE_"))
            },
        },
    }
    for stage in CARRY_STAGES:
        if stage in record:
            clean = stage_rows_clean(record[stage])
            if clean is None:
                record.pop(stage)
            else:
                record[stage] = clean
        if stage not in record and stage in prev:
            # Carry the previous artifact's rows forward under the TRUE
            # origin's provenance — the new record's top-level provenance
            # must not claim old rows were measured under this run's
            # commit/env.
            attach_carried(record, prev, stage)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(record, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    except OSError:
        pass


def main() -> None:
    import os

    import jax

    dev = jax.devices()[0]
    backend = jax.default_backend()
    kind = getattr(dev, "device_kind", "")
    on_tpu = backend not in ("cpu",)

    result = {"backend": backend, "device_kind": kind}
    from ..ops import attention as att

    try:
        train_res = bench_train_step(on_tpu)
    except Exception as exc:
        first_error = f"{type(exc).__name__}: {exc}"[:300]
        if not att.pallas_wanted():
            # Pallas was already off — retrying cannot help; report the
            # failure as data (exit 0) so the caller does not burn another
            # full run on an identical failure.
            print(json.dumps({**result, "train_error": first_error}))
            return
        # Degrade, never vanish: retry the whole train step with the Pallas
        # path disabled so a kernel regression still yields a (slower,
        # tagged) tokens/sec number instead of an empty benchmark.
        att.DISABLE_PALLAS = True
        try:
            train_res = bench_train_step(on_tpu)
        except Exception as exc2:
            # Both paths failed: the cause is not the Pallas kernels.
            # Report instead of crashing, so the caller's subprocess-level
            # HIVED_DISABLE_PALLAS retry (which exists for hard crashes
            # the in-process fallback cannot catch) is not triggered for a
            # failure that retrying cannot fix.
            print(json.dumps({
                **result,
                "train_error": first_error,
                "train_error_no_pallas": f"{type(exc2).__name__}: {exc2}"[:300],
            }))
            return
        train_res["attention_fallback"] = "xla"
        train_res["attention_fallback_reason"] = first_error
    result.update(train_res)
    result.update(
        mfu_fields(
            train_res["flops_per_token"],
            train_res["tokens_per_sec_per_chip"],
            kind,
        )
    )
    result.update(bench_attention(on_tpu))
    if (
        os.environ.get("HIVED_PERF_LONGCTX", "0") == "1"
        and "attention_fallback" not in train_res
        and att.pallas_wanted()
    ):
        # The sweep is flash-kernel evidence; on the XLA fallback its
        # quadratic-cost steps (~11 s/step at 8k, ~4x/16x at 16k/32k)
        # would blow the caller's subprocess timeout and erase the
        # salvaged headline number.
        try:
            result["long_context"] = bench_long_context(on_tpu)
        except Exception as exc:  # optional stage: degrade, never crash
            result["long_context"] = {
                "error": f"{type(exc).__name__}: {exc}"[:300]
            }
    if os.environ.get("HIVED_PERF_ZOO", "0") == "1":
        try:
            result["zoo"] = bench_zoo(on_tpu)
        except Exception as exc:  # optional stage: degrade, never crash
            result["zoo"] = {"error": f"{type(exc).__name__}: {exc}"[:300]}
    if os.environ.get("HIVED_PERF_DECODE", "0") == "1":
        try:
            result["decode_sweep"] = bench_decode_sweep(on_tpu)
        except Exception as exc:  # optional stage: degrade, never crash
            result["decode_sweep"] = {
                "error": f"{type(exc).__name__}: {exc}"[:300]
            }
    persist_result(result, on_tpu)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
