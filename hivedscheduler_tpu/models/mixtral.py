"""Mixtral-style sparse-MoE decoder with expert parallelism (BASELINE
config 5: Mixtral 8x7B expert-parallel on a pinned-cell VC).

GShard-style static-shape MoE, the TPU-native formulation: top-2 routing is
expressed as dense one-hot dispatch/combine einsums against a fixed expert
capacity — no dynamic shapes, no sort; everything lands on the MXU and the
``ep``-sharded expert dim turns the dispatch einsum into an XLA all-to-all
over ICI. Attention/RoPE/norms are shared with models/transformer.py.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..parallel import sharding
from .transformer import rms_norm, rope

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    n_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    max_seq_len: int = 8192
    rope_theta: float = 1000000.0
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # Sequence-parallel backend when the mesh has sp > 1 (see
    # parallel/sharding.sp_attention): auto | ring | ulysses.
    sp_mode: str = "auto"
    # Part of the shared decode-config contract (generate._forward_cached);
    # Mixtral ships untied heads.
    tied_embeddings: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def mixtral_8x7b() -> MixtralConfig:
    return MixtralConfig()


def tiny(vocab: int = 512) -> MixtralConfig:
    return MixtralConfig(
        vocab_size=vocab,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=128,
        n_experts=4,
        experts_per_token=2,
        max_seq_len=256,
        rope_theta=10000.0,
        dtype=jnp.float32,
        remat=False,
    )


def init(config: MixtralConfig, key: jax.Array) -> Params:
    c = config
    d, h, hk, dh, f, L, E = (
        c.d_model, c.n_heads, c.n_kv_heads, c.head_dim, c.d_ff, c.n_layers,
        c.n_experts,
    )
    ks = jax.random.split(key, 10)

    def norm(k, fan_in, shape):
        return jax.random.normal(k, shape, dtype=jnp.float32) / jnp.sqrt(fan_in)

    return {
        "embed": norm(ks[0], 1, (c.vocab_size, d)),
        "layers": {
            "ln1": jnp.ones((L, d), jnp.float32),
            "wq": norm(ks[1], d, (L, d, h * dh)),
            "wk": norm(ks[2], d, (L, d, hk * dh)),
            "wv": norm(ks[3], d, (L, d, hk * dh)),
            "wo": norm(ks[4], h * dh, (L, h * dh, d)),
            "ln2": jnp.ones((L, d), jnp.float32),
            "router": norm(ks[5], d, (L, d, E)),
            "w_gate": norm(ks[6], d, (L, E, d, f)),
            "w_up": norm(ks[7], d, (L, E, d, f)),
            "w_down": norm(ks[8], f, (L, E, f, d)),
        },
        "ln_f": jnp.ones((d,), jnp.float32),
        "lm_head": norm(ks[9], d, (d, c.vocab_size)),
    }


def logical_axes(config: MixtralConfig) -> Params:
    return {
        "embed": ("vocab", "embed"),
        "layers": {
            "ln1": ("layers", None),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "ln2": ("layers", None),
            "router": ("layers", "embed", None),
            # Experts shard over ep; within an expert, tp shards the ffn.
            "w_gate": ("layers", "expert", "embed", "mlp"),
            "w_up": ("layers", "expert", "embed", "mlp"),
            "w_down": ("layers", "expert", "mlp", "embed"),
        },
        "ln_f": (None,),
        "lm_head": ("embed", "vocab"),
    }


def moe_ffn(
    h: jax.Array,  # [B, S, D]
    layer: Params,
    config: MixtralConfig,
) -> Tuple[jax.Array, jax.Array]:
    """Top-2 routed expert FFN; returns (out [B,S,D], aux_loss).

    Static-shape dispatch: tokens -> [E, C] slots via one-hot einsums
    (GShard). Tokens over capacity are dropped (their combine weight is 0);
    the aux load-balancing loss pushes the router toward uniform load.
    """
    c = config
    b, s, d = h.shape
    E, K = c.n_experts, c.experts_per_token
    T = b * s
    capacity = max(K, int(math.ceil(K * T / E * c.capacity_factor)))

    x = h.reshape(T, d)
    router_logits = (x @ layer["router"]).astype(jnp.float32)  # [T, E]
    gates = jax.nn.softmax(router_logits, axis=-1)

    # Iteratively pick top-K experts per token, assigning capacity positions
    # expert-by-expert so earlier tokens win slots (deterministic). Each
    # round's positions start AFTER the expert's occupancy from previous
    # rounds (GShard: position_in_expert_2 += sum(mask1) per expert) —
    # otherwise round-2 tokens collide with round-1 slots.
    combine = jnp.zeros((T, E, capacity), dtype=jnp.float32)
    remaining = gates
    expert_occupancy = jnp.zeros((E,), dtype=jnp.float32)
    aux_me = jnp.zeros((E,), dtype=jnp.float32)
    aux_ce = jnp.zeros((E,), dtype=jnp.float32)
    for _ in range(K):
        idx = jnp.argmax(remaining, axis=-1)  # [T]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # [T, E]
        gate_k = jnp.sum(gates * onehot, axis=-1)  # [T]
        # Position of each token within its chosen expert's capacity.
        pos = (jnp.cumsum(onehot, axis=0) - onehot) + expert_occupancy[None, :]
        pos_in_expert = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)
        fits = pos_in_expert < capacity
        slot = jax.nn.one_hot(pos_in_expert, capacity, dtype=jnp.float32)
        combine = combine + (
            onehot[:, :, None] * slot[:, None, :] *
            (gate_k * fits)[:, None, None]
        )
        expert_occupancy = expert_occupancy + jnp.sum(onehot, axis=0)
        aux_me = aux_me + jnp.mean(gates * onehot, axis=0)
        aux_ce = aux_ce + jnp.mean(onehot, axis=0)
        remaining = remaining * (1.0 - onehot)

    # Load-balancing loss (Switch/GShard): E * sum(me * ce), K-normalized.
    aux_loss = E * jnp.sum(aux_me * aux_ce) / (K * K)

    dispatch = (combine > 0.0).astype(h.dtype)  # [T, E, C]
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)  # [E, C, D]
    expert_in = sharding.constrain(expert_in, "expert", None, None)
    gate = jax.nn.silu(
        jnp.einsum("ecd,edf->ecf", expert_in, layer["w_gate"])
    )
    up = jnp.einsum("ecd,edf->ecf", expert_in, layer["w_up"])
    expert_out = jnp.einsum("ecf,efd->ecd", gate * up, layer["w_down"])
    expert_out = sharding.constrain(expert_out, "expert", None, None)
    out = jnp.einsum(
        "tec,ecd->td", combine.astype(h.dtype), expert_out
    )  # [T, D]
    # Renormalize top-K gate mass (Mixtral normalizes the K gates to sum 1).
    denom = jnp.sum(combine, axis=(1, 2)).astype(h.dtype)  # [T]
    out = out / jnp.maximum(denom, 1e-9)[:, None]
    return out.reshape(b, s, d), aux_loss


def _block(x, layer, config, mesh, use_sp):
    c = config
    b, s, d = x.shape
    h = rms_norm(x, layer["ln1"])
    q = (h @ layer["wq"]).reshape(b, s, c.n_heads, c.head_dim)
    k = (h @ layer["wk"]).reshape(b, s, c.n_kv_heads, c.head_dim)
    v = (h @ layer["wv"]).reshape(b, s, c.n_kv_heads, c.head_dim)
    positions = jnp.arange(s)
    q = rope(q, positions, c.rope_theta)
    k = rope(k, positions, c.rope_theta)
    if use_sp:
        attn = sharding.sp_attention(
            q, k, v, mesh, causal=True, sp_mode=c.sp_mode
        )
    else:
        attn = sharding.sharded_mha(q, k, v, mesh, causal=True)
    x = x + attn.reshape(b, s, d) @ layer["wo"]

    h = rms_norm(x, layer["ln2"])
    moe_out, aux = moe_ffn(h, layer, c)
    return x + moe_out, aux


def forward(
    params: Params,
    tokens: jax.Array,
    config: MixtralConfig,
    mesh: Optional[Mesh] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (logits [B,S,V], total aux load-balancing loss)."""
    c = config
    sharding.validate_sp_mode(c.sp_mode)
    if mesh is not None and mesh.shape.get("pp", 1) > 1:
        # Mixtral's forward is a plain lax.scan — it never pipelines. With
        # DEFAULT_RULES mapping "layers" -> "pp", a pp>1 mesh would silently
        # shard the stacked layer params over pp and force a cross-stage
        # gather every layer: correct numerics, pathological performance.
        # Scale Mixtral over ep instead (parallel/pipeline.py docstring).
        raise NotImplementedError(
            "mixtral.forward does not pipeline; use ep (expert) parallelism "
            f"instead of pp (mesh has pp={mesh.shape['pp']})"
        )
    use_sp = mesh is not None and mesh.shape.get("sp", 1) > 1
    params = jax.tree.map(lambda a: a.astype(c.dtype), params)
    x = params["embed"][tokens]
    x = sharding.constrain(x, "batch", "seq", "act_embed")

    def block(x, layer):
        y, aux = _block(x, layer, c, mesh, use_sp)
        return y, aux

    if c.remat:
        block = jax.checkpoint(block)
    x, aux_losses = jax.lax.scan(block, x, params["layers"])

    x = rms_norm(x, params["ln_f"])
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, jnp.sum(aux_losses)


@functools.lru_cache(maxsize=None)
def decode_ffn(config: MixtralConfig):
    """FFN hook for the shared KV-cache decode machinery
    (``generate.prefill/decode_step``'s ``ffn`` parameter): the routed MoE
    layer applied to the current token(s); the aux load-balancing loss is
    a training-only signal and is dropped. Cached per config so the jitted
    decode functions see ONE static hook object (a fresh closure per call
    would retrace).

    Inference note: expert capacity scales with the visible token count
    (GShard batched-capacity semantics), so a decode step's capacity is
    computed over the step's B tokens — raise ``capacity_factor`` if
    routing collisions at tiny decode batches matter."""

    def ffn(h: jax.Array, layer: Params) -> jax.Array:
        out, _ = moe_ffn(h, layer, config)
        return out

    return ffn


def lm_loss(
    params: Params,
    tokens: jax.Array,
    config: MixtralConfig,
    mesh: Optional[Mesh] = None,
    aux_weight: float = 0.01,
) -> jax.Array:
    logits, aux = forward(params, tokens, config, mesh)
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    ll = jnp.take_along_axis(logp, tokens[:, 1:][..., None], axis=-1)[..., 0]
    return -jnp.mean(ll) + aux_weight * aux
