"""Llama-style decoder-only transformer, TPU-first.

The flagship workload for BASELINE configs 3-4 (BERT-large reuses the
encoder-ized blocks, Llama-3-8B is the ``llama3_8b`` preset). The reference
repo schedules such jobs but contains no model code (SURVEY.md §2.2); this is
the jax.distributed workload the scheduler's bind-time env boots.

TPU design choices:
  - params and compute in bf16 (MXU-native), softmax/layernorm accumulate in
    f32; the optimizer keeps f32 master state (models/train.py).
  - one ``lax.scan`` over stacked layer params: O(1) compile time in depth.
  - ``jax.checkpoint`` per block: activations rematerialized in backward,
    trading MXU FLOPs for HBM (the usual TPU bottleneck).
  - GQA (n_kv_heads < n_heads) shrinks KV cache/bandwidth.
  - parallelism is all declarative: logical axis names on every param
    (``logical_axes``) + sharding constraints on activations; the mesh rule
    table (parallel/sharding.py) decides dp/fsdp/sp/tp. When the mesh has
    sp > 1, sequence parallelism engages: Ulysses all-to-all
    (parallel/ulysses.py) where head counts divide, ring attention
    (parallel/ring.py) otherwise — see ``TransformerConfig.sp_mode``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..ops.attention import mha
from ..parallel import pipeline, sharding

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # "full": recompute everything in backward (min memory).
    # "dots": save matmul (MXU) outputs, recompute only elementwise — less
    # recompute FLOPs for ~b*s*(d+d_ff) extra bytes per layer.
    # "flash": save only the flash-attention kernel residuals (bf16 out +
    # thin f32 lse, named in ops/attention._flash_fwd) — the backward then
    # skips the whole pallas forward recompute (the block's most expensive
    # piece) for ~2*b*s*d_model extra bytes per layer; everything else
    # remats.
    # "dots+flash": both of the above.
    remat_policy: str = "full"
    tied_embeddings: bool = False
    # Sequence-parallel backend when the mesh has sp > 1 (see
    # parallel/sharding.sp_attention): "auto" picks Ulysses all-to-all when
    # legal AND the flash kernels will run locally (lower traffic), ring
    # attention otherwise; "ring"/"ulysses" force one.
    sp_mode: str = "auto"
    # GPipe microbatch count when the mesh has pp > 1 (parallel/pipeline.py);
    # None = the largest divisor of batch <= 2*pp (pipeline_blocks), which
    # can be smaller than 2*pp — e.g. batch=10, pp=4 gives M=5, not 8.
    # The bubble is (pp-1)/(M+pp-1) of step time.
    pp_microbatches: Optional[int] = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def llama3_8b() -> TransformerConfig:
    """Llama-3-8B shapes (BASELINE config 4)."""
    return TransformerConfig(
        vocab_size=128256,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        d_ff=14336,
        max_seq_len=8192,
        rope_theta=500000.0,
    )


def tiny(vocab: int = 512) -> TransformerConfig:
    """Small config for tests / compile checks."""
    return TransformerConfig(
        vocab_size=vocab,
        d_model=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=256,
        max_seq_len=512,
        rope_theta=10000.0,
        dtype=jnp.float32,
        remat=False,
    )


def init(config: TransformerConfig, key: jax.Array) -> Params:
    """Stacked-layer param tree ([n_layers, ...] leading dim for lax.scan)."""
    c = config
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    d, h, hk, dh, f, L = (
        c.d_model, c.n_heads, c.n_kv_heads, c.head_dim, c.d_ff, c.n_layers,
    )

    # Master params stay f32 (the optimizer needs them); forward casts to
    # config.dtype (bf16 on TPU) per step.
    def norm(k, fan_in, shape):
        return jax.random.normal(k, shape, dtype=jnp.float32) / jnp.sqrt(fan_in)

    ks = jax.random.split(k_layers, 7)
    params: Params = {
        "embed": norm(k_embed, 1, (c.vocab_size, d)),
        "layers": {
            "ln1": jnp.ones((L, d), dtype=jnp.float32),
            "wq": norm(ks[0], d, (L, d, h * dh)),
            "wk": norm(ks[1], d, (L, d, hk * dh)),
            "wv": norm(ks[2], d, (L, d, hk * dh)),
            "wo": norm(ks[3], h * dh, (L, h * dh, d)),
            "ln2": jnp.ones((L, d), dtype=jnp.float32),
            "w_gate": norm(ks[4], d, (L, d, f)),
            "w_up": norm(ks[5], d, (L, d, f)),
            "w_down": norm(ks[6], f, (L, f, d)),
        },
        "ln_f": jnp.ones((d,), dtype=jnp.float32),
    }
    if not c.tied_embeddings:
        params["lm_head"] = norm(k_head, d, (d, c.vocab_size))
    return params


def logical_axes(config: TransformerConfig) -> Params:
    """Logical dim names per param; parallel/sharding.py maps them to mesh
    axes (embed->fsdp for ZeRO-3, heads/mlp/vocab->tp)."""
    axes: Params = {
        "embed": ("vocab", "embed"),
        "layers": {
            "ln1": ("layers", None),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "ln2": ("layers", None),
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        },
        "ln_f": (None,),
    }
    if not config.tied_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def rms_norm(x: jax.Array, scale: jax.Array, eps: float = 1e-5) -> jax.Array:
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps)).astype(x.dtype) * scale


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embeddings; x: [B, S, H, D], positions: [S]."""
    d = x.shape[-1]
    freqs = 1.0 / (
        theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    )  # [D/2]
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [S, D/2]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _block(
    x: jax.Array,
    layer: Params,
    config: TransformerConfig,
    mesh: Optional[Mesh],
    use_sp: bool,
    sp_manual: bool = False,
) -> jax.Array:
    """One pre-norm block. ``sp_manual``: the block is being traced inside a
    shard_map that is already manual over the sp axis (the pp x sp pipeline,
    parallel/pipeline.py seq_axis) — x is the LOCAL sequence shard, so rope
    positions offset by the shard index, attention dispatches through
    sp_attention_manual (the backends' local collectives — a nested sp
    shard_map would be illegal), and sharding constraints that mention the
    now-manual seq axis are skipped (weight shardings still drive the
    auto-axes partitioning)."""
    c = config
    b, s, d = x.shape
    con = (lambda t, *axes: t) if sp_manual else sharding.constrain

    h = rms_norm(x, layer["ln1"])
    q = (h @ layer["wq"]).reshape(b, s, c.n_heads, c.head_dim)
    k = (h @ layer["wk"]).reshape(b, s, c.n_kv_heads, c.head_dim)
    v = (h @ layer["wv"]).reshape(b, s, c.n_kv_heads, c.head_dim)
    if sp_manual:
        positions = jax.lax.axis_index("sp") * s + jnp.arange(s)
    else:
        positions = jnp.arange(s)
    q = rope(q, positions, c.rope_theta)
    k = rope(k, positions, c.rope_theta)
    q = con(q, "batch", "seq", "heads", None)
    k = con(k, "batch", "seq", "kv_heads", None)
    v = con(v, "batch", "seq", "kv_heads", None)
    if sp_manual:
        attn = sharding.sp_attention_manual(
            q, k, v, mesh, causal=True, sp_mode=c.sp_mode
        )
    elif use_sp:
        assert mesh is not None
        attn = sharding.sp_attention(
            q, k, v, mesh, causal=True, sp_mode=c.sp_mode
        )
    else:
        # Pallas flash kernels on TPU (shard_map-wrapped under a mesh,
        # since GSPMD cannot partition a pallas_call); XLA reference off-TPU.
        attn = sharding.sharded_mha(q, k, v, mesh, causal=True)
    attn = attn.reshape(b, s, c.n_heads * c.head_dim)
    x = x + con(attn @ layer["wo"], "batch", "seq", "act_embed")

    h = rms_norm(x, layer["ln2"])
    gate = jax.nn.silu(h @ layer["w_gate"])
    up = h @ layer["w_up"]
    ffn = (gate * up) @ layer["w_down"]
    return x + con(ffn, "batch", "seq", "act_embed")


def _remat_policy(name: str):
    """Map a config's remat_policy string to a jax.checkpoint policy.
    On paths without the flash kernels (XLA fallback, ring attention) the
    "flash" names simply never appear, degrading to full remat — correct,
    just without the saved-residual speedup."""
    p = jax.checkpoint_policies
    flash = p.save_only_these_names("flash_out", "flash_lse")
    policies = {
        "full": None,
        "dots": p.dots_with_no_batch_dims_saveable,
        "flash": flash,
        "dots+flash": p.save_from_both_policies(
            p.dots_with_no_batch_dims_saveable, flash
        ),
    }
    if name not in policies:
        raise ValueError(
            f"unknown remat_policy {name!r}; one of {sorted(policies)}"
        )
    return policies[name]


def forward_hidden(
    params: Params,
    tokens: jax.Array,  # [B, S] int32
    config: TransformerConfig,
    mesh: Optional[Mesh] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Final normed hidden states [B, S, D] (compute dtype) and the LM-head
    weight [D, V] — the pieces the fused vocab-chunked loss consumes without
    ever materializing [B, S, V] logits."""
    c = config
    sharding.validate_sp_mode(c.sp_mode)
    use_sp = mesh is not None and mesh.shape.get("sp", 1) > 1
    use_pp = mesh is not None and mesh.shape.get("pp", 1) > 1
    # Mixed precision: f32 master params -> bf16 compute copies.
    params = jax.tree.map(lambda a: a.astype(c.dtype), params)
    # Vocab-parallel lookup when possible: a plain gather on a tp-sharded
    # table makes SPMD replicate the result (involuntary full remat).
    x = sharding.embed_lookup(params["embed"], tokens, mesh)
    x = sharding.constrain(x, "batch", "seq", "act_embed")

    sp_manual = use_sp and use_pp
    block = lambda x, layer: (
        _block(x, layer, c, mesh, use_sp, sp_manual=sp_manual), None
    )
    if c.remat:
        block = jax.checkpoint(block, policy=_remat_policy(c.remat_policy))
    if use_pp:
        # Layer stack sharded over pp stages: GPipe microbatch pipeline
        # (same per-microbatch computation, pipelined schedule). With sp > 1
        # the sp axis joins the manual region: activations stay seq-sharded
        # and the blocks run ring attention's local collectives directly.
        x = pipeline.pipeline_blocks(
            params["layers"], x, mesh, block, c.pp_microbatches,
            seq_axis="sp" if sp_manual else None,
        )
    else:
        x, _ = jax.lax.scan(block, x, params["layers"])

    x = rms_norm(x, params["ln_f"])
    head = params["embed"].T if c.tied_embeddings else params["lm_head"]
    return x, head


def forward(
    params: Params,
    tokens: jax.Array,  # [B, S] int32
    config: TransformerConfig,
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    """Logits [B, S, V]. Set ``mesh`` with sp>1 to engage sequence-parallel
    attention (Ulysses or ring per ``config.sp_mode``)."""
    x, head = forward_hidden(params, tokens, config, mesh)
    logits = x @ head
    return sharding.constrain(
        logits.astype(jnp.float32), "batch", "seq", "vocab"
    )
