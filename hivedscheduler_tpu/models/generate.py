"""Autoregressive decoding with a static KV cache (TPU-friendly inference).

Same weights as ``models/transformer.py``; decoding is reformulated for
XLA: a fixed-capacity cache ([layers, batch, max_len, kv_heads, head_dim]),
``lax.dynamic_update_slice`` writes at the current position, and a position
mask instead of dynamic shapes — one compiled ``decode_step`` serves every
position. Prefill processes the prompt in one causal forward pass while
filling the cache (MXU-batched), then steps generate token by token.

GQA keeps the cache small (kv_heads << heads): for Llama-3-8B shapes the
bf16 cache is 8192 pos x 8 kv heads x 128 dim x 2 x 32 layers = 1 GiB per
sequence — the reason GQA is the default.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..ops.attention import NEG_INF, mha
from .quantize import quantized_matmul as _mm
from .transformer import Params, TransformerConfig, rms_norm, rope


class KVCache(NamedTuple):
    k: jax.Array  # [L, B, S_max, Hkv, D]
    v: jax.Array  # [L, B, S_max, Hkv, D]
    length: jax.Array  # [] int32: filled positions


def init_cache(
    config: TransformerConfig, batch: int, max_len: int
) -> KVCache:
    c = config
    shape = (c.n_layers, batch, max_len, c.n_kv_heads, c.head_dim)
    return KVCache(
        k=jnp.zeros(shape, dtype=c.dtype),
        v=jnp.zeros(shape, dtype=c.dtype),
        length=jnp.zeros((), dtype=jnp.int32),
    )


def _attend_cached(
    q: jax.Array,  # [B, T, H, D]
    k_cache: jax.Array,  # [B, S_max, Hkv, D]
    v_cache: jax.Array,
    q_offset: jax.Array,  # [] int32: absolute position of q[0]
    config: TransformerConfig,
) -> jax.Array:
    c = config
    b, t, h, d = q.shape
    s_max = k_cache.shape[1]
    # GQA via a grouped einsum: fold the h/kv query-head group into its
    # own axis instead of jnp.repeat-ing the cache — decode is bound by
    # reading the cache from HBM, and the repeat would multiply those
    # reads (4x for Llama-3-8B's 32/8 heads) besides materializing the
    # expanded copy.
    g = h // c.n_kv_heads
    qg = q.reshape(b, t, c.n_kv_heads, g, d)
    scores = jnp.einsum(
        "bqkgd,bskd->bkgqs", qg, k_cache,
        preferred_element_type=jnp.float32,
    ) / jnp.sqrt(jnp.float32(d))
    q_pos = q_offset + jnp.arange(t)[:, None]
    k_pos = jnp.arange(s_max)[None, :]
    mask = q_pos >= k_pos  # causal over absolute positions; empty slots
    # beyond q_offset+t are masked by causality automatically.
    scores = jnp.where(mask[None, None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgqs,bskd->bqkgd", probs, v_cache)
    return out.reshape(b, t, h, d)


def _block_cached(
    x: jax.Array,  # [B, T, D]
    layer: Params,
    k_cache: jax.Array,
    v_cache: jax.Array,
    pos: jax.Array,
    config: TransformerConfig,
    ffn=None,
    attn_mode: str = "auto",
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One decoder block over cached KV; returns (x, new_k, new_v).

    ``ffn``: optional hook ``(h_normed, layer) -> out`` replacing the
    dense SwiGLU — how the MoE family reuses this exact attention-cache
    machinery (mixtral.decode_ffn).

    ``attn_mode`` (static) picks the multi-token attention program:
    "flash" = fresh-cache prefill, prompt-only causal attention on the
    flash kernels; "cached" = chunked prefill over existing history;
    "auto" = runtime cond between the two (exact, but reserves both
    branches' buffers)."""
    assert attn_mode in ("auto", "flash", "cached"), attn_mode
    c = config
    b, t, d = x.shape
    h = rms_norm(x, layer["ln1"])
    # _mm accepts plain or int8-quantized weight leaves (models/quantize):
    # the whole decode path serves either representation.
    q = _mm(h, layer["wq"]).reshape(b, t, c.n_heads, c.head_dim)
    k = _mm(h, layer["wk"]).reshape(b, t, c.n_kv_heads, c.head_dim)
    v = _mm(h, layer["wv"]).reshape(b, t, c.n_kv_heads, c.head_dim)
    positions = pos + jnp.arange(t)
    q = rope(q, positions, c.rope_theta)
    k = rope(k, positions, c.rope_theta)
    k_cache = jax.lax.dynamic_update_slice(k_cache, k, (0, pos, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(v_cache, v, (0, pos, 0, 0))
    if t > 1 and attn_mode == "flash":
        # Prefill from an empty cache is plain causal self-attention over
        # the prompt — route it to ops.attention.mha, which dispatches to
        # the flash kernels on TPU. The attention op itself is ~20x the
        # O(S^2) einsum at 8k; the measured whole-prefill TTFT win is
        # 1.96x (doc/perf.md). No cached branch exists in this program,
        # so no quadratic score buffer is ever reserved — this is what
        # keeps 32k+ single-shot prefill inside HBM.
        attn = mha(q, k, v, causal=True).astype(q.dtype)
    elif t > 1 and attn_mode == "auto":
        # Offset unknown at trace time (prefill inside a caller's jit):
        # decide at runtime. Exact either way, but the untaken cached
        # branch still reserves its O(t*s_max) score buffer — callers
        # that KNOW the cache is fresh should reach this function with
        # attn_mode="flash" (the public prefill wrapper does when the
        # length is concrete).
        attn = jax.lax.cond(
            pos == 0,
            lambda: mha(q, k, v, causal=True).astype(q.dtype),
            lambda: _attend_cached(q, k_cache, v_cache, pos, c),
        )
    else:  # t == 1 (decode step) or an explicitly chunked prefill
        attn = _attend_cached(q, k_cache, v_cache, pos, c)
    x = x + _mm(attn.reshape(b, t, c.n_heads * c.head_dim), layer["wo"])
    hh = rms_norm(x, layer["ln2"])
    if ffn is None:
        out = _mm(
            jax.nn.silu(_mm(hh, layer["w_gate"])) * _mm(hh, layer["w_up"]),
            layer["w_down"],
        )
    else:
        out = ffn(hh, layer)
    return x + out, k_cache, v_cache


def _forward_cached(
    params: Params,
    tokens: jax.Array,  # [B, T]
    cache: KVCache,
    config: TransformerConfig,
    ffn=None,
    attn_mode: str = "auto",
) -> Tuple[jax.Array, KVCache]:
    c = config
    # Unify compute dtype, but int8-quantized weight leaves must survive
    # as int8 — casting them here would materialize dequantized copies
    # and erase the halved HBM traffic quantization exists for (the
    # per-matmul cast in quantize.quantized_matmul fuses into the read).
    params = jax.tree.map(
        lambda a: a if a.dtype == jnp.int8 else a.astype(c.dtype), params
    )
    x = params["embed"][tokens]
    pos = cache.length

    def block(x, layer_and_cache):
        layer, k_c, v_c = layer_and_cache
        x, k_c, v_c = _block_cached(
            x, layer, k_c, v_c, pos, c, ffn, attn_mode
        )
        return x, (k_c, v_c)

    x, (new_k, new_v) = jax.lax.scan(
        block, x, (params["layers"], cache.k, cache.v)
    )
    x = rms_norm(x, params["ln_f"])
    if c.tied_embeddings:
        logits = x @ params["embed"].T
    else:
        logits = _mm(x, params["lm_head"])
    new_cache = KVCache(
        k=new_k, v=new_v, length=cache.length + tokens.shape[1]
    )
    return logits.astype(jnp.float32), new_cache


@functools.partial(
    jax.jit, static_argnames=("config", "ffn", "attn_mode")
)
def _prefill_jit(params, prompt, cache, config, ffn, attn_mode):
    logits, cache = _forward_cached(
        params, prompt, cache, config, ffn, attn_mode
    )
    return logits[:, -1], cache


def prefill(
    params: Params,
    prompt: jax.Array,  # [B, T_prompt]
    cache: KVCache,
    config: TransformerConfig,
    ffn=None,
    chunked: bool | None = None,
) -> Tuple[jax.Array, KVCache]:
    """Fill the cache with the prompt; returns (last-position logits, cache).
    ``ffn`` is static: reuse ONE hook object across calls (a fresh closure
    per call would retrace).

    When the cache length is concrete (the normal case: prefill called
    from host code), the attention program is specialized at trace time —
    fresh cache → flash-kernel prompt attention with NO quadratic score
    buffer in the program (what keeps 32k+ prefill inside HBM), non-zero
    offset → chunked prefill over history. Inside a caller's jit the
    length is a tracer, so the exact-but-bigger runtime-cond program is
    used instead.

    ``chunked``: pass explicitly when you know the cache state to skip
    the length probe — the probe ``int()``s a device scalar, which on a
    length derived from a previous chunk's forward blocks the host until
    that chunk finishes. ``chunked=True`` keeps multi-chunk prefill
    fully async; ``chunked=False`` asserts a fresh cache (prompt-only
    attention — WRONG, not just slow, if the cache actually holds
    history)."""
    if chunked is not None:
        mode = "cached" if chunked else "flash"
    else:
        try:
            concrete = int(cache.length)  # raises on tracers
        except Exception:
            mode = "auto"
        else:
            mode = "flash" if concrete == 0 else "cached"
    return _prefill_jit(params, prompt, cache, config, ffn, mode)


@functools.partial(jax.jit, static_argnames=("config", "ffn"))
def decode_step(
    params: Params,
    token: jax.Array,  # [B] int32: previous token
    cache: KVCache,
    config: TransformerConfig,
    ffn=None,
) -> Tuple[jax.Array, KVCache]:
    """One decoding step; returns (logits [B, V], cache)."""
    logits, cache = _forward_cached(params, token[:, None], cache, config, ffn)
    return logits[:, 0], cache


def generate_greedy_scan(
    params: Params,
    prompt: jax.Array,  # [B, T_prompt]
    config: TransformerConfig,
    max_new_tokens: int,
) -> jax.Array:
    """Greedy generation as ONE compiled program. Semantically identical to
    ``generate(temperature=0)`` but with a single dispatch for the whole
    sequence — the Python-loop version pays per-token dispatch latency,
    which dominates decode through any remote/tunneled runtime. Delegates
    to ``generate_scan``: at temperature 0 the sampling branch compiles to
    the same argmax program and the key is never consumed."""
    return generate_scan(
        params, prompt, config, max_new_tokens,
        jax.random.PRNGKey(0), temperature=0.0,
    )



def sample_logits(
    logits: jax.Array,  # [..., V]
    key: jax.Array | None,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """Temperature / top-k / top-p (nucleus) sampling; greedy when
    ``temperature <= 0`` or ``key is None``.

    TPU-friendly static-shape formulation: top-k masks below the k-th
    logit (``lax.top_k``), top-p masks tokens whose EXCLUSIVE prefix mass
    in the sorted distribution reaches ``top_p`` (the top-1 token is
    always kept) — no dynamic shapes, so this jits and scans."""
    if temperature <= 0.0 or key is None:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / temperature
    v = logits.shape[-1]
    if top_k and top_k < v:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, NEG_INF, logits)
    if top_p < 1.0:
        sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        exclusive_mass = jnp.cumsum(probs, axis=-1) - probs
        keep = exclusive_mass < top_p
        # Force-keep the best token: top_p <= 0 would otherwise mask the
        # whole row and degenerate to UNIFORM sampling over the vocab.
        keep = keep.at[..., 0].set(True)
        threshold = jnp.min(
            jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < threshold, NEG_INF, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def generate(
    params: Params,
    prompt: jax.Array,  # [B, T_prompt]
    config: TransformerConfig,
    max_new_tokens: int,
    temperature: float = 0.0,
    key: jax.Array | None = None,
    top_k: int = 0,
    top_p: float = 1.0,
    ffn=None,
) -> jax.Array:
    """Greedy (temperature=0) or sampled generation; returns
    [B, T_prompt + max_new_tokens]. ``ffn``: MoE decode hook
    (mixtral.decode_ffn) — reuse one object across calls."""
    b, t = prompt.shape
    cache = init_cache(config, b, t + max_new_tokens)
    logits, cache = prefill(params, prompt, cache, config, ffn=ffn)
    out = [prompt]

    def next_key():
        # Split-then-use: sampling must never consume a key that later
        # derives another (JAX key-reuse discipline) — same schedule shape
        # as generate_scan's step().
        nonlocal key
        if key is None:
            return None
        key, sub = jax.random.split(key)
        return sub

    token = sample_logits(logits, next_key(), temperature, top_k, top_p)
    for i in range(max_new_tokens):
        out.append(token[:, None])
        if i == max_new_tokens - 1:
            break
        logits, cache = decode_step(params, token, cache, config, ffn=ffn)
        token = sample_logits(logits, next_key(), temperature, top_k, top_p)
    return jnp.concatenate(out, axis=1)


@functools.partial(
    jax.jit,
    static_argnames=("config", "max_new_tokens", "temperature", "top_k",
                     "top_p", "ffn"),
)
def generate_scan(
    params: Params,
    prompt: jax.Array,  # [B, T_prompt]
    config: TransformerConfig,
    max_new_tokens: int,
    key: jax.Array,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
    ffn=None,
) -> jax.Array:
    """Sampled generation as ONE compiled program (the sampling sibling of
    ``generate_greedy_scan``): prefill + a lax.scan over decode steps with
    the PRNG key split inside the scan carry. Temperature/top-k/top-p are
    static (they select the compiled masking program)."""
    b, t = prompt.shape
    cache = init_cache(config, b, t + max_new_tokens)
    # The cache was built fresh two lines up, so the prompt pass is
    # statically known to be empty-cache prefill: take the flash program
    # (no quadratic score buffer) even though this runs under jit where
    # cache.length is a tracer.
    logits, cache = _forward_cached(
        params, prompt, cache, config, ffn, attn_mode="flash"
    )
    key, sub = jax.random.split(key)
    token = sample_logits(logits[:, -1], sub, temperature, top_k, top_p)

    def step(carry, _):
        token, cache, key = carry
        logits, cache = _forward_cached(params, token[:, None], cache, config,
                                        ffn)
        key, sub = jax.random.split(key)
        nxt = sample_logits(logits[:, 0], sub, temperature, top_k, top_p)
        return (nxt, cache, key), nxt

    (_, _, _), rest = jax.lax.scan(
        step, (token, cache, key), None, length=max_new_tokens - 1
    )
    return jnp.concatenate(
        [prompt, token[:, None], rest.T.astype(jnp.int32)], axis=1
    )
