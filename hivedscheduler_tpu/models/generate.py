"""Autoregressive decoding with a static KV cache (TPU-friendly inference).

Same weights as ``models/transformer.py``; decoding is reformulated for
XLA: a fixed-capacity cache ([layers, batch, max_len, kv_heads, head_dim]),
``lax.dynamic_update_slice`` writes at the current position, and a position
mask instead of dynamic shapes — one compiled ``decode_step`` serves every
position. Prefill processes the prompt in one causal forward pass while
filling the cache (MXU-batched), then steps generate token by token.

GQA keeps the cache small (kv_heads << heads): for Llama-3-8B shapes the
bf16 cache is 8192 pos x 8 kv heads x 128 dim x 2 x 32 layers = 1 GiB per
sequence — the reason GQA is the default.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..ops.attention import NEG_INF
from .transformer import Params, TransformerConfig, rms_norm, rope


class KVCache(NamedTuple):
    k: jax.Array  # [L, B, S_max, Hkv, D]
    v: jax.Array  # [L, B, S_max, Hkv, D]
    length: jax.Array  # [] int32: filled positions


def init_cache(
    config: TransformerConfig, batch: int, max_len: int
) -> KVCache:
    c = config
    shape = (c.n_layers, batch, max_len, c.n_kv_heads, c.head_dim)
    return KVCache(
        k=jnp.zeros(shape, dtype=c.dtype),
        v=jnp.zeros(shape, dtype=c.dtype),
        length=jnp.zeros((), dtype=jnp.int32),
    )


def _attend_cached(
    q: jax.Array,  # [B, T, H, D]
    k_cache: jax.Array,  # [B, S_max, Hkv, D]
    v_cache: jax.Array,
    q_offset: jax.Array,  # [] int32: absolute position of q[0]
    config: TransformerConfig,
) -> jax.Array:
    c = config
    b, t, h, d = q.shape
    s_max = k_cache.shape[1]
    if c.n_kv_heads != h:
        k_cache = jnp.repeat(k_cache, h // c.n_kv_heads, axis=2)
        v_cache = jnp.repeat(v_cache, h // c.n_kv_heads, axis=2)
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k_cache, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.float32(d))
    q_pos = q_offset + jnp.arange(t)[:, None]
    k_pos = jnp.arange(s_max)[None, :]
    mask = q_pos >= k_pos  # causal over absolute positions; empty slots
    # beyond q_offset+t are masked by causality automatically.
    scores = jnp.where(mask[None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v_cache)


def _block_cached(
    x: jax.Array,  # [B, T, D]
    layer: Params,
    k_cache: jax.Array,
    v_cache: jax.Array,
    pos: jax.Array,
    config: TransformerConfig,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One decoder block over cached KV; returns (x, new_k, new_v)."""
    c = config
    b, t, d = x.shape
    h = rms_norm(x, layer["ln1"])
    q = (h @ layer["wq"]).reshape(b, t, c.n_heads, c.head_dim)
    k = (h @ layer["wk"]).reshape(b, t, c.n_kv_heads, c.head_dim)
    v = (h @ layer["wv"]).reshape(b, t, c.n_kv_heads, c.head_dim)
    positions = pos + jnp.arange(t)
    q = rope(q, positions, c.rope_theta)
    k = rope(k, positions, c.rope_theta)
    k_cache = jax.lax.dynamic_update_slice(k_cache, k, (0, pos, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(v_cache, v, (0, pos, 0, 0))
    attn = _attend_cached(q, k_cache, v_cache, pos, c)
    x = x + attn.reshape(b, t, c.n_heads * c.head_dim) @ layer["wo"]
    hh = rms_norm(x, layer["ln2"])
    ffn = (jax.nn.silu(hh @ layer["w_gate"]) * (hh @ layer["w_up"])) @ layer[
        "w_down"
    ]
    return x + ffn, k_cache, v_cache


def _forward_cached(
    params: Params,
    tokens: jax.Array,  # [B, T]
    cache: KVCache,
    config: TransformerConfig,
) -> Tuple[jax.Array, KVCache]:
    c = config
    params = jax.tree.map(lambda a: a.astype(c.dtype), params)
    x = params["embed"][tokens]
    pos = cache.length

    def block(x, layer_and_cache):
        layer, k_c, v_c = layer_and_cache
        x, k_c, v_c = _block_cached(x, layer, k_c, v_c, pos, c)
        return x, (k_c, v_c)

    x, (new_k, new_v) = jax.lax.scan(
        block, x, (params["layers"], cache.k, cache.v)
    )
    x = rms_norm(x, params["ln_f"])
    if c.tied_embeddings:
        logits = x @ params["embed"].T
    else:
        logits = x @ params["lm_head"]
    new_cache = KVCache(
        k=new_k, v=new_v, length=cache.length + tokens.shape[1]
    )
    return logits.astype(jnp.float32), new_cache


@functools.partial(jax.jit, static_argnames=("config",))
def prefill(
    params: Params,
    prompt: jax.Array,  # [B, T_prompt]
    cache: KVCache,
    config: TransformerConfig,
) -> Tuple[jax.Array, KVCache]:
    """Fill the cache with the prompt; returns (last-position logits, cache)."""
    logits, cache = _forward_cached(params, prompt, cache, config)
    return logits[:, -1], cache


@functools.partial(jax.jit, static_argnames=("config",))
def decode_step(
    params: Params,
    token: jax.Array,  # [B] int32: previous token
    cache: KVCache,
    config: TransformerConfig,
) -> Tuple[jax.Array, KVCache]:
    """One decoding step; returns (logits [B, V], cache)."""
    logits, cache = _forward_cached(params, token[:, None], cache, config)
    return logits[:, 0], cache


@functools.partial(
    jax.jit, static_argnames=("config", "max_new_tokens")
)
def generate_greedy_scan(
    params: Params,
    prompt: jax.Array,  # [B, T_prompt]
    config: TransformerConfig,
    max_new_tokens: int,
) -> jax.Array:
    """Greedy generation as ONE compiled program: prefill + a lax.scan over
    decode steps, cache carried through the scan. Semantically identical to
    ``generate(temperature=0)`` but with a single dispatch for the whole
    sequence — the Python-loop version pays per-token dispatch latency,
    which dominates decode through any remote/tunneled runtime."""
    b, t = prompt.shape
    cache = init_cache(config, b, t + max_new_tokens)
    logits, cache = _forward_cached(params, prompt, cache, config)
    token = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)

    def step(carry, _):
        token, cache = carry
        logits, cache = _forward_cached(params, token[:, None], cache, config)
        nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
        return (nxt, cache), nxt

    (_, _), rest = jax.lax.scan(
        step, (token, cache), None, length=max_new_tokens - 1
    )
    return jnp.concatenate(
        [prompt, token[:, None], rest.T.astype(jnp.int32)], axis=1
    )


def generate(
    params: Params,
    prompt: jax.Array,  # [B, T_prompt]
    config: TransformerConfig,
    max_new_tokens: int,
    temperature: float = 0.0,
    key: jax.Array | None = None,
) -> jax.Array:
    """Greedy (temperature=0) or sampled generation; returns
    [B, T_prompt + max_new_tokens]."""
    b, t = prompt.shape
    cache = init_cache(config, b, t + max_new_tokens)
    logits, cache = prefill(params, prompt, cache, config)
    out = [prompt]
    token = _select(logits, temperature, key)
    for i in range(max_new_tokens):
        out.append(token[:, None])
        if i == max_new_tokens - 1:
            break
        logits, cache = decode_step(params, token, cache, config)
        if key is not None:
            key = jax.random.split(key, 1)[0]
        token = _select(logits, temperature, key)
    return jnp.concatenate(out, axis=1)


def _select(logits: jax.Array, temperature: float, key) -> jax.Array:
    if temperature <= 0.0 or key is None:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(key, logits / temperature, axis=-1).astype(
        jnp.int32
    )
