"""Sharded checkpoint/resume for training jobs (orbax-backed).

The scheduler's own checkpoint story is pod annotations (SURVEY.md §5);
this is the *workload* half: periodically persist sharded params/opt-state
so a preempted or rescheduled gang (the scheduler's whole point) resumes
instead of restarting. Orbax writes each process's shards in parallel and
restores directly into the target NamedShardings — no host-side full copy.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax


def _manager(directory: str, max_to_keep: int = 3):
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        directory,
        options=ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, create=True
        ),
    )


class TrainCheckpointer:
    """Save/restore (params, opt_state, step) with their shardings."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self._mgr = _manager(self.directory, max_to_keep)

    def save(self, step: int, params: Any, opt_state: Any) -> None:
        import orbax.checkpoint as ocp

        self._mgr.save(
            step,
            args=ocp.args.Composite(
                params=ocp.args.StandardSave(params),
                opt_state=ocp.args.StandardSave(opt_state),
            ),
        )

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def _restore_items(self, step: Optional[int], **likes: Any):
        """Composite restore of the named items into the shardings/dtypes
        of the provided abstract trees; shared step resolution."""
        import orbax.checkpoint as ocp

        step = self.latest_step() if step is None else step
        assert step is not None, f"no checkpoint found under {self.directory}"
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(**{
                name: ocp.args.StandardRestore(like)
                for name, like in likes.items()
            }),
        )
        return restored, step

    def restore(
        self,
        params_like: Any,
        opt_state_like: Any,
        step: Optional[int] = None,
    ) -> Tuple[Any, Any, int]:
        """Restore into the shardings/dtypes of the provided abstract trees
        (pass the live trees or jax.eval_shape results + shardings)."""
        restored, step = self._restore_items(
            step, params=params_like, opt_state=opt_state_like
        )
        return restored["params"], restored["opt_state"], step

    def restore_params(
        self, params_like: Any, step: Optional[int] = None
    ) -> Tuple[Any, int]:
        """Params-only restore (the serving path): orbax Composite restore
        of a subset of the saved items — the optimizer moments (2x the
        param bytes of I/O and transient device memory) are never read or
        materialized."""
        restored, step = self._restore_items(step, params=params_like)
        return restored["params"], step

    def close(self) -> None:
        self._mgr.close()
