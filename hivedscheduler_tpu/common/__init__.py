"""Generic utilities shared by every layer.

Python equivalent of the reference's ``pkg/common`` (common/types.go:33,
common/utils.go:119-212): YAML/JSON codecs, logging init, small helpers.
Python sets/dicts replace the reference's hand-rolled ``Set``.
"""

from __future__ import annotations

import functools
import json
import logging
import math
import re
import sys
from typing import Any, Iterable, List

import yaml

# libyaml C codecs are ~10x the pure-Python ones; annotation YAML dominates
# the scheduling hot path otherwise (bind-info parse on every replay).
_SafeLoader = getattr(yaml, "CSafeLoader", yaml.SafeLoader)
_SafeDumper = getattr(yaml, "CSafeDumper", yaml.SafeDumper)

log = logging.getLogger("hivedscheduler_tpu")


def init_logging(level: int = logging.INFO) -> None:
    """Configure structured stderr logging (reference: common/utils.go:124-149
    routes klog to stderr)."""
    if log.handlers:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname).1s %(name)s: %(message)s")
    )
    log.addHandler(handler)
    log.setLevel(level)


def to_yaml(obj: Any) -> str:
    """Serialize to YAML (reference: common/utils.go:176-181 ``ToYaml``)."""
    return yaml.dump(
        obj, Dumper=_SafeDumper, default_flow_style=False, sort_keys=False
    )


def from_yaml(text: str) -> Any:
    """Deserialize YAML; raises on malformed input
    (reference: common/utils.go:183-189 ``FromYaml`` panics on error).

    JSON is valid YAML — documents that look like JSON take the C json
    parser (the bind-info annotation is written that way; see
    new_binding_pod), everything else the libyaml loader."""
    stripped = text.lstrip()
    if stripped[:1] in ("{", "["):
        try:
            return json.loads(text)
        except json.JSONDecodeError:
            pass
    return yaml.load(text, Loader=_SafeLoader)


_BARE_SCALAR = re.compile(r"^[A-Za-z][A-Za-z0-9_./-]*$")
_BOOLISH = {"true", "false", "yes", "no", "on", "off", "null", "~"}


def _fast_scalar(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, int):
        return repr(v)
    if isinstance(v, float):
        # YAML's float resolver needs a dot before any exponent: repr(1e-05)
        # = "1e-05" would round-trip as a STRING under PyYAML.
        if math.isnan(v):
            return ".nan"
        if math.isinf(v):
            return ".inf" if v > 0 else "-.inf"
        s = repr(v)
        if "e" in s and "." not in s:
            mantissa, _, exponent = s.partition("e")
            s = f"{mantissa}.0e{exponent}"
        return s
    if v is None:
        return "null"
    if not isinstance(v, str):
        # Fail at serialization time, not at replay: str(v) on a tuple/
        # bytes/date would emit text that parses back as a different value,
        # silently corrupting the bind-info annotation.
        raise TypeError(
            f"to_yaml_fast supports dict/list/str/int/float/bool/None "
            f"leaves only, got {type(v).__name__}: {v!r}"
        )
    s = v
    if _BARE_SCALAR.match(s) and s.lower() not in _BOOLISH:
        return s
    return json.dumps(s)  # JSON string quoting is valid YAML


def _fast_emit(obj: Any, indent: str, lines: List[str]) -> None:
    pad = indent
    if isinstance(obj, dict):
        for k, v in obj.items():
            key = _fast_scalar(k)
            if isinstance(v, dict) and v:
                lines.append(f"{pad}{key}:")
                _fast_emit(v, indent + "  ", lines)
            elif isinstance(v, list) and v:
                lines.append(f"{pad}{key}:")
                _fast_emit(v, indent, lines)
            elif isinstance(v, (dict, list)):
                lines.append(f"{pad}{key}: {'{}' if isinstance(v, dict) else '[]'}")
            else:
                lines.append(f"{pad}{key}: {_fast_scalar(v)}")
    elif isinstance(obj, list):
        for item in obj:
            if isinstance(item, dict) and item:
                first, *rest = item.items()
                k, v = first
                if isinstance(v, (dict, list)) and v:
                    lines.append(f"{pad}- {_fast_scalar(k)}:")
                    _fast_emit(v, indent + ("    " if isinstance(v, dict) else "  "), lines)
                else:
                    lines.append(
                        f"{pad}- {_fast_scalar(k)}: "
                        f"{'{}' if v == {} else '[]' if v == [] else _fast_scalar(v)}"
                    )
                sub: List[str] = []
                _fast_emit(dict(rest), indent + "  ", sub)
                lines.extend(sub)
            elif isinstance(item, list):
                if not item:
                    lines.append(f"{pad}- []")
                else:
                    lines.append(f"{pad}-")
                    _fast_emit(item, indent + "  ", lines)
            elif item == {}:
                lines.append(f"{pad}- {{}}")
            else:
                lines.append(f"{pad}- {_fast_scalar(item)}")


def to_yaml_fast(obj: Any) -> str:
    """Hand-rolled YAML emitter for the annotation hot path (bind info / env
    blocks): plain dicts/lists/scalars only, ~20x PyYAML's Python
    representer. Output is ordinary block YAML, readable by any loader;
    round-trip is asserted in tests."""
    lines: List[str] = []
    _fast_emit(obj, "", lines)
    return "\n".join(lines) + "\n"


@functools.lru_cache(maxsize=8192)
def from_yaml_cached(text: str) -> Any:
    """Memoized parse for hot annotation strings (bind info is re-parsed on
    every group-replay lookup). Callers must treat the result as immutable —
    copy before mutating."""
    return from_yaml(text)


def to_json(obj: Any) -> str:
    """Serialize to JSON (reference: common/utils.go:191-199)."""
    return json.dumps(obj, separators=(",", ":"))


def from_json(text: str) -> Any:
    return json.loads(text)


def to_indices_string(indices: Iterable[int]) -> str:
    """Render leaf-cell indices as the isolation annotation value, e.g.
    ``0,1,2,3`` (reference: common/utils.go ``ToIndicesString`` used by
    internal/utils.go:180-181)."""
    return ",".join(str(i) for i in indices)


def from_indices_string(text: str) -> List[int]:
    if not text:
        return []
    return [int(x) for x in text.split(",")]
