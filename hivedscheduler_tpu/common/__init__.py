"""Generic utilities shared by every layer.

Python equivalent of the reference's ``pkg/common`` (common/types.go:33,
common/utils.go:119-212): YAML/JSON codecs, logging init, small helpers.
Python sets/dicts replace the reference's hand-rolled ``Set``.
"""

from __future__ import annotations

import json
import logging
import sys
from typing import Any, Iterable, List

import yaml

log = logging.getLogger("hivedscheduler_tpu")


def init_logging(level: int = logging.INFO) -> None:
    """Configure structured stderr logging (reference: common/utils.go:124-149
    routes klog to stderr)."""
    if log.handlers:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname).1s %(name)s: %(message)s")
    )
    log.addHandler(handler)
    log.setLevel(level)


def to_yaml(obj: Any) -> str:
    """Serialize to YAML (reference: common/utils.go:176-181 ``ToYaml``)."""
    return yaml.safe_dump(obj, default_flow_style=False, sort_keys=False)


def from_yaml(text: str) -> Any:
    """Deserialize YAML; raises on malformed input
    (reference: common/utils.go:183-189 ``FromYaml`` panics on error)."""
    return yaml.safe_load(text)


def to_json(obj: Any) -> str:
    """Serialize to JSON (reference: common/utils.go:191-199)."""
    return json.dumps(obj, separators=(",", ":"))


def from_json(text: str) -> Any:
    return json.loads(text)


def to_indices_string(indices: Iterable[int]) -> str:
    """Render leaf-cell indices as the isolation annotation value, e.g.
    ``0,1,2,3`` (reference: common/utils.go ``ToIndicesString`` used by
    internal/utils.go:180-181)."""
    return ",".join(str(i) for i in indices)


def from_indices_string(text: str) -> List[int]:
    if not text:
        return []
    return [int(x) for x in text.split(",")]
