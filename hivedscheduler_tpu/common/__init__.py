"""Generic utilities shared by every layer.

Python equivalent of the reference's ``pkg/common`` (common/types.go:33,
common/utils.go:119-212): YAML/JSON codecs, logging init, small helpers.
Python sets/dicts replace the reference's hand-rolled ``Set``.
"""

from __future__ import annotations

import functools
import json
import logging
import sys
from typing import Any, Iterable, List

import yaml

# libyaml C codecs are ~10x the pure-Python ones; annotation YAML dominates
# the scheduling hot path otherwise (bind-info parse on every replay).
_SafeLoader = getattr(yaml, "CSafeLoader", yaml.SafeLoader)
_SafeDumper = getattr(yaml, "CSafeDumper", yaml.SafeDumper)

log = logging.getLogger("hivedscheduler_tpu")


def init_logging(level: int = logging.INFO) -> None:
    """Configure structured stderr logging (reference: common/utils.go:124-149
    routes klog to stderr)."""
    if log.handlers:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname).1s %(name)s: %(message)s")
    )
    log.addHandler(handler)
    log.setLevel(level)


def to_yaml(obj: Any) -> str:
    """Serialize to YAML (reference: common/utils.go:176-181 ``ToYaml``)."""
    return yaml.dump(
        obj, Dumper=_SafeDumper, default_flow_style=False, sort_keys=False
    )


def from_yaml(text: str) -> Any:
    """Deserialize YAML; raises on malformed input
    (reference: common/utils.go:183-189 ``FromYaml`` panics on error)."""
    return yaml.load(text, Loader=_SafeLoader)


@functools.lru_cache(maxsize=8192)
def from_yaml_cached(text: str) -> Any:
    """Memoized parse for hot annotation strings (bind info is re-parsed on
    every group-replay lookup). Callers must treat the result as immutable —
    copy before mutating."""
    return from_yaml(text)


def to_json(obj: Any) -> str:
    """Serialize to JSON (reference: common/utils.go:191-199)."""
    return json.dumps(obj, separators=(",", ":"))


def from_json(text: str) -> Any:
    return json.loads(text)


def to_indices_string(indices: Iterable[int]) -> str:
    """Render leaf-cell indices as the isolation annotation value, e.g.
    ``0,1,2,3`` (reference: common/utils.go ``ToIndicesString`` used by
    internal/utils.go:180-181)."""
    return ",".join(str(i) for i in indices)


def from_indices_string(text: str) -> List[int]:
    if not text:
        return []
    return [int(x) for x in text.split(",")]
