"""Cell allocation: buddy allocation and virtual->physical binding.

Python equivalent of the reference's ``pkg/algorithm/cell_allocation.go``:
backtracking buddy allocation (L42-80), VC-safe relaxed split
(L84-150), virtual placement mapping (L166-198), candidate filtering (L200-249),
backtracking virtual->physical cell mapping (L252-318), the inverse
physical->virtual mapping used by recovery (L320-383), bind/unbind chains
(L386-420), and priority/usage propagation (L425-454).

On TPU, "buddies" are ICI-adjacent sub-slices of a common enclosing slice, so
splitting a free v5p-64 yields four v5p-16 cells that remain contiguous on
the torus; the dynamic (lazy) binding of virtual to physical cells is what
makes a VC's quota a guarantee over slice *shapes* rather than a static
partition of the torus.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..api import types as api
from .. import common
from .cell import (
    Cell,
    CellLevel,
    CellPriority,
    ChainCellList,
    FREE_PRIORITY,
    LOWEST_LEVEL,
    MAX_GUARANTEED_PRIORITY,
    OPPORTUNISTIC_PRIORITY,
    PhysicalCell,
    VirtualCell,
    cell_equal,
)
from .group import BindingPathVertex


def buddy_alloc(
    vertex: BindingPathVertex,
    free_list: ChainCellList,
    current_level: CellLevel,
    suggested_nodes: Optional[Set[str]],
    ignore_suggested: bool,
    bindings: Dict[api.CellAddress, PhysicalCell],
) -> bool:
    """Allocate a free physical cell to a preassigned virtual cell, splitting
    a higher-level free cell when the current level is empty. Backtracking
    version: the buddy invariant guarantees a cell exists, but it may be bad
    or outside K8s-suggested nodes, so we search the free list
    (reference: cell_allocation.go:42-80)."""
    if current_level == vertex.cell.level:
        ok, picked = map_virtual_cells_to_physical(
            [vertex],
            free_list[current_level],
            suggested_nodes,
            ignore_suggested,
            bindings,
            return_picked=True,
        )
        if ok:
            for c in picked:
                free_list.remove(c, current_level)
            return True
        return False

    free_cells = get_usable_physical_cells(
        free_list[current_level], 1, suggested_nodes, ignore_suggested
    )
    if free_cells is None:
        return False
    for c in free_cells:
        free_list[current_level - 1].extend(c.children)
        if buddy_alloc(
            vertex, free_list, current_level - 1, suggested_nodes, ignore_suggested,
            bindings,
        ):
            free_list.remove(c, current_level)
            return True
        # Backtrack: withdraw exactly the children we offered. (The original
        # code cleared the whole level — dropping any PRE-EXISTING free cells
        # at it, so a later vertex of the same mapping could spuriously fail
        # or split more than VC safety allowed; demonstrated by
        # tests/test_buddy_backtracking.py. A failed recursive call restores
        # its own splits, so all of c's children are still present here.)
        for child in c.children:
            free_list.remove(child, current_level - 1)
    return False


def safe_relaxed_buddy_alloc(
    vertex: BindingPathVertex,
    free_list: ChainCellList,
    free_cell_num: Dict[CellLevel, int],
    current_level: CellLevel,
    suggested_nodes: Optional[Set[str]],
    ignore_suggested: bool,
    bindings: Dict[api.CellAddress, PhysicalCell],
) -> bool:
    """When buddy_alloc fails because the candidate cells are bad or not
    suggested, split *higher*-level free cells — but only as many as VC
    safety allows: ``splittable = free - reserved-for-VC-quota`` at each
    level, cascading down (reference: cell_allocation.go:84-150). A negative
    splittable count means the VC safety invariant is already broken, which
    is an internal assertion failure."""
    top = free_list.top_level
    splittable_num: Dict[CellLevel, int] = {}
    splittable_cell: Optional[Cell] = None
    for l in range(top, current_level, -1):
        splittable_num[l] = len(free_list[l]) - free_cell_num.get(l, 0)
        if l < top and splittable_cell is not None:
            splittable_num[l] += splittable_num[l + 1] * len(
                splittable_cell.children
            )
        if splittable_cell is None and free_list[l]:
            splittable_cell = free_list[l][0]
        elif splittable_cell is not None:
            splittable_cell = splittable_cell.children[0]
        if splittable_num[l] < 0:
            raise api.internal_error(
                f"VC Safety Broken: level {l} cell with free list "
                f"{[c.address for c in free_list[l]]} is unsplittable, "
                f"splittableNum={splittable_num[l]}"
            )

    for l in range(current_level + 1, top + 1):
        cell_num = min(len(free_list[l]), splittable_num.get(l, 0))
        if cell_num <= 0:
            continue
        split_list: List[Cell] = []
        for _ in range(cell_num):
            split_list.append(free_list[l][0])
            free_list.remove(free_list[l][0], l)
        splittable_num[l] -= cell_num
        for _ in range(l, current_level, -1):
            split_list = [child for sc in split_list for child in sc.children]
        free_list.prepend(split_list, current_level)
        ok, picked = map_virtual_cells_to_physical(
            [vertex],
            free_list[current_level],
            suggested_nodes,
            ignore_suggested,
            bindings,
            return_picked=True,
        )
        if ok:
            for c in picked:
                free_list.remove(c, current_level)
            return True
    return False


def get_lowest_free_cell_level(
    free_list: ChainCellList, level: CellLevel
) -> CellLevel:
    """(reference: cell_allocation.go:153-162)"""
    for l in range(level, free_list.top_level + 1):
        if free_list[l]:
            return l
    raise api.internal_error(
        f"VC Safety Broken: free cell not found even split to the highest "
        f"level {free_list.top_level}"
    )


def map_virtual_placement_to_physical(
    preassigned: List[BindingPathVertex],
    non_preassigned: List[List[BindingPathVertex]],
    free_list: ChainCellList,
    free_cell_num: Dict[CellLevel, int],
    suggested_nodes: Optional[Set[str]],
    ignore_suggested: bool,
    bindings: Dict[api.CellAddress, PhysicalCell],
) -> bool:
    """Map a VC placement's unbound cells to physical cells: buddy-alloc the
    preassigned roots, then map the non-preassigned subtrees inside their
    parents' physical cells (reference: cell_allocation.go:166-198)."""
    for vertex in preassigned:
        if buddy_alloc(
            vertex,
            free_list,
            get_lowest_free_cell_level(free_list, vertex.cell.level),
            suggested_nodes,
            ignore_suggested,
            bindings,
        ):
            free_cell_num[vertex.cell.level] = (
                free_cell_num.get(vertex.cell.level, 0) - 1
            )
        else:
            common.log.info(
                "Buddy allocation failed due to bad cells, trying to split "
                "higher level cells"
            )
            if not safe_relaxed_buddy_alloc(
                vertex,
                free_list,
                free_cell_num,
                vertex.cell.level,
                suggested_nodes,
                ignore_suggested,
                bindings,
            ):
                common.log.info("Cannot split higher level cells")
                return False
    for vertices in non_preassigned:
        parent_vc = vertices[0].cell.parent
        assert isinstance(parent_vc, VirtualCell)
        ok, _ = map_virtual_cells_to_physical(
            vertices,
            parent_vc.physical_cell.children,
            suggested_nodes,
            ignore_suggested,
            bindings,
            return_picked=False,
        )
        if not ok:
            return False
    return True


def get_usable_physical_cells(
    candidates: Iterable[Cell],
    num_needed: int,
    suggested_nodes: Optional[Set[str]],
    ignore_suggested: bool,
) -> Optional[List[PhysicalCell]]:
    """Filter candidates for binding: unbound, not a bad single-node cell,
    and (unless ignored) having at least one suggested node; prefer cells with
    fewer opportunistic pods to reduce preemption
    (reference: cell_allocation.go:200-249). ``candidates`` may be a plain
    child list or an address-indexed CellList level — only iterated here;
    membership tests against the free list go through the index."""
    usable: List[PhysicalCell] = []
    for c in candidates:
        assert isinstance(c, PhysicalCell)
        if c.virtual_cell is not None:
            continue
        if not c.children:
            # Leaf candidate: bad or draining chips are never bound
            # (checked directly — white-box tests toggle leaf.healthy
            # without the setter, so the counter is advisory here).
            if (not c.healthy) or c.draining:
                continue
        elif c.unusable_leaf_num >= c.total_leaf_cell_num:
            # Every chip inside is bad or draining: nothing to serve. A
            # PARTIALLY degraded cell stays a candidate — chip-granular
            # health: the recursion below it skips the degraded chips, so a
            # host with one dead chip still serves smaller work (the old
            # whole-cell `not c.healthy` gate condemned the host).
            continue
        if not ignore_suggested and suggested_nodes is not None:
            if all(n not in suggested_nodes for n in c.nodes):
                continue
        usable.append(c)
    if len(usable) < num_needed:
        return None
    # Sort: fewer opportunistic pods first (reduce preemption), then fewer
    # bad/draining chips (a partially-degraded cell is placeable — the
    # whole point of chip-granular health — but a pristine one must win
    # while it exists, or a VC's quota gets bound to degraded hardware
    # with healthy capacity sitting free), then config order. Every key is
    # a pure function of cell STATE, never of free-list insertion order —
    # the list's internal order is history-dependent and not reconstructed
    # by crash recovery, so an order-broken tie would make a recovered
    # scheduler place differently than the continuous one (found by the
    # chaos harness's probe-equivalence once drains made such ties
    # consequential). config_order equals a fresh boot's insertion order,
    # so fresh-cluster placements are unchanged.
    usable.sort(
        key=lambda c: (
            c.used_leaf_cells_at_priority.get(OPPORTUNISTIC_PRIORITY, 0),
            c.unusable_leaf_num,
            c.config_order,
        )
    )
    return usable


def map_virtual_cells_to_physical(
    vertices: List[BindingPathVertex],
    candidates: Iterable[Cell],
    suggested_nodes: Optional[Set[str]],
    ignore_suggested: bool,
    bindings: Dict[api.CellAddress, PhysicalCell],
    return_picked: bool,
) -> Tuple[bool, List[PhysicalCell]]:
    """Backtracking assignment of sibling virtual cells to candidate physical
    cells, recursing into children so the topology inside a preassigned cell
    matches its physical counterpart exactly
    (reference: cell_allocation.go:252-318)."""
    if not vertices:
        return True, []
    usable = get_usable_physical_cells(
        candidates, len(vertices), suggested_nodes, ignore_suggested
    )
    if usable is None:
        return False, []

    picked_for: List[int] = [0] * len(vertices)
    picked_set: Set[int] = set()
    cell_index = 0
    while cell_index >= 0:
        candidate_index = picked_for[cell_index]
        advanced = False
        while candidate_index < len(usable):
            if candidate_index in picked_set:
                candidate_index += 1
                continue
            candidate = usable[candidate_index]
            if candidate.level == LOWEST_LEVEL:
                picked = True
                bindings[vertices[cell_index].cell.address] = candidate
            else:
                picked, _ = map_virtual_cells_to_physical(
                    vertices[cell_index].children_to_bind,
                    candidate.children,
                    suggested_nodes,
                    ignore_suggested,
                    bindings,
                    return_picked=False,
                )
            if picked:
                picked_for[cell_index] = candidate_index
                picked_set.add(candidate_index)
                if cell_index == len(vertices) - 1:
                    if not return_picked:
                        return True, []
                    return True, [usable[i] for i in picked_for]
                advanced = True
                break
            candidate_index += 1
        if advanced:
            cell_index += 1
            picked_for[cell_index] = 0
        else:
            cell_index -= 1
            if cell_index >= 0:
                picked_set.discard(picked_for[cell_index])
                picked_for[cell_index] += 1
    return False, []


def map_physical_cell_to_virtual(
    c: PhysicalCell,
    vccl: ChainCellList,
    preassigned_level: CellLevel,
    p: CellPriority,
) -> Tuple[Optional[VirtualCell], str]:
    """Inverse mapping used when replaying an allocated pod after restart:
    find the virtual cell a physical cell should bind to
    (reference: cell_allocation.go:320-350, plus one deliberate fix: an
    existing binding is only reusable if it belongs to THIS VC's cell list —
    the reference returns any binding unchecked, so a replayed pod whose
    cells carry another VC's doomed-bad binding would silently record that
    VC's virtual cells as its own placement, corrupting both VCs' counters
    (found by the restart-replay fuzzer))."""
    if c.virtual_cell is not None:
        pac = c.virtual_cell.preassigned_cell
        if any(
            cell_equal(pac, candidate)
            for candidate in vccl[preassigned_level]
        ):
            return c.virtual_cell, ""
        target = vccl[preassigned_level][0] if vccl[preassigned_level] else None
        if target is not None and getattr(target, "vc", None) == c.virtual_cell.vc:
            # Same VC, different cell list: the binding belongs to a pinned
            # cell while the replay targets the non-pinned quota (or vice
            # versa) — not a foreign-VC conflict.
            return None, (
                f"physical cell {c.address} is bound to virtual cell "
                f"{c.virtual_cell.address} of the same VC but outside the "
                "target (pinned vs non-pinned) cell list"
            )
        return None, (
            f"physical cell {c.address} is bound to virtual cell "
            f"{c.virtual_cell.address} of another VC"
        )
    if c.level == preassigned_level:
        preassigned = get_lowest_priority_virtual_cell(
            vccl[preassigned_level], p
        )
        if preassigned is None:
            return None, (
                "insufficient free cell in the VC at the preassigned level "
                f"({preassigned_level})"
            )
        return preassigned, ""
    if c.parent is None:
        return None, (
            "physical and virtual cell hierarchies not match (cannot reach "
            f"the preassigned level {preassigned_level} in physical)"
        )
    parent_virtual, message = map_physical_cell_to_virtual(
        c.parent, vccl, preassigned_level, p
    )
    if parent_virtual is None:
        return None, message
    return get_lowest_priority_virtual_cell(parent_virtual.children, p), ""


def get_lowest_priority_virtual_cell(
    cl: List[Cell], p: CellPriority
) -> Optional[VirtualCell]:
    """A free unbound cell if one exists, else the lowest-priority cell below
    p (it will be lazy-preempted) — needed after reconfiguration when no free
    cell may be left (reference: cell_allocation.go:352-377)."""
    lowest_priority = MAX_GUARANTEED_PRIORITY
    lowest_cell: Optional[VirtualCell] = None
    for c in cl:
        assert isinstance(c, VirtualCell)
        if c.priority == FREE_PRIORITY:
            if c.physical_cell is None:
                return c
            # A free cell with a binding is a doomed bad cell; skip it.
            continue
        if c.priority < p and c.priority < lowest_priority:
            lowest_priority = c.priority
            lowest_cell = c
    return lowest_cell


def get_unbound_virtual_cell(cl: List[Cell]) -> Optional[VirtualCell]:
    """(reference: cell_allocation.go:379-383)"""
    for c in cl:
        assert isinstance(c, VirtualCell)
        if c.physical_cell is None:
            return c
    return None


def bind_cell(pc: PhysicalCell, vc: VirtualCell) -> None:
    """Bind a virtual cell chain to a physical cell chain bottom-up, stopping
    at the first already-bound ancestor (reference: cell_allocation.go:386-397)."""
    cur_vc: Optional[VirtualCell] = vc
    cur_pc: Optional[PhysicalCell] = pc
    while cur_vc is not None and cur_vc.physical_cell is None:
        cur_pc.set_virtual_cell(cur_vc)
        cur_vc.set_physical_cell(cur_pc)
        common.log.debug(
            "Virtual cell %s is bound to physical cell %s",
            cur_vc.address,
            cur_pc.address,
        )
        cur_vc = cur_vc.parent  # type: ignore[assignment]
        cur_pc = cur_pc.parent  # type: ignore[assignment]


def unbind_cell(c: PhysicalCell) -> None:
    """Unbind bottom-up, stopping at pinned cells (statically bound) or at an
    ancestor that still has bound children (reference: cell_allocation.go:401-420)."""
    bound_virtual = c.virtual_cell
    while bound_virtual is not None and not bound_virtual.physical_cell.pinned:
        bound_physical = bound_virtual.physical_cell
        common.log.debug(
            "Virtual cell %s is unbound from physical cell %s",
            bound_virtual.address,
            bound_physical.address,
        )
        bound_virtual.set_physical_cell(None)
        bound_physical.set_virtual_cell(None)
        parent = bound_virtual.parent
        if parent is None:
            return
        for child in parent.children:
            assert isinstance(child, VirtualCell)
            if child.physical_cell is not None:
                return
        assert isinstance(parent, VirtualCell)
        bound_virtual = parent


def set_cell_priority(c: Cell, p: CellPriority) -> None:
    """Set priority bottom-up, maintaining parent = max(children)
    (reference: cell_allocation.go:425-443)."""
    original = c.priority
    if isinstance(c, (PhysicalCell, VirtualCell)):
        c.set_priority(p)
    else:
        c.priority = p
    parent = c.parent
    if parent is not None:
        if p > parent.priority:
            set_cell_priority(parent, p)
        elif original == parent.priority and p < original:
            max_buddy = FREE_PRIORITY
            for buddy in parent.children:
                if buddy.priority > max_buddy:
                    max_buddy = buddy.priority
            set_cell_priority(parent, max_buddy)


def update_used_leaf_cell_numbers(c: Cell, p: CellPriority, increase: bool) -> None:
    """Propagate used-chip counters up the tree
    (reference: cell_allocation.go:447-454)."""
    delta = 1 if increase else -1
    cur: Optional[Cell] = c
    while cur is not None:
        cur.increase_used_leaf_cells_at_priority(p, delta)
        cur = cur.parent
