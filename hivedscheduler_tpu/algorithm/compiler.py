"""Config compiler: YAML spec -> cell trees.

Python equivalent of the reference's ``pkg/algorithm/config.go``: cell-type
chain compilation (cellTypeConstructor L45-108), physical cell instantiation
(physicalCellConstructor L110-235), per-VC virtual cell instantiation
(virtualCellConstructor L237-413), and the chain metadata maps
(parseCellChainInfo L415-440, ParseConfig L442-477).

For TPU clusters the chains encode the ICI torus decomposition, e.g.::

    v5p-chip -> v5p-host(4 chips) -> v5p-cube(16 hosts) -> v5p-slice

with node level = the TPU-VM host (the K8s node). See tpu/topology.py for
preset chain generators.
"""

from __future__ import annotations

import multiprocessing
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..api import types as api
from ..api.config import Config
from .cell import (
    Cell,
    CellChain,
    CellLevel,
    ChainCellList,
    LOWEST_LEVEL,
    PhysicalCell,
    VirtualCell,
)

# Parallel physical compile (doc/hot-path.md "Boot and transport plane"):
# "0" forces the serial builder (today's path exactly), a positive integer
# forces that worker count, unset auto-enables for fleets past
# _PARALLEL_MIN_CELLS on multi-core hosts. Start method defaults to fork
# (the compile runs at boot, before accelerator threads exist; workers
# only build cells and pickle them back).
PARALLEL_COMPILE_ENV = "HIVED_PARALLEL_COMPILE"
PARALLEL_START_ENV = "HIVED_PARALLEL_COMPILE_START"
# Lazy per-VC virtual compile: "0" restores the eager all-VC compile.
LAZY_VC_ENV = "HIVED_LAZY_VC"

# Auto-enable floor: below this many physical cells the pool startup
# costs more than the build (432-host fleet is ~2.6k cells; 10k hosts is
# ~60k+). Tests and small configs stay on the serial path.
_PARALLEL_MIN_CELLS = 20_000


@dataclass
class ChainElement:
    """Compiled metadata for one cell type in a chain
    (reference: config.go:34-43 ``cellChainElement``)."""

    cell_type: api.CellType
    level: CellLevel
    child_cell_type: api.CellType
    child_number: int
    has_node: bool       # at or above node (TPU-VM host) level
    is_multi_nodes: bool  # strictly above node level (multi-host slice)
    leaf_cell_type: str
    leaf_cell_number: int


def build_cell_chains(
    cell_types: Dict[api.CellType, api.CellTypeSpec]
) -> Dict[api.CellType, ChainElement]:
    """Compile the cell-type forest into per-type chain elements. A type not
    present in the map is a leaf cell (one TPU chip)
    (reference: config.go:59-108)."""
    elements: Dict[api.CellType, ChainElement] = {}

    def add(ct: api.CellType) -> None:
        if ct in elements:
            return
        spec = cell_types.get(ct)
        if spec is None:
            elements[ct] = ChainElement(
                cell_type=ct,
                level=LOWEST_LEVEL,
                child_cell_type="",
                child_number=0,
                has_node=False,
                is_multi_nodes=False,
                leaf_cell_type=str(ct),
                leaf_cell_number=1,
            )
            return
        add(spec.child_cell_type)
        child = elements[spec.child_cell_type]
        elements[ct] = ChainElement(
            cell_type=ct,
            level=child.level + 1,
            child_cell_type=child.cell_type,
            child_number=spec.child_cell_number,
            has_node=child.has_node or spec.is_node_level,
            is_multi_nodes=child.has_node,
            leaf_cell_type=child.leaf_cell_type,
            leaf_cell_number=child.leaf_cell_number * spec.child_cell_number,
        )

    for ct in cell_types:
        add(ct)
    return elements


def spec_cell_count(spec: api.PhysicalCellSpec) -> int:
    """Number of cells a physical spec subtree compiles to (== the number
    of spec nodes: ``_build_cell`` creates exactly one cell per node).
    This is what makes the parallel compile's ``config_order`` stamps
    precomputable: a spec's stamp range is [base+1, base+count] where
    base is the total count of all earlier specs, independent of which
    worker builds it."""
    count = 0
    stack = [spec]
    while stack:
        s = stack.pop()
        count += 1
        if s.cell_children:
            stack.extend(s.cell_children)
    return count


def type_cell_count(
    elements: Dict[api.CellType, ChainElement], ct: api.CellType
) -> int:
    """Cells in a full subtree of type ``ct`` (type-determined: the
    VIRTUAL builder always constructs exactly child_number children per
    cell, so per-VC ``config_order`` offsets are computable without
    building anything)."""
    memo: Dict[api.CellType, int] = {}

    def size(t: api.CellType) -> int:
        cached = memo.get(t)
        if cached is not None:
            return cached
        ce = elements[t]
        n = 1 if ce.level == LOWEST_LEVEL else (
            1 + ce.child_number * size(ce.child_cell_type)
        )
        memo[t] = n
        return n

    return size(ct)


def chain_families(
    cell_types: Dict[api.CellType, api.CellTypeSpec],
    physical_cells: Sequence[api.PhysicalCellSpec],
) -> Tuple[Tuple[CellChain, ...], ...]:
    """Connected components of the "shares a leaf SKU" relation over the
    configured chains — the PR-8 RoutingTable partition, lifted into the
    compiler so both the shards frontend and the parallel physical build
    derive it without instantiating a throwaway core. Chains in one
    family may be probed by the same typed pod (and must co-reside on a
    shard); chains in DIFFERENT families share no cell type, so their
    trees compile independently. Families and their members are sorted
    (the RoutingTable contract)."""
    elements = build_cell_chains(cell_types)
    chains = sorted({
        str(spec.cell_type)
        for spec in physical_cells
        if spec.cell_type in elements
    })
    leaf_to_chains: Dict[str, List[str]] = {}
    for chain in chains:
        leaf_to_chains.setdefault(
            elements[api.CellType(chain)].leaf_cell_type, []
        ).append(chain)
    parent: Dict[str, str] = {c: c for c in chains}

    def find(c: str) -> str:
        while parent[c] != c:
            parent[c] = parent[parent[c]]
            c = parent[c]
        return c

    for group in leaf_to_chains.values():
        for c in group[1:]:
            parent[find(group[0])] = find(c)
    groups: Dict[str, List[str]] = {}
    for c in chains:
        groups.setdefault(find(c), []).append(c)
    return tuple(sorted(tuple(sorted(g)) for g in groups.values()))


class _PhysicalBuilder:
    """Instantiate physical cell trees from specs
    (reference: config.go:110-235)."""

    def __init__(
        self,
        elements: Dict[api.CellType, ChainElement],
        specs: List[api.PhysicalCellSpec],
    ):
        self.elements = elements
        self.specs = specs
        self.full_list: Dict[CellChain, ChainCellList] = {}
        self.free_list: Dict[CellChain, ChainCellList] = {}
        self.pinned_cells: Dict[api.PinnedCellId, PhysicalCell] = {}
        self._chain: CellChain = ""
        self._order = 0

    def build_top(
        self, spec: api.PhysicalCellSpec, order_base: Optional[int] = None
    ) -> None:
        """Compile one top-level spec. ``order_base`` pins the first
        ``config_order`` stamp of this subtree (parallel compile: each
        spec's range is precomputed from spec_cell_count so any partition
        of the spec list yields the serial stamps bit-identically)."""
        if order_base is not None:
            self._order = order_base
        self._chain = spec.cell_type
        element = self.elements.get(spec.cell_type)
        if element is None:
            raise api.bad_request(
                f"cellType {spec.cell_type} in physicalCells is not found "
                "in cell types definition"
            )
        if not element.has_node:
            raise api.bad_request(
                f"top cell must be node-level or above: {spec.cell_type}"
            )
        root = self._build_cell(spec, spec.cell_type, "")
        self.free_list.setdefault(root.chain, ChainCellList(root.level))
        self.free_list[root.chain][root.level].append(root)

    def build(
        self,
    ) -> Tuple[
        Dict[CellChain, ChainCellList],
        Dict[CellChain, ChainCellList],
        Dict[api.PinnedCellId, PhysicalCell],
    ]:
        for spec in self.specs:
            self.build_top(spec)
        return self.full_list, self.free_list, self.pinned_cells

    def _build_cell(
        self, spec: api.PhysicalCellSpec, ct: api.CellType, current_node: str
    ) -> PhysicalCell:
        """(reference: config.go:141-183 ``buildChildCell``)"""
        ce = self.elements[ct]
        last_segment = spec.cell_address.rsplit("/", 1)[-1]
        if ce.has_node and not ce.is_multi_nodes:
            # Node-level cell: its address segment is the K8s node name,
            # passed down so leaf cells know their host.
            current_node = last_segment

        cell = PhysicalCell(
            self._chain,
            ce.level,
            spec.cell_address,
            ce.has_node,
            ce.leaf_cell_number,
            cell_type=ce.cell_type,
            is_node_level=ce.has_node and not ce.is_multi_nodes,
        )
        # Canonical candidate tiebreak: the compile traversal position (==
        # a fresh boot's free-list insertion order), NOT the live list
        # order, which is history-dependent and not recovered.
        self._order += 1
        cell.config_order = self._order
        self.full_list.setdefault(self._chain, ChainCellList())
        self.full_list[self._chain][ce.level].append(cell)
        if spec.pinned_cell_id:
            self.pinned_cells[spec.pinned_cell_id] = cell
            cell.pinned = True

        if ce.level == LOWEST_LEVEL:
            # Leaf: one chip; address segment is the chip index on its host.
            cell.set_physical_resources([current_node], [int(last_segment)])
            return cell

        nodes: List[str] = []
        indices: List[int] = []
        children: List[Cell] = []
        for child_spec in spec.cell_children:
            child = self._build_cell(child_spec, ce.child_cell_type, current_node)
            child.parent = cell
            children.append(child)
            if ce.is_multi_nodes:
                nodes.extend(child.nodes)
            else:
                indices.extend(child.leaf_cell_indices)
        cell.set_children(children)
        if ce.is_multi_nodes:
            # Multi-host slice cell: chip indices are meaningless above the
            # host (reference: config.go:176 sets [-1]).
            indices = [-1]
        else:
            nodes = [current_node]
        cell.set_physical_resources(nodes, indices)
        return cell


def _compile_spec_batch(
    cell_types: Dict[api.CellType, api.CellTypeSpec],
    batch: List[Tuple[api.PhysicalCellSpec, int]],
):
    """Worker entry for the parallel physical compile: build a batch of
    (top spec, config_order base) pairs and return the partial listings.
    Each batch holds specs of ONE chain family in original spec order, so
    the parent's merge is a per-chain concatenation."""
    elements = build_cell_chains(cell_types)
    builder = _PhysicalBuilder(elements, [])
    for spec, base in batch:
        builder.build_top(spec, order_base=base)
    return builder.full_list, builder.free_list, builder.pinned_cells


def _compile_spec_batch_wire(
    cell_types: Dict[api.CellType, api.CellTypeSpec],
    batch: List[Tuple[api.PhysicalCellSpec, int]],
):
    """_compile_spec_batch, handed back as ONE columnar wire frame
    (bytes) instead of a pickled object graph: PR 11 measured the
    parent-side unpickle of ~75k PhysicalCell objects at ~1.6 s —
    slower than just building serially — because pickle walks and
    reconstructs every object, parent pointer, and per-cell dict.
    The frame ships five struct-packed columns plus two interned
    string blobs; the parent rebuilds the trees in one tight loop
    (doc/hot-path.md "One wire"). Falls back to the legacy triple on
    any encode surprise — the parent accepts either shape."""
    res = _compile_spec_batch(cell_types, batch)
    try:
        return _encode_cell_batch(*res)
    except Exception:  # noqa: BLE001 — fall back to the pickled triple
        return res


def _encode_cell_batch(full_list, free_list, pinned_cells) -> bytes:
    """Columnar encode of one batch's build results. Preorder records
    per tree, trees grouped by chain in free-list order (the merge is
    per-chain, so cross-chain interleaving inside a batch need not be
    preserved); everything else the constructor needs is either a
    packed column or derivable (config_order from the tree's base,
    nodes/leaf indices from addresses + levels, exactly the way
    _build_cell derives them)."""
    from array import array

    from ..scheduler import wire

    type_table: Dict[str, int] = {}
    addrs: List[str] = []
    levels = array("H")
    nchild = array("I")
    typeids = array("H")
    leafnums = array("I")
    flags = array("B")
    trees: List[Tuple[str, int, int]] = []
    pinned_pairs: List[Tuple[int, str]] = []
    pinned_by_id = {id(c): pid for pid, c in pinned_cells.items()}
    idx = 0
    for chain, ccl in free_list.items():
        top = ccl.top_level
        for root in ccl[top]:
            n0 = idx
            stack = [root]
            while stack:
                cell = stack.pop()
                levels.append(cell.level)
                nchild.append(len(cell.children))
                tid = type_table.setdefault(
                    str(cell.cell_type), len(type_table)
                )
                typeids.append(tid)
                leafnums.append(cell.total_leaf_cell_num)
                flags.append(
                    (1 if cell.at_or_higher_than_node else 0)
                    | (2 if cell.is_node_level else 0)
                    | (4 if cell.pinned else 0)
                )
                addrs.append(str(cell.address))
                if cell.pinned:
                    pinned_pairs.append((idx, pinned_by_id[id(cell)]))
                idx += 1
                stack.extend(reversed(cell.children))
            # config_order stamps are base+1..base+n in preorder, so
            # the root's stamp recovers the whole tree's range.
            trees.append((str(chain), idx - n0, root.config_order - 1))
    payload = (
        tuple(type_table),  # insertion order == id order
        addrs,
        levels.tobytes(),
        nchild.tobytes(),
        typeids.tobytes(),
        leafnums.tobytes(),
        flags.tobytes(),
        tuple(trees),
        tuple(pinned_pairs),
    )
    return wire.dumps(payload, kind=wire.KIND_CELLS)


def _decode_cell_batch(buf: bytes):
    """Rebuild (full_list, free_list, pinned_cells) from one columnar
    frame: one tight preorder loop over packed columns. The bookkeeping
    mirrors _build_cell/_build_top exactly — full-list append at visit
    time (preorder == the serial append order per level), free-list
    holds only roots, nodes/leaf indices derived from the node-level
    address segments the same way the builder derives them — which is
    what lets the differential compile test assert bit-identity."""
    from array import array

    from ..scheduler import wire

    (
        type_table, addrs, levels_b, nchild_b, typeids_b, leafnums_b,
        flags_b, trees, pinned_pairs,
    ) = wire.loads(buf, kind=wire.KIND_CELLS)
    levels = array("H")
    levels.frombytes(levels_b)
    nchild = array("I")
    nchild.frombytes(nchild_b)
    typeids = array("H")
    typeids.frombytes(typeids_b)
    leafnums = array("I")
    leafnums.frombytes(leafnums_b)
    flags = array("B")
    flags.frombytes(flags_b)
    pinned_of = dict(pinned_pairs)

    full: Dict[CellChain, ChainCellList] = {}
    free: Dict[CellChain, ChainCellList] = {}
    pinned: Dict[api.PinnedCellId, PhysicalCell] = {}

    def finalize(cell: PhysicalCell, cur_node: str) -> None:
        # Mirrors _build_cell's resource derivation at subtree
        # completion time.
        if cell.level == LOWEST_LEVEL:
            last = cell.address.rsplit("/", 1)[-1]
            cell.set_physical_resources([cur_node], [int(last)])
        elif cell.at_or_higher_than_node and not cell.is_node_level:
            nodes: List[str] = []
            for ch in cell.children:
                nodes.extend(ch.nodes)
            cell.set_physical_resources(nodes, [-1])
        else:
            indices: List[int] = []
            for ch in cell.children:
                indices.extend(ch.leaf_cell_indices)
            cell.set_physical_resources([cur_node], indices)

    idx = 0
    for chain, n_cells, base in trees:
        ccl = full.get(chain)
        if ccl is None:
            ccl = full[chain] = ChainCellList()
        # stack entries: [cell, children remaining, its current_node]
        stack: List[List] = []
        tree_root: Optional[PhysicalCell] = None
        for k in range(n_cells):
            lvl = levels[idx]
            f = flags[idx]
            address = addrs[idx]
            cell = PhysicalCell(
                chain,
                lvl,
                address,
                bool(f & 1),
                leafnums[idx],
                cell_type=type_table[typeids[idx]],
                is_node_level=bool(f & 2),
            )
            cell.config_order = base + k + 1
            ccl[lvl].append(cell)
            if f & 4:
                cell.pinned = True
                pinned[pinned_of[idx]] = cell
            cur_node = stack[-1][2] if stack else ""
            if f & 2:
                cur_node = address.rsplit("/", 1)[-1]
            if stack:
                cell.parent = stack[-1][0]
                stack[-1][0].children.append(cell)
            else:
                tree_root = cell
            n = nchild[idx]
            idx += 1
            if n:
                stack.append([cell, n, cur_node])
                continue
            finalize(cell, cur_node)
            while stack:
                stack[-1][1] -= 1
                if stack[-1][1]:
                    break
                done, _, done_node = stack.pop()
                finalize(done, done_node)
        if stack:
            # A malformed frame would desync the tree walk; the wire
            # length/crc layers should make this unreachable.
            raise ValueError("cell frame tree walk desynced")
        if tree_root is not None:
            fccl = free.get(chain)
            if fccl is None:
                fccl = free[chain] = ChainCellList(tree_root.level)
            fccl[tree_root.level].append(tree_root)
    return full, free, pinned


def _parallel_worker_count(total_cells: int) -> int:
    """Workers for the parallel physical compile; 0 = serial. Env
    HIVED_PARALLEL_COMPILE: "0"/unset = serial (the default), N = N
    workers, "auto" = one per core past the cell floor.

    Default-off is a MEASURED honest null, not caution (doc/hot-path.md
    "Boot and transport plane"): the per-family build is embarrassingly
    parallel and bit-identical (the differential compile test), but the
    results cross the process boundary by pickle, and at 75k cells the
    parent-side unpickle alone (~1.6 s) exceeds the serial build
    (~1.1 s) — so pickle-back parallelism loses at every worker count.
    The lazy-VC and boot-fold planes carry the boot budget instead; the
    env stays for hosts where a cheaper transport (or a faster pickle)
    changes the arithmetic."""
    env = os.environ.get(PARALLEL_COMPILE_ENV, "").strip()
    if not env or env == "0":
        return 0
    cpu = os.cpu_count() or 1
    try:
        if multiprocessing.current_process().daemon:
            return 0  # a daemonic shard worker cannot fork children
    except Exception:  # noqa: BLE001
        return 0
    if env == "auto":
        if cpu < 2 or total_cells < _PARALLEL_MIN_CELLS:
            return 0
        return min(cpu, 16)
    try:
        return max(0, int(env))
    except ValueError:
        return 0


def _build_physical_parallel(
    config: Config,
    elements: Dict[api.CellType, ChainElement],
    workers: int,
) -> Tuple[
    Dict[CellChain, ChainCellList],
    Dict[CellChain, ChainCellList],
    Dict[api.PinnedCellId, PhysicalCell],
]:
    """Family-partitioned parallel physical compile. Determinism argument
    (doc/hot-path.md "Boot and transport plane"): (1) config_order stamps
    are precomputed per top spec from spec_cell_count, so a subtree's
    stamps do not depend on which worker builds it or when; (2) chains in
    different families share no cell type, and specs of one chain are
    batched in original relative order, so per-chain cell-list order is
    the serial order; (3) the merge rebuilds every dict in the serial
    insertion order (chain first-occurrence; pinned ids by config_order).
    The differential compile test walks the full tree asserting exactly
    this."""
    from concurrent import futures

    pc = config.physical_cluster
    specs = list(pc.physical_cells)
    counts = [spec_cell_count(s) for s in specs]
    bases: List[int] = []
    total = 0
    for n in counts:
        bases.append(total)
        total += n

    families = chain_families(pc.cell_types, specs)
    family_of: Dict[str, int] = {
        c: i for i, fam in enumerate(families) for c in fam
    }
    per_family: Dict[int, List[int]] = {}
    for i, spec in enumerate(specs):
        fam = family_of.get(str(spec.cell_type))
        if fam is None:
            # Unknown chain: let the serial builder raise its user error.
            raise api.bad_request(
                f"cellType {spec.cell_type} in physicalCells is not found "
                "in cell types definition"
            )
        per_family.setdefault(fam, []).append(i)

    # Family-major batches, each family split into contiguous chunks of
    # roughly total/(2*workers) cells for load balance.
    target = max(1, total // max(1, 2 * workers))
    batches: List[List[Tuple[api.PhysicalCellSpec, int]]] = []
    for fam in sorted(per_family):
        chunk: List[Tuple[api.PhysicalCellSpec, int]] = []
        chunk_cells = 0
        for i in per_family[fam]:
            chunk.append((specs[i], bases[i]))
            chunk_cells += counts[i]
            if chunk_cells >= target:
                batches.append(chunk)
                chunk, chunk_cells = [], 0
        if chunk:
            batches.append(chunk)

    start = os.environ.get(PARALLEL_START_ENV) or "fork"
    try:
        ctx = multiprocessing.get_context(start)
    except ValueError:
        ctx = multiprocessing.get_context()
    # One wire (doc/hot-path.md "One wire"): the hand-back crosses the
    # pool boundary as a columnar frame unless HIVED_WIRE=0 — the
    # pickled-object-graph hand-back is the measured reason parallel
    # compile used to lose to the serial build.
    from ..scheduler import wire as wire_mod

    worker_fn = (
        _compile_spec_batch_wire if wire_mod.enabled()
        else _compile_spec_batch
    )
    with futures.ProcessPoolExecutor(
        max_workers=min(workers, max(1, len(batches))), mp_context=ctx
    ) as pool:
        results = [
            _decode_cell_batch(r) if isinstance(r, bytes) else r
            for r in pool.map(
                worker_fn,
                [pc.cell_types] * len(batches),
                batches,
            )
        ]

    # Merge in the serial insertion orders.
    chain_order: List[CellChain] = []
    seen = set()
    for spec in specs:
        c = str(spec.cell_type)
        if c not in seen:
            seen.add(c)
            chain_order.append(c)
    full: Dict[CellChain, ChainCellList] = {}
    free: Dict[CellChain, ChainCellList] = {}
    pinned_cells: List[PhysicalCell] = []
    pinned_ids: Dict[int, api.PinnedCellId] = {}
    by_chain_full: Dict[CellChain, List[ChainCellList]] = {}
    by_chain_free: Dict[CellChain, List[ChainCellList]] = {}
    for part_full, part_free, part_pinned in results:
        for chain, ccl in part_full.items():
            by_chain_full.setdefault(chain, []).append(ccl)
        for chain, ccl in part_free.items():
            by_chain_free.setdefault(chain, []).append(ccl)
        for pid, cell in part_pinned.items():
            pinned_cells.append(cell)
            pinned_ids[cell.config_order] = pid
    for chain in chain_order:
        parts = by_chain_full.get(chain, [])
        if not parts:
            continue
        merged = parts[0]
        for extra in parts[1:]:
            for level, cl in extra.levels.items():
                merged[level].extend(cl)
        full[chain] = merged
        fparts = by_chain_free.get(chain, [])
        fmerged = fparts[0]
        for extra in fparts[1:]:
            for level, cl in extra.levels.items():
                fmerged[level].extend(cl)
        free[chain] = fmerged
    # Serial pinned-dict order is the compile traversal order, which the
    # config_order stamp records exactly.
    pinned: Dict[api.PinnedCellId, PhysicalCell] = {}
    for cell in sorted(pinned_cells, key=lambda c: c.config_order):
        pinned[pinned_ids[cell.config_order]] = cell
    return full, free, pinned


class _VirtualBuilder:
    """Instantiate per-VC virtual cell trees
    (reference: config.go:237-413)."""

    def __init__(
        self,
        elements: Dict[api.CellType, ChainElement],
        specs: Dict[api.VirtualClusterName, api.VirtualClusterSpec],
        raw_pinned: Dict[api.PinnedCellId, PhysicalCell],
    ):
        self.elements = elements
        self.specs = specs
        self.raw_pinned = raw_pinned
        self.vc_free_cell_num: Dict[
            api.VirtualClusterName, Dict[CellChain, Dict[CellLevel, int]]
        ] = {}
        self.non_pinned_full: Dict[
            api.VirtualClusterName, Dict[CellChain, ChainCellList]
        ] = {}
        self.non_pinned_free: Dict[
            api.VirtualClusterName, Dict[CellChain, ChainCellList]
        ] = {}
        self.pinned: Dict[
            api.VirtualClusterName, Dict[api.PinnedCellId, ChainCellList]
        ] = {}
        self.pinned_physical: Dict[
            api.VirtualClusterName, Dict[api.PinnedCellId, PhysicalCell]
        ] = {}
        # building state
        self._vc: api.VirtualClusterName = ""
        self._chain: CellChain = ""
        self._root: Optional[VirtualCell] = None
        self._pid: api.PinnedCellId = ""
        # Canonical tiebreak stamp for VIRTUAL cells, mirroring the
        # physical builder's: the packing view's total sort order
        # (placement._NodeView.sort_key) must be a pure function of cell
        # state for virtual anchors too, or intra-VC view order would
        # fall back to scoring history on equal scores.
        self._order = 0

    def build(self):
        for vc in self.specs:
            self.build_vc(vc)
        return (
            self.vc_free_cell_num,
            self.non_pinned_full,
            self.non_pinned_free,
            self.pinned,
            self.pinned_physical,
        )

    def build_vc(self, vc: api.VirtualClusterName,
                 order_base: Optional[int] = None):
        """Compile ONE VC's virtual cell trees. ``order_base`` pins the
        VC's first config_order stamp (lazy per-VC compile: offsets are
        precomputed from type_cell_count so a VC compiled on first touch
        carries the same stamps the eager all-VC compile would have
        given it)."""
        if order_base is not None:
            self._order = order_base
        spec = self.specs[vc]
        self.vc_free_cell_num[vc] = {}
        self.non_pinned_full[vc] = {}
        self.non_pinned_free[vc] = {}
        self.pinned[vc] = {}
        self.pinned_physical[vc] = {}

        num_cells = 0
        for vcell in spec.virtual_cells:
            # Fully-qualified dotted type: chain.segment...segment; the
            # first segment is the chain, the last is the preassigned
            # cell's own type (reference: config.go:367-373).
            parts = vcell.cell_type.split(".")
            chain: CellChain = parts[0]
            root_type: api.CellType = parts[-1]
            if root_type not in self.elements:
                raise api.bad_request(
                    f"cellType {root_type} in virtualCells is not found in "
                    "cell types definition"
                )
            root_level = self.elements[root_type].level
            self.vc_free_cell_num[vc].setdefault(chain, {})
            self.vc_free_cell_num[vc][chain][root_level] = (
                self.vc_free_cell_num[vc][chain].get(root_level, 0)
                + vcell.cell_number
            )
            for _ in range(vcell.cell_number):
                self._vc, self._chain, self._root, self._pid = vc, chain, None, ""
                root = self._build_cell(root_type, f"{vc}/{num_cells}")
                self.non_pinned_free[vc].setdefault(chain, ChainCellList())
                self.non_pinned_free[vc][chain][root.level].append(root)
                num_cells += 1

        for pcell in spec.pinned_cells:
            pid = pcell.pinned_cell_id
            pc = self.raw_pinned.get(pid)
            if pc is None:
                raise api.bad_request(
                    f"pinned cell not found in physicalCells: VC: {vc}, ID: {pid}"
                )
            self.pinned_physical[vc][pid] = pc
            # Find the cell type at the pinned cell's level by walking
            # down the chain (reference: config.go:394-398).
            child_type = api.CellType(pc.chain)
            while self.elements[child_type].level > pc.level:
                child_type = self.elements[child_type].child_cell_type
            self.vc_free_cell_num[vc].setdefault(pc.chain, {})
            self.vc_free_cell_num[vc][pc.chain][pc.level] = (
                self.vc_free_cell_num[vc][pc.chain].get(pc.level, 0) + 1
            )
            self._vc, self._chain, self._root, self._pid = vc, pc.chain, None, pid
            self._build_cell(child_type, f"{vc}/{num_cells}")
            num_cells += 1

    def _build_cell(self, ct: api.CellType, address: api.CellAddress) -> VirtualCell:
        """(reference: config.go:316-340 ``buildChildCell``)"""
        ce = self.elements[ct]
        cell = VirtualCell(
            self._vc,
            self._chain,
            ce.level,
            address,
            ce.has_node,
            ce.leaf_cell_number,
            cell_type=ce.cell_type,
            is_node_level=ce.has_node and not ce.is_multi_nodes,
        )
        self._order += 1
        cell.config_order = self._order
        if not self._pid:
            vc_lists = self.non_pinned_full[self._vc]
            vc_lists.setdefault(self._chain, ChainCellList())
            vc_lists[self._chain][ce.level].append(cell)
        else:
            pid_lists = self.pinned[self._vc]
            pid_lists.setdefault(self._pid, ChainCellList())
            pid_lists[self._pid][ce.level].append(cell)
        if self._root is None:
            self._root = cell
        cell.preassigned_cell = self._root

        if ce.level > LOWEST_LEVEL:
            # Child addresses restart at 0 under each preassigned cell and are
            # globally positional below (reference: config.go:322-330).
            parts = address.split("/")
            offset = 0 if len(parts) == 2 else int(parts[-1]) * ce.child_number
            children: List[Cell] = []
            for i in range(ce.child_number):
                child = self._build_cell(
                    ce.child_cell_type, f"{address}/{offset + i}"
                )
                child.parent = cell
                children.append(child)
            cell.set_children(children)
        return cell


@dataclass
class CompiledConfig:
    """Everything the core algorithm needs, compiled from the YAML config
    (reference: config.go:442-477 ``ParseConfig`` return values)."""

    # chain -> level -> all physical cells (including non-top levels)
    physical_full_list: Dict[CellChain, ChainCellList] = field(default_factory=dict)
    # chain -> level -> free physical cells (initially only top-level roots)
    physical_free_list: Dict[CellChain, ChainCellList] = field(default_factory=dict)
    # vc -> chain -> level -> quota cell count
    vc_free_cell_num: Dict[
        api.VirtualClusterName, Dict[CellChain, Dict[CellLevel, int]]
    ] = field(default_factory=dict)
    # vc -> chain -> level -> all / free virtual cells (non-pinned)
    virtual_non_pinned_full: Dict[
        api.VirtualClusterName, Dict[CellChain, ChainCellList]
    ] = field(default_factory=dict)
    virtual_non_pinned_free: Dict[
        api.VirtualClusterName, Dict[CellChain, ChainCellList]
    ] = field(default_factory=dict)
    # vc -> pinnedCellId -> level -> virtual cells
    virtual_pinned: Dict[
        api.VirtualClusterName, Dict[api.PinnedCellId, ChainCellList]
    ] = field(default_factory=dict)
    # vc -> pinnedCellId -> the pinned physical cell
    physical_pinned: Dict[
        api.VirtualClusterName, Dict[api.PinnedCellId, PhysicalCell]
    ] = field(default_factory=dict)
    # chain -> level -> leaf cells per cell of that level
    cell_level_to_leaf_num: Dict[CellChain, Dict[CellLevel, int]] = field(
        default_factory=dict
    )
    # chain -> level -> cell type name
    cell_level_to_type: Dict[CellChain, Dict[CellLevel, api.CellType]] = field(
        default_factory=dict
    )
    # leaf cell type (chip SKU, e.g. "v5p-chip") -> chains containing it
    leaf_cell_type_to_chain: Dict[str, List[CellChain]] = field(default_factory=dict)
    # chain -> leaf cell type
    chain_to_leaf_type: Dict[CellChain, str] = field(default_factory=dict)
    # Configured VC names in spec order (iterable without forcing any
    # compile) and, per VC, the chains it holds NON-PINNED quota in
    # (first-occurrence order of spec.virtualCells — what the compiled
    # IntraVCScheduler's non_pinned_preassigned keys would be). Both are
    # derived from the spec scan, so lock-chain derivation and shard
    # routing never force a VC compile.
    vc_names: List[api.VirtualClusterName] = field(default_factory=list)
    vc_nonpinned_chains: Dict[api.VirtualClusterName, List[CellChain]] = field(
        default_factory=dict
    )
    # Chain families (shares-a-leaf-SKU connected components): the
    # parallel-compile / shard-routing partition.
    families: Tuple[Tuple[CellChain, ...], ...] = ()
    # Lazy per-VC virtual compile (doc/hot-path.md "Boot and transport
    # plane"): quota counters and validation are eager (above); cell-tree
    # construction happens on first compile_vc(vc). False = everything
    # compiled already (HIVED_LAZY_VC=0 or legacy callers).
    lazy_vc: bool = False
    # internal: the memoizing virtual builder + per-VC config_order bases
    _virtual_builder: Optional[_VirtualBuilder] = None
    _vc_order_offsets: Dict[api.VirtualClusterName, int] = field(
        default_factory=dict
    )

    def vc_compiled(self, vc: api.VirtualClusterName) -> bool:
        return vc in self.virtual_non_pinned_full

    def compile_vc(self, vc: api.VirtualClusterName) -> None:
        """Compile one VC's virtual cell trees on first touch (memoized;
        a no-op for compiled VCs). config_order stamps come from the
        precomputed per-VC offsets, so a lazily compiled VC is
        bit-identical to its eager twin. NOT thread-safe by itself —
        HivedCore.ensure_vc serializes callers."""
        if vc in self.virtual_non_pinned_full:
            return
        vb = self._virtual_builder
        if vb is None or vc not in vb.specs:
            raise api.bad_request(f"VC {vc} does not exists!")
        vb.build_vc(vc, order_base=self._vc_order_offsets.get(vc))

    def compile_all_vcs(self) -> None:
        for vc in self.vc_names:
            self.compile_vc(vc)


def physical_spec_metadata(config: Config):
    """Routing metadata from a spec WALK — no cell instantiation: the
    shards frontend's RoutingTable used to pay a full throwaway core
    compile (plus its all-bad bootstrap) just to learn these maps, which
    at 50k hosts is its own boot wall. Returns
    ``(chains, node_chains, pinned_chain)``:

    - chains: sorted tuple of configured chain names;
    - node_chains: node name -> sorted tuple of chains with leaves on it;
    - pinned_chain: pinned cell id -> its chain.
    """
    pc = config.physical_cluster
    elements = build_cell_chains(pc.cell_types)
    chains: Set[str] = set()
    node_chains: Dict[str, Set[str]] = {}
    pinned_chain: Dict[str, str] = {}
    for top in pc.physical_cells:
        chain = str(top.cell_type)
        if top.cell_type not in elements:
            continue  # parse_config raises the user error; routing skips
        chains.add(chain)
        stack: List[Tuple[api.PhysicalCellSpec, api.CellType]] = [
            (top, top.cell_type)
        ]
        while stack:
            spec, ct = stack.pop()
            ce = elements[ct]
            if spec.pinned_cell_id:
                pinned_chain[str(spec.pinned_cell_id)] = chain
            if ce.has_node and not ce.is_multi_nodes:
                node = spec.cell_address.rsplit("/", 1)[-1]
                node_chains.setdefault(node, set()).add(chain)
            # Keep descending below node level: no new node names there
            # (child elements have has_node False), but pinned_cell_id
            # is legal at ANY depth and the routing table must know
            # every pinned cell's chain.
            for child in spec.cell_children or ():
                stack.append((child, ce.child_cell_type))
    return (
        tuple(sorted(chains)),
        {n: tuple(sorted(cs)) for n, cs in sorted(node_chains.items())},
        pinned_chain,
    )


def _vc_quota_scan(
    elements: Dict[api.CellType, ChainElement],
    vc_specs: Dict[api.VirtualClusterName, api.VirtualClusterSpec],
    raw_pinned: Dict[api.PinnedCellId, PhysicalCell],
):
    """Eager spec scan of the virtual clusters: quota counters, non-pinned
    chain lists, pinned physical cells, and per-VC config_order offsets —
    everything the core's boot accounting and validation need, WITHOUT
    constructing a single virtual cell. Raises exactly the user errors the
    cell builder would, so a bad config still fails at parse time even
    when every VC compiles lazily."""
    vc_free: Dict[
        api.VirtualClusterName, Dict[CellChain, Dict[CellLevel, int]]
    ] = {}
    pinned_physical: Dict[
        api.VirtualClusterName, Dict[api.PinnedCellId, PhysicalCell]
    ] = {}
    nonpinned_chains: Dict[api.VirtualClusterName, List[CellChain]] = {}
    offsets: Dict[api.VirtualClusterName, int] = {}
    base = 0
    for vc, spec in vc_specs.items():
        offsets[vc] = base
        vc_free[vc] = {}
        pinned_physical[vc] = {}
        nonpinned_chains[vc] = []
        for vcell in spec.virtual_cells:
            parts = vcell.cell_type.split(".")
            chain: CellChain = parts[0]
            root_type: api.CellType = parts[-1]
            if root_type not in elements:
                raise api.bad_request(
                    f"cellType {root_type} in virtualCells is not found in "
                    "cell types definition"
                )
            root_level = elements[root_type].level
            vc_free[vc].setdefault(chain, {})
            vc_free[vc][chain][root_level] = (
                vc_free[vc][chain].get(root_level, 0) + vcell.cell_number
            )
            if vcell.cell_number > 0 and chain not in nonpinned_chains[vc]:
                # Zero-count entries leave counters (matching the
                # builder's setdefault) but compile no cells, so the
                # chain never appears in non_pinned_preassigned.
                nonpinned_chains[vc].append(chain)
            base += vcell.cell_number * type_cell_count(elements, root_type)
        for pcell in spec.pinned_cells:
            pid = pcell.pinned_cell_id
            pc = raw_pinned.get(pid)
            if pc is None:
                raise api.bad_request(
                    f"pinned cell not found in physicalCells: VC: {vc}, ID: {pid}"
                )
            pinned_physical[vc][pid] = pc
            child_type = api.CellType(pc.chain)
            while elements[child_type].level > pc.level:
                child_type = elements[child_type].child_cell_type
            vc_free[vc].setdefault(pc.chain, {})
            vc_free[vc][pc.chain][pc.level] = (
                vc_free[vc][pc.chain].get(pc.level, 0) + 1
            )
            base += type_cell_count(elements, child_type)
    return vc_free, pinned_physical, nonpinned_chains, offsets


def parse_config(config: Config, lazy_vc: Optional[bool] = None) -> CompiledConfig:
    """(reference: config.go:442-477 ``ParseConfig``; boot plane:
    doc/hot-path.md "Boot and transport plane")

    The physical compile parallelizes by chain family when the fleet is
    large (HIVED_PARALLEL_COMPILE; bit-identical to serial by the offset
    argument in _build_physical_parallel). The virtual compile is LAZY
    per VC by default (HIVED_LAZY_VC=0 restores the eager build):
    validation and quota counters are computed here, cell trees on first
    compile_vc."""
    elements = build_cell_chains(config.physical_cluster.cell_types)
    specs = config.physical_cluster.physical_cells
    est_cells = 0
    for spec in specs:
        if spec.cell_type in elements:
            est_cells += type_cell_count(elements, spec.cell_type)
    workers = _parallel_worker_count(est_cells)
    full = free = raw_pinned = None
    if workers >= 1 and len(specs) > 1:
        try:
            full, free, raw_pinned = _build_physical_parallel(
                config, elements, workers
            )
        except api.WebServerError:
            raise
        except Exception as e:  # noqa: BLE001 — pool failure: build serially
            import logging

            logging.getLogger("hivedscheduler").warning(
                "parallel compile unavailable (%s); building serially", e
            )
            full = None
    if full is None:
        full, free, raw_pinned = _PhysicalBuilder(elements, specs).build()

    if lazy_vc is None:
        lazy_vc = os.environ.get(LAZY_VC_ENV, "1").strip() != "0"
    (
        vc_free_cell_num,
        pinned_physical,
        nonpinned_chains,
        offsets,
    ) = _vc_quota_scan(elements, config.virtual_clusters, raw_pinned)
    vb = _VirtualBuilder(elements, config.virtual_clusters, raw_pinned)

    cc = CompiledConfig(
        physical_full_list=full,
        physical_free_list=free,
        vc_free_cell_num=vc_free_cell_num,
        virtual_non_pinned_full=vb.non_pinned_full,
        virtual_non_pinned_free=vb.non_pinned_free,
        virtual_pinned=vb.pinned,
        physical_pinned=pinned_physical,
        vc_names=list(config.virtual_clusters),
        vc_nonpinned_chains=nonpinned_chains,
        families=chain_families(
            config.physical_cluster.cell_types, specs
        ),
        lazy_vc=lazy_vc,
        _virtual_builder=vb,
        _vc_order_offsets=offsets,
    )
    if not lazy_vc:
        cc.compile_all_vcs()
    # Chain metadata (reference: config.go:415-440 ``parseCellChainInfo``).
    for chain in full:
        ce = elements[api.CellType(chain)]
        cc.leaf_cell_type_to_chain.setdefault(ce.leaf_cell_type, []).append(chain)
        cc.chain_to_leaf_type[chain] = ce.leaf_cell_type
        cc.cell_level_to_leaf_num[chain] = {}
        cc.cell_level_to_type[chain] = {}
        cur: Optional[ChainElement] = ce
        while cur is not None:
            cc.cell_level_to_leaf_num[chain][cur.level] = cur.leaf_cell_number
            cc.cell_level_to_type[chain][cur.level] = cur.cell_type
            cur = elements.get(cur.child_cell_type)
    return cc
