"""Config compiler: YAML spec -> cell trees.

Python equivalent of the reference's ``pkg/algorithm/config.go``: cell-type
chain compilation (cellTypeConstructor L45-108), physical cell instantiation
(physicalCellConstructor L110-235), per-VC virtual cell instantiation
(virtualCellConstructor L237-413), and the chain metadata maps
(parseCellChainInfo L415-440, ParseConfig L442-477).

For TPU clusters the chains encode the ICI torus decomposition, e.g.::

    v5p-chip -> v5p-host(4 chips) -> v5p-cube(16 hosts) -> v5p-slice

with node level = the TPU-VM host (the K8s node). See tpu/topology.py for
preset chain generators.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..api import types as api
from ..api.config import Config
from .cell import (
    Cell,
    CellChain,
    CellLevel,
    ChainCellList,
    LOWEST_LEVEL,
    PhysicalCell,
    VirtualCell,
)


@dataclass
class ChainElement:
    """Compiled metadata for one cell type in a chain
    (reference: config.go:34-43 ``cellChainElement``)."""

    cell_type: api.CellType
    level: CellLevel
    child_cell_type: api.CellType
    child_number: int
    has_node: bool       # at or above node (TPU-VM host) level
    is_multi_nodes: bool  # strictly above node level (multi-host slice)
    leaf_cell_type: str
    leaf_cell_number: int


def build_cell_chains(
    cell_types: Dict[api.CellType, api.CellTypeSpec]
) -> Dict[api.CellType, ChainElement]:
    """Compile the cell-type forest into per-type chain elements. A type not
    present in the map is a leaf cell (one TPU chip)
    (reference: config.go:59-108)."""
    elements: Dict[api.CellType, ChainElement] = {}

    def add(ct: api.CellType) -> None:
        if ct in elements:
            return
        spec = cell_types.get(ct)
        if spec is None:
            elements[ct] = ChainElement(
                cell_type=ct,
                level=LOWEST_LEVEL,
                child_cell_type="",
                child_number=0,
                has_node=False,
                is_multi_nodes=False,
                leaf_cell_type=str(ct),
                leaf_cell_number=1,
            )
            return
        add(spec.child_cell_type)
        child = elements[spec.child_cell_type]
        elements[ct] = ChainElement(
            cell_type=ct,
            level=child.level + 1,
            child_cell_type=child.cell_type,
            child_number=spec.child_cell_number,
            has_node=child.has_node or spec.is_node_level,
            is_multi_nodes=child.has_node,
            leaf_cell_type=child.leaf_cell_type,
            leaf_cell_number=child.leaf_cell_number * spec.child_cell_number,
        )

    for ct in cell_types:
        add(ct)
    return elements


class _PhysicalBuilder:
    """Instantiate physical cell trees from specs
    (reference: config.go:110-235)."""

    def __init__(
        self,
        elements: Dict[api.CellType, ChainElement],
        specs: List[api.PhysicalCellSpec],
    ):
        self.elements = elements
        self.specs = specs
        self.full_list: Dict[CellChain, ChainCellList] = {}
        self.free_list: Dict[CellChain, ChainCellList] = {}
        self.pinned_cells: Dict[api.PinnedCellId, PhysicalCell] = {}
        self._chain: CellChain = ""
        self._order = 0

    def build(
        self,
    ) -> Tuple[
        Dict[CellChain, ChainCellList],
        Dict[CellChain, ChainCellList],
        Dict[api.PinnedCellId, PhysicalCell],
    ]:
        for spec in self.specs:
            self._chain = spec.cell_type
            element = self.elements.get(spec.cell_type)
            if element is None:
                raise api.bad_request(
                    f"cellType {spec.cell_type} in physicalCells is not found "
                    "in cell types definition"
                )
            if not element.has_node:
                raise api.bad_request(
                    f"top cell must be node-level or above: {spec.cell_type}"
                )
            root = self._build_cell(spec, spec.cell_type, "")
            self.free_list.setdefault(root.chain, ChainCellList(root.level))
            self.free_list[root.chain][root.level].append(root)
        return self.full_list, self.free_list, self.pinned_cells

    def _build_cell(
        self, spec: api.PhysicalCellSpec, ct: api.CellType, current_node: str
    ) -> PhysicalCell:
        """(reference: config.go:141-183 ``buildChildCell``)"""
        ce = self.elements[ct]
        last_segment = spec.cell_address.rsplit("/", 1)[-1]
        if ce.has_node and not ce.is_multi_nodes:
            # Node-level cell: its address segment is the K8s node name,
            # passed down so leaf cells know their host.
            current_node = last_segment

        cell = PhysicalCell(
            self._chain,
            ce.level,
            spec.cell_address,
            ce.has_node,
            ce.leaf_cell_number,
            cell_type=ce.cell_type,
            is_node_level=ce.has_node and not ce.is_multi_nodes,
        )
        # Canonical candidate tiebreak: the compile traversal position (==
        # a fresh boot's free-list insertion order), NOT the live list
        # order, which is history-dependent and not recovered.
        self._order += 1
        cell.config_order = self._order
        self.full_list.setdefault(self._chain, ChainCellList())
        self.full_list[self._chain][ce.level].append(cell)
        if spec.pinned_cell_id:
            self.pinned_cells[spec.pinned_cell_id] = cell
            cell.pinned = True

        if ce.level == LOWEST_LEVEL:
            # Leaf: one chip; address segment is the chip index on its host.
            cell.set_physical_resources([current_node], [int(last_segment)])
            return cell

        nodes: List[str] = []
        indices: List[int] = []
        children: List[Cell] = []
        for child_spec in spec.cell_children:
            child = self._build_cell(child_spec, ce.child_cell_type, current_node)
            child.parent = cell
            children.append(child)
            if ce.is_multi_nodes:
                nodes.extend(child.nodes)
            else:
                indices.extend(child.leaf_cell_indices)
        cell.set_children(children)
        if ce.is_multi_nodes:
            # Multi-host slice cell: chip indices are meaningless above the
            # host (reference: config.go:176 sets [-1]).
            indices = [-1]
        else:
            nodes = [current_node]
        cell.set_physical_resources(nodes, indices)
        return cell


class _VirtualBuilder:
    """Instantiate per-VC virtual cell trees
    (reference: config.go:237-413)."""

    def __init__(
        self,
        elements: Dict[api.CellType, ChainElement],
        specs: Dict[api.VirtualClusterName, api.VirtualClusterSpec],
        raw_pinned: Dict[api.PinnedCellId, PhysicalCell],
    ):
        self.elements = elements
        self.specs = specs
        self.raw_pinned = raw_pinned
        self.vc_free_cell_num: Dict[
            api.VirtualClusterName, Dict[CellChain, Dict[CellLevel, int]]
        ] = {}
        self.non_pinned_full: Dict[
            api.VirtualClusterName, Dict[CellChain, ChainCellList]
        ] = {}
        self.non_pinned_free: Dict[
            api.VirtualClusterName, Dict[CellChain, ChainCellList]
        ] = {}
        self.pinned: Dict[
            api.VirtualClusterName, Dict[api.PinnedCellId, ChainCellList]
        ] = {}
        self.pinned_physical: Dict[
            api.VirtualClusterName, Dict[api.PinnedCellId, PhysicalCell]
        ] = {}
        # building state
        self._vc: api.VirtualClusterName = ""
        self._chain: CellChain = ""
        self._root: Optional[VirtualCell] = None
        self._pid: api.PinnedCellId = ""
        # Canonical tiebreak stamp for VIRTUAL cells, mirroring the
        # physical builder's: the packing view's total sort order
        # (placement._NodeView.sort_key) must be a pure function of cell
        # state for virtual anchors too, or intra-VC view order would
        # fall back to scoring history on equal scores.
        self._order = 0

    def build(self):
        for vc, spec in self.specs.items():
            self.vc_free_cell_num[vc] = {}
            self.non_pinned_full[vc] = {}
            self.non_pinned_free[vc] = {}
            self.pinned[vc] = {}
            self.pinned_physical[vc] = {}

            num_cells = 0
            for vcell in spec.virtual_cells:
                # Fully-qualified dotted type: chain.segment...segment; the
                # first segment is the chain, the last is the preassigned
                # cell's own type (reference: config.go:367-373).
                parts = vcell.cell_type.split(".")
                chain: CellChain = parts[0]
                root_type: api.CellType = parts[-1]
                if root_type not in self.elements:
                    raise api.bad_request(
                        f"cellType {root_type} in virtualCells is not found in "
                        "cell types definition"
                    )
                root_level = self.elements[root_type].level
                self.vc_free_cell_num[vc].setdefault(chain, {})
                self.vc_free_cell_num[vc][chain][root_level] = (
                    self.vc_free_cell_num[vc][chain].get(root_level, 0)
                    + vcell.cell_number
                )
                for _ in range(vcell.cell_number):
                    self._vc, self._chain, self._root, self._pid = vc, chain, None, ""
                    root = self._build_cell(root_type, f"{vc}/{num_cells}")
                    self.non_pinned_free[vc].setdefault(chain, ChainCellList())
                    self.non_pinned_free[vc][chain][root.level].append(root)
                    num_cells += 1

            for pcell in spec.pinned_cells:
                pid = pcell.pinned_cell_id
                pc = self.raw_pinned.get(pid)
                if pc is None:
                    raise api.bad_request(
                        f"pinned cell not found in physicalCells: VC: {vc}, ID: {pid}"
                    )
                self.pinned_physical[vc][pid] = pc
                # Find the cell type at the pinned cell's level by walking
                # down the chain (reference: config.go:394-398).
                child_type = api.CellType(pc.chain)
                while self.elements[child_type].level > pc.level:
                    child_type = self.elements[child_type].child_cell_type
                self.vc_free_cell_num[vc].setdefault(pc.chain, {})
                self.vc_free_cell_num[vc][pc.chain][pc.level] = (
                    self.vc_free_cell_num[vc][pc.chain].get(pc.level, 0) + 1
                )
                self._vc, self._chain, self._root, self._pid = vc, pc.chain, None, pid
                self._build_cell(child_type, f"{vc}/{num_cells}")
                num_cells += 1

        return (
            self.vc_free_cell_num,
            self.non_pinned_full,
            self.non_pinned_free,
            self.pinned,
            self.pinned_physical,
        )

    def _build_cell(self, ct: api.CellType, address: api.CellAddress) -> VirtualCell:
        """(reference: config.go:316-340 ``buildChildCell``)"""
        ce = self.elements[ct]
        cell = VirtualCell(
            self._vc,
            self._chain,
            ce.level,
            address,
            ce.has_node,
            ce.leaf_cell_number,
            cell_type=ce.cell_type,
            is_node_level=ce.has_node and not ce.is_multi_nodes,
        )
        self._order += 1
        cell.config_order = self._order
        if not self._pid:
            vc_lists = self.non_pinned_full[self._vc]
            vc_lists.setdefault(self._chain, ChainCellList())
            vc_lists[self._chain][ce.level].append(cell)
        else:
            pid_lists = self.pinned[self._vc]
            pid_lists.setdefault(self._pid, ChainCellList())
            pid_lists[self._pid][ce.level].append(cell)
        if self._root is None:
            self._root = cell
        cell.preassigned_cell = self._root

        if ce.level > LOWEST_LEVEL:
            # Child addresses restart at 0 under each preassigned cell and are
            # globally positional below (reference: config.go:322-330).
            parts = address.split("/")
            offset = 0 if len(parts) == 2 else int(parts[-1]) * ce.child_number
            children: List[Cell] = []
            for i in range(ce.child_number):
                child = self._build_cell(
                    ce.child_cell_type, f"{address}/{offset + i}"
                )
                child.parent = cell
                children.append(child)
            cell.set_children(children)
        return cell


@dataclass
class CompiledConfig:
    """Everything the core algorithm needs, compiled from the YAML config
    (reference: config.go:442-477 ``ParseConfig`` return values)."""

    # chain -> level -> all physical cells (including non-top levels)
    physical_full_list: Dict[CellChain, ChainCellList] = field(default_factory=dict)
    # chain -> level -> free physical cells (initially only top-level roots)
    physical_free_list: Dict[CellChain, ChainCellList] = field(default_factory=dict)
    # vc -> chain -> level -> quota cell count
    vc_free_cell_num: Dict[
        api.VirtualClusterName, Dict[CellChain, Dict[CellLevel, int]]
    ] = field(default_factory=dict)
    # vc -> chain -> level -> all / free virtual cells (non-pinned)
    virtual_non_pinned_full: Dict[
        api.VirtualClusterName, Dict[CellChain, ChainCellList]
    ] = field(default_factory=dict)
    virtual_non_pinned_free: Dict[
        api.VirtualClusterName, Dict[CellChain, ChainCellList]
    ] = field(default_factory=dict)
    # vc -> pinnedCellId -> level -> virtual cells
    virtual_pinned: Dict[
        api.VirtualClusterName, Dict[api.PinnedCellId, ChainCellList]
    ] = field(default_factory=dict)
    # vc -> pinnedCellId -> the pinned physical cell
    physical_pinned: Dict[
        api.VirtualClusterName, Dict[api.PinnedCellId, PhysicalCell]
    ] = field(default_factory=dict)
    # chain -> level -> leaf cells per cell of that level
    cell_level_to_leaf_num: Dict[CellChain, Dict[CellLevel, int]] = field(
        default_factory=dict
    )
    # chain -> level -> cell type name
    cell_level_to_type: Dict[CellChain, Dict[CellLevel, api.CellType]] = field(
        default_factory=dict
    )
    # leaf cell type (chip SKU, e.g. "v5p-chip") -> chains containing it
    leaf_cell_type_to_chain: Dict[str, List[CellChain]] = field(default_factory=dict)
    # chain -> leaf cell type
    chain_to_leaf_type: Dict[CellChain, str] = field(default_factory=dict)


def parse_config(config: Config) -> CompiledConfig:
    """(reference: config.go:442-477 ``ParseConfig``)"""
    elements = build_cell_chains(config.physical_cluster.cell_types)
    full, free, raw_pinned = _PhysicalBuilder(
        elements, config.physical_cluster.physical_cells
    ).build()
    (
        vc_free_cell_num,
        non_pinned_full,
        non_pinned_free,
        pinned,
        pinned_physical,
    ) = _VirtualBuilder(elements, config.virtual_clusters, raw_pinned).build()

    cc = CompiledConfig(
        physical_full_list=full,
        physical_free_list=free,
        vc_free_cell_num=vc_free_cell_num,
        virtual_non_pinned_full=non_pinned_full,
        virtual_non_pinned_free=non_pinned_free,
        virtual_pinned=pinned,
        physical_pinned=pinned_physical,
    )
    # Chain metadata (reference: config.go:415-440 ``parseCellChainInfo``).
    for chain in full:
        ce = elements[api.CellType(chain)]
        cc.leaf_cell_type_to_chain.setdefault(ce.leaf_cell_type, []).append(chain)
        cc.chain_to_leaf_type[chain] = ce.leaf_cell_type
        cc.cell_level_to_leaf_num[chain] = {}
        cc.cell_level_to_type[chain] = {}
        cur: Optional[ChainElement] = ce
        while cur is not None:
            cc.cell_level_to_leaf_num[chain][cur.level] = cur.leaf_cell_number
            cc.cell_level_to_type[chain][cur.level] = cur.cell_type
            cur = elements.get(cur.child_cell_type)
    return cc
