"""Topology-aware intra-chain placement: pick hosts, then pick chips.

Python equivalent of the reference's
``pkg/algorithm/topology_aware_scheduler.go``: cluster view + packing sort
(L118-266), greedy node selection (L268-307), and the backtracking
LCA-affinity chip search inside a host (L309-463).

On TPU, "best affinity" = lowest common ancestor in the cell tree = smallest
enclosing ICI sub-slice, so minimizing the LCA level is exactly minimizing
ICI hop distance between the chips granted to one pod.

Unlike the reference (which re-scores and re-sorts every node per request,
topology_aware_scheduler.go:256-266), the cluster view here is persistent
and incrementally maintained: cell mutations mark only the touched node
anchors dirty (cell.py ``view_reg``), and ``_update_cluster_view`` re-scores
just those — skipping both scoring and sorting entirely when nothing changed
and the request parameters match the previous call. See doc/hot-path.md for
the invalidation contract, and tests/test_placement_equivalence.py for the
differential proof against the naive rebuild.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ..api import types as api
from ..scheduler import tracing
from .cell import (
    Cell,
    CellLevel,
    CellPriority,
    ChainCellList,
    FREE_PRIORITY,
    HIGHEST_LEVEL,
    LOWEST_LEVEL,
    OPPORTUNISTIC_PRIORITY,
    PhysicalCell,
    VirtualCell,
)

# Differential-test escape hatch: when True, every scheduler built afterwards
# re-scores and re-sorts the full view on every request (the reference's
# behavior). tests/test_placement_equivalence.py runs a naive core against an
# incremental one and asserts identical placements.
NAIVE_VIEW_DEFAULT = os.environ.get("HIVED_NAIVE_VIEW", "0") == "1"

# Above this many dirty nodes a full re-sort is assumed cheaper than any
# bookkeeping finesse; below it Timsort's natural-run detection makes the
# near-sorted re-sort effectively linear anyway, so the threshold only
# controls when we bother computing the dirty subset at all.
FULL_RESCORE_FRACTION = 0.5

# Per-priority cached view slots (doc/hot-path.md "Per-priority view
# slots"): distinct (priority, ignore-suggested) parameter points each keep
# their own scored+sorted view, so alternating between them — every
# guaranteed request trials OPPORTUNISTIC first and retries at its real
# priority when the trial fails — costs O(dirty) instead of a full
# re-score + re-sort of the fleet. The cap bounds memory (each slot holds
# one _NodeView per node anchor); overflow evicts the least-recently-used
# slot, which simply rebuilds in full if that parameter point returns.
MAX_VIEW_SLOTS = 6

# A/B escape hatch (bench_view_slots_ab, doc/hot-path.md): =0 pins every
# scheduler built afterwards to ONE slot that fully re-scores whenever the
# (priority, ignore-suggested) point changes — the pre-slot behavior's cost
# profile — so the win is measurable interleaved inside one process.
MULTI_SLOTS_DEFAULT = os.environ.get("HIVED_VIEW_SLOTS", "") != "0"


class PhaseStats:
    """Per-phase latency accumulators for the filter hot path (lock-wait,
    core-schedule, leaf-cell search), shared by the framework and every
    TopologyAwareScheduler of one core. With the scheduler lock sharded per
    chain, two chains' schedulers can accumulate concurrently, so ``add``
    takes its own (uncontended-cheap) lock; snapshots are read-only and
    tolerate torn floats."""

    __slots__ = ("phases", "_lock")

    def __init__(self) -> None:
        # phase name -> [count, total_seconds]
        self.phases: Dict[str, List[float]] = {}
        self._lock = threading.Lock()

    def add(self, phase: str, seconds: float, n: int = 1) -> None:
        with self._lock:
            entry = self.phases.get(phase)
            if entry is None:
                entry = self.phases[phase] = [0, 0.0]
            entry[0] += n
            entry[1] += seconds

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        # list(): a concurrent add() may insert a phase key mid-scrape (the
        # metrics endpoint reads without the scheduler lock); torn floats are
        # fine, a resized-dict iteration error is not.
        for phase, (count, total) in list(self.phases.items()):
            out[phase] = {
                "count": int(count),
                "totalMs": round(total * 1e3, 3),
                "avgMs": round(total / count * 1e3, 4) if count else 0.0,
            }
        return out


class _NodeView:
    """Sortable per-node scheduling stats
    (reference: topology_aware_scheduler.go:118-156 ``node``)."""

    __slots__ = (
        "cell",
        "free_at_priority",
        "used_same_priority",
        "used_higher_priority",
        "unusable_free",
        "unusable_bad",
        "unusable_draining",
        "degraded",
        "healthy",
        "suggested",
        "node_address",
        # The score bucket this view currently sits in (None before the
        # first scoring pass) — the O(dirty) maintenance moves a view
        # only when its re-scored key leaves this bucket.
        "bucket_key",
    )

    def __init__(self, cell: Cell):
        self.cell = cell
        self.free_at_priority = 0
        self.used_same_priority = 0
        self.used_higher_priority = 0
        # Leaves counted free at the scoring priority that cannot take new
        # placements (bad or draining): the health plane is chip-granular,
        # so a host with one dead chip still serves smaller work —
        # free_at_priority - unusable_free is the node's REAL new-placement
        # capacity (see _node_unusable_free).
        self.unusable_free = 0
        # The bad-vs-draining split of unusable_free (diagnostic only —
        # decision records attribute a rejection to the chip-health or the
        # maintenance-drain gate; scheduling reads unusable_free alone).
        self.unusable_bad = 0
        self.unusable_draining = 0
        # Sort-only: any bad/draining chip in the anchor's physical subtree
        # (partially-degraded hosts remain placeable but pack last).
        self.degraded = False
        self.healthy = True
        self.suggested = True
        self.node_address: api.CellAddress = ""
        self.bucket_key: Optional[Tuple] = None

    def update_for_priority(self, p: CellPriority, cross_priority_pack: bool) -> None:
        """(reference: topology_aware_scheduler.go:147-156; see the comment
        above it for why cross-priority packing applies to intra-VC scheduling
        but not to opportunistic scheduling)"""
        used = self.cell.used_leaf_cells_at_priority
        self.used_same_priority = used.get(p, 0)
        self.used_higher_priority = 0
        self.free_at_priority = self.cell.total_leaf_cell_num
        for priority, num in used.items():
            if cross_priority_pack:
                if priority != p:
                    self.used_same_priority += num
            elif priority > p:
                self.used_higher_priority += num
            if priority >= p:
                self.free_at_priority -= num

    def score_key(self) -> Tuple:
        """The packing score: fully-usable first (healthy AND nothing
        draining — partially-degraded hosts are placeable but
        dispreferred), suggested first, more same-priority usage first,
        less higher-priority usage first (reference:
        topology_aware_scheduler.go:232-253). A small tuple of BOUNDED
        ints (two booleans plus two per-node chip counts) — the bucket
        key of the O(dirty) view maintenance."""
        return (
            self.degraded,
            not self.suggested,
            -self.used_same_priority,
            self.used_higher_priority,
        )

    def sort_key(self) -> Tuple:
        """score_key extended to a TOTAL order by the compile traversal
        stamp: the view order is a pure function of cell state + config —
        never of scoring history (equal-score order used to be whatever
        the stable sort inherited from past requests, which recovery
        cannot reconstruct; PR 4 fixed candidate ties the same way)."""
        return (
            self.degraded,
            not self.suggested,
            -self.used_same_priority,
            self.used_higher_priority,
            self.cell.config_order,
        )


def _ancestor_no_higher_than_node(c: Cell) -> Cell:
    """(reference: topology_aware_scheduler.go:184-191)"""
    while not c.at_or_higher_than_node and c.parent is not None:
        c = c.parent
    return c


class _ViewSlot:
    """One cached scored+sorted cluster view, pinned to a fixed
    (priority, ignore_suggested) parameter point.

    Each slot owns its _NodeView instances (the scored fields are
    priority-dependent), its score buckets, and its dirty set — cell
    mutations mark every live slot dirty (TopologyAwareScheduler.mark_dirty),
    and a slot re-scores only ITS dirty backlog when its parameter point is
    next requested. A fresh slot scores everything once (never_scored)."""

    __slots__ = (
        "priority",
        "ignore_suggested",
        "view",
        "by_addr",
        "dirty",
        "buckets",
        "bucket_order",
        "scored_stamp",
        "last_suggested",
        "never_scored",
        "last_used",
    )

    def __init__(
        self,
        priority: CellPriority,
        ignore_suggested: bool,
        anchors: List[Cell],
    ):
        self.priority = priority
        self.ignore_suggested = ignore_suggested
        self.view: List[_NodeView] = [_NodeView(c) for c in anchors]
        self.by_addr: Dict[api.CellAddress, _NodeView] = {
            v.cell.address: v for v in self.view
        }
        self.dirty: Set[api.CellAddress] = set()
        self.buckets: Dict[Tuple, List[_NodeView]] = {}
        self.bucket_order: List[Tuple] = []
        self.scored_stamp = -1
        self.last_suggested: Optional[Set[str]] = None
        self.never_scored = True
        self.last_used = 0


class TopologyAwareScheduler:
    """Schedules a gang's pods onto the "nodes" of one chain, packing onto
    busier nodes first, then picking chips with minimal ICI spread per pod
    (reference: topology_aware_scheduler.go:36-115).

    The view is built once from a chain cell list (physical for opportunistic
    scheduling, virtual for intra-VC scheduling) and maintained incrementally:
    cell mutations call :meth:`mark_dirty` / :meth:`bump_binding_stamp`
    through their ``view_reg`` back-pointer (see cell.py), and only the dirty
    nodes are re-scored per request.
    """

    def __init__(
        self,
        ccl: ChainCellList,
        level_leaf_cell_num: Dict[CellLevel, int],
        cross_priority_pack: bool,
        phase_stats: Optional[PhaseStats] = None,
        naive: Optional[bool] = None,
    ):
        self.level_leaf_cell_num = level_leaf_cell_num
        self.cross_priority_pack = cross_priority_pack
        self.phase_stats = phase_stats
        self.naive = NAIVE_VIEW_DEFAULT if naive is None else naive
        # The ACTIVE view: in naive mode the one and only (rebuilt fully per
        # request); in incremental mode the last-scored slot's list — kept
        # as an attribute so inspection/tests can read the current packing
        # order without knowing about slots.
        self.cluster_view = self._build_cluster_view(ccl)
        self._anchors: List[Cell] = [v.cell for v in self.cluster_view]
        # Per-priority view slots (doc/hot-path.md): (priority,
        # ignore_suggested) -> _ViewSlot. Cell mutations dirty every live
        # slot; binding changes above node level bump the shared stamp.
        self._slots: Dict[Tuple, _ViewSlot] = {}
        self._slot_clock = 0
        self._binding_stamp = 0
        self.multi_slots = MULTI_SLOTS_DEFAULT
        if not self.naive:
            self._register_view()

    # -- invalidation hooks (called from cell.py mutators) ------------------ #

    def mark_dirty(self, address: api.CellAddress) -> None:
        for slot in self._slots.values():
            slot.dirty.add(address)

    def bump_binding_stamp(self) -> None:
        self._binding_stamp += 1

    def invalidate_all(self) -> None:
        """Wholesale invalidation: every anchor re-scores at the next
        schedule call. The snapshot restore rewrites cell state with direct
        field assignments (no mutator hooks), so the incremental dirty
        marks cannot be trusted afterwards."""
        for slot in self._slots.values():
            slot.dirty.update(slot.by_addr)
        self._binding_stamp += 1

    def _register_view(self) -> None:
        """Give every node anchor (and its ancestors) a back-pointer so cell
        mutations can invalidate exactly the views they affect."""
        for anchor in self._anchors:
            anchor.view_reg = (self, True)
            parent = anchor.parent
            while parent is not None and parent.view_reg is None:
                parent.view_reg = (self, False)
                parent = parent.parent

    def _get_slot(self, p: CellPriority, ignore_suggested: bool) -> _ViewSlot:
        """The slot for one parameter point, LRU-evicting past the cap (an
        evicted slot that returns simply scores in full once)."""
        key = (p, ignore_suggested)
        slot = self._slots.get(key)
        if slot is None:
            if len(self._slots) >= MAX_VIEW_SLOTS:
                lru = min(
                    self._slots, key=lambda k: self._slots[k].last_used
                )
                del self._slots[lru]
            slot = self._slots[key] = _ViewSlot(
                p, ignore_suggested, self._anchors
            )
        self._slot_clock += 1
        slot.last_used = self._slot_clock
        return slot

    # -- view construction & scoring ---------------------------------------- #

    @staticmethod
    def _build_cluster_view(ccl: ChainCellList) -> List[_NodeView]:
        """Extract node-level cells (or top-level cells below node level)
        (reference: topology_aware_scheduler.go:160-182)."""
        top = ccl.top_level
        node_level = LOWEST_LEVEL
        for l in range(LOWEST_LEVEL, top + 1):
            if ccl[l] and ccl[l][0].at_or_higher_than_node:
                node_level = l
                break
        else:
            node_level = top
        view: List[_NodeView] = []
        seen: Set[api.CellAddress] = set()
        for l in range(node_level, LOWEST_LEVEL - 1, -1):
            for c in ccl[l]:
                anchor = _ancestor_no_higher_than_node(c)
                if anchor.address not in seen:
                    seen.add(anchor.address)
                    view.append(_NodeView(anchor))
        return view

    def _update_cluster_view(
        self,
        p: CellPriority,
        suggested_nodes: Optional[Set[str]],
        ignore_suggested: bool,
    ) -> List[_NodeView]:
        """Return the scored+sorted view for this parameter point,
        re-scoring only what changed (reference:
        topology_aware_scheduler.go:256-266 re-scores everything; the
        incremental path must produce byte-identical results — the order is
        a total key over cell state, so equality of scores implies equality
        of order). Each (priority, ignore_suggested) point keeps its own
        slot, so a request alternating priorities — every guaranteed
        schedule trials OPPORTUNISTIC first — pays O(its own dirty
        backlog), never a fleet-wide re-sort."""
        if self.naive:
            view = self.cluster_view
            cross = self.cross_priority_pack
            for n in view:
                n.update_for_priority(p, cross)
                n.healthy, n.suggested, n.node_address = (
                    _node_health_and_suggested(
                        n.cell, suggested_nodes, ignore_suggested
                    )
                )
                n.unusable_free, n.unusable_bad, n.unusable_draining = (
                    _node_unusable_free(n.cell, p)
                )
                n.degraded = (not n.healthy) or _node_degraded(n.cell)
            view.sort(key=_NodeView.sort_key)
            return view
        if self.multi_slots:
            slot = self._get_slot(p, ignore_suggested)
            point_changed = False
        else:
            # A/B escape hatch: one slot for every parameter point — a
            # point change forces the pre-slot full re-score + re-sort.
            key = ("single",)
            slot = self._slots.get(key)
            if slot is None:
                slot = self._slots[key] = _ViewSlot(
                    p, ignore_suggested, self._anchors
                )
            point_changed = (
                slot.priority != p
                or slot.ignore_suggested != ignore_suggested
            )
            slot.priority = p
            slot.ignore_suggested = ignore_suggested
        view = slot.view
        params_changed = (
            slot.never_scored
            or point_changed
            or (
                not ignore_suggested
                and (
                    suggested_nodes != slot.last_suggested
                    or slot.scored_stamp != self._binding_stamp
                )
            )
        )
        full = (
            params_changed
            or len(slot.dirty) > len(view) * FULL_RESCORE_FRACTION
        )
        if full:
            dirty_views: List[_NodeView] = view
        elif slot.dirty:
            by_addr = slot.by_addr
            dirty_views = [by_addr[a] for a in slot.dirty]
        else:
            # Clean slot, same parameters: still scored & sorted.
            self.cluster_view = view
            return view
        cross = self.cross_priority_pack
        for n in dirty_views:
            n.update_for_priority(p, cross)
            n.healthy, n.suggested, n.node_address = _node_health_and_suggested(
                n.cell, suggested_nodes, ignore_suggested
            )
            n.unusable_free, n.unusable_bad, n.unusable_draining = (
                _node_unusable_free(n.cell, p)
            )
            n.degraded = (not n.healthy) or _node_degraded(n.cell)
        if full:
            # Full pass: one total-key sort (score, then config order —
            # a pure function of cell state), buckets rebuilt from the
            # sorted run.
            view.sort(key=_NodeView.sort_key)
            self._rebuild_buckets_from_sorted(slot)
        else:
            # O(dirty) reordering: a re-scored view moves between score
            # buckets only when its (bounded-int) key changed; within a
            # bucket, views sit in config order. The flat list is
            # re-concatenated only when some membership moved.
            moved = False
            for n in dirty_views:
                key = n.score_key()
                if key == n.bucket_key:
                    continue
                moved = True
                old = slot.buckets.get(n.bucket_key)
                if old is not None:
                    old.remove(n)
                    if not old:
                        del slot.buckets[n.bucket_key]
                        slot.bucket_order.remove(n.bucket_key)
                bucket = slot.buckets.get(key)
                if bucket is None:
                    bucket = slot.buckets[key] = []
                    bisect.insort(slot.bucket_order, key)
                bisect.insort(
                    bucket, n, key=lambda v: v.cell.config_order
                )
                n.bucket_key = key
            if moved:
                flat: List[_NodeView] = []
                for key in slot.bucket_order:
                    flat.extend(slot.buckets[key])
                view[:] = flat
        slot.dirty.clear()
        slot.never_scored = False
        slot.last_suggested = suggested_nodes
        slot.scored_stamp = self._binding_stamp
        self.cluster_view = view
        return view

    @staticmethod
    def _rebuild_buckets_from_sorted(slot: _ViewSlot) -> None:
        slot.buckets = {}
        slot.bucket_order = []
        for n in slot.view:
            key = n.score_key()
            n.bucket_key = key
            bucket = slot.buckets.get(key)
            if bucket is None:
                bucket = slot.buckets[key] = []
                slot.bucket_order.append(key)
            bucket.append(n)

    def schedule(
        self,
        pod_leaf_cell_numbers: Dict[int, int],
        priority: CellPriority,
        suggested_nodes: Optional[Set[str]] = None,
        ignore_suggested_nodes: bool = True,
        avoid_anchors: Optional[Set[api.CellAddress]] = None,
    ) -> Tuple[Optional[Dict[int, List[List[Cell]]]], str]:
        """Place all pods of a gang; returns (placement, "") or
        (None, failure reason) (reference: topology_aware_scheduler.go:65-115).

        First tries at opportunistic priority (no preemption); if that fails
        and the request is guaranteed, retries at the real priority, allowing
        lower-priority cells to be treated as free (preemption). The retry is
        the only second view refresh — and with the parameter cache it costs
        nothing when the gang priority IS opportunistic.

        ``avoid_anchors`` excludes specific node anchors (by cell address)
        from the greedy pick WITHOUT entering the score/sort cache — it is a
        transient per-attempt filter used by the intra-VC → physical mapping
        retry (core._schedule_guaranteed_group): an anchor whose mapping
        already failed is skipped so the next-best placement gets a chance.
        """
        sorted_leaf_nums: List[int] = []
        for leaf_num, pod_num in pod_leaf_cell_numbers.items():
            sorted_leaf_nums.extend([leaf_num] * pod_num)
        sorted_leaf_nums.sort()

        trial_priority = OPPORTUNISTIC_PRIORITY
        view = self._update_cluster_view(
            trial_priority, suggested_nodes, ignore_suggested_nodes
        )
        picked, failed_reason = _find_nodes_for_pods(
            view, sorted_leaf_nums, avoid_anchors
        )
        if picked is None and priority > OPPORTUNISTIC_PRIORITY:
            trial_priority = priority
            view = self._update_cluster_view(
                trial_priority, suggested_nodes, ignore_suggested_nodes
            )
            picked, failed_reason = _find_nodes_for_pods(
                view, sorted_leaf_nums, avoid_anchors
            )
        if picked is None:
            return None, failed_reason

        ps = self.phase_stats
        t0 = time.perf_counter() if ps is not None else 0.0
        placements: Dict[int, List[List[Cell]]] = {}
        node_available: Dict[api.CellAddress, List[Cell]] = {}
        for pod_index, leaf_num in enumerate(sorted_leaf_nums):
            node_cell = view[picked[pod_index]].cell
            chips, node_available[node_cell.address] = _find_leaf_cells_in_node(
                node_cell,
                leaf_num,
                trial_priority,
                node_available.get(node_cell.address),
                self.level_leaf_cell_num,
            )
            placements.setdefault(leaf_num, []).append(chips)
        if ps is not None:
            dt = time.perf_counter() - t0
            ps.add("leafCellSearch", dt, len(sorted_leaf_nums))
            # Placement-descent span on the current request trace, if one
            # is sampled (tracing.add_span is a None check otherwise).
            tracing.add_span(
                "leafCellSearch", dt, pods=len(sorted_leaf_nums)
            )
        return placements, ""


def _leaf_unusable(c: Cell) -> bool:
    """A leaf cell that cannot take NEW placements: bad or draining. For
    virtual leaves the verdict comes from the bound physical chip; an
    unbound virtual leaf has no hardware yet, so the (drain/health-aware)
    virtual->physical mapping decides later."""
    if isinstance(c, PhysicalCell):
        return (not c.healthy) or c.draining
    if isinstance(c, VirtualCell) and c.physical_cell is not None:
        pc = c.physical_cell
        return (not pc.healthy) or pc.draining
    return False


def _node_unusable_free(cell: Cell, p: CellPriority) -> Tuple[int, int, int]:
    """Leaves of this node anchor that are counted free at priority ``p``
    but are actually unusable (bad or draining) — the chip-granular
    correction to the node's free count. The contract is exact alignment
    with ``_collect_leaf_cells``: free_at_priority - unusable_free equals
    the number of chips the in-node search will actually offer, or the
    picked-node assert fires. That forces the walk to use the SAME priority
    space as the free count: virtual priorities for a virtual anchor (an
    opportunistic squatter on a bad chip has physical priority -1 but
    virtual FREE — counting it by physical priority double-excludes it;
    found by the node-flap fuzzer), physical priorities for a physical
    anchor.

    Returns ``(unusable, bad, draining)``: the total plus its
    bad-vs-draining split (a chip both bad and draining counts bad — the
    decision-record gate attribution prefers the harder fault). Only the
    total feeds scheduling; the split labels rejection reasons."""
    if isinstance(cell, VirtualCell):
        if cell.physical_cell is None:
            return 0, 0, 0  # no hardware yet: mapping decides
        n = bad = draining = 0
        stack: List[Cell] = [cell]
        while stack:
            c = stack.pop()
            if c.children:
                stack.extend(c.children)
            else:
                assert isinstance(c, VirtualCell)
                pc = c.physical_cell
                if (
                    pc is not None
                    and ((not pc.healthy) or pc.draining)
                    and c.priority < p
                ):
                    n += 1
                    if not pc.healthy:
                        bad += 1
                    else:
                        draining += 1
        return n, bad, draining
    assert isinstance(cell, PhysicalCell)
    if cell.healthy and cell.unusable_leaf_num == 0:
        # Fast path: fully usable (the overwhelmingly common case). Checked
        # alongside `healthy` so white-box tests that toggle leaf.healthy
        # without the setter still get the walk below.
        return 0, 0, 0
    n = bad = draining = 0
    stack = [cell]
    while stack:
        c = stack.pop()
        if c.children:
            stack.extend(c.children)
        elif ((not c.healthy) or c.draining) and c.priority < p:
            # priority >= p leaves are already excluded from the free count.
            n += 1
            if not c.healthy:
                bad += 1
            else:
                draining += 1
    return n, bad, draining


def _node_degraded(cell: Cell) -> bool:
    """Sort-only view of hardware degradation: any bad or draining chip in
    the anchor's PHYSICAL subtree (for a bound virtual anchor too — an
    unbound draining chip is invisible to the virtual capacity walk but
    still makes the node a worse packing target). Unbound virtual anchors
    have no hardware yet and sort clean."""
    if isinstance(cell, VirtualCell):
        cell = cell.physical_cell
        if cell is None:
            return False
    assert isinstance(cell, PhysicalCell)
    return (not cell.healthy) or cell.unusable_leaf_num > 0


def _node_health_and_suggested(
    c: Cell,
    suggested_nodes: Optional[Set[str]],
    ignore_suggested: bool,
) -> Tuple[bool, bool, api.CellAddress]:
    """(reference: topology_aware_scheduler.go:268-289, with one deliberate
    improvement over the reference for unbound virtual cells — see below)"""
    if isinstance(c, PhysicalCell):
        return (
            c.healthy,
            ignore_suggested
            or suggested_nodes is None
            or c.nodes[0] in suggested_nodes,
            c.address,
        )
    if isinstance(c, VirtualCell) and c.physical_cell is not None:
        pc = c.physical_cell
        return (
            pc.healthy,
            ignore_suggested
            or suggested_nodes is None
            or pc.nodes[0] in suggested_nodes,
            pc.address,
        )
    if isinstance(c, VirtualCell) and not ignore_suggested and suggested_nodes is not None:
        # Unbound virtual cell: the reference scores it "location unknown →
        # suggested", but if an ANCESTOR is already bound, this cell can only
        # ever map inside that ancestor's physical cell — so score it against
        # the ancestor's node set. Without this, intra-VC packing happily
        # places a pod into a bound-elsewhere preassigned cell and the
        # virtual→physical mapping then dies on suggested-node grounds where
        # an alternate (still-free) preassigned cell would have worked; the
        # reference waits in that situation
        # (topology_aware_scheduler.go:243-266), we bind.
        anc = c.parent
        while anc is not None:
            if isinstance(anc, VirtualCell) and anc.physical_cell is not None:
                pc = anc.physical_cell
                return (
                    True,
                    any(n in suggested_nodes for n in pc.nodes),
                    pc.address,
                )
            anc = anc.parent
    return True, True, ""


def _find_nodes_for_pods(
    view: List[_NodeView],
    leaf_cell_nums: List[int],
    avoid_anchors: Optional[Set[api.CellAddress]] = None,
) -> Tuple[Optional[List[int]], str]:
    """Greedy assignment of pods (sorted by chip count) to the packed-sorted
    node list (reference: topology_aware_scheduler.go:291-337, made
    chip-granular: capacity is counted over USABLE chips — bad and draining
    leaves are discounted — so a host with one dead chip still serves
    smaller pods instead of condemning the whole node). A node that fits
    only by counting unusable chips is skipped (recorded as the failure
    reason); a usable node outside the suggested set still fails the whole
    attempt so the caller can fall back (relaxed split or K8s retry).
    Anchors in ``avoid_anchors`` (a mapping-retry exclusion, see
    ``TopologyAwareScheduler.schedule``) are skipped outright. The caller
    (``_update_cluster_view``) guarantees the view is already sorted."""
    picked = [0] * len(leaf_cell_nums)
    pod_index = 0
    picked_leaf_num = 0
    node_index = 0
    bad_reason = ""
    while node_index < len(view):
        n = view[node_index]
        if avoid_anchors is not None and n.cell.address in avoid_anchors:
            # Restart the current pod's packing on the next anchor: skipping
            # mid-gang must not let the greedy run treat two anchors as one.
            picked_leaf_num = 0
            node_index += 1
            continue
        needed = leaf_cell_nums[pod_index]
        if n.free_at_priority - n.unusable_free - picked_leaf_num >= needed:
            if not n.suggested:
                return (
                    None,
                    f"have to use at least one non-suggested node {n.node_address}",
                )
            picked[pod_index] = node_index
            picked_leaf_num += leaf_cell_nums[pod_index]
            pod_index += 1
            if pod_index == len(leaf_cell_nums):
                return picked, ""
        else:
            if (
                not bad_reason
                and n.unusable_free > 0
                and n.free_at_priority - picked_leaf_num >= needed
            ):
                # Would fit counting its bad/draining chips: the truthful
                # wait reason when nothing else fits either. Drain-only
                # shortfalls say so — the decision journal attributes the
                # rejection to the maintenance gate, not chip health.
                kind = (
                    "draining"
                    if n.unusable_draining and not n.unusable_bad
                    else "bad"
                )
                bad_reason = (
                    f"have to use at least one {kind} node {n.node_address}"
                )
            picked_leaf_num = 0
            node_index += 1
    return None, bad_reason or "insufficient capacity"


def _optimal_affinity(
    leaf_cell_num: int, level_leaf_cell_num: Dict[CellLevel, int]
) -> CellLevel:
    """Lowest level whose cells can hold leaf_cell_num chips: the best
    possible LCA (smallest enclosing ICI sub-slice)
    (reference: topology_aware_scheduler.go:390-400)."""
    for l in sorted(level_leaf_cell_num):
        if level_leaf_cell_num[l] >= leaf_cell_num:
            return l
    raise api.internal_error(
        "Assert Failure: pod allocated a node but exceeds the capacity of the "
        "current chain"
    )


def _find_lca(lower: Cell, higher: Cell) -> Optional[Cell]:
    """Lowest common ancestor of two cells, None if disjoint
    (reference: topology_aware_scheduler.go:444-463)."""
    while lower.level < higher.level:
        if lower.parent is None:
            return None
        lower = lower.parent
    if lower.address == higher.address:
        return lower
    while True:
        lp, hp = lower.parent, higher.parent
        if lp is None or hp is None:
            return None
        if lp.address == hp.address:
            return lp
        lower, higher = lp, hp


def _collect_leaf_cells(
    c: Cell, p: CellPriority, free: List[Cell], preemptible: List[Cell]
) -> None:
    """Collect free then preemptible (strictly lower priority) chips in a
    node (reference: topology_aware_scheduler.go:465-476). Bad and draining
    chips are never offered — chip-granular health means the rest of the
    node still is."""
    if c.level > LOWEST_LEVEL:
        for cc in c.children:
            _collect_leaf_cells(cc, p, free, preemptible)
    elif _leaf_unusable(c):
        return
    elif c.priority == FREE_PRIORITY:
        free.append(c)
    elif c.priority < p:
        preemptible.append(c)


def _find_leaf_cells_in_node(
    node_cell: Cell,
    leaf_cell_num: int,
    p: CellPriority,
    available: Optional[List[Cell]],
    level_leaf_cell_num: Dict[CellLevel, int],
) -> Tuple[List[Cell], List[Cell]]:
    """Backtracking search for the chip set with the lowest LCA inside one
    node (reference: topology_aware_scheduler.go:309-387
    ``findLeafCellsInNode``), with the same pruning (abandon a branch once
    its LCA exceeds the best seen) and early exit on an optimal (all-buddy)
    solution. Returns (picked chips, remaining available chips)."""
    if available is None:
        free: List[Cell] = []
        preemptible: List[Cell] = []
        _collect_leaf_cells(node_cell, p, free, preemptible)
        available = free + preemptible  # free chips are preferred

    optimal = _optimal_affinity(leaf_cell_num, level_leaf_cell_num)
    best_affinity = HIGHEST_LEVEL
    best_cells: List[Optional[Cell]] = [None] * leaf_cell_num
    best_indices: List[int] = [0] * leaf_cell_num

    current_indices = [0] * leaf_cell_num
    current_affinity: List[Optional[Cell]] = [None] * leaf_cell_num

    search_index = 0
    avail_index = 0
    while True:
        while avail_index < len(available):
            leaf = available[avail_index]
            current_indices[search_index] = avail_index
            if search_index == 0:
                current_affinity[0] = leaf
            else:
                lca = _find_lca(leaf, current_affinity[search_index - 1])
                current_affinity[search_index] = lca
                # Pruning (reference: L344-352).
                if (lca is None and best_affinity < HIGHEST_LEVEL) or (
                    lca is not None and lca.level > best_affinity
                ):
                    avail_index += 1
                    continue
            if search_index == leaf_cell_num - 1:
                affinity = current_affinity[-1].level if current_affinity[-1] else HIGHEST_LEVEL
                if affinity < best_affinity:
                    best_affinity = affinity
                    best_indices = list(current_indices)
                    best_cells = [available[i] for i in current_indices]
                    if affinity == optimal:
                        return _finish(available, best_indices, best_cells)
            else:
                search_index += 1
            avail_index += 1
        search_index -= 1
        if search_index < 0:
            if best_affinity == HIGHEST_LEVEL:
                raise api.internal_error(
                    f"Assert Failure: failed to allocate {leaf_cell_num} leaf "
                    f"cells in picked node {node_cell.address}"
                )
            return _finish(available, best_indices, best_cells)
        avail_index = current_indices[search_index] + 1


def _finish(
    available: List[Cell], picked_indices: List[int], picked: List[Optional[Cell]]
) -> Tuple[List[Cell], List[Cell]]:
    picked_set = set(picked_indices)
    remaining = [c for i, c in enumerate(available) if i not in picked_set]
    return [c for c in picked if c is not None], remaining
