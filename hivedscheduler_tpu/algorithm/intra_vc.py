"""Per-VC scheduling facade.

Python equivalent of the reference's ``pkg/algorithm/intra_vc_scheduler.go``:
routes a request to the topology-aware scheduler of the target chain or
pinned cell, with cross-priority packing enabled (high priority avoids
preemption globally inside a VC).

Lazy-compile contract (doc/hot-path.md "Boot and transport plane"): an
IntraVCScheduler is constructed ON FIRST TOUCH of its VC by
``HivedCore.ensure_vc`` — never eagerly at boot — from the memoized
``CompiledConfig.compile_vc`` output. Construction must therefore stay a
pure function of that compiled output (cell lists + leaf counts): it
registers placement views over the freshly built virtual trees and reads
nothing from live scheduling state, so forcing a VC mid-traffic from any
access path (filter, inspect, snapshot restore, doomed-ledger rebuild)
is safe and order-independent.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .. import common
from ..api import types as api
from .cell import Cell, CellChain, CellLevel, CellPriority, ChainCellList
from .placement import PhaseStats, TopologyAwareScheduler


@dataclass
class SchedulingRequest:
    """(reference: algorithm/types.go:43-53 ``schedulingRequest``)"""

    vc: api.VirtualClusterName
    priority: CellPriority
    affinity_group_name: str
    affinity_group_pod_nums: Dict[int, int]  # leaf cell num -> pod num
    pinned_cell_id: api.PinnedCellId = ""
    chain: CellChain = ""
    suggested_nodes: Optional[Set[str]] = None
    ignore_suggested_nodes: bool = True


class IntraVCScheduler:
    """(reference: intra_vc_scheduler.go:45-117 ``defaultIntraVCScheduler``)"""

    def __init__(
        self,
        non_pinned_full: Dict[CellChain, ChainCellList],
        non_pinned_preassigned: Dict[CellChain, ChainCellList],
        pinned_cells: Dict[api.PinnedCellId, ChainCellList],
        leaf_cell_nums: Dict[CellChain, Dict[CellLevel, int]],
        phase_stats: Optional[PhaseStats] = None,
    ):
        self.non_pinned_full = non_pinned_full
        self.non_pinned_preassigned = non_pinned_preassigned
        self.pinned_cells = pinned_cells
        self._chain_schedulers = {
            chain: TopologyAwareScheduler(
                ccl,
                leaf_cell_nums[chain],
                cross_priority_pack=True,
                phase_stats=phase_stats,
            )
            for chain, ccl in non_pinned_full.items()
        }
        self._pinned_schedulers = {
            pid: TopologyAwareScheduler(
                ccl,
                leaf_cell_nums[ccl[1][0].chain],
                cross_priority_pack=True,
                phase_stats=phase_stats,
            )
            for pid, ccl in pinned_cells.items()
        }

    def schedule(
        self,
        sr: SchedulingRequest,
        avoid_anchors: Optional[Set] = None,
    ) -> Tuple[Optional[Dict[int, List[List[Cell]]]], str]:
        """(reference: intra_vc_scheduler.go:92-117)

        ``avoid_anchors`` is the virtual→physical mapping-retry exclusion
        (node-anchor addresses whose mapping already failed this request);
        see TopologyAwareScheduler.schedule."""
        if sr.pinned_cell_id:
            scheduler = self._pinned_schedulers.get(sr.pinned_cell_id)
            target = f"pinned cell {sr.pinned_cell_id}"
        else:
            scheduler = self._chain_schedulers.get(sr.chain)
            target = f"chain {sr.chain}"
        common.log.debug(
            "Processing scheduling request in VC %s: %s, leaf cell numbers %s, "
            "priority %s",
            sr.vc, target, sr.affinity_group_pod_nums, sr.priority,
        )
        placement: Optional[Dict[int, List[List[Cell]]]] = None
        failed_reason = ""
        if scheduler is not None:
            placement, failed_reason = scheduler.schedule(
                sr.affinity_group_pod_nums,
                sr.priority,
                sr.suggested_nodes,
                sr.ignore_suggested_nodes,
                avoid_anchors=avoid_anchors,
            )
        if placement is None:
            return None, f"{failed_reason} when scheduling in VC {sr.vc}"
        return placement, ""
