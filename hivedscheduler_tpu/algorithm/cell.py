"""The cell tree: the resource model of the scheduler.

A *cell* is a set of TPU chips affinitized by the ICI interconnect topology:
level 1 is one chip, higher levels are progressively larger contiguous
sub-slices (4-chip TPU-VM host, 4x4x4 cube, full slice). Cells form trees via
parent/child pointers; a *chain* is a tree shape named by its top cell type.

Python equivalent of the reference's ``pkg/algorithm/cell.go`` (Cell interface
L34-48, GenericCell L58-128, PhysicalCell L130-313, VirtualCell L315-423) and
the container types in ``pkg/algorithm/types.go`` (CellList L55, ChainCellList
L97). Unlike the reference, inspect-API statuses are generated on demand by
walking the trees (see core.py) instead of being incrementally mirrored.

Two departures from the reference for the gang-schedule hot path
(doc/hot-path.md):

- ``CellList``/``ChainCellList`` are address-indexed: membership and removal
  are O(1) dict operations instead of linear ``cell_equal`` scans, so the
  backtracking buddy allocator no longer pays O(free-list) per backtrack.
- Cells carry a ``view_reg`` back-pointer to the cluster view that scores
  them (placement.TopologyAwareScheduler): every mutation that can change a
  node's packing score marks only the touched node dirty, letting the view
  re-score incrementally instead of rebuilding per request.
"""

from __future__ import annotations

import enum
from typing import Dict, Iterable, Iterator, List, Optional, Tuple, TYPE_CHECKING

from ..api import types as api

if TYPE_CHECKING:
    from .group import AffinityGroup
    from .placement import TopologyAwareScheduler

CellChain = str
CellLevel = int
CellPriority = int

# Internal priority space (reference: algorithm/constants.go:30-36).
MAX_GUARANTEED_PRIORITY: CellPriority = api.constants.MAX_GUARANTEED_PRIORITY
MIN_GUARANTEED_PRIORITY: CellPriority = api.constants.MIN_GUARANTEED_PRIORITY
OPPORTUNISTIC_PRIORITY: CellPriority = api.constants.OPPORTUNISTIC_PRIORITY
FREE_PRIORITY: CellPriority = OPPORTUNISTIC_PRIORITY - 1

LOWEST_LEVEL: CellLevel = 1
HIGHEST_LEVEL: CellLevel = 2**31 - 1


class CellState(str, enum.Enum):
    """Cell states (reference: algorithm/constants.go:40-58 and
    doc/design/state-machine.md "Cell State Machine"):

    - FREE:      no group is associated; priority must be FREE_PRIORITY.
    - USED:      a group is using it; nobody is reserving it.
    - RESERVING: a group is using it AND a preempting group is reserving it.
    - RESERVED:  nobody is using it and a preempting group has reserved it.
    """

    FREE = "Free"
    USED = "Used"
    RESERVING = "Reserving"
    RESERVED = "Reserved"


class Cell:
    """Common cell behavior (reference: GenericCell, cell.go:58-128)."""

    __slots__ = (
        "chain",
        "level",
        "address",
        "cell_type",
        "is_node_level",
        "parent",
        "children",
        "at_or_higher_than_node",
        "priority",
        "state",
        "healthy",
        "total_leaf_cell_num",
        "used_leaf_cells_at_priority",
        "view_reg",
        "unusable_leaf_num",
        "config_order",
        "epoch_ref",
    )

    def __init__(
        self,
        chain: CellChain,
        level: CellLevel,
        address: api.CellAddress,
        at_or_higher_than_node: bool,
        total_leaf_cell_num: int,
        cell_type: api.CellType = "",
        is_node_level: bool = False,
    ):
        self.chain = chain
        self.level = level
        self.address = address
        self.cell_type = cell_type
        self.is_node_level = is_node_level
        self.parent: Optional[Cell] = None
        self.children: List[Cell] = []
        self.at_or_higher_than_node = at_or_higher_than_node
        self.priority: CellPriority = FREE_PRIORITY
        self.state: CellState = CellState.FREE
        # Healthy if all children are healthy; orthogonal to priority/state
        # (reference: cell.go:100-103). Cells start healthy; HivedCore's
        # init marks every node bad until the informer reports it
        # (reference: hived_algorithm.go:453-465).
        self.healthy = True
        self.total_leaf_cell_num = total_leaf_cell_num
        # Count of leaf cells under (or at) this cell that cannot take NEW
        # placements: bad (health plane) or draining (maintenance plane).
        # Maintained incrementally by the leaf-level setters below so the
        # placement hot path can gate candidates in O(1) instead of walking
        # subtrees. Only meaningful on physical cells; virtual views derive
        # usability from their bound physical cells at re-score time.
        self.unusable_leaf_num = 0
        # Position in the config-compile traversal: the canonical,
        # state-pure candidate tiebreak (free-list insertion order is
        # history-dependent and not reconstructed by crash recovery, so it
        # must never decide a placement; see get_usable_physical_cells).
        self.config_order = 0
        # (scheduler, is_anchor) when a cluster view scores this cell:
        # is_anchor=True for the node-anchor cells that back a _NodeView,
        # False for their ancestors (binding changes above node level).
        # See TopologyAwareScheduler._register_view.
        self.view_reg: Optional[Tuple["TopologyAwareScheduler", bool]] = None
        # Per-chain mutation epoch (a shared one-element list installed by
        # HivedCore): every status-visible mutation — state, priority,
        # healthiness, draining, bindings — bumps it, so the mirrored
        # inspect statuses and the preempt-probe victims cache can tell
        # "nothing in this chain changed" in O(1) instead of re-walking
        # the tree (doc/hot-path.md "Preempt-path indexing").
        self.epoch_ref: Optional[List[int]] = None

        # Leaf-cell usage per priority, for VC-safety and preemption decisions
        # (reference: cell.go:104-106, 122-127).
        self.used_leaf_cells_at_priority: Dict[CellPriority, int] = {}

    def _bump_epoch(self) -> None:
        ref = self.epoch_ref
        if ref is not None:
            ref[0] += 1

    def set_children(self, children: List["Cell"]) -> None:
        self.children = children

    def increase_used_leaf_cells_at_priority(
        self, priority: CellPriority, delta: int
    ) -> None:
        """(reference: cell.go:122-127)"""
        n = self.used_leaf_cells_at_priority.get(priority, 0) + delta
        if n == 0:
            self.used_leaf_cells_at_priority.pop(priority, None)
        else:
            self.used_leaf_cells_at_priority[priority] = n
        reg = self.view_reg
        if reg is not None and reg[1]:
            reg[0].mark_dirty(self.address)

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.address}, p={self.priority})"


def cell_equal(c1: Optional[Cell], c2: Optional[Cell]) -> bool:
    """(reference: cell.go:50-56)"""
    if c1 is None or c2 is None:
        return c1 is None and c2 is None
    return c1.address == c2.address


class PhysicalCell(Cell):
    """A cell in the physical cluster (reference: cell.go:130-313)."""

    __slots__ = (
        "nodes",
        "leaf_cell_indices",
        "using_group",
        "reserving_or_reserved_group",
        "virtual_cell",
        "split",
        "pinned",
        "draining",
        "binding_reg",
    )

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # Physical placement: K8s node names inside the cell and (for cells at
        # or below node level) per-node chip indices; [-1] above node level
        # (reference: cell.go:132-134, config.go:176).
        self.nodes: List[str] = []
        self.leaf_cell_indices: List[int] = []
        self.using_group: Optional["AffinityGroup"] = None
        self.reserving_or_reserved_group: Optional["AffinityGroup"] = None
        self.virtual_cell: Optional["VirtualCell"] = None
        self.split = False
        self.pinned = False
        # Maintenance drain (health plane): a draining cell takes no NEW
        # placements but keeps whatever is already running on it. Orthogonal
        # to healthiness — a drained chip is fine hardware being emptied for
        # maintenance, so it must not enter the bad-free / doomed accounting.
        self.draining = False
        # Live binding registry (HivedCore.bound_physical): address -> bound
        # physical cell, kept current by set_virtual_cell so the snapshot
        # plane can enumerate/clear bindings without walking the cell trees.
        # None on cells not owned by a core (unit-test fixtures).
        self.binding_reg: Optional[Dict[api.CellAddress, "PhysicalCell"]] = None

    def set_physical_resources(
        self, nodes: List[str], leaf_cell_indices: List[int]
    ) -> None:
        self.nodes = nodes
        self.leaf_cell_indices = leaf_cell_indices

    def placement_string(self) -> str:
        return f"{self.nodes}:{self.leaf_cell_indices}"

    def set_state(self, s: CellState) -> None:
        """State changes mirror into the bound virtual cell
        (reference: cell.go:195-205)."""
        self.state = s
        self._bump_epoch()
        if self.virtual_cell is not None:
            self.virtual_cell.state = s

    def set_priority(self, p: CellPriority) -> None:
        self.priority = p
        self._bump_epoch()

    def _bump_unusable(self, delta: int) -> None:
        """Propagate a leaf usability change up the tree (O(depth)) and
        invalidate the cluster views scoring any ancestor. The dirty marks
        MUST ride this walk, not the healthiness propagation: when a chip
        under an already-unhealthy anchor changes usability, _set_bad_cell
        short-circuits before reaching the anchor, yet the anchor's
        usable-capacity score changed (found by the node-flap fuzzer)."""
        cur: Optional[Cell] = self
        while cur is not None:
            cur.unusable_leaf_num += delta
            reg = cur.view_reg
            if reg is not None and reg[1]:
                reg[0].mark_dirty(cur.address)
            vc = cur.virtual_cell if isinstance(cur, PhysicalCell) else None
            if vc is not None:
                vreg = vc.view_reg
                if vreg is not None and vreg[1]:
                    vreg[0].mark_dirty(vc.address)
            cur = cur.parent

    def set_healthiness(self, healthy: bool) -> None:
        """Healthiness mirrors into the bound virtual cell
        (reference: cell.go:302-313)."""
        if not self.children:
            # Leaf transition: maintain the unusable-leaf counters (a leaf
            # is unusable when bad OR draining; count it once).
            before = (not self.healthy) or self.draining
            after = (not healthy) or self.draining
            if after != before:
                self._bump_unusable(1 if after else -1)
        self.healthy = healthy
        self._bump_epoch()
        reg = self.view_reg
        if reg is not None and reg[1]:
            reg[0].mark_dirty(self.address)
        vc = self.virtual_cell
        if vc is not None:
            vc.healthy = healthy
            # The virtual view scores a bound anchor off the PHYSICAL cell's
            # healthiness (placement._node_health_and_suggested), so the
            # bound virtual node must be re-scored too.
            reg = vc.view_reg
            if reg is not None and reg[1]:
                reg[0].mark_dirty(vc.address)

    def set_draining(self, draining: bool) -> None:
        """Maintenance-drain transition (leaf cells only — the health plane
        applies drains chip by chip). Maintains the unusable-leaf counters
        and invalidates the cluster views the same way a health transition
        does, so a drained chip stops being offered to new placements on the
        very next schedule call."""
        if self.draining == draining:
            return
        before = (not self.healthy) or self.draining
        self.draining = draining
        self._bump_epoch()
        after = (not self.healthy) or draining
        if not self.children and after != before:
            # The bump walk also dirties every view scoring an ancestor
            # (drain is leaf-only, so unlike healthiness there is no other
            # propagation that would reach the node anchor). A toggle that
            # does NOT change usability (the chip is also bad) changes no
            # placement-visible score, so no invalidation is needed.
            self._bump_unusable(1 if after else -1)

    def add_using_group(self, g: "AffinityGroup") -> None:
        """(reference: cell.go:225-232; conflicting adds are logged, last
        writer wins, matching the reference's non-fatal error log)"""
        self.using_group = g

    def delete_using_group(self, g: "AffinityGroup") -> None:
        self.using_group = None

    def add_reserving_or_reserved_group(self, g: "AffinityGroup") -> None:
        self.reserving_or_reserved_group = g

    def delete_reserving_or_reserved_group(self, g: "AffinityGroup") -> None:
        self.reserving_or_reserved_group = None

    def set_virtual_cell(self, cell: Optional["VirtualCell"]) -> None:
        self.virtual_cell = cell
        self._bump_epoch()
        reg = self.binding_reg
        if reg is not None:
            if cell is not None:
                reg[self.address] = self
            else:
                reg.pop(self.address, None)


class VirtualCell(Cell):
    """A cell in a virtual cluster (reference: cell.go:315-423)."""

    __slots__ = ("vc", "pinned_cell_id", "preassigned_cell", "physical_cell")

    def __init__(self, vc: api.VirtualClusterName, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.vc = vc
        self.pinned_cell_id: api.PinnedCellId = ""
        # Top-level ancestor: the cell the VC's quota is actually counted in
        # (reference: cell.go:319).
        self.preassigned_cell: Optional["VirtualCell"] = None
        self.physical_cell: Optional[PhysicalCell] = None

    def set_priority(self, p: CellPriority) -> None:
        self.priority = p
        self._bump_epoch()

    def set_physical_cell(self, cell: Optional[PhysicalCell]) -> None:
        """Unbinding resets state/health since a dangling virtual cell has no
        hardware underneath (reference: cell.go:401-420)."""
        self.physical_cell = cell
        self._bump_epoch()
        if cell is None:
            self.state = CellState.FREE
            self.healthy = True
        else:
            self.healthy = cell.healthy
        # Find the nearest registered ancestor: cells BELOW the node anchor
        # carry no view_reg of their own, but the anchor's usable-capacity
        # score now reads leaf bindings (an advisory bad-binding appearing
        # on a virtual chip changes _node_unusable_free), so their binding
        # changes must dirty the anchor too (found by the chaos harness's
        # probe-equivalence at 600-seed scale).
        target: Optional[Cell] = self
        while target is not None and target.view_reg is None:
            target = target.parent
        if target is not None:
            reg = target.view_reg
            if reg[1]:
                reg[0].mark_dirty(target.address)
            else:
                # A binding (dis)appearing ABOVE node level changes how every
                # unbound node under it scores against suggested nodes; the
                # view treats it as an epoch, not a per-node dirty mark.
                reg[0].bump_binding_stamp()


class CellList:
    """An ordered, address-indexed collection of cells.

    Replaces the plain ``List[Cell]`` per-level storage of the reference's
    ChainCellList: backed by an insertion-ordered dict keyed by cell address,
    so ``contains``/``remove`` are O(1) while iteration order (which the
    packing sort and buddy allocator depend on) is preserved exactly —
    removing an entry keeps the relative order of the rest, like
    ``list.pop(i)`` did.
    """

    __slots__ = ("_cells",)

    def __init__(self, cells: Iterable[Cell] = ()):
        self._cells: Dict[api.CellAddress, Cell] = {
            c.address: c for c in cells
        }

    def __iter__(self) -> Iterator[Cell]:
        return iter(self._cells.values())

    def __len__(self) -> int:
        return len(self._cells)

    def __bool__(self) -> bool:
        return bool(self._cells)

    def __getitem__(self, index: int) -> Cell:
        if index == 0:
            # The hot case ([0] peeking by the buddy allocator / compiler).
            try:
                return next(iter(self._cells.values()))
            except StopIteration:
                raise IndexError("cell list index out of range")
        return list(self._cells.values())[index]

    def append(self, c: Cell) -> None:
        self._cells[c.address] = c

    def extend(self, cells: Iterable[Cell]) -> None:
        for c in cells:
            self._cells[c.address] = c

    def contains(self, c: Cell) -> bool:
        return c.address in self._cells

    def __contains__(self, c: Cell) -> bool:
        return c.address in self._cells

    def remove(self, c: Cell) -> None:
        try:
            del self._cells[c.address]
        except KeyError:
            raise api.internal_error(
                f"Cell not found in list when removing: {c.address}"
            )

    def copy(self) -> "CellList":
        copied = CellList()
        copied._cells = dict(self._cells)
        return copied

    def __repr__(self) -> str:
        return repr([c.address for c in self])


class ChainCellList:
    """Per-level cell lists for one chain
    (reference: algorithm/types.go:97-131 ``ChainCellList``)."""

    def __init__(self, top_level: CellLevel = 0):
        self.levels: Dict[CellLevel, CellList] = {
            l: CellList() for l in range(LOWEST_LEVEL, top_level + 1)
        }

    def __getitem__(self, level: CellLevel) -> CellList:
        lst = self.levels.get(level)
        if lst is None:
            lst = self.levels[level] = CellList()
        return lst

    def __contains__(self, level: CellLevel) -> bool:
        return level in self.levels

    @property
    def top_level(self) -> CellLevel:
        return max(self.levels) if self.levels else 0

    def contains(self, c: Cell, level: CellLevel) -> bool:
        lst = self.levels.get(level)
        return lst is not None and lst.contains(c)

    def remove(self, c: Cell, level: CellLevel) -> None:
        self.levels[level].remove(c)

    def prepend(self, cells: List[Cell], level: CellLevel) -> None:
        """Insert ``cells`` BEFORE the current entries of ``level`` (the
        relaxed buddy allocator offers freshly split cells first)."""
        merged = CellList(cells)
        merged.extend(self.levels.get(level, ()))
        self.levels[level] = merged

    def shallow_copy(self) -> "ChainCellList":
        copied = ChainCellList()
        copied.levels = {l: cl.copy() for l, cl in self.levels.items()}
        return copied

    def __repr__(self) -> str:
        return "\n".join(
            f"level {l}: {[c.address for c in cl]}"
            for l, cl in sorted(self.levels.items())
        )
