"""The HiveD core algorithm: VC-safe, topology-guaranteed gang scheduling.

Python equivalent of the reference's ``pkg/algorithm/hived_algorithm.go``
(HivedAlgorithm, L40-1565) plus the helpers in ``pkg/algorithm/utils.go``
(result generation L38-200, victim collection L202-248, recovery helpers
L250-396, cell-state propagation L397-417, opportunistic status L419-452).

Responsibilities:
  - guaranteed scheduling: intra-VC placement then virtual->physical mapping
    via buddy allocation (scheduleGuaranteedAffinityGroup, ref L900-942)
  - opportunistic scheduling straight on the physical chains (ref L968-980)
  - the cell state machine Free/Used/Reserving/Reserved x group state machine
    Allocated/Preempting/BeingPreempted (doc/design/state-machine.md)
  - lazy preemption and its revert (ref L1166-1230)
  - VC-safety bookkeeping (vcFreeCellNum / allVCFreeCellNum / totalLeftCellNum)
  - bad-node tracking with doomed-bad-cell bind/unbind (ref L453-653)
  - crash recovery by replaying pod-bind-info annotations
    (createAllocatedAffinityGroup, ref L982-1041)
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from .. import common
from ..api import types as api
from ..api.config import Config
from ..scheduler.types import (
    Node,
    Pod,
    PodPreemptInfo,
    PodScheduleResult,
    PodWaitInfo,
    SchedulingPhase,
    extract_pod_bind_info,
    extract_pod_preempt_info,
    extract_pod_scheduling_spec,
    is_node_healthy,
)
from . import allocation, compiler
from .cell import (
    Cell,
    CellChain,
    CellLevel,
    CellPriority,
    CellState,
    ChainCellList,
    FREE_PRIORITY,
    LOWEST_LEVEL,
    MIN_GUARANTEED_PRIORITY,
    OPPORTUNISTIC_PRIORITY,
    PhysicalCell,
    VirtualCell,
    cell_equal,
)
from .group import (
    AffinityGroup,
    GroupState,
    Placement,
    build_binding_paths,
    virtual_to_physical_placement,
)
from .intra_vc import IntraVCScheduler, SchedulingRequest
from .placement import (
    PhaseStats,
    TopologyAwareScheduler,
    _ancestor_no_higher_than_node,
)

###############################################################################
# Free-standing helpers (reference: pkg/algorithm/utils.go)
###############################################################################


def _placement_node_anchors(placement: Placement) -> Set[api.CellAddress]:
    """The node-anchor addresses a (virtual) placement lands on — the unit
    the mapping-retry exclusion works in (see
    HivedCore._schedule_guaranteed_group and placement._find_nodes_for_pods)."""
    anchors: Set[api.CellAddress] = set()
    for pod_placements in placement.values():
        for row in pod_placements:
            for leaf in row:
                if leaf is not None:
                    anchors.add(_ancestor_no_higher_than_node(leaf).address)
    return anchors


def in_free_cell_list(c: PhysicalCell) -> bool:
    """True if the cell or an ancestor is in the global free list
    (reference: utils.go:381-392)."""
    while True:
        if c.virtual_cell is not None or c.split:
            return False
        if c.parent is None or c.parent.split:
            return True
        c = c.parent


def all_children_same_state(c: PhysicalCell, s: CellState) -> bool:
    """(reference: utils.go:410-417)"""
    return all(child.state == s for child in c.children)


def _cells_overlap(a: Cell, b: Cell) -> bool:
    """True when one cell's subtree contains the other (same chain)."""
    hi, lo = (a, b) if a.level >= b.level else (b, a)
    cur: Optional[Cell] = lo
    while cur is not None and cur.level < hi.level:
        cur = cur.parent
    return cur is not None and cell_equal(cur, hi)


def set_cell_state(c: PhysicalCell, s: CellState) -> None:
    """Propagate state up: a parent is Used if ANY child is Used; it takes
    the other states only when ALL children share them
    (reference: utils.go:397-407)."""
    c.set_state(s)
    if c.parent is not None:
        parent = c.parent
        if s == CellState.USED or all_children_same_state(parent, s):
            set_cell_state(parent, s)


def get_new_pod_index(pods: List[Optional[Pod]]) -> int:
    """First free slot for a new pod in its group (reference: utils.go:300-309)."""
    for i, p in enumerate(pods):
        if p is None:
            return i
    return -1


def get_allocated_pod_index(info: api.PodBindInfo, leaf_cell_num: int) -> int:
    """Locate an allocated pod inside its group bind info by node + first
    chip index (reference: utils.go:312-325)."""
    for gms in info.affinity_group_bind_info:
        if not gms.pod_placements:
            continue
        if len(gms.pod_placements[0].physical_leaf_cell_indices) == leaf_cell_num:
            for pod_index, placement in enumerate(gms.pod_placements):
                if (
                    placement.physical_node == info.node
                    and info.leaf_cell_isolation
                    and info.leaf_cell_isolation[0]
                    in placement.physical_leaf_cell_indices
                ):
                    return pod_index
    return -1


def all_pods_released(allocated_pods: Dict[int, List[Optional[Pod]]]) -> bool:
    """(reference: utils.go:328-337)"""
    return all(p is None for pods in allocated_pods.values() for p in pods)


def group_chain(g: AffinityGroup) -> Optional[CellChain]:
    """The cell chain a group's placement lives in (a gang is scheduled
    transactionally onto ONE chain; group.py module docstring). None while
    no leaf is placed yet. Cells never change chain, so the first non-None
    leaf answers."""
    for pod_placements in g.physical_placement.values():
        for pod_placement in pod_placements:
            for leaf in pod_placement:
                if leaf is not None:
                    return leaf.chain
    return None


def find_physical_leaf_cell(
    full_cell_list: Dict[CellChain, ChainCellList],
    chain: CellChain,
    node: str,
    leaf_cell_index: int,
) -> Optional[PhysicalCell]:
    """Find a leaf cell by (node, chip index); searches other chains if not
    found in the recorded one (the cell may have moved due to
    reconfiguration) (reference: utils.go:340-378)."""
    found = _find_leaf_in_chain(full_cell_list, chain, node, leaf_cell_index)
    if found is None:
        for c in full_cell_list:
            if c != chain:
                found = _find_leaf_in_chain(full_cell_list, c, node, leaf_cell_index)
                if found is not None:
                    common.log.warning(
                        "Leaf cell %s on node %s has been moved to chain %s",
                        leaf_cell_index, node, c,
                    )
                    return found
    return found


def _find_leaf_in_chain(
    full_cell_list: Dict[CellChain, ChainCellList],
    chain: CellChain,
    node: str,
    leaf_cell_index: int,
) -> Optional[PhysicalCell]:
    if chain not in full_cell_list:
        return None
    ccl = full_cell_list[chain]
    # Per-node index, built lazily and cached on the list object: the FULL
    # cell list's leaf membership is fixed at config-compile time (only
    # free lists mutate), and every assume-bind replays each pod's leaves
    # through this lookup — the linear scan over all chain leaves was the
    # single largest profile entry in the gang-latency bench.
    cache = getattr(ccl, "_node_leaf_cache", None)
    if cache is None:
        cache = {}
        for c in ccl[LOWEST_LEVEL]:
            assert isinstance(c, PhysicalCell)
            for n in c.nodes:
                cache.setdefault(n, []).append(c)
        ccl._node_leaf_cache = cache
    for c in cache.get(node, ()):
        if leaf_cell_index < 0 or leaf_cell_index in c.leaf_cell_indices:
            return c
    return None


def collect_bad_or_non_suggested_nodes(
    placement: Placement,
    suggested_nodes: Optional[Set[str]],
    ignore_suggested: bool,
) -> Set[str]:
    """(reference: utils.go:177-200)"""
    bad: Set[str] = set()
    for pod_placements in placement.values():
        for pod_placement in pod_placements:
            for leaf in pod_placement:
                if leaf is None:
                    continue
                assert isinstance(leaf, PhysicalCell)
                if not leaf.healthy or (
                    not ignore_suggested
                    and suggested_nodes is not None
                    and leaf.nodes[0] not in suggested_nodes
                ):
                    bad.add(leaf.nodes[0])
    return bad


def collect_preemption_victims(
    placement: Placement,
) -> Tuple[Dict[str, Dict[str, Pod]], List[AffinityGroup]]:
    """Victim pods (gang-preempted: all pods of any overlapping group) and
    the preempting groups whose reservations overlap this placement
    (reference: utils.go:202-248).

    Each victim GROUP's pods are walked once, on the first leaf that
    names it — the reference re-walks the whole gang per overlapping leaf,
    which is O(leaves x gang size) for the common case of preempting one
    big gang. Insertion order of the victims dicts is unchanged (the first
    occurrence ordered the entries before too; re-visits only overwrote)."""
    victims: Dict[str, Dict[str, Pod]] = {}  # node -> uid -> pod
    overlapping_preemptors: List[AffinityGroup] = []
    seen_victim_groups: List[AffinityGroup] = []
    for pod_placements in placement.values():
        for pod_placement in pod_placements:
            for leaf in pod_placement:
                if leaf is None:
                    continue
                assert isinstance(leaf, PhysicalCell)
                state = leaf.state
                if state in (CellState.USED, CellState.RESERVING):
                    ug = leaf.using_group
                    if all(ug is not sg for sg in seen_victim_groups):
                        seen_victim_groups.append(ug)
                        for pods in ug.allocated_pods.values():
                            for v in pods:
                                if v is not None:
                                    victims.setdefault(
                                        v.node_name, {}
                                    )[v.uid] = v
                if state in (CellState.RESERVING, CellState.RESERVED):
                    g = leaf.reserving_or_reserved_group
                    if g is not None and all(
                        g is not og for og in overlapping_preemptors
                    ):
                        overlapping_preemptors.append(g)
    return victims, overlapping_preemptors


def retrieve_missing_pod_placement(
    g: AffinityGroup, leaf_cell_num: int, pod_index: int
) -> Tuple[api.PodPlacementInfo, str]:
    """Recover a pod's placement from the bind-info annotation of any other
    allocated pod of the same group (reference: utils.go:250-268)."""
    for pods in g.allocated_pods.values():
        for p in pods:
            if p is not None:
                info = extract_pod_bind_info(p)
                for mbi in info.affinity_group_bind_info:
                    if mbi.pod_placements and len(
                        mbi.pod_placements[0].physical_leaf_cell_indices
                    ) == leaf_cell_num:
                        return mbi.pod_placements[pod_index], info.cell_chain
    raise api.internal_error(
        f"No allocated pod found in an allocated group {g.name} when "
        f"retrieving placement for pod {pod_index} with leaf cell number "
        f"{leaf_cell_num}"
    )


def generate_pod_preempt_info(
    victims: Dict[str, Dict[str, Pod]],
    pod: Pod,
    rng: Optional[random.Random] = None,
) -> PodPreemptInfo:
    """Pick one node's victims (K8s preempts one node at a time; random node
    to spread preemptors) (reference: utils.go:82-105).

    ``rng`` makes the pick seedable (chaos/probe determinism: the harness
    sets ``HivedCore.preempt_rng``); None keeps the process-random default
    production has always used."""
    nodes = sorted(victims)
    node_to_preempt = nodes[(rng or random).randrange(len(nodes))]
    victim_pods = list(victims[node_to_preempt].values())
    common.log.info(
        "[%s]: need to preempt pods %s",
        pod.key, [v.key for v in victim_pods],
    )
    return PodPreemptInfo(victim_pods=victim_pods)


def select_pod_from_bind_info(
    bind_info: List[api.AffinityGroupMemberBindInfo],
    group_physical: Placement,
    current_leaf_cell_num: int,
    current_pod_index: int,
    chain: str,
) -> Tuple[str, List[int], str]:
    """Pick the current pod's (node, chip indices, chain) out of an
    already-generated group-level bind info record — the cache-hit
    counterpart of the selection block inside
    ``generate_affinity_group_bind_info``."""
    node, indices = "", []
    for mbi in bind_info:
        if mbi.pod_placements and len(
            mbi.pod_placements[0].physical_leaf_cell_indices
        ) == current_leaf_cell_num:
            node = mbi.pod_placements[current_pod_index].physical_node
            indices = mbi.pod_placements[
                current_pod_index
            ].physical_leaf_cell_indices
            first = group_physical[current_leaf_cell_num][current_pod_index][0]
            if first is not None:
                chain = first.chain
            break
    return node, indices, chain


def generate_affinity_group_bind_info(
    group_physical: Placement,
    group_virtual: Optional[Placement],
    cell_level_to_type: Dict[CellChain, Dict[CellLevel, api.CellType]],
    current_leaf_cell_num: int,
    current_pod_index: int,
    group: Optional[AffinityGroup],
    group_name: str,
) -> Tuple[List[api.AffinityGroupMemberBindInfo], str, List[int], str]:
    """Translate placements into the durable bind-info record; also returns
    the current pod's (node, chip indices, chain)
    (reference: utils.go:108-174).

    The group-level record is memoized on the AffinityGroup: a gang's
    placements are fixed once allocated, so the reference's per-pod
    regeneration is O(gang²) across one gang's admission — every pod after
    the first reuses the cached record and only re-derives its own (node,
    chips) selection. The cache is invalidated when the virtual placement
    changes (lazy preemption / revert; see those methods)."""
    if group is not None and group.bind_info_cache is not None:
        cached_info, cached_chain = group.bind_info_cache
        node, indices, chain = select_pod_from_bind_info(
            cached_info,
            group_physical,
            current_leaf_cell_num,
            current_pod_index,
            cached_chain,
        )
        return cached_info, node, indices, chain
    bind_info: List[api.AffinityGroupMemberBindInfo] = []
    chain = ""
    for pod_leaf_num in sorted(group_physical):
        pod_placements = group_physical[pod_leaf_num]
        mbi = api.AffinityGroupMemberBindInfo(
            pod_placements=[
                api.PodPlacementInfo(
                    physical_leaf_cell_indices=[0] * pod_leaf_num,
                    preassigned_cell_types=[""] * pod_leaf_num,
                )
                for _ in pod_placements
            ]
        )
        for pod_index, pod_placement in enumerate(pod_placements):
            for leaf_index, p_leaf in enumerate(pod_placement):
                if p_leaf is None:
                    if group is None or group.state == GroupState.PREEMPTING:
                        raise api.internal_error(
                            f"The first pod in group {group_name} was "
                            "allocated invalid resource"
                        )
                    # Placement lost (e.g. reconfiguration): recover it from
                    # the other pods' annotations (reference: utils.go:131-138).
                    mbi.pod_placements[pod_index], chain = (
                        retrieve_missing_pod_placement(
                            group, pod_leaf_num, pod_index
                        )
                    )
                    common.log.warning(
                        "pod placement has been invalid and is retrieved from "
                        "annotation of other pods: node %s, leaf cells %s",
                        mbi.pod_placements[pod_index].physical_node,
                        mbi.pod_placements[pod_index].physical_leaf_cell_indices,
                    )
                else:
                    assert isinstance(p_leaf, PhysicalCell)
                    if not mbi.pod_placements[pod_index].physical_node:
                        mbi.pod_placements[pod_index].physical_node = p_leaf.nodes[0]
                    mbi.pod_placements[pod_index].physical_leaf_cell_indices[
                        leaf_index
                    ] = p_leaf.leaf_cell_indices[0]
                    if group_virtual is not None:
                        v_leaf = group_virtual[pod_leaf_num][pod_index][leaf_index]
                        assert isinstance(v_leaf, VirtualCell)
                        mbi.pod_placements[pod_index].preassigned_cell_types[
                            leaf_index
                        ] = cell_level_to_type[v_leaf.chain][
                            v_leaf.preassigned_cell.level
                        ]
                    else:
                        mbi.pod_placements[pod_index].preassigned_cell_types[
                            leaf_index
                        ] = ""
        bind_info.append(mbi)
    node, indices, chain = select_pod_from_bind_info(
        bind_info, group_physical, current_leaf_cell_num, current_pod_index, chain
    )
    if group is not None:
        group.bind_info_cache = (bind_info, chain)
    return bind_info, node, indices, chain


def generate_pod_schedule_result(
    group_physical: Optional[Placement],
    group_virtual: Optional[Placement],
    preemption_victims: Optional[Dict[str, Dict[str, Pod]]],
    wait_reason: str,
    cell_level_to_type: Dict[CellChain, Dict[CellLevel, api.CellType]],
    current_leaf_cell_num: int,
    current_pod_index: int,
    group: Optional[AffinityGroup],
    group_name: str,
    pod: Pod,
    preempt_rng: Optional[random.Random] = None,
) -> PodScheduleResult:
    """(reference: utils.go:38-79)"""
    if group_physical is None:
        common.log.info("[%s]: Pod needs to wait, reason: %s", pod.key, wait_reason)
        return PodScheduleResult(pod_wait_info=PodWaitInfo(reason=wait_reason))
    if preemption_victims:
        return PodScheduleResult(
            pod_preempt_info=generate_pod_preempt_info(
                preemption_victims, pod, preempt_rng
            )
        )
    bind_info, node, indices, chain = generate_affinity_group_bind_info(
        group_physical,
        group_virtual,
        cell_level_to_type,
        current_leaf_cell_num,
        current_pod_index,
        group,
        group_name,
    )
    common.log.info(
        "[%s]: pod is decided to be scheduled to node %s, leaf cells %s",
        pod.key, node, indices,
    )
    return PodScheduleResult(
        pod_bind_info=api.PodBindInfo(
            node=node,
            leaf_cell_isolation=indices,
            cell_chain=chain,
            affinity_group_bind_info=bind_info,
        ),
        # Batched admission: the framework hands this straight back to
        # add_allocated_pod, skipping the per-pod decode + index scan.
        pod_index=current_pod_index,
    )


###############################################################################
# The core
###############################################################################


class _LazyVCSchedulers:
    """Mapping facade over the per-VC intra-VC schedulers (lazy compile,
    doc/hot-path.md "Boot and transport plane").

    Name iteration, membership, and length are free (the configured VC
    name list); ``[vc]`` / ``get`` compile the VC on first touch via
    HivedCore.ensure_vc; ``values()`` / ``items()`` force EVERY VC (the
    inspect-all surface — a deliberate, documented force point). Callers
    that must not force use ``compiled_values()``."""

    def __init__(self, core: "HivedCore"):
        self._core = core
        self._compiled: Dict[api.VirtualClusterName, IntraVCScheduler] = {}

    def __contains__(self, vc) -> bool:
        return vc in self._core._vc_name_set

    def __iter__(self):
        return iter(self._core.compiled.vc_names)

    def __len__(self) -> int:
        return len(self._core.compiled.vc_names)

    def keys(self):
        return list(self._core.compiled.vc_names)

    def __getitem__(self, vc) -> IntraVCScheduler:
        vcs = self._compiled.get(vc)
        if vcs is not None:
            return vcs
        return self._core.ensure_vc(vc)

    def get(self, vc, default=None):
        if vc not in self:
            return default
        return self[vc]

    def values(self):
        return [self[vc] for vc in self]

    def items(self):
        return [(vc, self[vc]) for vc in self]

    def compiled_values(self) -> List[IntraVCScheduler]:
        return list(self._compiled.values())


class HivedCore:
    """The scheduling algorithm (reference: hived_algorithm.go:40-105).

    Thread-safety contract: the framework serializes all calls
    (reference: internal/types.go:67-75); this class itself is not locked.
    """

    def __init__(self, config: Config):
        _boot_t0 = time.monotonic()
        cc = compiler.parse_config(config)
        # Boot-phase ledger (doc/hot-path.md "Boot and transport plane"):
        # wall seconds per boot phase, surfaced by the framework as
        # bootPhaseSeconds / hived_boot_phase_seconds{phase=...}.
        self.boot_phase_seconds: Dict[str, float] = {
            "compile": time.monotonic() - _boot_t0
        }
        self.compiled = cc
        self.full_cell_list = cc.physical_full_list
        self.free_cell_list = cc.physical_free_list
        self.vc_free_cell_num = cc.vc_free_cell_num
        self.cell_types = cc.cell_level_to_type
        self.cell_chains = cc.leaf_cell_type_to_chain
        self.chain_to_leaf_type = cc.chain_to_leaf_type
        self.affinity_groups: Dict[str, AffinityGroup] = {}

        # Validate every VC-referenced chain against the physical cluster
        # BEFORE constructing the intra-VC schedulers: an unknown chain
        # (e.g. a dotted quota type naming a nonexistent top cell) would
        # otherwise escape as a raw KeyError from scheduler construction
        # instead of the reference's user error (hived_algorithm.go:374-380).
        for vc, vc_free in self.vc_free_cell_num.items():
            for chain in vc_free:
                if chain not in self.full_cell_list:
                    raise api.bad_request(
                        f"Illegal initial VC assignment: Chain {chain} "
                        "does not exist in physical cluster"
                    )

        # Per-phase latency accumulators shared with every topology-aware
        # scheduler (leaf-cell search) and the framework (lock-wait /
        # core-schedule); surfaced via framework.get_metrics().
        self.phase_stats = PhaseStats()

        # Lazy per-VC virtual compile (doc/hot-path.md "Boot and
        # transport plane"): vc_schedulers is a mapping FACADE — name
        # iteration and membership are free, item access compiles the
        # VC's cell trees on first touch (ensure_vc). Under HIVED_LAZY_VC=0
        # every VC compiles right here, restoring the eager constructor.
        self._vc_name_set = set(cc.vc_names)
        self._vc_compile_lock = threading.RLock()
        self.vc_schedulers = _LazyVCSchedulers(self)
        self.opportunistic_schedulers: Dict[CellChain, TopologyAwareScheduler] = {
            chain: TopologyAwareScheduler(
                ccl,
                cc.cell_level_to_leaf_num[chain],
                cross_priority_pack=False,
                phase_stats=self.phase_stats,
            )
            for chain, ccl in self.full_cell_list.items()
        }

        # Per-chain mutation epochs: one shared counter per chain, installed
        # as epoch_ref on every physical AND virtual cell of that chain.
        # Any status-visible cell mutation bumps it (cell.py), as does a
        # pod-slot change in a group of that chain (add/delete_allocated_pod)
        # — so "epoch unchanged" certifies both the mirrored inspect
        # statuses and the preempt-probe victims caches are still fresh.
        self.chain_epochs: Dict[CellChain, List[int]] = {}
        # Snapshot-plane indexes (doc/fault-model.md "HA and snapshot
        # recovery plane"): bound_physical is the live binding registry
        # (address -> bound physical cell, maintained by
        # PhysicalCell.set_virtual_cell via binding_reg) so restore can
        # clear bindings without a tree walk, and the address indexes make
        # export/restore_projection's address <-> cell resolution O(1);
        # cell membership is fixed at config-compile time.
        self.bound_physical: Dict[api.CellAddress, PhysicalCell] = {}
        self._install_epoch_refs()
        self._phys_cell_index: Dict[api.CellAddress, PhysicalCell] = {
            c.address: c
            for ccl in self.full_cell_list.values()
            for cl in ccl.levels.values()
            for c in cl
        }
        # Virtual cells join the index per VC at ensure_vc time (lazy
        # compile); eager mode fills it below via the forced compiles.
        self._virt_cell_index: Dict[api.CellAddress, VirtualCell] = {}
        # Lock-sharding contract hook (scheduler.locks): the framework
        # installs ChainShardedLock.require_global here so the cross-chain
        # mutators below (node/chip health, drains, node deletes) ASSERT
        # they run under the total-order global mode. None for bare cores
        # (tests, benches driving the core directly, single-threaded).
        self.lock_validator: Optional[Callable[[], None]] = None
        # Shadow what-if audit hook (scheduler.whatif): installed on the
        # LIVE core only, called before every state-changing entry point
        # (schedule, pod add/delete, resize, epoch bumps, and the
        # cross-chain mutators via _require_global). A shadow-forecast
        # thread reaching a live mutator raises instead of corrupting
        # served state — the read-only-fork contract's runtime teeth,
        # mirroring lock_validator's. None for shadow cores and ordinary
        # schedulers (zero overhead beyond one None check).
        self.write_guard: Optional[Callable[[], None]] = None
        # Hot-path counters (surfaced via framework.get_metrics): pods
        # admitted through the batched (decode-free) gang admission path,
        # and preempt probes served from the epoch-gated victims cache.
        # Guarded by _counter_lock — chains mutate them concurrently.
        self.gang_admission_batched_count = 0
        self.preempt_probe_incremental_count = 0
        # Elastic gang plane (doc/fault-model.md "Elastic gang plane").
        # resize_events records every applied shrink/grow (the framework
        # drains it to bump metrics and re-sync surviving pods' stale
        # annotations); resize_orphans collects replayed pods whose
        # placement a NEWER generation already shrank away (the framework
        # re-queues their eviction). Both are drained at mutator exit.
        self.resize_events: List[Dict] = []
        self.resize_orphans: List[Pod] = []
        self.gang_shrink_count = 0
        self.gang_grow_count = 0
        # Guaranteed schedules that succeeded only after retrying the
        # intra-VC placement past a failed virtual→physical mapping
        # (chip-granular dooming fix; doc/fault-model.md).
        self.mapping_retry_count = 0
        self._counter_lock = threading.Lock()
        # Mirrored inspect statuses (the reference maintains apiStatus
        # mirrors, hived_algorithm.go:412-437; we rebuild per chain only
        # when its epoch moved): chain -> (epoch, [top-cell status dicts]),
        # VC -> (total epoch, status list). Returned structures are shared
        # and read-only by contract (the webserver JSON-encodes them).
        self._phys_status_cache: Dict[CellChain, Tuple[int, List[Dict]]] = {}
        self._vc_status_cache: Dict[
            api.VirtualClusterName, Tuple[int, List[Dict]]
        ] = {}
        # Incremental snapshot export: chain -> (epoch, section dict).
        # The flusher's export walk re-serialized every chain each beat;
        # keying each chain's slice of the durable projection on its
        # mutation epoch makes a quiet chain one dict lookup
        # (doc/hot-path.md). Cleared wholesale by restore_projection —
        # the restore writes cell fields directly, without mutator hooks.
        self._export_chain_memo: Dict[CellChain, Tuple[int, Dict]] = {}
        self._export_cells_by_chain: Optional[Dict] = None

        # VC-safety and bad-cell bookkeeping
        # (reference: hived_algorithm.go:52-93).
        self.all_vc_free_cell_num: Dict[CellChain, Dict[CellLevel, int]] = {}
        self.total_left_cell_num: Dict[CellChain, Dict[CellLevel, int]] = {}
        self.bad_free_cells: Dict[CellChain, ChainCellList] = {}
        self.vc_doomed_bad_cells: Dict[
            api.VirtualClusterName, Dict[CellChain, ChainCellList]
        ] = {}
        self.all_vc_doomed_bad_cell_num: Dict[CellChain, Dict[CellLevel, int]] = {}
        self.bad_nodes: Set[str] = set()
        # Chip-granular health plane (doc/fault-model.md "Hardware health
        # plane"): chip indices marked bad per node (device-health
        # annotation / node conditions) and chip indices draining per node
        # (maintenance annotation). Badness composes with node badness — a
        # leaf is bad while EITHER holds; draining is orthogonal to badness
        # (no doomed/bad-free accounting, placement exclusion only).
        self.bad_chips: Dict[str, Set[int]] = {}
        self.draining_chips: Dict[str, Set[int]] = {}
        # node -> its leaf cells across every chain, precomputed once: the
        # cell population is fixed at config-compile time, and the health
        # plane consults this on EVERY node event (a relist delivers N of
        # them) — a per-event full-cluster leaf scan under the scheduler
        # lock would stall filtering at fleet scale.
        self._node_leaf_index: Dict[str, List[PhysicalCell]] = {}
        for ccl in self.full_cell_list.values():
            for cell in ccl[LOWEST_LEVEL]:
                assert isinstance(cell, PhysicalCell)
                self._node_leaf_index.setdefault(cell.nodes[0], []).append(
                    cell
                )
        # Lazily-filled config-static cache behind node_chip_indices().
        self._node_chip_index: Dict[str, Set[int]] = {}
        # Opportunistic cells currently charged to each VC, for the inspect
        # API (reference: utils.go:419-452 OT virtual cells). Keyed by cell
        # address (insertion-ordered, so the inspect output order matches
        # the old list exactly): with the lock sharded per chain, two
        # chains can allocate/release opportunistically into the same VC
        # concurrently, and dict item ops are atomic where a list
        # scan-and-pop is not.
        self._ot_cells: Dict[
            api.VirtualClusterName, Dict[api.CellAddress, PhysicalCell]
        ] = {}
        # (chain, level) -> count of doomed-bad shortfalls that must be
        # re-checked after the current pod replay completes: evicting a
        # doomed binding mid-replay leaves the shortfall unaddressed, but
        # re-dooming immediately could grab the very virtual cell the
        # replayed pod is about to claim — so the check is deferred to
        # add_allocated_pod, and the safety checks discount the pending
        # units meanwhile (the freed quota is spoken for, not actually free).
        # THREAD-LOCAL under lock sharding: the deferral is scoped to one
        # replay call, whose chains the calling thread holds locked — a
        # concurrent replay in another chain must neither see these
        # discounts (different chains) nor steal the deferred re-checks
        # at its own flush.
        self._pending_doomed_local = threading.local()
        # Seedable source for the preemption victim-node pick; the chaos
        # harness and probe battery replace it with a seeded Random so
        # preemption schedules are deterministic per seed. Production keeps
        # process randomness.
        self.preempt_rng: Optional[random.Random] = None
        # Doomed-ledger persistence support (doc/fault-model.md
        # "Reconfiguration plane"): every advisory-binding change bumps the
        # epoch so the framework knows when to rewrite the ledger ConfigMap,
        # and during recovery the persisted ledger seeds the preference map
        # so dooms re-bind to the SAME bad cells the pre-crash scheduler
        # chose instead of arbitrary ones (that arbitrariness is what made
        # the doomed subsystem non-reconstructible before).
        self.doomed_epoch = 0
        self._doomed_epoch_lock = threading.Lock()
        self.preferred_doomed: Dict[
            Tuple[api.VirtualClusterName, CellChain, CellLevel], Set[str]
        ] = {}
        # (vcn, chain, level, physical address) -> the virtual address
        # the pre-crash scheduler had the doom bound to (the ledger's
        # virtualAddress field): recovery rebinds the exact pairing so
        # annotation replay converges with snapshot restore and the live
        # timeline (the lazy-VC plane removed the boot-churn list
        # rotation that used to make first-unbound coincide with it).
        self.preferred_doomed_virtual: Dict[Tuple, str] = {}
        # While True (recovery with a loaded ledger), the persisted ledger
        # is AUTHORITATIVE: organic doom bind/retire is suspended and
        # rebuild_doomed_from_ledger is the only creator. Recovery replays
        # through intermediate states (final node health, no pods yet) the
        # continuous timeline never visited, so organic shortfall checks
        # there would create — or retire — advisory bindings the pre-crash
        # scheduler did not have.
        self.doomed_ledger_mode = False
        # Optional hook observing preempting-group lifecycle transitions
        # ("cancelled" / "allocated"), called with the group while its
        # preempting_pods are still populated. The framework uses it to
        # clear preempt-info annotations outside the scheduler lock.
        self.preemption_observer: Optional[Callable[[AffinityGroup, str], None]] = None
        # Decision journal (scheduler.decisions.DecisionJournal), installed
        # by the framework. The inner scheduling gates enrich the request
        # thread's CURRENT record (begun by filter/preempt routines) with
        # per-chain rejection reasons; bare cores (tests, benches, the
        # chaos probe battery) have no journal and record nothing.
        self.decisions = None

        self._init_cell_nums()
        if not cc.lazy_vc:
            # Eager mode: the all-VC virtual compile is boot compile
            # work — account it where the lazy path's deferral shows.
            _t_vc = time.monotonic()
            for vc in cc.vc_names:
                self.ensure_vc(vc)
            self.boot_phase_seconds["compile"] += (
                time.monotonic() - _t_vc
            )
        else:
            # A VC holding pinned cells compiles eagerly even in lazy
            # mode: _init_pinned_cells binds into its virtual tree, and
            # badness under an allocated top hangs advisory bindings off
            # that tree — both need the cells to exist. Pinned VCs are
            # rare and small; the 37-idle-VC win is untouched.
            for vc in cc.vc_names:
                if cc.physical_pinned.get(vc):
                    self.ensure_vc(vc)
        self._init_pinned_cells(cc.physical_pinned)
        _t_health = time.monotonic()
        self._init_bad_nodes()
        self.boot_phase_seconds["healthInit"] = time.monotonic() - _t_health

    # -- init ---------------------------------------------------------------

    def vc_compiled(self, vc: api.VirtualClusterName) -> bool:
        """True when the VC's virtual cell trees exist (lazy compile has
        run, or eager mode). Lock-free dict read."""
        return vc in self.vc_schedulers._compiled

    def ensure_vc(self, vc: api.VirtualClusterName) -> IntraVCScheduler:
        """Force one VC's virtual compile (memoized). Every VC access
        path funnels here via the vc_schedulers facade: schedule,
        inspect, snapshot restore (pre-forced per projection), and the
        doomed-ledger rebuild. Raises KeyError for unknown VCs (dict
        semantics — callers gate with ``in``).

        The force is a PURE COMPILE: fresh cells (all free/healthy),
        index and epoch-ref installs, cache invalidations — no
        placement-visible state changes, so forcing from any path
        (including the chaos probe battery) is order-independent and
        restart-equivalent. Advisory doomed-bad bindings the VC's quota
        shortfall demands appear at the NEXT organic trigger
        (_try_bind_doomed_bad_cell fires on every bad-free/allocation
        transition), exactly when a restarted scheduler's would — never
        at force time, where the two timelines' trigger histories
        differ."""
        vcs = self.vc_schedulers._compiled.get(vc)
        if vcs is not None:
            return vcs
        with self._vc_compile_lock:
            vcs = self.vc_schedulers._compiled.get(vc)
            if vcs is not None:
                return vcs
            if vc not in self._vc_name_set:
                raise KeyError(vc)
            cc = self.compiled
            cc.compile_vc(vc)
            vcs = IntraVCScheduler(
                cc.virtual_non_pinned_full[vc],
                cc.virtual_non_pinned_free[vc],
                cc.virtual_pinned[vc],
                cc.cell_level_to_leaf_num,
                phase_stats=self.phase_stats,
            )
            self._install_vc_epoch_refs(vcs)
            for ccl in vcs.non_pinned_full.values():
                for cl in ccl.levels.values():
                    for c in cl:
                        self._virt_cell_index[c.address] = c
            for ccl in vcs.pinned_cells.values():
                for cl in ccl.levels.values():
                    for c in cl:
                        self._virt_cell_index[c.address] = c
            # Static export caches were built without this VC's cells.
            self._export_cells_by_chain = None
            self._export_chain_memo.clear()
            self._vc_status_cache.pop(vc, None)
            self.vc_schedulers._compiled[vc] = vcs
        return vcs

    def _init_cell_nums(self) -> None:
        """Aggregate VC quotas, compute total capacity per level, and
        validate the VCs fit the physical cluster
        (reference: hived_algorithm.go:369-410)."""
        for vc, vc_free in self.vc_free_cell_num.items():
            self.vc_doomed_bad_cells[vc] = {}
            for chain, chain_free in vc_free.items():
                self.vc_doomed_bad_cells[vc][chain] = ChainCellList()
                self.all_vc_free_cell_num.setdefault(chain, {})
                for level, n in chain_free.items():
                    self.all_vc_free_cell_num[chain][level] = (
                        self.all_vc_free_cell_num[chain].get(level, 0) + n
                    )
        # Capacity-side structures (total_left, bad-free, doomed counters)
        # exist for EVERY physical chain, including chains no VC currently
        # has quota in — node-health tracking walks all chains, and a
        # quota-less chain is a legitimate config (capacity not yet
        # assigned; found by the reconfiguration-mutation fuzzer).
        for chain, ccl in self.full_cell_list.items():
            chain_free = self.all_vc_free_cell_num.get(chain, {})
            top = ccl.top_level
            available = len(ccl[top])
            self.total_left_cell_num[chain] = {top: available}
            self.bad_free_cells[chain] = ChainCellList()
            self.all_vc_doomed_bad_cell_num[chain] = {}
            for l in range(top, LOWEST_LEVEL - 1, -1):
                left = available - chain_free.get(l, 0)
                if left < 0:
                    raise api.bad_request(
                        "Illegal initial VC assignment: Insufficient physical "
                        f"cells at chain {chain} level {l}: "
                        f"{chain_free.get(l, 0)} needed, {available} available"
                    )
                if l > LOWEST_LEVEL:
                    child_num = len(ccl[l][0].children)
                    available = left * child_num
                    self.total_left_cell_num[chain][l - 1] = (
                        self.total_left_cell_num[chain][l] * child_num
                    )

    def _init_pinned_cells(
        self,
        pinned: Dict[api.VirtualClusterName, Dict[api.PinnedCellId, PhysicalCell]],
    ) -> None:
        """Static bindings for pinned cells
        (reference: hived_algorithm.go:439-449)."""
        for vcn, vc_pinned in pinned.items():
            for pid, pinned_physical in vc_pinned.items():
                self._allocate_preassigned_cell(pinned_physical, vcn, False)
                virtual_list = self.vc_schedulers[vcn].pinned_cells[pid]
                pinned_virtual = virtual_list[virtual_list.top_level][0]
                assert isinstance(pinned_virtual, VirtualCell)
                allocation.bind_cell(pinned_physical, pinned_virtual)

    def _init_bad_nodes(self) -> None:
        """All nodes are bad until the informer says otherwise
        (reference: hived_algorithm.go:453-465).

        Boot fold (doc/hot-path.md "Boot and transport plane"): on the
        pristine constructor state WITH NO COMPILED VC (the lazy-compile
        default — advisory dooming is wholly deferred, so nothing can
        observe intermediate flag state), each free top cell is marked
        bad by one direct flag pass emitting the subtree in pre-order —
        exactly the per-leaf recursion's bad-free append order — instead
        of O(leaves) recursive _set_bad_cell walks. End state is
        identical: every cell unhealthy, unusable == its leaf count,
        bad_free holding the whole subtree per level in first-touch
        order. Any compiled VC (pinned VCs, or HIVED_LAZY_VC=0), a
        non-free top, or a node shared across tops falls back to the
        per-node slow path wholesale — dooms then interleave with
        partially-flagged subtrees exactly as they always did.
        HIVED_BOOT_FOLD=0 forces the slow path (the differential boot
        test proves state equality both ways)."""
        fold = (
            os.environ.get("HIVED_BOOT_FOLD", "1").strip() != "0"
            and not self.vc_schedulers._compiled
        )
        if fold:
            # A node whose leaves span top cells breaks the
            # one-top-per-node ordering argument; take the slow path.
            tops_of_node: Dict[str, int] = {}
            for ccl in self.full_cell_list.values():
                for c in ccl[ccl.top_level]:
                    for n in set(c.nodes):
                        tops_of_node[n] = tops_of_node.get(n, 0) + 1
            fold = all(v == 1 for v in tops_of_node.values())
        for ccl in self.full_cell_list.values():
            for c in ccl[ccl.top_level]:
                assert isinstance(c, PhysicalCell)
                if fold and in_free_cell_list(c):
                    self.bad_nodes.update(c.nodes)
                    self._bootstrap_bad_subtree(c)
                else:
                    for n in c.nodes:
                        self.set_bad_node(n)

    def _bootstrap_bad_subtree(self, top: PhysicalCell) -> None:
        """Pristine-state bulk badness: flip health flags and unusable
        counters directly and append each cell to the bad-free list in
        pre-order (== the recursion's first-touch order). Valid ONLY from
        the constructor with no compiled VCs (no bindings, no drains, no
        prior badness, no live view slots, dooming deferred)."""
        stack: List[Cell] = [top]
        while stack:
            cell = stack.pop()
            cell.healthy = False
            cell.unusable_leaf_num = cell.total_leaf_cell_num
            if cell.children:
                stack.extend(reversed(cell.children))
            assert isinstance(cell, PhysicalCell)
            self._add_bad_free_cell(cell)
        self.bump_chain_epoch(top.chain)

    def _install_epoch_refs(self) -> None:
        """Give every PHYSICAL cell of a chain the chain's shared
        mutation-epoch counter (virtual cells join per VC at ensure_vc —
        cell membership is fixed once a VC compiles)."""
        for chain, ccl in self.full_cell_list.items():
            r = self._epoch_ref(chain)
            for cl in ccl.levels.values():
                for c in cl:
                    c.epoch_ref = r
                    c.binding_reg = self.bound_physical

    def _epoch_ref(self, chain: CellChain) -> List[int]:
        r = self.chain_epochs.get(chain)
        if r is None:
            r = self.chain_epochs[chain] = [0]
        return r

    def _install_vc_epoch_refs(self, vcs: IntraVCScheduler) -> None:
        """The per-VC half of _install_epoch_refs, run at compile-force
        time (pinned cells key off their own chain, as before)."""
        for chain, ccl in vcs.non_pinned_full.items():
            r = self._epoch_ref(chain)
            for cl in ccl.levels.values():
                for c in cl:
                    c.epoch_ref = r
        for ccl in vcs.pinned_cells.values():
            for cl in ccl.levels.values():
                for c in cl:
                    c.epoch_ref = self._epoch_ref(c.chain)

    def chain_epoch(self, chain: CellChain) -> int:
        r = self.chain_epochs.get(chain)
        return r[0] if r is not None else 0

    def bump_chain_epoch(self, chain: CellChain) -> None:
        """Explicit bump for mutations that change chain-visible state
        WITHOUT touching a cell: pod-slot assignments in a group's
        allocated_pods (the victims caches list those pods)."""
        self._audit_write()
        r = self.chain_epochs.get(chain)
        if r is not None:
            r[0] += 1

    def _audit_write(self) -> None:
        """Shadow what-if read-only audit (see write_guard): raises when
        a shadow-forecast thread reaches a live-core mutator."""
        if self.write_guard is not None:
            self.write_guard()

    def epoch_total(self) -> int:
        """Monotonic sum over all chain epochs (epochs only grow, so equal
        totals imply equal per-chain epochs) — the VC-status cache key."""
        return sum(r[0] for r in self.chain_epochs.values())

    def _bump_doomed_epoch(self) -> None:
        self._audit_write()
        with self._doomed_epoch_lock:
            self.doomed_epoch += 1

    def _require_global(self) -> None:
        """Assert the calling thread holds the global lock order before a
        cross-chain mutation (no-op on bare cores; see lock_validator)."""
        self._audit_write()
        if self.lock_validator is not None:
            self.lock_validator()

    def _pending_doomed(self) -> Dict[Tuple[CellChain, CellLevel], int]:
        d = getattr(self._pending_doomed_local, "d", None)
        if d is None:
            d = self._pending_doomed_local.d = {}
        return d

    def _decision_rec(self):
        """The request thread's in-flight decision record, or None (no
        journal installed, or the call is not under a recorded attempt)."""
        j = self.decisions
        return j.current() if j is not None else None

    def vc_quota_chains(self, vc: api.VirtualClusterName) -> List[CellChain]:
        """The chains a VC holds non-pinned quota in — the exact chain set
        a GUARANTEED pod without a leafCellType can probe
        (_schedule_group_for_leaf_type gates every chain on membership in
        the VC's non_pinned_preassigned). Compile-time constant per config;
        the framework narrows untyped pods' lock sections to it. Served
        from the eager spec scan — this must never force a lazy VC
        compile (lock-chain derivation and shard routing call it
        lock-free)."""
        return list(self.compiled.vc_nonpinned_chains.get(vc, []))

    # -- pending-pod plane (doc/hot-path.md "Pending-pod plane") ------------

    def quota_token(
        self, vc: api.VirtualClusterName, chains
    ) -> Tuple[Tuple[int, int, int], ...]:
        """Compact digest of the quota counters a schedule attempt for
        ``vc`` can read over ``chains``: per chain, the VC's own free-cell
        quota, the all-VC free total, and the all-VC doomed-bad total
        (level-summed — any counter movement changes a sum, counters are
        non-negative, and every movement rides a mutation that also bumps
        a monotonic epoch, so the composed version vector cannot ABA).
        Defense-in-depth alongside the chain epochs: the rejection
        certificate's vector stays valid only while the quota arithmetic
        the safety checks read is byte-for-byte what the WAIT saw."""
        vc_free = self.vc_free_cell_num.get(vc, {})
        return tuple(
            (
                sum(vc_free.get(chain, {}).values()),
                sum(self.all_vc_free_cell_num.get(chain, {}).values()),
                sum(self.all_vc_doomed_bad_cell_num.get(chain, {}).values()),
            )
            for chain in chains
        )

    def rejection_certificate(
        self,
        spec: api.PodSchedulingSpec,
        wait_reason: str,
        chains,
        suggested_token,
    ) -> Dict:
        """The compact certificate a WAIT verdict carries: the gate that
        failed plus the version vector the placement descent read — the
        mutation epochs of every chain the attempt's lock section covered,
        the doomed-ledger epoch, the VC quota counters, and the
        suggested-set token (None when the spec ignores suggested nodes).
        ``certificate_current`` answering True certifies a re-run of
        ``schedule()`` for the identical spec would return the identical
        WAIT: every input the descent reads lives in the covered chains'
        cell state (the lock-sharding contract, doc/hot-path.md), and any
        completed mutation of that state bumps at least one monotonic
        component of the vector."""
        from ..scheduler.decisions import classify_reason

        chains = tuple(str(c) for c in chains)
        return {
            "gate": classify_reason(wait_reason),
            "vc": str(spec.virtual_cluster),
            "chainEpochs": {c: self.chain_epoch(c) for c in chains},
            "doomedEpoch": self.doomed_epoch,
            "quota": self.quota_token(spec.virtual_cluster, chains),
            "suggested": suggested_token,
        }

    def certificate_current(self, cert: Dict) -> bool:
        """One version-vector compare, lock-free: the epoch and doomed-
        epoch reads are GIL-atomic ints and monotonic, and quota
        movements always accompany an epoch bump — so equality means no
        mutation covered by the certificate completed before the epoch
        reads (an in-flight mutation still holds its chain locks and is
        linearized after this answer). Any mismatch — including a
        concurrent mutator resizing a quota dict mid-iteration (the
        quota sums walk shared nested dicts a lock-holder may insert a
        new level key into) — sends the caller to the full filter pass;
        the compare can only ever be conservative."""
        epochs = cert["chainEpochs"]
        for chain, epoch in epochs.items():
            if self.chain_epoch(chain) != epoch:
                return False
        if self.doomed_epoch != cert["doomedEpoch"]:
            return False
        try:
            return (
                self.quota_token(cert["vc"], tuple(epochs))
                == cert["quota"]
            )
        except RuntimeError:
            # "dictionary changed size during iteration": a mutation is
            # in flight — the vector is moving, treat as stale.
            return False

    # -- node events --------------------------------------------------------

    def add_node(self, node: Node) -> None:
        if not is_node_healthy(node):
            self.set_bad_node(node.name)
        else:
            self.set_healthy_node(node.name)

    def update_node(self, old: Node, new: Node) -> None:
        if is_node_healthy(old) != is_node_healthy(new):
            if is_node_healthy(old):
                self.set_bad_node(new.name)
            else:
                self.set_healthy_node(new.name)

    def delete_node(self, node: Node) -> None:
        self._require_global()
        self.set_bad_node(node.name)
        # Drains are lifted on node delete (the annotation died with the
        # node object); chip-badness records die with it too — the leaves
        # stay bad through the node badness above, and a re-added node's
        # annotations are re-applied from scratch.
        self.apply_drain(node.name, set())
        self.bad_chips.pop(node.name, None)

    def _node_leaf_cells(
        self, node_name: str, chip_index: Optional[int] = None
    ) -> List[PhysicalCell]:
        """Leaf cells on a node (optionally: only those holding one chip
        index) across every chain, from the precomputed index."""
        leaves = self._node_leaf_index.get(node_name, [])
        if chip_index is None:
            return leaves
        return [
            leaf for leaf in leaves if chip_index in leaf.leaf_cell_indices
        ]

    def node_chip_indices(self, node_name: str) -> Set[int]:
        """Every chip index the config places on a node (used to expand a
        whole-node drain into per-chip drains). Config-static, so computed
        once per node — the health plane consults this on every node event
        (a relist delivers N of them)."""
        cached = self._node_chip_index.get(node_name)
        if cached is None:
            cached = self._node_chip_index[node_name] = {
                i
                for leaf in self._node_leaf_index.get(node_name, [])
                for i in leaf.leaf_cell_indices
            }
        return cached

    def set_bad_node(self, node_name: str) -> None:
        """(reference: hived_algorithm.go:467-481)"""
        self._require_global()
        if node_name in self.bad_nodes:
            return
        self.bad_nodes.add(node_name)
        for leaf in self._node_leaf_cells(node_name):
            self._set_bad_cell(leaf)

    def set_healthy_node(self, node_name: str) -> None:
        """(reference: hived_algorithm.go:484-498, chip-granular: leaves
        individually marked bad by the device-health plane stay bad when
        the node as a whole heals)"""
        self._require_global()
        if node_name not in self.bad_nodes:
            return
        self.bad_nodes.discard(node_name)
        bad_chips = self.bad_chips.get(node_name, set())
        for leaf in self._node_leaf_cells(node_name):
            if not bad_chips or bad_chips.isdisjoint(leaf.leaf_cell_indices):
                self._set_healthy_cell(leaf)

    # -- chip-granular health + maintenance drains --------------------------

    def set_bad_leaf(self, node_name: str, chip_index: int) -> None:
        """Mark one chip's leaf cell bad (device-health plane). Partial
        badness propagates up the cell tree through the ordinary
        _set_bad_cell walk — the host stays placeable for work fitting its
        remaining healthy chips."""
        self._require_global()
        chips = self.bad_chips.setdefault(node_name, set())
        if chip_index in chips:
            return
        chips.add(chip_index)
        if node_name in self.bad_nodes:
            return  # already bad via the node; the record alone suffices
        for leaf in self._node_leaf_cells(node_name, chip_index):
            self._set_bad_cell(leaf)

    def set_healthy_leaf(self, node_name: str, chip_index: int) -> None:
        """Heal one chip's leaf cell. No-op while the node itself is bad —
        the chip record is dropped, and the node-level heal decides."""
        self._require_global()
        chips = self.bad_chips.get(node_name)
        if chips is None or chip_index not in chips:
            return
        chips.discard(chip_index)
        if not chips:
            del self.bad_chips[node_name]
        if node_name in self.bad_nodes:
            return
        for leaf in self._node_leaf_cells(node_name, chip_index):
            self._set_healthy_cell(leaf)

    def apply_drain(self, node_name: str, chip_indices: Set[int]) -> None:
        """Reconcile a node's draining chip set (maintenance plane): the
        listed chips take no new placements; running gangs keep their
        cells. Draining is NOT badness — no doomed-bad binding, no
        bad-free accounting — so lifting a drain is always a pure
        placement-visibility change."""
        self._require_global()
        current = self.draining_chips.get(node_name, set())
        if current == chip_indices:
            return
        for leaf in self._node_leaf_cells(node_name):
            want = any(i in chip_indices for i in leaf.leaf_cell_indices)
            if leaf.draining != want:
                leaf.set_draining(want)
        if chip_indices:
            self.draining_chips[node_name] = set(chip_indices)
        else:
            self.draining_chips.pop(node_name, None)

    def health_snapshot(self) -> Dict:
        """The core half of /v1/inspect/health: applied badness and drains
        (the framework adds the damper and stranded-gang views)."""
        return {
            "badNodes": sorted(self.bad_nodes),
            "badChips": {
                n: sorted(c)
                for n, c in sorted(self.bad_chips.items())
                if c
            },
            "drainingChips": {
                n: sorted(c)
                for n, c in sorted(self.draining_chips.items())
                if c
            },
        }

    def _set_bad_cell(self, c: PhysicalCell) -> None:
        """Mark bad, propagate up, track in bad-free lists or bind into the
        VC view (reference: hived_algorithm.go:500-523)."""
        if not c.healthy:
            return
        c.set_healthiness(False)
        if c.parent is not None:
            self._set_bad_cell(c.parent)
        if in_free_cell_list(c):
            self._add_bad_free_cell(c)
        elif c.virtual_cell is None and not c.split:
            # An ancestor is bound to a virtual cell: bind c too so the VC
            # scheduler sees this failure.
            vc = allocation.get_unbound_virtual_cell(
                c.parent.virtual_cell.children
            )
            c.set_virtual_cell(vc)
            vc.set_physical_cell(c)
            common.log.info(
                "Virtual cell %s is bound to physical cell %s (bad)",
                vc.address, c.address,
            )

    def _set_healthy_cell(self, c: PhysicalCell) -> None:
        """(reference: hived_algorithm.go:526-560)"""
        if c.healthy:
            return
        c.set_healthiness(True)
        if in_free_cell_list(c):
            self._remove_bad_free_cell(c)
        elif c.virtual_cell is not None:
            vc = c.virtual_cell
            if (
                not c.pinned
                and c.priority < MIN_GUARANTEED_PRIORITY
                and not (self.doomed_ledger_mode and vc.parent is None)
            ):
                # (In ledger mode, a preassigned — i.e. doomed — binding
                # healing during the recovery health replay must SURVIVE
                # until the pod replay decides its fate: the pre-crash
                # scheduler kept it because a guaranteed allocation rode
                # its healthy chips, and that allocation has not replayed
                # yet. Dooms still unpinned when recovery finishes are
                # retired by clear_preferred_doomed; the heal itself still
                # propagates below.)
                # The binding existed only because the cell was bad.
                c.set_virtual_cell(None)
                vc.set_physical_cell(None)
                common.log.info(
                    "Virtual cell %s is unbound from physical cell %s "
                    "(healthy again)", vc.address, c.address,
                )
                if vc.parent is None:
                    # A preassigned cell unbound here must be a doomed bad cell.
                    self.vc_doomed_bad_cells[vc.vc][c.chain].remove(c, c.level)
                    self.all_vc_doomed_bad_cell_num[c.chain][c.level] -= 1
                    self._bump_doomed_epoch()
                    self._release_preassigned_cell(c, vc.vc, True)
        if c.parent is None:
            return
        for buddy in c.parent.children:
            assert isinstance(buddy, PhysicalCell)
            if not buddy.healthy:
                return
        self._set_healthy_cell(c.parent)

    def _add_bad_free_cell(self, c: PhysicalCell) -> None:
        """(reference: hived_algorithm.go:563-583)"""
        chain, level = c.chain, c.level
        self.bad_free_cells[chain][level].append(c)
        if self.all_vc_free_cell_num.get(chain, {}).get(level, 0) > (
            self.total_left_cell_num[chain][level]
            - len(self.bad_free_cells[chain][level])
        ):
            common.log.warning(
                "Cell type %s (chain %s level %s) now has fewer healthy cells "
                "than the total free cells of all the VCs. Certain VCs' cells "
                "may be doomed to be bad.",
                self.cell_types[chain].get(level), chain, level,
            )
            self._try_bind_doomed_bad_cell(chain, level)

    def _remove_bad_free_cell(self, c: PhysicalCell) -> None:
        """(reference: hived_algorithm.go:586-602)"""
        chain, level = c.chain, c.level
        self.bad_free_cells[chain].remove(c, level)
        self._try_unbind_doomed_bad_cell(chain, level)

    def _try_bind_doomed_bad_cell(self, chain: CellChain, level: CellLevel) -> None:
        """If a VC's free cells exceed healthy free physical cells, bind bad
        free cells into the VC so the failure is visible
        (reference: hived_algorithm.go:604-630)."""
        if self.doomed_ledger_mode:
            return  # recovery: the persisted ledger is authoritative
        for vc_name, vc_free in self.vc_free_cell_num.items():
            if chain not in vc_free:
                continue
            if not self.vc_compiled(vc_name):
                # Lazy VC: no virtual cells to bind yet. Its organic
                # dooms appear at the first trigger AFTER it compiles (a
                # boot-scale saving: the all-bad bootstrap no longer
                # dooms 40 idle VCs' entire quota — and a deliberate
                # equivalence property: force time never binds state).
                continue
            self._bind_vc_dooms(vc_name, chain, level)

    def _bind_vc_dooms(
        self, vc_name: api.VirtualClusterName, chain: CellChain,
        level: CellLevel,
    ) -> None:
        """One VC's organic shortfall loop (the body _try_bind_doomed_
        bad_cell runs per VC; also the lazy-compile doom replay unit)."""
        vc_free = self.vc_free_cell_num[vc_name]
        while vc_free[chain].get(level, 0) > (
            self.total_left_cell_num[chain][level]
            - len(self.bad_free_cells[chain][level])
        ):
            if len(self.bad_free_cells[chain][level]) == 0:
                # Shortfall with no bad free cell to bind (possible when
                # a deferred re-check runs after the last bad cell was
                # claimed): nothing to doom until one appears.
                break
            pc = self.bad_free_cells[chain][level][0]
            assert isinstance(pc, PhysicalCell)
            preassigned = self.vc_schedulers[vc_name].non_pinned_preassigned
            if chain not in preassigned:
                break  # pinned-only quota in this chain: nothing to doom
            vc = allocation.get_unbound_virtual_cell(preassigned[chain][level])
            if vc is None:
                break
            pc.set_virtual_cell(vc)
            vc.set_physical_cell(pc)
            common.log.warning(
                "Cell %s is doomed to be bad and bound to %s (VC %s)",
                vc.address, pc.address, vc_name,
            )
            self.vc_doomed_bad_cells[vc_name][chain][level].append(pc)
            self.all_vc_doomed_bad_cell_num[chain][level] = (
                self.all_vc_doomed_bad_cell_num[chain].get(level, 0) + 1
            )
            self._bump_doomed_epoch()
            self._allocate_preassigned_cell(pc, vc_name, True)

    def _try_unbind_doomed_bad_cell(self, chain: CellChain, level: CellLevel) -> None:
        """(reference: hived_algorithm.go:632-653, with one deliberate fix:
        a doomed-bound cell whose healthy children are MEANWHILE hosting a
        real allocation — possible because partially-bad cells remain
        placeable — must not be unbound/released while in use. The reference
        pops list[0] unguarded; its setHealthyCell applies exactly this
        priority guard on the sibling path (hived_algorithm.go:535-547), so
        we apply it here too. Without it, releasing the cell back to the
        free list while pods run on it corrupts the free lists (found by
        sequence fuzzing).)"""
        if self.doomed_ledger_mode:
            return  # recovery: the persisted ledger is authoritative
        for vc_name, vc_free in self.vc_free_cell_num.items():
            if chain not in vc_free:
                continue
            if not self.vc_compiled(vc_name):
                continue  # lazy VC: provably no dooms to retire
            while vc_free[chain].get(level, 0) < (
                self.total_left_cell_num[chain][level]
                - len(self.bad_free_cells[chain][level])
            ):
                pc = next(
                    (
                        c
                        for c in self.vc_doomed_bad_cells[vc_name][chain][level]
                        if c.priority < MIN_GUARANTEED_PRIORITY
                    ),
                    None,
                )
                if pc is None:
                    break  # all doomed cells of this VC/level are in use
                assert isinstance(pc, PhysicalCell)
                common.log.info(
                    "Cell %s is no longer doomed to be bad and is unbound "
                    "from %s", pc.virtual_cell.address, pc.address,
                )
                self._unbind_doomed_cell(pc)

    def _unbind_doomed_cell(self, pc: PhysicalCell) -> None:
        """Destroy a doomed-bad advisory binding and release its quota
        allocation — the shared tail of doomed retirement and the two
        replay-eviction paths. Callers log their own reason first."""
        vc = pc.virtual_cell
        vcn = vc.vc
        vc.set_physical_cell(None)
        pc.set_virtual_cell(None)
        self._unbind_bad_descendants(pc)
        self.vc_doomed_bad_cells[vcn][pc.chain].remove(pc, pc.level)
        self.all_vc_doomed_bad_cell_num[pc.chain][pc.level] -= 1
        self._bump_doomed_epoch()
        self._release_preassigned_cell(pc, vcn, True)

    # -- doomed-ledger persistence ------------------------------------------

    def doomed_ledger_snapshot(self) -> Dict:
        """Serialize the doomed-bad bindings for the scheduler-owned
        ConfigMap: which bad cell each VC's unsatisfiable quota is pinned
        to. Deterministically ordered so identical states produce identical
        ConfigMap payloads."""
        vcs: Dict[str, List[Dict]] = {}
        for vcn, per_chain in sorted(self.vc_doomed_bad_cells.items()):
            entries: List[Dict] = []
            for chain, ccl in sorted(per_chain.items()):
                for level, cl in sorted(ccl.levels.items()):
                    for c in cl:
                        entry = {
                            "chain": str(chain),
                            "level": int(level),
                            "address": c.address,
                        }
                        # The VIRTUAL side of the pairing: recovery
                        # rebinds the doom to exactly this preassigned
                        # cell, so annotation-replay recovery converges
                        # with the live timeline's (and the snapshot
                        # restore's) virtual pairing — with lazy VC
                        # compile the live free-list order is pristine
                        # and the old first-unbound rule no longer
                        # coincides with it.
                        if c.virtual_cell is not None:  # type: ignore[union-attr]
                            entry["virtualAddress"] = c.virtual_cell.address
                        entries.append(entry)
            if entries:
                entries.sort(key=lambda e: (e["chain"], e["level"], e["address"]))
                vcs[str(vcn)] = entries
        return {"epoch": self.doomed_epoch, "vcs": vcs}

    def set_preferred_doomed(self, ledger: Optional[Dict]) -> None:
        """Install the persisted ledger for the recovery replay. A dict —
        even one listing zero dooms — is authoritative and enters ledger
        mode (organic doom bind/retire suspended; see doomed_ledger_mode);
        None (first boot, or the ConfigMap read failed) keeps the organic
        behavior. Entries naming VCs, chains, or cells absent from the
        current config are ignored — a reconfiguration between restarts
        legitimately invalidates them."""
        self.preferred_doomed = {}
        self.preferred_doomed_virtual = {}
        self.doomed_ledger_mode = isinstance(ledger, dict)
        if not ledger:
            return
        for vcn, entries in (ledger.get("vcs") or {}).items():
            if vcn not in self.vc_free_cell_num:
                continue
            for e in entries:
                try:
                    key = (vcn, str(e["chain"]), int(e["level"]))
                    address = str(e["address"])
                except (KeyError, TypeError, ValueError):
                    continue
                if key[1] not in self.full_cell_list:
                    continue
                self.preferred_doomed.setdefault(key, set()).add(address)
                virt = e.get("virtualAddress")
                if virt:
                    # The recorded virtual half of the pairing (absent in
                    # pre-upgrade ledgers: rebuild falls back to
                    # first-unbound, the old behavior).
                    self.preferred_doomed_virtual[key + (address,)] = str(
                        virt
                    )

    def clear_preferred_doomed(self) -> None:
        """Recovery done: steady-state doom choices revert to the organic
        shortfall-driven behavior so a recovered scheduler behaves exactly
        like a fresh one from here on. Ledger dooms that fully healed
        during the replay and were NOT pinned by a replayed allocation are
        retired first — the continuous timeline's heal/release paths would
        have retired them (a healed doom survives only while in use), and
        _set_healthy_cell deliberately kept them alive through the health
        replay for exactly the pinned case."""
        if self.doomed_ledger_mode:
            for per_chain in self.vc_doomed_bad_cells.values():
                for ccl in per_chain.values():
                    for level in list(ccl.levels):
                        for c in list(ccl.levels[level]):
                            if (
                                c.healthy
                                and c.priority < MIN_GUARANTEED_PRIORITY
                            ):
                                assert isinstance(c, PhysicalCell)
                                common.log.info(
                                    "Retiring healed, unpinned ledger doom "
                                    "%s", c.address,
                                )
                                self._unbind_doomed_cell(c)
        self.preferred_doomed = {}
        self.preferred_doomed_virtual = {}
        self.doomed_ledger_mode = False

    def rebuild_doomed_from_ledger(
        self, chains: Optional[Set[str]] = None
    ) -> None:
        """Make the advisory doomed set exactly the persisted ledger's:
        retire the organic dooms the constructor's all-nodes-bad bootstrap
        bound (they predate the ledger and sit on arbitrary cells), then
        bind precisely the ledger's (VC, chain, level, address) entries.
        Called by recover() before the node-health replay, while every
        cell is still marked bad — the ledger cells (bad on the pre-crash
        side, or they would not be listed) are guaranteed bindable. No-op
        outside ledger mode (first boot: organic dooming stands).

        ``chains`` scopes both the retire and the bind to those chains —
        the PARTIAL snapshot import's doom gate: corrupt-section chains
        still sit in the constructor's bootstrap state (bad cells,
        possibly organically doomed by the non-fold boot path) and need
        the ledger rebuild, while healthy-section chains already restored
        their doomed bindings verbatim and must not be touched."""
        if not self.doomed_ledger_mode:
            return
        for vcn, per_chain in self.vc_doomed_bad_cells.items():
            for chain, ccl in per_chain.items():
                if chains is not None and str(chain) not in chains:
                    continue
                for level in list(ccl.levels):
                    for c in list(ccl.levels[level]):
                        if c.priority < MIN_GUARANTEED_PRIORITY:
                            assert isinstance(c, PhysicalCell)
                            self._unbind_doomed_cell(c)
        for (vcn, chain, level), addresses in sorted(
            self.preferred_doomed.items()
        ):
            if chains is not None and str(chain) not in chains:
                continue
            doomed = self.vc_doomed_bad_cells.get(vcn, {}).get(chain)
            preassigned = self.vc_schedulers[vcn].non_pinned_preassigned
            if doomed is None or chain not in preassigned:
                continue
            for address in sorted(addresses):
                if any(c.address == address for c in doomed[level]):
                    continue
                pc = next(
                    (
                        c
                        for c in self.bad_free_cells[chain][level]
                        if c.address == address and c.virtual_cell is None
                    ),
                    None,
                )
                if pc is None:
                    common.log.warning(
                        "Ledger doom %s (VC %s, chain %s level %s) is no "
                        "longer a bad free cell; dropping the entry",
                        address, vcn, chain, level,
                    )
                    continue
                assert isinstance(pc, PhysicalCell)
                vc = None
                want = self.preferred_doomed_virtual.get(
                    (vcn, chain, level, address)
                )
                if want is not None:
                    cand = self._virt_cell_index.get(want)
                    if (
                        cand is not None
                        and cand.physical_cell is None
                        and cand.vc == vcn
                        and cand.chain == chain
                        and cand.level == level
                        and cand.parent is None
                    ):
                        # Rebind the exact pre-crash pairing (the
                        # ledger's virtualAddress); a stale/invalid name
                        # (reconfiguration) falls back to first-unbound.
                        vc = cand
                if vc is None:
                    vc = allocation.get_unbound_virtual_cell(
                        preassigned[chain][level]
                    )
                if vc is None:
                    continue
                pc.set_virtual_cell(vc)
                vc.set_physical_cell(pc)
                common.log.warning(
                    "Cell %s is doomed to be bad and bound to %s (VC %s, "
                    "from the persisted ledger)", vc.address, pc.address, vcn,
                )
                self.vc_doomed_bad_cells[vcn][chain][level].append(pc)
                self.all_vc_doomed_bad_cell_num[chain][level] = (
                    self.all_vc_doomed_bad_cell_num[chain].get(level, 0) + 1
                )
                self._bump_doomed_epoch()
                self._allocate_preassigned_cell(pc, vcn, True)

    # -- snapshot projection export / restore -------------------------------
    # (doc/fault-model.md "HA and snapshot recovery plane")

    # Pristine per-cell defaults: any cell whose mutable state matches these
    # is omitted from the export (the sparse record set) and reset to them
    # by restore. Kept next to the export/restore pair so a new mutable
    # field fails loudly in the golden schema test rather than silently
    # diverging at recovery.
    _PRISTINE_STATE = CellState.FREE

    def export_projection(self) -> Dict:
        """Serialize the core's mutable scheduling state verbatim — the
        cell-level durable projection the chaos harness proves
        restart-equivalent. Pure data walk under the caller's (global)
        lock; no mutation, no I/O.

        The exporter requires a NORMALIZED core: no PREEMPTING groups (so
        no Reserving/Reserved overlays) and every ALLOCATED group anchored
        by at least one confirmed-bound pod — the framework's flusher
        gates on exactly that (see HivedScheduler._export_body_locked) and
        skips the flush otherwise, so a persisted snapshot never carries
        transient overlays a real crash would forget.

        Sparse representation: only cells deviating from the pristine
        defaults get a record, so the payload scales with allocation +
        badness + fragmentation, not fleet size.

        Incremental: the projection is assembled from PER-CHAIN sections
        memoized on the chain mutation epochs (PR-5's epoch refs, bumped
        by every state/priority/health/binding/pod-slot mutator) — a
        quiet chain's slice is one dict lookup instead of a cell walk,
        so the flusher's lock-held cost scales with the chains that
        actually moved since the last beat, not fleet size. The memo is
        cleared wholesale by restore_projection (direct field writes
        bypass the mutator hooks). tests/test_snapshot_ha.py proves the
        memoized assembly identical to a cold rebuild differentially."""
        sections = [
            self._chain_section_cached(chain) for chain in self.full_cell_list
        ]
        merged = self._merge_projection_sections(sections)
        # Groups without a placement chain (none in a normalized export;
        # defensive) are attributed fresh each walk.
        groups = merged["groups"]
        for name, g in self.affinity_groups.items():
            if name not in groups and group_chain(g) is None:
                groups[name] = self._export_group_record(g)
        return merged

    def _chain_section_cached(self, chain: CellChain) -> Dict:
        epoch = self.chain_epoch(chain)
        cached = self._export_chain_memo.get(chain)
        if cached is None or cached[0] != epoch:
            cached = self._export_chain_memo[chain] = (
                epoch, self._export_chain_section(chain)
            )
        return cached[1]

    @staticmethod
    def _merge_projection_sections(sections: List[Dict]) -> Dict:
        """Merge per-chain (or per-family) export sections into one core
        body — mirrored byte-for-byte by scheduler.snapshot's
        merge_core_slices (which reassembles a sectioned snapshot's
        healthy families without importing this module); the snapshot
        differential tests pin the two equivalent."""
        phys: Dict[str, List] = {}
        virt: Dict[str, List] = {}
        free_lists: Dict[str, Dict] = {}
        bad_free: Dict[str, Dict] = {}
        vc_doomed: Dict[str, Dict] = {}
        ot_cells: Dict[str, List[str]] = {}
        vc_free: Dict[str, Dict] = {}
        all_vc_free: Dict[str, Dict] = {}
        total_left: Dict[str, Dict] = {}
        all_vc_doomed: Dict[str, Dict] = {}
        groups: Dict[str, Dict] = {}
        for sec in sections:
            phys.update(sec["phys"])
            virt.update(sec["virt"])
            free_lists.update(sec["freeLists"])
            bad_free.update(sec["badFree"])
            for vcn, per_chain in sec["vcDoomed"].items():
                vc_doomed.setdefault(vcn, {}).update(per_chain)
            for vcn, addrs in sec["otCells"].items():
                ot_cells.setdefault(vcn, []).extend(addrs)
            for vcn, per_chain in sec["vcFree"].items():
                vc_free.setdefault(vcn, {}).update(per_chain)
            all_vc_free.update(sec["allVCFree"])
            total_left.update(sec["totalLeft"])
            all_vc_doomed.update(sec["allVCDoomed"])
            groups.update(sec["groups"])
        return {
            "phys": phys,
            "virt": virt,
            "freeLists": free_lists,
            "badFree": bad_free,
            "vcDoomed": vc_doomed,
            "otCells": ot_cells,
            "counters": {
                "vcFree": vc_free,
                "allVCFree": all_vc_free,
                "totalLeft": total_left,
                "allVCDoomed": all_vc_doomed,
            },
            "groups": groups,
        }

    def export_projection_sections(self) -> Tuple[List[Dict], Dict]:
        """The durable projection sliced per CHAIN FAMILY (the compiled
        shares-a-leaf-SKU partition, compiler.chain_families) — the unit
        of the sectioned snapshot (schema v3): each family's slice is the
        merge of its chains' memoized export sections, so a family whose
        chains were quiet since the last flush costs dict lookups, not a
        cell walk. Returns ``(families, chainless_groups)``: families is
        ``[{"chains": [...], "core": {...}}]`` in compiled-family order;
        chainless_groups are the no-placement groups export_projection
        attributes fresh each walk (they belong to no family and ride the
        snapshot's meta section). Same normalization contract as
        export_projection."""
        families: List[Dict] = []
        for chains in self.compiled.families:
            secs = [
                self._chain_section_cached(c)
                for c in chains
                if c in self.full_cell_list
            ]
            families.append({
                "chains": [str(c) for c in chains],
                "core": self._merge_projection_sections(secs),
            })
        chainless = {
            name: self._export_group_record(g)
            for name, g in self.affinity_groups.items()
            if group_chain(g) is None
        }
        return families, chainless

    def family_node_names(self) -> List[Set[str]]:
        """Per chain-family node-name sets (config-static, cached on
        first use): which hosts carry each family's cells. The partial
        snapshot import uses this for the demotion closure — a node that
        hosts BOTH a corrupt and a healthy family forces the healthy one
        down to annotation replay too, because node-level health records
        cannot be split between a restored and a replayed family."""
        cached = getattr(self, "_family_nodes_cache", None)
        if cached is None:
            cached = []
            for chains in self.compiled.families:
                nodes: Set[str] = set()
                for chain in chains:
                    ccl = self.full_cell_list.get(chain)
                    if ccl is None:
                        continue
                    for c in ccl[ccl.top_level]:
                        nodes.update(c.nodes)
                cached.append(nodes)
            self._family_nodes_cache = cached
        return cached

    def _export_cell_groups(self) -> Dict:
        """chain -> (physical cells, virtual cells): static post-compile,
        built once on first export."""
        if self._export_cells_by_chain is None:
            by_chain: Dict = {
                chain: ([], []) for chain in self.full_cell_list
            }
            for c in self._phys_cell_index.values():
                by_chain[c.chain][0].append(c)
            for v in self._virt_cell_index.values():
                if v.chain in by_chain:
                    by_chain[v.chain][1].append(v)
            self._export_cells_by_chain = by_chain
        return self._export_cells_by_chain

    def _export_chain_section(self, chain: CellChain) -> Dict:
        """One chain's slice of the durable projection — exactly the
        records export_projection's pre-incremental single walk built for
        this chain's cells, listings, counters, and groups.

        The cell walk below is the flusher's main lock-held cost at
        fleet scale (every configured cell of a DIRTY chain is visited):
        locals are hoisted and the pristine skip is ordered cheapest-
        fails-first so the common (pristine) cell costs a few attribute
        reads, not a record build."""
        free_state = CellState.FREE
        free_prio = FREE_PRIORITY
        phys_cells, virt_cells = self._export_cell_groups()[chain]
        phys: Dict[str, List] = {}
        for c in phys_cells:
            used = c.used_leaf_cells_at_priority
            if (
                c.state is free_state
                and c.priority == free_prio
                and not used
                and c.healthy
                and not c.draining
                and not c.split
                and c.using_group is None
                and c.virtual_cell is None
                and c.unusable_leaf_num == 0
            ):
                continue
            using = c.using_group
            vcell = c.virtual_cell
            phys[c.address] = [
                c.state.value,
                c.priority,
                int(c.healthy),
                int(c.draining),
                int(c.split),
                using.name if using is not None else None,
                vcell.address if vcell is not None else None,
                {str(p): n for p, n in used.items()},
                c.unusable_leaf_num,
            ]
        virt: Dict[str, List] = {}
        for v in virt_cells:
            used = v.used_leaf_cells_at_priority
            if (
                v.state is free_state
                and v.priority == free_prio
                and not used
                and v.healthy
                and v.unusable_leaf_num == 0
            ):
                continue
            virt[v.address] = [
                v.state.value,
                v.priority,
                int(v.healthy),
                {str(p): n for p, n in used.items()},
                v.unusable_leaf_num,
            ]

        def dump_ccl(ccl: ChainCellList) -> Dict[str, List[str]]:
            return {
                str(l): [c.address for c in cl]
                for l, cl in ccl.levels.items()
                if len(cl)
            }

        def chain_counter(d: Dict[CellChain, Dict[CellLevel, int]]) -> Dict:
            per = d.get(chain)
            if per is None:
                return {}
            return {str(chain): {str(l): n for l, n in per.items()}}

        groups: Dict[str, Dict] = {}
        for name, g in self.affinity_groups.items():
            if group_chain(g) == chain:
                groups[name] = self._export_group_record(g)
        ccl = self.free_cell_list.get(chain)
        bad = self.bad_free_cells.get(chain)
        return {
            "phys": phys,
            "virt": virt,
            "freeLists": (
                {str(chain): dump_ccl(ccl)} if ccl is not None else {}
            ),
            "badFree": (
                {str(chain): dump_ccl(bad)} if bad is not None else {}
            ),
            "vcDoomed": {
                str(vcn): {str(chain): dump_ccl(per_chain[chain])}
                for vcn, per_chain in self.vc_doomed_bad_cells.items()
                if chain in per_chain
            },
            "otCells": {
                str(vcn): kept
                for vcn, cells in self._ot_cells.items()
                if (kept := [
                    a for a, pl in cells.items() if pl.chain == chain
                ])
            },
            "vcFree": {
                str(vcn): sliced
                for vcn, per in self.vc_free_cell_num.items()
                if (sliced := chain_counter(per))
            },
            "allVCFree": chain_counter(self.all_vc_free_cell_num),
            "totalLeft": chain_counter(self.total_left_cell_num),
            "allVCDoomed": chain_counter(self.all_vc_doomed_bad_cell_num),
            "groups": groups,
        }

    @staticmethod
    def _export_group_record(g: AffinityGroup) -> Dict:
        return {
            "spec": g.spec_dict(),
            "resizeGeneration": g.resize_generation,
            "vc": str(g.vc),
            "lazyPreemptionEnable": bool(g.lazy_preemption_enable),
            "priority": g.priority,
            "state": g.state.value,
            "ignoreSuggested": bool(g.ignore_k8s_suggested_nodes),
            "lazyPreemptionStatus": g.lazy_preemption_status,
            "phys": {
                str(n): [
                    [c.address if c is not None else None for c in row]
                    for row in rows
                ]
                for n, rows in g.physical_placement.items()
            },
            "virt": None
            if g.virtual_placement is None
            else {
                str(n): [
                    [c.address if c is not None else None for c in row]
                    for row in rows
                ]
                for n, rows in g.virtual_placement.items()
            },
        }

    def restore_projection(
        self,
        core_body: Dict,
        health: Optional[Dict] = None,
        live_node_names: Optional[Set[str]] = None,
        chains: Optional[Set[str]] = None,
    ) -> None:
        """Reinstate an exported projection by direct field assignment —
        the O(delta) recovery fast path. Every mutable field of every cell
        is reset to its pristine default, then the sparse records, lists,
        counters, and groups are applied wholesale; derived caches (chain
        epochs, cluster views, mirrored statuses) are invalidated at the
        end, so the result does not depend on the core's prior state.

        ``live_node_names`` normalizes nodes the cluster no longer has: a
        configured node absent from the live list is marked bad, exactly
        the state full replay leaves it in (the constructor's bootstrap
        badness never healed by a node event).

        ``chains`` scopes the restore to those chains for the PARTIAL
        snapshot import (sectioned snapshots, doc/fault-model.md
        "Durable-state plane v2"): cells, listings, and counters of
        chains OUTSIDE the set are left completely untouched — on the
        VIRGIN core the partial import runs against, that is exactly the
        constructor's all-bad bootstrap state full annotation replay
        starts from, so the excluded (corrupt-section) chains replay from
        annotations while the scoped ones restore wholesale. Scoped
        restore is only meaningful on a virgin core; the unscoped default
        keeps the historical does-not-depend-on-prior-state contract.

        The caller (framework.import_snapshot) wraps any failure here in a
        wholesale reset + full annotation replay — a half-restored core is
        never served."""
        # Lazy plane: pre-force every VC the projection names (virtual
        # records, group owners, dooms, opportunistic charges) so the
        # address->cell resolution below finds their cells. VCs the
        # snapshot does not touch stay uncompiled — their state is
        # vacuously pristine, exactly what the reset would produce.
        for vcn in self._projection_vc_names(core_body):
            if vcn in self._vc_name_set:
                self.ensure_vc(vcn)
        phys_recs = core_body.get("phys") or {}
        virt_recs = core_body.get("virt") or {}
        free = CellState.FREE
        for addr, c in self._phys_cell_index.items():
            if addr in phys_recs:
                continue  # every field overwritten by its record below
            if chains is not None and str(c.chain) not in chains:
                continue  # out-of-scope chain: untouched (partial import)
            c.state = free
            c.priority = FREE_PRIORITY
            c.healthy = True
            c.draining = False
            c.split = False
            c.using_group = None
            c.reserving_or_reserved_group = None
            c.virtual_cell = None
            c.unusable_leaf_num = 0
            if c.used_leaf_cells_at_priority:
                c.used_leaf_cells_at_priority.clear()
        for addr, v in self._virt_cell_index.items():
            if addr in virt_recs:
                continue
            if chains is not None and str(v.chain) not in chains:
                continue
            v.state = free
            v.priority = FREE_PRIORITY
            v.healthy = True
            v.physical_cell = None
            v.unusable_leaf_num = 0
            if v.used_leaf_cells_at_priority:
                v.used_leaf_cells_at_priority.clear()
        if chains is None:
            self.bound_physical.clear()
        else:
            for addr in [
                a for a, c in self.bound_physical.items()
                if str(c.chain) in chains
            ]:
                del self.bound_physical[addr]

        # Groups first (no cell pointers yet) so the physical records can
        # resolve using-group names. A scoped restore keeps the groups of
        # out-of-scope chains (none on the virgin core it targets;
        # defensive) — a group record only ever references cells of its
        # own chain, so cross-family pointers cannot dangle.
        if chains is None:
            self.affinity_groups = {}
        else:
            self.affinity_groups = {
                n: g for n, g in self.affinity_groups.items()
                if (gc := group_chain(g)) is not None and str(gc) not in chains
            }
        groups = self.affinity_groups
        for name, rec in (core_body.get("groups") or {}).items():
            g = AffinityGroup(
                api.AffinityGroupSpec.from_dict(rec["spec"]),
                rec["vc"],
                bool(rec["lazyPreemptionEnable"]),
                int(rec["priority"]),
                GroupState(rec["state"]),
                init_placements=False,
            )
            g.ignore_k8s_suggested_nodes = bool(rec["ignoreSuggested"])
            g.lazy_preemption_status = rec["lazyPreemptionStatus"]
            g.resize_generation = int(rec.get("resizeGeneration", 0))
            g.physical_placement = {
                int(n): [
                    [
                        self._phys_cell_index[a] if a is not None else None
                        for a in row
                    ]
                    for row in rows
                ]
                for n, rows in rec["phys"].items()
            }
            g.virtual_placement = (
                None
                if rec["virt"] is None
                else {
                    int(n): [
                        [
                            self._virt_cell_index[a] if a is not None else None
                            for a in row
                        ]
                        for row in rows
                    ]
                    for n, rows in rec["virt"].items()
                }
            )
            groups[name] = g

        # Record-covered cells skipped the reset above, so every mutable
        # field is assigned here unconditionally. (Virtual physical_cell
        # back-pointers are derived from the physical records' bindings —
        # record-covered virtual cells get theirs cleared first.)
        state_by_value = {s.value: s for s in CellState}
        for addr in virt_recs:
            self._virt_cell_index[addr].physical_cell = None
        for addr, rec in phys_recs.items():
            c = self._phys_cell_index[addr]
            c.state = state_by_value[rec[0]]
            c.priority = rec[1]
            c.healthy = bool(rec[2])
            c.draining = bool(rec[3])
            c.split = bool(rec[4])
            c.using_group = groups[rec[5]] if rec[5] is not None else None
            c.reserving_or_reserved_group = None
            if rec[6] is not None:
                v = self._virt_cell_index[rec[6]]
                c.virtual_cell = v
                v.physical_cell = c
                self.bound_physical[addr] = c
            else:
                c.virtual_cell = None
            c.used_leaf_cells_at_priority = {
                int(p): n for p, n in rec[7].items()
            }
            c.unusable_leaf_num = rec[8]
        for addr, rec in virt_recs.items():
            v = self._virt_cell_index[addr]
            v.state = state_by_value[rec[0]]
            v.priority = rec[1]
            v.healthy = bool(rec[2])
            v.used_leaf_cells_at_priority = {
                int(p): n for p, n in rec[3].items()
            }
            v.unusable_leaf_num = rec[4]

        # Free / bad-free / doomed listings, rebuilt wholesale. Iteration
        # order is rebuilt in config_order — the compile traversal stamp
        # placement already uses as its only tiebreak (doc/hot-path.md),
        # so list order carries no scheduling meaning to preserve.
        def fill_ccl(ccl: ChainCellList, dumped: Dict) -> None:
            for l in ccl.levels:
                lst = ccl.levels[l]
                if len(lst):
                    ccl.levels[l] = type(lst)()
            for l, addrs in (dumped or {}).items():
                cells = [self._phys_cell_index[a] for a in addrs]
                cells.sort(key=lambda c: c.config_order)
                for c in cells:
                    ccl[int(l)].append(c)

        def in_scope(chain) -> bool:
            return chains is None or str(chain) in chains

        free_dump = core_body.get("freeLists") or {}
        for chain, ccl in self.free_cell_list.items():
            if in_scope(chain):
                fill_ccl(ccl, free_dump.get(str(chain)))
        bad_free_dump = core_body.get("badFree") or {}
        for chain, ccl in self.bad_free_cells.items():
            if in_scope(chain):
                fill_ccl(ccl, bad_free_dump.get(str(chain)))
        doomed_dump = core_body.get("vcDoomed") or {}
        for vcn, per_chain in self.vc_doomed_bad_cells.items():
            vc_dump = doomed_dump.get(str(vcn)) or {}
            for chain, ccl in per_chain.items():
                if in_scope(chain):
                    fill_ccl(ccl, vc_dump.get(str(chain)))
        if chains is None:
            self._ot_cells = {}
        else:
            for vcn in list(self._ot_cells):
                kept = {
                    a: c for a, c in self._ot_cells[vcn].items()
                    if str(c.chain) not in chains
                }
                if kept:
                    self._ot_cells[vcn] = kept
                else:
                    del self._ot_cells[vcn]
        for vcn, addrs in (core_body.get("otCells") or {}).items():
            self._ot_cells.setdefault(vcn, {}).update({
                a: self._phys_cell_index[a] for a in addrs
            })

        counters = core_body.get("counters") or {}

        def fill_counters(
            target: Dict[CellChain, Dict[CellLevel, int]], dumped: Dict
        ) -> None:
            for chain in list(target):
                if not in_scope(chain):
                    continue
                per = (dumped or {}).get(str(chain)) or {}
                target[chain] = {int(l): n for l, n in per.items()}

        for vcn in list(self.vc_free_cell_num):
            fill_counters(
                self.vc_free_cell_num[vcn],
                (counters.get("vcFree") or {}).get(str(vcn)),
            )
        fill_counters(self.all_vc_free_cell_num, counters.get("allVCFree"))
        fill_counters(self.total_left_cell_num, counters.get("totalLeft"))
        fill_counters(
            self.all_vc_doomed_bad_cell_num, counters.get("allVCDoomed")
        )

        # Health plane records (applied badness and drains, the same
        # snapshot moment as the cell healthy/draining flags above).
        health = health or {}
        self.bad_nodes = set(health.get("badNodes") or ())
        self.bad_chips = {
            n: set(chips)
            for n, chips in (health.get("badChips") or {}).items()
            if chips
        }
        self.draining_chips = {
            n: set(chips)
            for n, chips in (health.get("drainingChips") or {}).items()
            if chips
        }

        # Derived caches cannot be trusted after raw field assignment:
        # every chain epoch moves (mirrored statuses, victims caches) and
        # every cluster view re-scores wholesale at its next schedule call.
        for ref in self.chain_epochs.values():
            ref[0] += 1
        self._phys_status_cache.clear()
        self._vc_status_cache.clear()
        # The export memo mirrors live cell state through the epoch refs;
        # the direct field writes above bypass the mutator hooks, so the
        # memo (like the status mirrors) must drop wholesale.
        self._export_chain_memo.clear()
        for sched in self._all_topology_schedulers():
            sched.invalidate_all()

        # Nodes the live cluster no longer has stay bad — full replay never
        # heals them out of the constructor's bootstrap badness. Runs last,
        # through the ordinary mutators, on the now-consistent state.
        if live_node_names is not None:
            for n in self.configured_node_names():
                if n not in live_node_names:
                    self.set_bad_node(n)

    def _all_topology_schedulers(self) -> List[TopologyAwareScheduler]:
        out: List[TopologyAwareScheduler] = list(
            self.opportunistic_schedulers.values()
        )
        # Compiled VCs only: an uncompiled VC has no views to invalidate,
        # and forcing 37 idle VCs' compiles from a restore would defeat
        # the lazy plane.
        for vcs in self.vc_schedulers.compiled_values():
            out.extend(vcs._chain_schedulers.values())
            out.extend(vcs._pinned_schedulers.values())
        return out

    @staticmethod
    def _projection_vc_names(core_body: Dict) -> Set[str]:
        """Every VC name an exported projection touches: virtual-record
        and physical-binding addresses are '{vc}/...'-prefixed, group
        records carry their VC, and the doomed / opportunistic sections
        are VC-keyed. The restore pre-forces exactly these compiles."""
        names: Set[str] = set()
        for addr in (core_body.get("virt") or {}):
            names.add(str(addr).split("/", 1)[0])
        for rec in (core_body.get("groups") or {}).values():
            vc = rec.get("vc")
            if vc:
                names.add(str(vc))
        for rec in (core_body.get("phys") or {}).values():
            # rec[6] is the bound virtual cell's address, if any.
            if len(rec) > 6 and rec[6]:
                names.add(str(rec[6]).split("/", 1)[0])
        for vcn, per_chain in (core_body.get("vcDoomed") or {}).items():
            # The export lists every VC key; only non-empty doom
            # listings make the VC part of the projection.
            if any(
                addrs
                for levels in per_chain.values()
                for addrs in levels.values()
            ):
                names.add(str(vcn))
        for vcn, addrs in (core_body.get("otCells") or {}).items():
            if addrs:
                names.add(str(vcn))
        return names

    def attach_restored_pod(
        self, group_name: str, leaf_cell_number: int, pod_index: int, pod: Pod
    ) -> None:
        """Slot a snapshot-imported pod into its restored group — the
        decode-free counterpart of _add_allocated_pod's slot assignment
        (the cell state was already restored verbatim)."""
        group = self.affinity_groups[group_name]
        group.allocated_pods[leaf_cell_number][pod_index] = pod
        chain = group_chain(group)
        if chain is not None:
            self.bump_chain_epoch(chain)

    # -- scheduling ---------------------------------------------------------

    def schedule(
        self,
        pod: Pod,
        suggested_nodes: List[str],
        phase: SchedulingPhase,
        spec: Optional[api.PodSchedulingSpec] = None,
        suggested_set: Optional[Set[str]] = None,
        leaf_types: Optional[Tuple[str, ...]] = None,
    ) -> PodScheduleResult:
        """(reference: hived_algorithm.go:180-224)

        ``spec``/``suggested_set`` let the framework parse the annotation and
        build the node set OUTSIDE its lock (framework.filter_routine); when
        omitted they are derived here, preserving the old call contract.

        ``leaf_types`` restricts an UNTYPED, unpinned pod's any-leaf-type
        scan to the named SKUs (the shards frontend's leaf-type-granular
        sweep, doc/hot-path.md "The multi-process contract"): the union
        of a sweep's restrictions is the full sorted scan, so placement-
        found-iff is preserved chunk by chunk. Typed/pinned specs ignore
        it."""
        # A schedule can mutate (lazy preemption, preempting-group
        # bookkeeping): the shadow what-if audit fences it like every
        # other mutator entry point.
        self._audit_write()
        common.log.info("[%s]: Scheduling pod in %s phase...", pod.key, phase.value)
        s = spec if spec is not None else extract_pod_scheduling_spec(pod)
        rec = self._decision_rec()
        if rec is not None:
            rec.set_spec(s)
        suggested = suggested_set if suggested_set is not None else set(suggested_nodes)
        group_physical: Optional[Placement] = None
        group_virtual: Optional[Placement] = None
        victims: Optional[Dict[str, Dict[str, Pod]]] = None
        wait_reason = ""
        pod_index = 0

        grow_generation: Optional[int] = None
        g = self.affinity_groups.get(s.affinity_group.name)
        if g is not None:
            (
                group_physical, group_virtual, victims, pod_index,
                grow_generation,
            ) = self._schedule_pod_from_existing_group(
                g, s, suggested, phase, pod
            )
        if grow_generation == -1:
            # Elastic grow attempted but no capacity: wait, don't reject.
            wait_reason = (
                f"affinity group {s.affinity_group.name} is at capacity; "
                "waiting for free cells to grow into"
            )
            grow_generation = None
        # The group may have been a preempting group deleted just above.
        if self.affinity_groups.get(s.affinity_group.name) is None:
            group_physical, group_virtual, victims, wait_reason = (
                self._schedule_pod_from_new_group(
                    s, suggested, phase, pod, leaf_types
                )
            )
        result = generate_pod_schedule_result(
            group_physical,
            group_virtual,
            victims,
            wait_reason,
            self.cell_types,
            s.leaf_cell_number,
            pod_index,
            # A grow placement is PROSPECTIVE (existing rows + the new
            # pod's row): the group's memoized bind info must neither
            # serve nor cache it — the group only reshapes when the bind
            # confirm replays the generated record through apply_resize.
            None
            if grow_generation is not None
            else self.affinity_groups.get(s.affinity_group.name),
            s.affinity_group.name,
            pod,
            self.preempt_rng,
        )
        if grow_generation is not None and result.pod_bind_info is not None:
            result.pod_bind_info.resize_generation = grow_generation
        return result

    def _schedule_pod_from_existing_group(
        self,
        g: AffinityGroup,
        s: api.PodSchedulingSpec,
        suggested: Set[str],
        phase: SchedulingPhase,
        pod: Pod,
    ) -> Tuple[
        Optional[Placement],
        Optional[Placement],
        Optional[Dict[str, Dict[str, Pod]]],
        int,
        Optional[int],
    ]:
        """(reference: hived_algorithm.go:658-714; the fifth element is
        the elastic-grow generation — non-None when the returned
        placement is the PROSPECTIVE grown gang, doc/fault-model.md
        "Elastic gang plane")"""
        group_physical: Optional[Placement] = None
        group_virtual: Optional[Placement] = None
        victims: Optional[Dict[str, Dict[str, Pod]]] = None
        pod_index = 0
        grow_generation: Optional[int] = None
        bad_or_non_suggested = collect_bad_or_non_suggested_nodes(
            g.physical_placement, suggested, g.ignore_k8s_suggested_nodes
        )
        rec = self._decision_rec()
        if g.state == GroupState.ALLOCATED:
            common.log.info(
                "[%s]: Pod is from an affinity group that is already "
                "allocated: %s", pod.key, g.name,
            )
            if rec is not None:
                rec.note(f"affinity group {g.name} already allocated")
                chain = group_chain(g)
                if chain is not None:
                    rec.consider_chain(chain)
            group_physical = g.physical_placement
            group_virtual = g.virtual_placement
            if bad_or_non_suggested:
                # Insist on the previous decision even so
                # (reference: hived_algorithm.go:677-682).
                common.log.warning(
                    "[%s]: Some nodes allocated to affinity group %s are no "
                    "longer healthy and within K8s suggested nodes: %s",
                    pod.key, g.name, sorted(bad_or_non_suggested),
                )
            pod_index = get_new_pod_index(
                g.allocated_pods.get(s.leaf_cell_number, [])
            )
            if pod_index == -1:
                grown = self._try_schedule_group_grow(g, s, suggested, pod)
                if grown is None:
                    raise api.bad_request(
                        f"Requesting more pods than the configured number "
                        f"for {s.leaf_cell_number} leaf cells "
                        f"({g.total_pod_nums.get(s.leaf_cell_number, 0)} "
                        f"pods) in affinity group {s.affinity_group.name}"
                    )
                if grown == "wait":
                    # Growable, but no free capacity right now: wait (a
                    # fixed-size gang would be a hard 400 instead).
                    return None, None, None, 0, -1
                group_physical, group_virtual, pod_index, grow_generation = (
                    grown
                )
        else:  # GroupState.PREEMPTING
            common.log.info(
                "[%s]: Pod is from an affinity group that is preempting "
                "others: %s", pod.key, g.name,
            )
            if rec is not None:
                rec.note(f"affinity group {g.name} is preempting")
            if phase == SchedulingPhase.PREEMPTING and bad_or_non_suggested:
                # Cancel and reschedule elsewhere; only Preempting-phase
                # suggested nodes consider preemption
                # (reference: hived_algorithm.go:692-702).
                common.log.info(
                    "[%s]: Canceling affinity group %s's preemption because "
                    "its placement is no longer fully healthy and within "
                    "Preempting-phase suggested nodes", pod.key, g.name,
                )
                if rec is not None:
                    rec.note(
                        f"cancelled {g.name}'s preemption: placement no "
                        "longer healthy/suggested"
                    )
                self._delete_preempting_affinity_group(g, pod)
            else:
                group_physical = g.physical_placement
                group_virtual = g.virtual_placement
                victims, _ = self._collect_victims_cached(g)
                if not victims:
                    common.log.info(
                        "Preemption victims have been cleaned up for the "
                        "preemptor affinity group %s", g.name,
                    )
                g.preempting_pods[pod.uid] = pod
        return group_physical, group_virtual, victims, pod_index, grow_generation

    def _try_schedule_group_grow(
        self,
        g: AffinityGroup,
        s: api.PodSchedulingSpec,
        suggested: Set[str],
        pod: Pod,
    ):
        """Elastic grow (doc/fault-model.md "Elastic gang plane"): a gang
        with maxMembers headroom admits one more pod into idle capacity
        on its own chain. An OPPORTUNISTIC gang grows through the
        opportunistic scheduler; a GUARANTEED gang grows through the
        quota-gated intra-VC path (_try_grow_guaranteed) — both ride the
        same prospective-record protocol. Returns None when the group is
        not growable (fixed size / at its ceiling / placement holes),
        ``"wait"`` when growable but currently out of capacity, else the
        prospective (physical, virtual, pod_index, generation) for the
        GROWN gang — applied only when the bind confirm replays the
        generated record through apply_resize."""
        max_members = max(
            g.max_members, getattr(s.affinity_group, "max_members", 0)
        )
        guaranteed = s.priority >= MIN_GUARANTEED_PRIORITY
        if (
            max_members <= g.total_pods
            or g.state != GroupState.ALLOCATED
            # A grow member must ride the same allocation plane as its
            # gang: opportunistic rows have no virtual placement to
            # extend, guaranteed rows must extend one (the new row
            # consumes VC quota IN FRONT of the safety checks).
            or guaranteed != (g.virtual_placement is not None)
            or s.leaf_cell_number <= 0
        ):
            return None
        chain = group_chain(g)
        if chain is None:
            return None
        # A gang with a LOST placement row (reconfiguration hole) cannot
        # grow: the prospective record is generated with group=None (the
        # memoized bind info must not serve or cache it), which has no
        # group to recover missing placements from — fall back to the
        # fixed-size rejection rather than a 500 mid-generate.
        for rows in g.physical_placement.values():
            for row in rows:
                if any(leaf is None for leaf in row):
                    return None
        if guaranteed:
            for rows in g.virtual_placement.values():
                for row in rows:
                    if any(leaf is None for leaf in row):
                        return None
            return self._try_grow_guaranteed(g, s, suggested, chain)
        rec = self._decision_rec()
        placement, failed_reason = self.opportunistic_schedulers[
            chain
        ].schedule(
            {s.leaf_cell_number: 1},
            OPPORTUNISTIC_PRIORITY,
            suggested,
            s.ignore_k8s_suggested_nodes,
        )
        if placement is None:
            if rec is not None:
                rec.note(
                    f"elastic grow of {g.name} found no capacity: "
                    f"{failed_reason}"
                )
            return "wait"
        new_row = placement[s.leaf_cell_number][0]
        group_physical: Placement = {
            n: list(rows) for n, rows in g.physical_placement.items()
        }
        group_physical.setdefault(s.leaf_cell_number, []).append(new_row)
        pod_index = len(group_physical[s.leaf_cell_number]) - 1
        if rec is not None:
            rec.note(
                f"elastic grow: {g.name} {g.total_pods} -> "
                f"{g.total_pods + 1} pods (generation "
                f"{g.resize_generation + 1})"
            )
        return group_physical, None, pod_index, g.resize_generation + 1

    def _try_grow_guaranteed(
        self,
        g: AffinityGroup,
        s: api.PodSchedulingSpec,
        suggested: Set[str],
        chain: CellChain,
    ):
        """Guaranteed-gang grow (the PR-10 recorded follow-on): a bounded
        gang at guaranteed priority grows into its VC's QUOTA HEADROOM —
        one more member placed through the intra-VC scheduler plus the
        standard buddy mapping, so the new row consumes VC quota in
        front of the safety checks like any new guaranteed row.

        The quota gate is layered: (1) config level — the VC must hold
        non-pinned quota on the gang's chain at all; (2) the intra-VC
        schedule itself — the row must fit the VC's free virtual cells;
        (3) headroom only — a virtual leaf whose physical twin is not
        FREE is skipped (retried around via anchor avoidance), so a grow
        NEVER preempts, lazily or otherwise (matching the opportunistic
        grow's free-capacity-only contract) and the probe is mutation-
        free: a "wait" answer leaves no lazy-preempt residue behind a
        prospective record that was never applied."""
        rec = self._decision_rec()
        vcs = self.vc_schedulers.get(g.vc)
        if vcs is None:
            return None
        # Quota gate, config level — in the gang's OWN quota plane: a
        # pinned gang grows inside its pinned cell (anything else would
        # break the operator's pinning isolation), an unpinned gang
        # needs non-pinned quota on its chain.
        if s.pinned_cell_id:
            if s.pinned_cell_id not in vcs.pinned_cells:
                if rec is not None:
                    rec.note(
                        f"guaranteed grow of {g.name} refused: VC "
                        f"{g.vc} has no pinned cell {s.pinned_cell_id}"
                    )
                return None
        elif chain not in vcs.non_pinned_preassigned:
            if rec is not None:
                rec.note(
                    f"guaranteed grow of {g.name} refused: VC {g.vc} "
                    f"holds no non-pinned quota on chain {chain}"
                )
            return None
        sr = SchedulingRequest(
            vc=g.vc,
            pinned_cell_id=s.pinned_cell_id,
            priority=s.priority,
            affinity_group_name=g.name,
            affinity_group_pod_nums={s.leaf_cell_number: 1},
            suggested_nodes=suggested,
            ignore_suggested_nodes=s.ignore_k8s_suggested_nodes,
            chain=chain,
        )
        leaf_cell_nums = [s.leaf_cell_number]
        avoid: Set[api.CellAddress] = set()
        physical: Optional[Placement] = None
        virtual: Optional[Placement] = None
        for _attempt in range(self.MAPPING_RETRY_LIMIT):
            virtual, vc_failed_reason = vcs.schedule(
                sr, avoid_anchors=avoid or None
            )
            if virtual is None:
                if rec is not None:
                    rec.note(
                        f"guaranteed grow of {g.name} found no quota "
                        f"headroom: {vc_failed_reason}"
                    )
                return "wait"
            candidate: Optional[Placement] = None
            bindings: Dict[api.CellAddress, PhysicalCell] = {}
            preassigned, non_preassigned = build_binding_paths(
                virtual, leaf_cell_nums, bindings
            )
            free_cell_num_copy = dict(
                self.all_vc_free_cell_num.get(chain, {})
            )
            if allocation.map_virtual_placement_to_physical(
                preassigned,
                non_preassigned,
                self.free_cell_list[chain].shallow_copy(),
                free_cell_num_copy,
                sr.suggested_nodes,
                sr.ignore_suggested_nodes,
                bindings,
            ):
                candidate = virtual_to_physical_placement(
                    virtual, bindings, leaf_cell_nums
                )
            if candidate is not None and all(
                # The FREE-ROW gate: the mapping may legitimately land on
                # cells USED by opportunistic pods inside the VC's bound
                # quota cells (that is how a NEW gang's preemption
                # victims arise) — a grow row must not: it is applied as
                # a resize with no victim protocol, so only a genuinely
                # free row may pass.
                leaf is not None
                and leaf.state == CellState.FREE
                and leaf.using_group is None
                for leaf in candidate[s.leaf_cell_number][0]
            ):
                physical = candidate
                break
            new_anchors = _placement_node_anchors(virtual)
            if not new_anchors - avoid:
                break  # no new exclusion possible: a retry would loop
            avoid |= new_anchors
        if physical is None:
            if rec is not None:
                rec.note(
                    f"guaranteed grow of {g.name}: no mapping onto free "
                    "capacity within quota (grow never preempts)"
                )
            return "wait"
        new_prow = physical[s.leaf_cell_number][0]
        new_vrow = virtual[s.leaf_cell_number][0]
        group_physical: Placement = {
            n: list(rows) for n, rows in g.physical_placement.items()
        }
        group_physical.setdefault(s.leaf_cell_number, []).append(new_prow)
        group_virtual: Placement = {
            n: list(rows) for n, rows in g.virtual_placement.items()
        }
        group_virtual.setdefault(s.leaf_cell_number, []).append(new_vrow)
        pod_index = len(group_physical[s.leaf_cell_number]) - 1
        if rec is not None:
            rec.note(
                f"guaranteed elastic grow: {g.name} {g.total_pods} -> "
                f"{g.total_pods + 1} pods (generation "
                f"{g.resize_generation + 1})"
            )
        return group_physical, group_virtual, pod_index, (
            g.resize_generation + 1
        )

    def _collect_victims_cached(
        self, g: AffinityGroup
    ) -> Tuple[Dict[str, Dict[str, Pod]], List[AffinityGroup]]:
        """Epoch-gated victims collection for repeated preempt probes of an
        existing PREEMPTING gang: every pod of the gang re-probes per
        extender round while victims terminate, and each probe used to
        re-walk the whole placement plus every victim gang's pod list. The
        chain mutation epoch certifies nothing placement- or pod-visible
        moved in the gang's chain since the last walk, so the cached result
        is byte-identical to a fresh one (victim deletions bump the epoch
        via the released cells AND the pod-slot bump in
        delete_allocated_pod). Results are shared read-only."""
        chain = group_chain(g)
        epoch = self.chain_epoch(chain) if chain is not None else -1
        cached = g.victims_cache
        if cached is not None and chain is not None and cached[0] == epoch:
            with self._counter_lock:
                self.preempt_probe_incremental_count += 1
            return cached[1], cached[2]
        victims, overlapping = collect_preemption_victims(
            g.physical_placement
        )
        if chain is not None:
            g.victims_cache = (epoch, victims, overlapping)
        return victims, overlapping

    def _schedule_pod_from_new_group(
        self,
        s: api.PodSchedulingSpec,
        suggested: Set[str],
        phase: SchedulingPhase,
        pod: Pod,
        leaf_types: Optional[Tuple[str, ...]] = None,
    ) -> Tuple[
        Optional[Placement],
        Optional[Placement],
        Optional[Dict[str, Dict[str, Pod]]],
        str,
    ]:
        """(reference: hived_algorithm.go:716-754)"""
        group_physical, group_virtual, wait_reason = self._schedule_new_group(
            pod, s, suggested, leaf_types
        )
        if group_physical is None:
            return None, None, None, wait_reason
        victims, overlapping_preemptors = collect_preemption_victims(group_physical)
        if phase == SchedulingPhase.PREEMPTING:
            # Cancel any lower-priority preemptor overlapping us, then commit
            # our own preemption so concurrent preemptors cannot deadlock on
            # the same victims (reference: hived_algorithm.go:733-747).
            for preemptor in overlapping_preemptors:
                common.log.info(
                    "[%s]: Canceling affinity group %s's preemption because "
                    "it is further preempted by a higher-priority affinity "
                    "group %s", pod.key, preemptor.name, s.affinity_group.name,
                )
                self._delete_preempting_affinity_group(preemptor, pod)
            if victims:
                self._create_preempting_affinity_group(
                    s, group_physical, group_virtual, pod
                )
        elif victims:
            common.log.info(
                "[%s]: Found preemption victims in non-Preempting phase, "
                "skipping", pod.key,
            )
        return group_physical, group_virtual, victims, wait_reason

    def _schedule_new_group(
        self,
        pod: Pod,
        s: api.PodSchedulingSpec,
        suggested: Set[str],
        leaf_types: Optional[Tuple[str, ...]] = None,
    ) -> Tuple[Optional[Placement], Optional[Placement], str]:
        """(reference: hived_algorithm.go:756-821)"""
        common.log.info(
            "[%s]: Scheduling new affinity group %s", pod.key, s.affinity_group.name
        )
        sr = SchedulingRequest(
            vc=s.virtual_cluster,
            pinned_cell_id=s.pinned_cell_id,
            priority=s.priority,
            affinity_group_name=s.affinity_group.name,
            affinity_group_pod_nums={},
            suggested_nodes=suggested,
            ignore_suggested_nodes=s.ignore_k8s_suggested_nodes,
        )
        for m in s.affinity_group.members:
            sr.affinity_group_pod_nums[m.leaf_cell_number] = (
                sr.affinity_group_pod_nums.get(m.leaf_cell_number, 0) + m.pod_number
            )
        self._validate_scheduling_request(sr, pod)
        if sr.pinned_cell_id:
            physical, virtual, failed_reason = self._handle_scheduling_request(
                sr
            )
            rec = self._decision_rec()
            if rec is not None and physical is None:
                rec.reject(f"pinned:{sr.pinned_cell_id}", failed_reason)
            return physical, virtual, failed_reason
        if s.leaf_cell_type:
            if s.leaf_cell_type not in self.cell_chains:
                raise api.bad_request(
                    f"[{pod.key}]: Pod requesting leaf cell type "
                    f"{s.leaf_cell_type} which the whole cluster does not have"
                )
            return self._schedule_group_for_leaf_type(
                sr, s.leaf_cell_type, pod, True
            )
        return self._schedule_group_for_any_leaf_type(sr, pod, leaf_types)

    def _schedule_group_for_leaf_type(
        self,
        sr: SchedulingRequest,
        leaf_cell_type: str,
        pod: Pod,
        type_specified: bool,
    ) -> Tuple[Optional[Placement], Optional[Placement], str]:
        """Try every chain containing the chip SKU
        (reference: hived_algorithm.go:824-854)."""
        vc_has_type = False
        failed_reason = ""
        rec = self._decision_rec()
        for chain in self.cell_chains.get(leaf_cell_type, []):
            if (
                sr.priority < MIN_GUARANTEED_PRIORITY
                or chain in self.vc_schedulers[sr.vc].non_pinned_preassigned
            ):
                vc_has_type = True
                sr.chain = chain
                if rec is not None:
                    rec.consider_chain(chain)
                physical, virtual, failed_reason = self._handle_scheduling_request(
                    sr
                )
                if physical is not None:
                    return physical, virtual, ""
                if rec is not None:
                    # Per-gate rejection: the reason string's producing
                    # site (VC quota / chip health / drains / buddy
                    # mapping / suggested nodes) determines the gate.
                    rec.reject(chain, failed_reason)
        if (
            type_specified
            and sr.priority >= MIN_GUARANTEED_PRIORITY
            and not vc_has_type
        ):
            raise api.bad_request(
                f"[{pod.key}]: Pod requesting leaf cell type {leaf_cell_type} "
                f"which VC {sr.vc} does not have"
            )
        return None, None, failed_reason

    def _schedule_group_for_any_leaf_type(
        self,
        sr: SchedulingRequest,
        pod: Pod,
        leaf_types: Optional[Tuple[str, ...]] = None,
    ) -> Tuple[Optional[Placement], Optional[Placement], str]:
        """(reference: hived_algorithm.go:857-877) ``leaf_types``
        restricts the sorted scan to a sweep chunk (see schedule)."""
        failed_reason = ""
        for leaf_cell_type in sorted(self.cell_chains):
            if leaf_types is not None and leaf_cell_type not in leaf_types:
                continue
            physical, virtual, type_failed_reason = (
                self._schedule_group_for_leaf_type(sr, leaf_cell_type, pod, False)
            )
            if physical is not None:
                return physical, virtual, ""
            if type_failed_reason:
                failed_reason = type_failed_reason
        return None, None, failed_reason

    def _validate_scheduling_request(self, sr: SchedulingRequest, pod: Pod) -> None:
        """(reference: hived_algorithm.go:879-895)"""
        message = ""
        if sr.vc not in self.vc_schedulers:
            message = f"VC {sr.vc} does not exists!"
        elif sr.pinned_cell_id:
            if sr.pinned_cell_id not in self.vc_schedulers[sr.vc].pinned_cells:
                message = (
                    f"VC {sr.vc} does not have pinned cell {sr.pinned_cell_id}"
                )
            elif sr.priority == OPPORTUNISTIC_PRIORITY:
                message = (
                    "opportunistic pod not supported to use pinned cell "
                    f"{sr.pinned_cell_id}"
                )
        if message:
            raise api.bad_request(f"[{pod.key}]: {message}")

    def _handle_scheduling_request(
        self, sr: SchedulingRequest
    ) -> Tuple[Optional[Placement], Optional[Placement], str]:
        """(reference: hived_algorithm.go:898-920)"""
        if sr.priority >= MIN_GUARANTEED_PRIORITY:
            return self._schedule_guaranteed_group(sr)
        physical, failed_reason = self._schedule_opportunistic_group(sr)
        return physical, None, failed_reason

    # Bound on the intra-VC → physical mapping retry loop below: each retry
    # excludes at least one more node anchor, so the loop terminates on its
    # own; the cap keeps the worst case (every anchor unmappable on a large
    # VC) from turning one filter call into O(fleet) failed mappings.
    MAPPING_RETRY_LIMIT = 16

    def _schedule_guaranteed_group(
        self, sr: SchedulingRequest
    ) -> Tuple[Optional[Placement], Optional[Placement], str]:
        """Intra-VC placement, then map it onto the physical cluster with
        buddy allocation (reference: hived_algorithm.go:900-942).

        The mapping is retried through the NEXT virtual placement when it
        fails: the intra-VC scheduler cannot see everything the mapping
        enforces (an unbound virtual cell has no node identity to check
        against the suggested set, and buddy allocation may find no free
        physical cell for it), so its first choice can be unmappable while
        an alternative — typically a doomed-bad binding whose healthy chips
        still serve sub-host work (ROADMAP "chip-granular dooming") — would
        map fine. Each failed attempt's node anchors are excluded and the
        virtual placement re-run, bounded by MAPPING_RETRY_LIMIT; the
        reference (and the pre-fix code) returned the failure verbatim,
        waiting forever on capacity it actually had."""
        avoid: Set[api.CellAddress] = set()
        failed_reason = ""
        for attempt in range(self.MAPPING_RETRY_LIMIT):
            virtual, vc_failed_reason = self.vc_schedulers[sr.vc].schedule(
                sr, avoid_anchors=avoid or None
            )
            if virtual is None:
                # Out of virtual alternatives: report the FIRST mapping
                # failure when there was one (the virtual-space reason of a
                # retry attempt — "insufficient capacity" with half the
                # anchors excluded — would be misleading).
                return None, None, failed_reason or vc_failed_reason
            bindings: Dict[api.CellAddress, PhysicalCell] = {}
            leaf_cell_nums = sorted(sr.affinity_group_pod_nums)
            lazy_preempted = self._try_lazy_preempt(
                virtual, leaf_cell_nums, sr.affinity_group_name
            )
            preassigned, non_preassigned = build_binding_paths(
                virtual, leaf_cell_nums, bindings
            )
            chain = sr.chain or (
                next(iter(virtual.values()))[0][0].chain if virtual else ""
            )
            free_cell_num_copy = dict(self.all_vc_free_cell_num.get(chain, {}))
            ok = allocation.map_virtual_placement_to_physical(
                preassigned,
                non_preassigned,
                self.free_cell_list[chain].shallow_copy(),
                free_cell_num_copy,
                sr.suggested_nodes,
                sr.ignore_suggested_nodes,
                bindings,
            )
            if ok:
                if attempt > 0:
                    with self._counter_lock:
                        self.mapping_retry_count += 1
                    rec = self._decision_rec()
                    if rec is not None:
                        rec.note(
                            f"virtual placement retried {attempt}x after "
                            f"mapping failures (anchors avoided: "
                            f"{sorted(str(a) for a in avoid)})"
                        )
                return (
                    virtual_to_physical_placement(
                        virtual, bindings, leaf_cell_nums
                    ),
                    virtual,
                    "",
                )
            for group_name, placement in lazy_preempted.items():
                self._revert_lazy_preempt(
                    self.affinity_groups[group_name], placement
                )
            if not failed_reason:
                failed_node_type = (
                    "bad" if sr.ignore_suggested_nodes else "bad or non-suggested"
                )
                failed_reason = (
                    f"Mapping the virtual placement would need to use at "
                    f"least one {failed_node_type} node"
                )
            new_anchors = _placement_node_anchors(virtual)
            if not new_anchors - avoid:
                break  # no new exclusion possible: a retry would loop
            avoid |= new_anchors
        return None, None, failed_reason

    def _try_lazy_preempt(
        self, virtual: Placement, leaf_cell_nums: List[int], group_name: str
    ) -> Dict[str, Placement]:
        """(reference: hived_algorithm.go:945-965)"""
        preempted: Dict[str, Placement] = {}
        for n in leaf_cell_nums:
            for pod_placement in virtual[n]:
                for leaf in pod_placement:
                    assert isinstance(leaf, VirtualCell)
                    p_leaf = leaf.physical_cell
                    if (
                        p_leaf is not None
                        and p_leaf.state == CellState.USED
                        and p_leaf.using_group is not None
                        and p_leaf.using_group.lazy_preemption_enable
                    ):
                        preempted[p_leaf.using_group.name] = (
                            self._lazy_preempt_group(
                                p_leaf.using_group, group_name
                            )
                        )
        return preempted

    def _schedule_opportunistic_group(
        self, sr: SchedulingRequest
    ) -> Tuple[Optional[Placement], str]:
        """(reference: hived_algorithm.go:968-980)"""
        placement, failed_reason = self.opportunistic_schedulers[sr.chain].schedule(
            sr.affinity_group_pod_nums,
            OPPORTUNISTIC_PRIORITY,
            sr.suggested_nodes,
            sr.ignore_suggested_nodes,
        )
        if placement is None:
            return None, f"{failed_reason} when scheduling in physical cluster"
        return placement, ""

    # -- pod lifecycle ------------------------------------------------------

    def add_unallocated_pod(self, pod: Pod) -> None:
        """(reference: hived_algorithm.go:226-227; no-op)"""

    def delete_unallocated_pod(self, pod: Pod) -> None:
        """Cancel a preemption when its last preemptor pod dies
        (reference: hived_algorithm.go:229-245)."""
        s = extract_pod_scheduling_spec(pod)
        g = self.affinity_groups.get(s.affinity_group.name)
        if g is not None and g.state == GroupState.PREEMPTING:
            if pod.uid in g.preempting_pods:
                del g.preempting_pods[pod.uid]
            if not g.preempting_pods:
                common.log.info(
                    "[%s]: Canceling affinity group %s's preemption because "
                    "its pods are all deleted", pod.key, g.name,
                )
                self._delete_preempting_affinity_group(g, pod)

    def validate_allocated_pod(self, pod: Pod) -> None:
        """Pure precheck for replaying a bound pod (crash recovery): raises a
        WebServerError — WITHOUT mutating any cell state — when the pod's
        annotations cannot be replayed against the current config, so the
        framework can quarantine it instead of aborting recovery mid-mutation.

        Rejected inputs: undecodable scheduling-spec/bind-info annotations,
        a bind info that does not contain the pod's own placement, and a
        placement none of whose leaf cells exist in the current config (the
        reference silently ignores such pods, hived_algorithm.go:1000-1005;
        partially-found placements are still tolerated below for
        work-preserving reconfiguration)."""
        s = extract_pod_scheduling_spec(pod)
        info = extract_pod_bind_info(pod)
        if get_allocated_pod_index(info, s.leaf_cell_number) == -1:
            raise api.bad_request(
                f"Pod placement not found in its bind info: node {info.node}, "
                f"leaf cells {info.leaf_cell_isolation}"
            )
        if not any(
            find_physical_leaf_cell(self.full_cell_list, info.cell_chain,
                                    info.node, idx) is not None
            for idx in info.leaf_cell_isolation
        ):
            raise api.bad_request(
                f"None of the pod's leaf cells (node {info.node}, chain "
                f"{info.cell_chain}, indices {info.leaf_cell_isolation}) "
                "exist in the current configuration"
            )

    def add_allocated_pod(
        self,
        pod: Pod,
        spec: Optional[api.PodSchedulingSpec] = None,
        bind_info: Optional[api.PodBindInfo] = None,
        pod_index: Optional[int] = None,
    ) -> None:
        """Confirm an assume-bind or replay a recovered pod
        (reference: hived_algorithm.go:247-270).

        ``spec``/``bind_info``/``pod_index`` are the batched-admission
        pass-through (doc/hot-path.md): the filter path just GENERATED the
        bind info and knows the pod's slot index, so re-decoding the
        annotations it serialized — once per pod of the gang — is pure
        waste. Recovery replay omits them and decodes from the annotations
        as before (there, the annotations are the only source of truth)."""
        self._audit_write()
        try:
            self._add_allocated_pod(pod, spec, bind_info, pod_index)
        finally:
            # Must run even when the replay raises (and the framework
            # quarantines the pod): evictions performed before the failure
            # incremented the pending discounts, and leaving them would
            # make _effective_vc_free under-count allVCFree in every later
            # safety check.
            self._flush_pending_doomed_checks()

    def _add_allocated_pod(
        self,
        pod: Pod,
        spec: Optional[api.PodSchedulingSpec] = None,
        bind_info: Optional[api.PodBindInfo] = None,
        given_pod_index: Optional[int] = None,
    ) -> None:
        s = spec if spec is not None else extract_pod_scheduling_spec(pod)
        if bind_info is not None:
            info = bind_info
            with self._counter_lock:
                self.gang_admission_batched_count += 1
        else:
            info = extract_pod_bind_info(pod)
        common.log.info(
            "[%s]: Adding allocated pod to affinity group %s (node %s, leaf "
            "cells %s)", pod.key, s.affinity_group.name, info.node,
            info.leaf_cell_isolation,
        )
        g = self.affinity_groups.get(s.affinity_group.name)
        if g is not None:
            if g.state == GroupState.PREEMPTING:
                self._allocate_preempting_affinity_group(g, pod)
            elif (
                g.state == GroupState.ALLOCATED
                and info.resize_generation > g.resize_generation
            ):
                # The pod carries a NEWER generation of the group's bind
                # info (elastic shrink/grow landed on its annotations, or
                # this is a grow pod's batched admission): reshape the
                # group to the new record before slotting the pod.
                self.apply_resize(g, s, info, pod)
        else:
            self._create_allocated_affinity_group(s, info, pod)
        # The slot index ALWAYS comes from the pod's placement position in
        # the bind info — including for the pod that just created the group
        # during recovery: hardcoding 0 there would collide with a later
        # same-sized pod whose true index is 0, silently dropping one of
        # them. (The reference hardcodes 0 in that branch,
        # hived_algorithm.go:250-262 — a latent recovery-order bug.)
        # The batched-admission path passes the index through: the schedule
        # call that generated the bind info selected this pod's placement
        # by exactly that index, so re-deriving it per pod is an O(gang)
        # scan that made gang admission O(gang²) in aggregate.
        group = self.affinity_groups[s.affinity_group.name]
        if given_pod_index is not None:
            pod_index = given_pod_index
        elif info.resize_generation == group.resize_generation:
            pod_index = get_allocated_pod_index(info, s.leaf_cell_number)
        else:
            # STALE-generation replay (mid-resize crash: this pod's
            # annotations predate a shrink/grow another pod's newer record
            # already applied). Its own placement never moves across
            # resizes, so locate its slot by physical coordinates instead
            # of by position in the stale record.
            pod_index = self._stale_generation_pod_index(group, s, info)
            if pod_index == -1:
                # Shrunk away: a newer generation dropped this member and
                # released its cells — the pod was mid-eviction when we
                # crashed. Surface it for the framework to re-evict.
                common.log.warning(
                    "[%s]: pod's placement was shrunk out of group %s "
                    "(generation %d < %d); queueing for re-eviction",
                    pod.key, group.name, info.resize_generation,
                    group.resize_generation,
                )
                self.resize_orphans.append(pod)
                return
        if pod_index == -1:
            common.log.error(
                "[%s]: Pod placement not found in group %s: node %s, leaf "
                "cells %s", pod.key, s.affinity_group.name, info.node,
                info.leaf_cell_isolation,
            )
            return
        group.allocated_pods[s.leaf_cell_number][pod_index] = pod
        # Pod-slot change: chain-visible (the victims caches list these
        # pods) but touches no cell — bump the chain epoch explicitly.
        chain = group_chain(group)
        if chain is not None:
            self.bump_chain_epoch(chain)

    def _flush_pending_doomed_checks(self) -> None:
        """Replay evictions may have deferred doomed-shortfall re-checks;
        once the replayed pod's quota is consumed, re-dooming cannot steal
        from it."""
        pending = self._pending_doomed()
        while pending:
            (chain, level), _ = pending.popitem()
            self._try_bind_doomed_bad_cell(chain, level)

    def delete_allocated_pod(self, pod: Pod) -> None:
        """(reference: hived_algorithm.go:272-296)"""
        self._audit_write()
        s = extract_pod_scheduling_spec(pod)
        info = extract_pod_bind_info(pod)
        common.log.info(
            "[%s]: Deleting allocated pod from affinity group %s",
            pod.key, s.affinity_group.name,
        )
        g = self.affinity_groups.get(s.affinity_group.name)
        if g is None:
            common.log.error(
                "[%s]: Group %s not found when deleting pod",
                pod.key, s.affinity_group.name,
            )
            return
        if info.resize_generation == g.resize_generation:
            pod_index = get_allocated_pod_index(info, s.leaf_cell_number)
        else:
            pod_index = self._stale_generation_pod_index(g, s, info)
            if pod_index == -1:
                # The pod was shrunk out of the group already (its cells
                # are released); its delete is the eviction completing.
                common.log.info(
                    "[%s]: deleting a pod already shrunk out of group %s",
                    pod.key, g.name,
                )
                return
        if pod_index == -1:
            common.log.error(
                "[%s]: Pod placement not found in group %s: node %s, leaf "
                "cells %s", pod.key, s.affinity_group.name, info.node,
                info.leaf_cell_isolation,
            )
            return
        g.allocated_pods[s.leaf_cell_number][pod_index] = None
        chain = group_chain(g)
        if chain is not None:
            # Victim sets listing this gang's pods are stale now.
            self.bump_chain_epoch(chain)
        if all_pods_released(g.allocated_pods):
            self._delete_allocated_affinity_group(g, pod)

    # -- elastic resize (doc/fault-model.md "Elastic gang plane") -----------

    @staticmethod
    def _placement_row_key(leaf_num: int, row: List[Optional[Cell]]):
        """Identity of one pod's placement row: (node, leaf_num, sorted
        chip indices). None when the row carries no cells (lost
        placements after reconfiguration never match)."""
        leaves = [c for c in row if c is not None]
        if not leaves:
            return None
        return (
            leaves[0].nodes[0],
            leaf_num,
            tuple(sorted(c.leaf_cell_indices[0] for c in leaves)),
        )

    def _stale_generation_pod_index(
        self, g: AffinityGroup, s: api.PodSchedulingSpec, info: api.PodBindInfo
    ) -> int:
        """Slot of a pod whose bind info is from another resize generation
        than its group. A pod's OWN placement never moves across resizes,
        so its physical coordinates identify its row; -1 means the row was
        shrunk out of the group (its cells are already released)."""
        if not info.leaf_cell_isolation:
            return -1
        p_leaf = find_physical_leaf_cell(
            self.full_cell_list, info.cell_chain, info.node,
            info.leaf_cell_isolation[0],
        )
        if p_leaf is None:
            return -1
        coords = g.find_leaf_coords(p_leaf.address)
        if coords is None or coords[0] != s.leaf_cell_number:
            return -1
        return coords[1]

    def export_group_bind_info(
        self, g: AffinityGroup
    ) -> Tuple[List[api.AffinityGroupMemberBindInfo], str]:
        """Regenerate the group-level bind-info record from the LIVE
        placements, as fresh objects (never the group's memoized record —
        resize callers filter/extend the result in place)."""
        leaf_num0 = next(iter(sorted(g.physical_placement)))
        member_info, _node, _idx, chain = generate_affinity_group_bind_info(
            g.physical_placement,
            g.virtual_placement,
            self.cell_types,
            leaf_num0,
            0,
            None,
            g.name,
        )
        return member_info, chain

    def _release_placement_row(
        self, g: AffinityGroup, row: List[Optional[Cell]]
    ) -> None:
        """Release one pod row's cells — the per-row slice of
        _delete_allocated_affinity_group."""
        for leaf in row:
            if leaf is None:
                continue
            assert isinstance(leaf, PhysicalCell)
            leaf.delete_using_group(g)
            if leaf.state == CellState.USED:
                self._release_leaf_cell(
                    leaf, g.vc, opportunistic=g.virtual_placement is None
                )
                set_cell_state(leaf, CellState.FREE)
            else:  # RESERVING: already allocated to a preemptor
                set_cell_state(leaf, CellState.RESERVED)

    def _allocate_resize_row(
        self,
        g: AffinityGroup,
        s: api.PodSchedulingSpec,
        chain: CellChain,
        leaf_num: int,
        node: str,
        indices: List[int],
        types: List[api.CellType],
        pod: Optional[Pod],
    ) -> Tuple[List[Optional[Cell]], List[Optional[Cell]]]:
        """Allocate the cells of one NEW pod row (grow) — the per-row
        slice of _create_allocated_affinity_group's replay loop."""
        prow: List[Optional[Cell]] = [None] * leaf_num
        vrow: List[Optional[Cell]] = [None] * leaf_num
        ref_pod = pod if pod is not None else Pod(name=g.name, uid=g.name)
        for leaf_index in range(leaf_num):
            p_leaf, v_leaf, _lazy = self._find_allocated_leaf_cell(
                leaf_index, indices, types, chain, node, False, s, g, ref_pod
            )
            if p_leaf is None:
                continue
            prow[leaf_index] = p_leaf
            if v_leaf is not None:
                vrow[leaf_index] = v_leaf
            safety_ok, reason = self._allocate_leaf_cell(
                p_leaf, v_leaf, s.priority, g.vc
            )
            p_leaf.add_using_group(g)
            set_cell_state(p_leaf, CellState.USED)
            if not safety_ok:
                common.log.warning("[%s]: %s", ref_pod.key, reason)
        return prow, vrow

    def apply_resize(
        self,
        g: AffinityGroup,
        s: api.PodSchedulingSpec,
        info: api.PodBindInfo,
        pod: Optional[Pod] = None,
        record_event: bool = True,
    ) -> List[Pod]:
        """Reshape an ALLOCATED group to a newer-generation group-level
        bind info: rows present in both generations carry their cells and
        pod objects over untouched; rows only in the OLD placement are
        released (shrink); rows only in the NEW record are allocated
        fresh (grow). Returns the pods of dropped rows (the members the
        shrink evicts). The one mutation path where placements move, so
        every placement-derived cache is invalidated at the end."""
        self._audit_write()
        if g.state != GroupState.ALLOCATED:
            common.log.error(
                "group %s: resize requested in state %s; ignored",
                g.name, g.state.value,
            )
            return []
        try:
            return self._apply_resize(g, s, info, pod, record_event)
        finally:
            # Releases may defer doomed-shortfall re-checks (same contract
            # as add_allocated_pod's wrapper).
            self._flush_pending_doomed_checks()

    def _apply_resize(
        self,
        g: AffinityGroup,
        s: api.PodSchedulingSpec,
        info: api.PodBindInfo,
        pod: Optional[Pod],
        record_event: bool,
    ) -> List[Pod]:
        chain = info.cell_chain or group_chain(g)
        old_total = g.total_pods
        # Index the old rows by placement identity.
        old_index: Dict[Tuple, Tuple[int, int]] = {}
        for leaf_num, pod_rows in g.physical_placement.items():
            for pi, row in enumerate(pod_rows):
                key = self._placement_row_key(leaf_num, row)
                if key is not None:
                    old_index[key] = (leaf_num, pi)
        matched: set = set()
        new_phys: Placement = {}
        new_virt: Optional[Placement] = (
            {} if g.virtual_placement is not None else None
        )
        new_pods: Dict[int, List[Optional[Pod]]] = {}
        for gms in info.affinity_group_bind_info:
            if not gms.pod_placements:
                continue
            leaf_num = max(
                len(pp.physical_leaf_cell_indices)
                for pp in gms.pod_placements
            )
            phys_rows = new_phys.setdefault(leaf_num, [])
            virt_rows = (
                new_virt.setdefault(leaf_num, [])
                if new_virt is not None
                else None
            )
            pod_slots = new_pods.setdefault(leaf_num, [])
            for pp in gms.pod_placements:
                key = (
                    pp.physical_node,
                    leaf_num,
                    tuple(sorted(pp.physical_leaf_cell_indices)),
                )
                coords = old_index.get(key)
                if coords is None or coords in matched:
                    # Relaxed match for rows with LOST placements: an old
                    # row that dropped a leaf after reconfiguration keys
                    # on its surviving indices only, while the regenerated
                    # record recovers the full set from other pods'
                    # annotations — same node + an index subset is the
                    # same row, and re-allocating it would double-count
                    # its still-USED cells.
                    new_set = set(pp.physical_leaf_cell_indices)
                    for okey, ocoords in old_index.items():
                        if (
                            ocoords not in matched
                            and okey[0] == pp.physical_node
                            and okey[1] == leaf_num
                            and set(okey[2]) <= new_set
                        ):
                            coords = ocoords
                            break
                if coords is not None and coords not in matched:
                    matched.add(coords)
                    on, oi = coords
                    phys_rows.append(g.physical_placement[on][oi])
                    if virt_rows is not None:
                        virt_rows.append(g.virtual_placement[on][oi])
                    pod_slots.append(g.allocated_pods[on][oi])
                else:
                    prow, vrow = self._allocate_resize_row(
                        g, s, chain, leaf_num, pp.physical_node,
                        list(pp.physical_leaf_cell_indices),
                        list(pp.preassigned_cell_types), pod,
                    )
                    phys_rows.append(prow)
                    if virt_rows is not None:
                        virt_rows.append(vrow)
                    pod_slots.append(None)
        # Release every old row the new record no longer names.
        dropped_pods: List[Pod] = []
        for leaf_num, pod_rows in g.physical_placement.items():
            for pi, row in enumerate(pod_rows):
                if (leaf_num, pi) in matched:
                    continue
                old_pod = g.allocated_pods.get(leaf_num, [])
                if pi < len(old_pod) and old_pod[pi] is not None:
                    dropped_pods.append(old_pod[pi])
                self._release_placement_row(g, row)
        g.physical_placement = new_phys
        g.virtual_placement = new_virt
        g.allocated_pods = new_pods
        g.total_pod_nums = {n: len(rows) for n, rows in new_phys.items()}
        g.resize_generation = info.resize_generation
        ag = s.affinity_group
        if ag is not None:
            g.min_members = getattr(ag, "min_members", g.min_members)
            g.max_members = getattr(ag, "max_members", g.max_members)
        g.invalidate_placement_caches()
        if chain is not None and chain in self.chain_epochs:
            self.bump_chain_epoch(chain)
        new_total = g.total_pods
        kind = "shrink" if new_total < old_total else "grow"
        with self._counter_lock:
            if kind == "shrink":
                self.gang_shrink_count += 1
            else:
                self.gang_grow_count += 1
        if record_event:
            self.resize_events.append(
                {
                    "group": g.name,
                    "kind": kind,
                    "generation": g.resize_generation,
                    "fromPods": old_total,
                    "toPods": new_total,
                }
            )
            # Replay path: an attached pod whose row the newer record
            # dropped was mid-eviction when we crashed — surface it so
            # the framework re-evicts (the live shrink path evicts its
            # dropped pods itself, record_event=False).
            self.resize_orphans.extend(dropped_pods)
        common.log.warning(
            "group %s resized (%s): %d -> %d pods, generation %d",
            g.name, kind, old_total, new_total, g.resize_generation,
        )
        if not new_phys:
            # Degenerate record (shrunk to nothing): the group is gone.
            del self.affinity_groups[g.name]
        return dropped_pods

    def take_resize_events(self) -> List[Dict]:
        events, self.resize_events = self.resize_events, []
        return events

    def take_resize_orphans(self) -> List[Pod]:
        orphans, self.resize_orphans = self.resize_orphans, []
        return orphans

    # -- defragmentation (compaction candidates) ----------------------------

    def compaction_candidates(self, limit: int = 4) -> List[Dict]:
        """Buddy-mergeable fragments: split parent cells whose free
        children would merge back into a whole free cell if ONE resident
        ALLOCATED gang (fully contained in the subtree) moved, with
        enough free chips elsewhere in the chain to re-home it. Pure
        read over the free lists + placements; callers needing a
        consistent view hold the global order. Proposals are ordered
        opportunistic-first then smallest-blast-radius (the migration
        preference order, mirroring stranded remediation)."""
        by_group: Dict[str, Dict] = {}
        for chain in sorted(self.full_cell_list):
            ccl = self.full_cell_list[chain]
            leaf_num = self.compiled.cell_level_to_leaf_num[chain]
            free_chips_total = sum(
                len(cells) * leaf_num[level]
                for level, cells in self.free_cell_list[chain].levels.items()
            )
            # Top-down: a gang fully inside a split slice is also fully
            # inside its split host — keep only the HIGHEST-gain fragment
            # per gang (merging the big parent implies the small one).
            for level in range(ccl.top_level, LOWEST_LEVEL, -1):
                for parent in ccl[level]:
                    assert isinstance(parent, PhysicalCell)
                    if not parent.split or not parent.healthy:
                        continue
                    cand = self._fragment_candidate(
                        parent, chain, leaf_num, free_chips_total
                    )
                    if cand is not None and (
                        cand["group"] not in by_group
                        or by_group[cand["group"]]["gainChips"]
                        < cand["gainChips"]
                    ):
                        by_group[cand["group"]] = cand
        proposals = list(by_group.values())
        proposals.sort(
            key=lambda p: (
                0 if p["opportunistic"] else 1,
                p["blastPods"],
                -p["gainChips"],
                p["group"],
            )
        )
        return proposals[:limit]

    def _fragment_candidate(
        self,
        parent: PhysicalCell,
        chain: CellChain,
        leaf_num: Dict[CellLevel, int],
        free_chips_total: int,
    ) -> Optional[Dict]:
        free_chips_inside = 0
        groups: List[AffinityGroup] = []
        stack: List[PhysicalCell] = [parent]
        while stack:
            c = stack.pop()
            if in_free_cell_list(c):
                free_chips_inside += leaf_num[c.level]
                continue
            if not c.children:
                if c.state == CellState.USED and c.using_group is not None:
                    if all(c.using_group is not g for g in groups):
                        groups.append(c.using_group)
                elif c.state != CellState.FREE:
                    return None  # reservations: leave preemptors alone
                continue
            for child in c.children:
                assert isinstance(child, PhysicalCell)
                stack.append(child)
        if len(groups) != 1 or free_chips_inside == 0:
            return None
        g = groups[0]
        if g.state != GroupState.ALLOCATED:
            return None
        # The gang must live entirely inside the fragment — moving it out
        # then frees the whole parent — and the rest of the chain must
        # have room for it.
        nodes_inside = set(parent.nodes)
        gang_chips = 0
        blast_pods = 0
        for n, rows in g.physical_placement.items():
            for row in rows:
                for leaf in row:
                    if leaf is None:
                        continue
                    if leaf.nodes[0] not in nodes_inside:
                        return None
                    gang_chips += 1
            blast_pods += len(rows)
        free_chips_outside = free_chips_total - free_chips_inside
        if free_chips_outside < gang_chips:
            return None
        return {
            "chain": str(chain),
            "fragment": parent.address,
            "gainChips": leaf_num[parent.level],
            "group": g.name,
            "vc": str(g.vc),
            "opportunistic": g.virtual_placement is None,
            "blastPods": blast_pods,
            "gangChips": gang_chips,
            "avoidNodes": sorted(nodes_inside),
        }

    # -- group lifecycle ----------------------------------------------------

    def _create_allocated_affinity_group(
        self, s: api.PodSchedulingSpec, info: api.PodBindInfo, pod: Pod
    ) -> None:
        """Create a group from a bind-info annotation (recovery / first
        assume-bind) (reference: hived_algorithm.go:982-1041)."""
        common.log.info(
            "[%s]: Creating new allocated affinity group: %s",
            pod.key, s.affinity_group.name,
        )
        new_group = AffinityGroup(
            s.affinity_group,
            s.virtual_cluster,
            s.lazy_preemption_enable,
            s.priority,
            GroupState.ALLOCATED,
        )
        new_group.resize_generation = info.resize_generation
        should_lazy_preempt = False
        for gms in info.affinity_group_bind_info:
            if not gms.pod_placements:
                continue
            leaf_cell_number = len(gms.pod_placements[0].physical_leaf_cell_indices)
            # The bind info is the durable truth of an allocated gang: a
            # resized gang's record can carry MORE rows than a stale spec
            # annotation declares (e.g. a grow pod whose spec re-sync
            # never landed). Size the matrices to the record, or the fill
            # below would crash mid-allocation and leak the placed rows.
            extra = len(gms.pod_placements) - len(
                new_group.physical_placement.setdefault(
                    leaf_cell_number,
                    [],
                )
            )
            if extra > 0:
                for target in (
                    new_group.physical_placement,
                    new_group.virtual_placement,
                ):
                    if target is not None:
                        target.setdefault(leaf_cell_number, []).extend(
                            [None] * leaf_cell_number for _ in range(extra)
                        )
                new_group.allocated_pods.setdefault(
                    leaf_cell_number, []
                ).extend([None] * extra)
                new_group.total_pod_nums[leaf_cell_number] = len(
                    gms.pod_placements
                )
            for pod_index, pp in enumerate(gms.pod_placements):
                node = pp.physical_node
                for leaf_index in range(len(pp.physical_leaf_cell_indices)):
                    p_leaf, v_leaf, lazy_preempt = self._find_allocated_leaf_cell(
                        leaf_index,
                        pp.physical_leaf_cell_indices,
                        pp.preassigned_cell_types,
                        info.cell_chain,
                        node,
                        should_lazy_preempt,
                        s,
                        new_group,
                        pod,
                    )
                    if p_leaf is None:
                        # The leaf no longer exists in the spec: ignore it but
                        # keep the rest of the pod's cells
                        # (reference: hived_algorithm.go:1000-1005).
                        continue
                    new_group.physical_placement[leaf_cell_number][pod_index][
                        leaf_index
                    ] = p_leaf
                    if lazy_preempt is None:
                        new_group.virtual_placement = None
                    elif v_leaf is not None:
                        new_group.virtual_placement[leaf_cell_number][pod_index][
                            leaf_index
                        ] = v_leaf
                        if (
                            in_free_cell_list(p_leaf)
                            and v_leaf.preassigned_cell.priority > FREE_PRIORITY
                        ):
                            # Post-reconfiguration: the chosen virtual cell's
                            # preassigned cell is already bound elsewhere;
                            # destroy that binding by lazy-preempting its
                            # groups (reference: hived_algorithm.go:1013-1019).
                            self._lazy_preempt_cell(
                                v_leaf.preassigned_cell, new_group.name
                            )
                    else:
                        should_lazy_preempt = should_lazy_preempt or lazy_preempt
                    safety_ok, reason = self._allocate_leaf_cell(
                        p_leaf, v_leaf, s.priority, new_group.vc
                    )
                    p_leaf.add_using_group(new_group)
                    set_cell_state(p_leaf, CellState.USED)
                    if not safety_ok:
                        should_lazy_preempt = True
                        common.log.warning("[%s]: %s", pod.key, reason)
        if should_lazy_preempt:
            self._lazy_preempt_group(new_group, new_group.name)
        self.affinity_groups[s.affinity_group.name] = new_group

    def _delete_allocated_affinity_group(self, g: AffinityGroup, pod: Pod) -> None:
        """(reference: hived_algorithm.go:1044-1073)"""
        common.log.info(
            "[%s]: All pods complete, deleting allocated affinity group: %s",
            pod.key, g.name,
        )
        for pod_placements in g.physical_placement.values():
            for pod_placement in pod_placements:
                for leaf in pod_placement:
                    if leaf is None:
                        continue
                    assert isinstance(leaf, PhysicalCell)
                    leaf.delete_using_group(g)
                    if leaf.state == CellState.USED:
                        self._release_leaf_cell(
                            leaf,
                            g.vc,
                            # No virtual placement = opportunistic mode
                            # (including lazily-preempted groups).
                            opportunistic=g.virtual_placement is None,
                        )
                        set_cell_state(leaf, CellState.FREE)
                    else:  # RESERVING: already allocated to a preemptor
                        set_cell_state(leaf, CellState.RESERVED)
        del self.affinity_groups[g.name]

    def _create_preempting_affinity_group(
        self,
        s: api.PodSchedulingSpec,
        physical: Placement,
        virtual: Optional[Placement],
        pod: Pod,
    ) -> None:
        """Reserve cells for a preemptor immediately so concurrent preemptors
        cannot deadlock on the same victims
        (reference: hived_algorithm.go:1076-1113)."""
        common.log.info(
            "[%s]: Creating new preempting affinity group: %s",
            pod.key, s.affinity_group.name,
        )
        new_group = AffinityGroup(
            s.affinity_group,
            s.virtual_cluster,
            s.lazy_preemption_enable,
            s.priority,
            GroupState.PREEMPTING,
        )
        new_group.physical_placement = physical
        new_group.virtual_placement = virtual
        for leaf_num in physical:
            for pod_index in range(len(physical[leaf_num])):
                for leaf_index, leaf in enumerate(physical[leaf_num][pod_index]):
                    assert isinstance(leaf, PhysicalCell)
                    v_leaf = virtual[leaf_num][pod_index][leaf_index]
                    assert isinstance(v_leaf, VirtualCell)
                    self._reserve_leaf_for_preemptor(leaf, v_leaf, new_group)
        new_group.preempting_pods[pod.uid] = pod
        self.affinity_groups[s.affinity_group.name] = new_group

    def _reserve_leaf_for_preemptor(
        self, leaf: PhysicalCell, v_leaf: VirtualCell, group: AffinityGroup
    ) -> None:
        """The per-leaf Reserving/Reserved transition shared by live
        preemption creation and crash recovery of preempting groups: release
        any victim using the leaf (its group becomes BeingPreempted),
        allocate the preemptor's virtual leaf, and reserve."""
        if leaf.state == CellState.USED:
            using_group = leaf.using_group
            self._release_leaf_cell(
                leaf,
                using_group.vc,
                opportunistic=using_group.virtual_placement is None,
            )
            using_group.state = GroupState.BEING_PREEMPTED
        self._allocate_leaf_cell(leaf, v_leaf, group.priority, group.vc)
        leaf.add_reserving_or_reserved_group(group)
        # Reserving if someone still uses it, Reserved if free (a
        # Reserving/Reserved cell would have had its previous preemption
        # canceled in schedule()).
        if leaf.state == CellState.USED:
            set_cell_state(leaf, CellState.RESERVING)
        else:
            set_cell_state(leaf, CellState.RESERVED)

    def _unreserve_leaf_for_preemptor(
        self, leaf: PhysicalCell, vcn: api.VirtualClusterName
    ) -> Optional[AffinityGroup]:
        """Per-leaf inverse of _reserve_leaf_for_preemptor, shared by the
        live cancellation walk and the recovery rollback: release the
        preemptor's allocation, drop the reservation pointer, and either
        return a Reserving cell to its victim (re-allocated at the
        victim's priority; the victim group is returned so callers can
        re-check its BeingPreempted state) or free a Reserved cell."""
        self._release_leaf_cell(leaf, vcn)
        leaf.delete_reserving_or_reserved_group(
            leaf.reserving_or_reserved_group
        )
        if leaf.state == CellState.RESERVING:
            set_cell_state(leaf, CellState.USED)
            being_preempted = leaf.using_group
            being_preempted_v_leaf: Optional[VirtualCell] = None
            if being_preempted.virtual_placement is not None:
                # Indexed form of retrieve_virtual_cell (utils.go:271-287):
                # the victim group's coordinate index answers in O(1)
                # instead of scanning its whole physical placement per
                # leaf — cancelling a preemption over a big gang was
                # O(placement²) in these walks.
                coords = being_preempted.find_leaf_coords(leaf.address)
                if coords is not None:
                    n_, i_, j_ = coords
                    v = being_preempted.virtual_placement[n_][i_][j_]
                    assert v is None or isinstance(v, VirtualCell)
                    being_preempted_v_leaf = v
            self._allocate_leaf_cell(
                leaf,
                being_preempted_v_leaf,
                being_preempted.priority,
                being_preempted.vc,
            )
            return being_preempted
        set_cell_state(leaf, CellState.FREE)  # RESERVED
        return None

    def _delete_preempting_affinity_group(self, g: AffinityGroup, pod: Pod) -> None:
        """Revoke an ongoing preemption: return Reserving cells to their
        being-preempted groups, free Reserved cells
        (reference: hived_algorithm.go:1116-1145)."""
        restored: List[AffinityGroup] = []
        for leaf_num in g.physical_placement:
            for pod_index in range(len(g.physical_placement[leaf_num])):
                for leaf in g.physical_placement[leaf_num][pod_index]:
                    assert isinstance(leaf, PhysicalCell)
                    victim = self._unreserve_leaf_for_preemptor(leaf, g.vc)
                    if victim is not None and all(
                        victim is not r for r in restored
                    ):
                        restored.append(victim)
        del self.affinity_groups[g.name]
        # First-class cancel transition: victims whose last reservation just
        # vanished return to Allocated. (The reference leaves them
        # BeingPreempted forever; with group state now part of the durable
        # restart-equivalence contract, a recovered scheduler — which
        # replays them as Allocated — would otherwise diverge.)
        self._restore_being_preempted_groups(restored)
        if self.preemption_observer is not None:
            self.preemption_observer(g, "cancelled")
        common.log.info(
            "[%s]: Preempting affinity group %s deleted", pod.key, g.name
        )

    def _restore_being_preempted_groups(
        self, groups: List[AffinityGroup]
    ) -> None:
        """BeingPreempted -> Allocated for victim groups none of whose cells
        remain reserved by any preemptor (a victim can be overlapped by
        several preemptors on disjoint leaves; it stays BeingPreempted while
        any reservation survives)."""
        for vg in groups:
            if vg.state != GroupState.BEING_PREEMPTED:
                continue
            if any(
                leaf is not None
                and leaf.reserving_or_reserved_group is not None
                for rows in vg.physical_placement.values()
                for row in rows
                for leaf in row
            ):
                continue
            vg.state = GroupState.ALLOCATED
            common.log.info(
                "Affinity group %s is no longer being preempted "
                "(preemption cancelled)", vg.name,
            )

    def _allocate_preempting_affinity_group(
        self, g: AffinityGroup, pod: Pod
    ) -> None:
        """Preemption complete: Reserved -> Used, group -> Allocated
        (reference: hived_algorithm.go:1148-1163)."""
        for leaf_num in g.physical_placement:
            for pod_index in range(len(g.physical_placement[leaf_num])):
                for leaf in g.physical_placement[leaf_num][pod_index]:
                    assert isinstance(leaf, PhysicalCell)
                    leaf.delete_reserving_or_reserved_group(g)
                    leaf.add_using_group(g)
                    set_cell_state(leaf, CellState.USED)
        g.state = GroupState.ALLOCATED
        if self.preemption_observer is not None:
            # Observed BEFORE preempting_pods resets: the framework clears
            # the preempt-info annotations those pods still carry.
            self.preemption_observer(g, "allocated")
        g.preempting_pods = {}
        common.log.info(
            "[%s]: Preempting affinity group %s transitioned to allocated",
            pod.key, g.name,
        )

    # -- preemption crash recovery ------------------------------------------

    def get_preempt_info_payload(self, name: str) -> Optional[Dict]:
        """The reserved placement of a PREEMPTING group in PodBindInfo dict
        shape — what the framework patches onto preemptor pods so the
        reservation survives a crash. None when the group is not preempting
        (nothing durable to record)."""
        g = self.affinity_groups.get(name)
        if (
            g is None
            or g.state != GroupState.PREEMPTING
            or g.virtual_placement is None
            or not g.physical_placement
        ):
            return None
        leaf_num = sorted(g.physical_placement)[0]
        bind_info, _node, _indices, chain = generate_affinity_group_bind_info(
            g.physical_placement,
            g.virtual_placement,
            self.cell_types,
            leaf_num,
            0,
            g,
            g.name,
        )
        return api.PodBindInfo(
            node="",
            leaf_cell_isolation=[],
            cell_chain=chain,
            affinity_group_bind_info=bind_info,
        ).to_dict()

    def recover_preempting_affinity_group(self, pod: Pod) -> Tuple[bool, str]:
        """Replay a preempting affinity group from the preempt-info
        annotation a preemptor pod carried when the scheduler crashed:
        re-reserve the cells (victims still alive become BeingPreempted
        again, exactly like the live path) or cancel the preemption when
        the reservation is no longer replayable — cells gone from the
        config, grabbed by another preemptor, occupied by an
        equal-or-higher-priority group, unhealthy, or ALL victims vanished
        while the scheduler was down (nothing left to preempt: the pod
        re-schedules fresh onto the now-free cells).

        Returns (recovered, reason); ``reason`` explains a cancellation."""
        s = extract_pod_scheduling_spec(pod)
        name = s.affinity_group.name
        g = self.affinity_groups.get(name)
        if g is not None:
            if g.state == GroupState.PREEMPTING:
                # Another pod of the gang already replayed the reservation.
                g.preempting_pods[pod.uid] = pod
                return True, ""
            return False, f"group {name} was already recovered as {g.state.value}"
        info = extract_pod_preempt_info(pod)
        new_group = AffinityGroup(
            s.affinity_group,
            s.virtual_cluster,
            s.lazy_preemption_enable,
            s.priority,
            GroupState.PREEMPTING,
        )
        # Pass 1 — pure: locate every reserved leaf and apply the cancel
        # guards WITHOUT mutating, so a cancelled recovery leaves no trace.
        # The annotation is user-writable pod metadata, so the shape checks
        # are load-bearing: ragged rows, duplicate member records, or
        # duplicate leaf references must cancel here — reserving them would
        # double-count quota or strand half-reserved cells.
        located: Dict[int, List[List[PhysicalCell]]] = {}
        located_types: Dict[int, List[List[str]]] = {}
        seen_leaves: Set[str] = set()
        any_victim = False
        for gms in info.affinity_group_bind_info:
            if not gms.pod_placements:
                continue
            leaf_num = len(gms.pod_placements[0].physical_leaf_cell_indices)
            if (
                leaf_num in located
                or leaf_num not in new_group.physical_placement
                or len(gms.pod_placements)
                != len(new_group.physical_placement[leaf_num])
            ):
                return False, "reserved placement does not match the group spec"
            rows: List[List[PhysicalCell]] = []
            type_rows: List[List[str]] = []
            for pp in gms.pod_placements:
                if len(pp.physical_leaf_cell_indices) != leaf_num:
                    return False, (
                        "reserved placement does not match the group spec"
                    )
                row: List[PhysicalCell] = []
                type_row: List[str] = []
                for i, idx in enumerate(pp.physical_leaf_cell_indices):
                    p_leaf = find_physical_leaf_cell(
                        self.full_cell_list, info.cell_chain,
                        pp.physical_node, idx,
                    )
                    if p_leaf is None:
                        return False, (
                            f"reserved leaf {idx} on node {pp.physical_node} "
                            "no longer exists in the configuration"
                        )
                    if not p_leaf.healthy:
                        # Mirrors the live cancel-on-bad-placement rule
                        # (_schedule_pod_from_existing_group, Preempting).
                        return False, (
                            f"reserved leaf {p_leaf.address} is no longer "
                            "healthy"
                        )
                    if p_leaf.state in (CellState.RESERVING, CellState.RESERVED):
                        return False, (
                            f"reserved leaf {p_leaf.address} is held by "
                            "another preemptor"
                        )
                    if (
                        p_leaf.state == CellState.USED
                        and p_leaf.using_group is not None
                        and p_leaf.priority >= s.priority
                    ):
                        # A stale reservation: the cell was re-allocated at
                        # an equal-or-higher priority since. Compared via
                        # the LEAF's priority (the allocation's effective
                        # priority), not the using group's spec priority: a
                        # lazy-preempted victim occupies its cells at
                        # OPPORTUNISTIC priority while its spec priority
                        # may equal the preemptor's — the live preemption
                        # legitimately reserved over it, so its recovery
                        # must too (found by the chaos health-event mix).
                        return False, (
                            f"reserved leaf {p_leaf.address} is used at "
                            "an equal-or-higher priority "
                            f"({p_leaf.using_group.name})"
                        )
                    if p_leaf.address in seen_leaves:
                        return False, (
                            f"reserved leaf {p_leaf.address} is referenced "
                            "twice by the preempt info"
                        )
                    seen_leaves.add(p_leaf.address)
                    if p_leaf.state == CellState.USED:
                        any_victim = True
                    row.append(p_leaf)
                    type_row.append(
                        pp.preassigned_cell_types[i]
                        if i < len(pp.preassigned_cell_types)
                        else ""
                    )
                rows.append(row)
                type_rows.append(type_row)
            located[leaf_num] = rows
            located_types[leaf_num] = type_rows
        if not located or set(located) != set(new_group.physical_placement):
            return False, "reserved placement does not match the group spec"
        if not any_victim:
            return False, "victims vanished while the scheduler was down"
        # Pass 2 — mutating: map each leaf into the VC and reserve it,
        # interleaved exactly like the live allocation order (a sibling's
        # mapping depends on the bindings the previous leaf created). A
        # mapping failure mid-way (e.g. quota moved away by a
        # reconfiguration) — or anything unexpected raising — rolls the
        # partial reservation back: leaked Reserved cells owned by a group
        # that never registered would be unfreeable forever.
        reserved: List[PhysicalCell] = []
        try:
            try:
                for leaf_num in sorted(located):
                    for pod_index, row in enumerate(located[leaf_num]):
                        for leaf_index, p_leaf in enumerate(row):
                            v_leaf, message = self._map_reserved_virtual_leaf(
                                p_leaf,
                                located_types[leaf_num][pod_index][leaf_index],
                                s,
                            )
                            if v_leaf is None:
                                self._rollback_partial_reservation(
                                    new_group, reserved
                                )
                                return False, message
                            new_group.physical_placement[leaf_num][pod_index][
                                leaf_index
                            ] = p_leaf
                            new_group.virtual_placement[leaf_num][pod_index][
                                leaf_index
                            ] = v_leaf
                            self._reserve_leaf_for_preemptor(
                                p_leaf, v_leaf, new_group
                            )
                            reserved.append(p_leaf)
            except Exception:
                self._rollback_partial_reservation(new_group, reserved)
                raise
            new_group.preempting_pods[pod.uid] = pod
            self.affinity_groups[name] = new_group
            common.log.info(
                "[%s]: Recovered preempting affinity group %s "
                "(Reserving/Reserved reservation replayed)", pod.key, name,
            )
            return True, ""
        finally:
            # Mirror add_allocated_pod: the mapping's doomed evictions
            # registered deferred shortfall re-checks; once the reservation
            # has consumed (or rolled back) the quota, leaving them would
            # make _effective_vc_free under-count allVCFree in every later
            # safety check.
            self._flush_pending_doomed_checks()

    def _map_reserved_virtual_leaf(
        self, p_leaf: PhysicalCell, preassigned_type: str,
        s: api.PodSchedulingSpec,
    ) -> Tuple[Optional[VirtualCell], str]:
        """Preemption-recovery face of the shared replay mapping
        (_map_replayed_leaf_to_virtual): a failure cancels the preemption
        instead of degrading the group to opportunistic — a preemptor
        without VC membership would be meaningless, its whole point is
        claiming guaranteed quota."""
        if not preassigned_type:
            return None, "preassigned cell type missing from preempt info"
        return self._map_replayed_leaf_to_virtual(p_leaf, preassigned_type, s)

    def _rollback_partial_reservation(
        self, group: AffinityGroup, reserved: List[PhysicalCell]
    ) -> None:
        """Undo the leaves a failed preemption recovery already reserved —
        the same per-leaf inverse (_unreserve_leaf_for_preemptor) the live
        cancellation walk in _delete_preempting_affinity_group uses."""
        restored: List[AffinityGroup] = []
        for leaf in reserved:
            victim = self._unreserve_leaf_for_preemptor(leaf, group.vc)
            if victim is not None and all(victim is not r for r in restored):
                restored.append(victim)
        self._restore_being_preempted_groups(restored)

    def cancel_preemption(self, name: str, pod: Pod, reason: str = "") -> bool:
        """Cancel a PREEMPTING group by name — the public form of the
        cancellation transition (used by the framework and the chaos
        harness's durable projection). Returns True when a group was
        actually cancelled."""
        g = self.affinity_groups.get(name)
        if g is None or g.state != GroupState.PREEMPTING:
            return False
        if reason:
            common.log.info(
                "[%s]: Canceling affinity group %s's preemption: %s",
                pod.key, name, reason,
            )
        self._delete_preempting_affinity_group(g, pod)
        return True

    def _lazy_preempt_group(
        self, victim: AffinityGroup, preemptor: str
    ) -> Optional[Placement]:
        """Downgrade a group to opportunistic: release its virtual placement
        (reference: hived_algorithm.go:1166-1190)."""
        if victim.virtual_placement is None:
            return None
        for pod_virtual_placements in victim.virtual_placement.values():
            for pod_virtual_placement in pod_virtual_placements:
                for leaf in pod_virtual_placement:
                    if leaf is None:
                        continue
                    assert isinstance(leaf, VirtualCell)
                    p_leaf = leaf.physical_cell
                    self._release_leaf_cell(p_leaf, victim.vc)
                    self._allocate_leaf_cell(
                        p_leaf, None, OPPORTUNISTIC_PRIORITY, victim.vc
                    )
        original = victim.virtual_placement
        victim.virtual_placement = None
        # The cached group bind info embeds preassignedCellTypes derived from
        # the virtual placement — regenerate on next use.
        victim.bind_info_cache = None
        victim.lazy_preemption_status = {
            "preemptor": preemptor,
            "preemptionTime": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
        common.log.info(
            "Affinity group %s is lazy preempted from VC by %s",
            victim.name, preemptor,
        )
        return original

    def _lazy_preempt_cell(self, c: VirtualCell, preemptor: str) -> None:
        """(reference: hived_algorithm.go:1193-1200)"""
        if c.level == LOWEST_LEVEL and c.state == CellState.USED:
            self._lazy_preempt_group(c.physical_cell.using_group, preemptor)
        for child in c.children:
            assert isinstance(child, VirtualCell)
            self._lazy_preempt_cell(child, preemptor)

    def _revert_lazy_preempt(
        self, g: AffinityGroup, virtual: Optional[Placement]
    ) -> None:
        """(reference: hived_algorithm.go:1203-1220)"""
        if virtual is None:
            return
        for leaf_num in g.physical_placement:
            for pod_index in range(len(g.physical_placement[leaf_num])):
                for leaf_index, leaf in enumerate(
                    g.physical_placement[leaf_num][pod_index]
                ):
                    if leaf is None:
                        continue
                    assert isinstance(leaf, PhysicalCell)
                    v_leaf = virtual[leaf_num][pod_index][leaf_index]
                    assert isinstance(v_leaf, VirtualCell)
                    # The group is currently opportunistic (lazy-preempted);
                    # release in that mode so an overlaid doomed-bad binding
                    # of another VC cannot hijack the release.
                    self._release_leaf_cell(
                        leaf, g.vc, opportunistic=g.virtual_placement is None
                    )
                    self._allocate_leaf_cell(leaf, v_leaf, g.priority, g.vc)
        g.virtual_placement = virtual
        g.lazy_preemption_status = None
        g.bind_info_cache = None  # preassignedCellTypes are back
        common.log.info("Lazy preemption of affinity group %s is reverted", g.name)

    def _find_allocated_leaf_cell(
        self,
        index: int,
        physical_leaf_cell_indices: List[int],
        preassigned_cell_types: List[api.CellType],
        chain: CellChain,
        node: str,
        lazy_preempted: bool,
        s: api.PodSchedulingSpec,
        group: AffinityGroup,
        pod: Pod,
    ) -> Tuple[Optional[PhysicalCell], Optional[VirtualCell], Optional[bool]]:
        """Locate the physical and virtual leaf cells for a replayed pod.
        Returns (p_leaf, v_leaf, lazy_preempt) where lazy_preempt None means
        the group is opportunistic (no virtual placement)
        (reference: hived_algorithm.go:1223-1291)."""
        priority = s.priority
        leaf_index_value = physical_leaf_cell_indices[index]
        p_leaf = find_physical_leaf_cell(
            self.full_cell_list, chain, node, leaf_index_value
        )
        if p_leaf is None:
            common.log.warning(
                "[%s]: Cannot find leaf cell %s on node %s: not found in the "
                "spec. Pod ignored", pod.key, leaf_index_value, node,
            )
            return None, None, False
        if not preassigned_cell_types:
            common.log.warning(
                "[%s]: Cannot find virtual cell: preassigned cell not found "
                "in pod bind info", pod.key,
            )
            return p_leaf, None, True
        if group.virtual_placement is not None and not lazy_preempted:
            preassigned_type = preassigned_cell_types[index]
            if preassigned_type:
                v_leaf, message = self._map_replayed_leaf_to_virtual(
                    p_leaf, preassigned_type, s
                )
                if v_leaf is None:
                    common.log.warning(
                        "[%s]: Cannot find virtual cell: %s", pod.key, message
                    )
                    return p_leaf, None, True
                return p_leaf, v_leaf, False
            return p_leaf, None, None
        return p_leaf, None, False

    def _map_replayed_leaf_to_virtual(
        self,
        p_leaf: PhysicalCell,
        preassigned_type: api.CellType,
        s: api.PodSchedulingSpec,
    ) -> Tuple[Optional[VirtualCell], str]:
        """The inverse physical->virtual mapping shared by the two replay
        paths — allocated pods (bound-pod crash recovery) and preempting
        groups (Reserving/Reserved recovery): resolve the preassigned type
        to a level, find the VC cell list, evict overlapping doomed
        advisory bindings, map with the same-VC-squatter retry, and reject
        mappings whose physical anchor is not claimable. The callers decide
        what a failure means: degrade to opportunistic (allocated replay,
        reference hived_algorithm.go:1223-1291) or cancel the preemption
        (a preemptor without VC membership would be meaningless)."""
        priority = s.priority
        preassigned_level: Optional[CellLevel] = None
        for l, t in self.cell_types.get(p_leaf.chain, {}).items():
            if t == preassigned_type:
                preassigned_level = l
        if preassigned_level is None:
            return None, (
                f"Preassigned cell type {preassigned_type} not found "
                f"in chain {p_leaf.chain}"
            )
        if s.virtual_cluster not in self.vc_schedulers:
            return None, f"VC {s.virtual_cluster} not found"
        vcs = self.vc_schedulers[s.virtual_cluster]
        if s.pinned_cell_id:
            vccl = vcs.pinned_cells.get(s.pinned_cell_id)
            target = str(s.pinned_cell_id)
        else:
            vccl = vcs.non_pinned_preassigned.get(p_leaf.chain)
            target = str(p_leaf.chain)
        if vccl is None:
            return None, f"VC {s.virtual_cluster} has no cell for {target}"
        # The subtree the pod's preassigned cell will claim.
        anchor: Optional[PhysicalCell] = p_leaf
        while anchor is not None and anchor.level < preassigned_level:
            anchor = anchor.parent  # type: ignore[assignment]
        if (
            anchor is not None
            and not s.pinned_cell_id
            and len(vccl[preassigned_level]) > 0
        ):
            # Replay may find DOOMED advisory bindings overlapping the
            # claim: recovery marks nodes bad before pods replay, so the
            # doomed binder saw these cells as free and grabbed them — at
            # or above the anchor (blocking the binding path) or strictly
            # inside it (splitting the anchor out of the free list). The
            # real allocation takes precedence: evict them; each doom is
            # re-bound onto a non-overlapping bad free cell when one
            # exists. Gated on the VC actually having cells at the
            # preassigned level: evictions are in service of THIS mapping,
            # and a VC whose quota moved away in a reconfiguration (the
            # pod is about to degrade/cancel) must leave other VCs' dooms
            # alone (found by the strict-ledger chaos equivalence).
            self._evict_doomed_overlapping(anchor, s.virtual_cluster)
        v_leaf, message = allocation.map_physical_cell_to_virtual(
            p_leaf, vccl, preassigned_level, priority
        )
        if (
            v_leaf is None
            and not s.pinned_cell_id
            and self._evict_doomed_binding_for_vc(
                s.virtual_cluster, p_leaf.chain, preassigned_level
            )
        ):
            # A doomed-bad binding of this pod's OWN VC was squatting on
            # the quota cell the replay needs (bound to a DIFFERENT
            # physical cell), so the real allocation failed to map. The
            # advisory binding yields; the shortfall is re-checked once
            # the pod's quota is consumed (add_allocated_pod flushes the
            # deferred checks). Found by the chaos harness
            # restart-equivalence invariant.
            v_leaf, message = allocation.map_physical_cell_to_virtual(
                p_leaf, vccl, preassigned_level, priority
            )
        if (
            v_leaf is not None
            and anchor is not None
            and not s.pinned_cell_id
            and v_leaf.preassigned_cell.physical_cell is None
            and not in_free_cell_list(anchor)
        ):
            # The mapping found a virtual cell but the physical anchor is
            # not claimable (e.g. a foreign REAL allocation splits it —
            # possible after overlapped safety violations). Fail the
            # mapping instead of crashing the replay mid-mutation.
            return None, (
                f"physical cell {anchor.address} is not a free cell "
                "(split or allocated elsewhere)"
            )
        return v_leaf, message

    def _evict_doomed_binding_for_vc(
        self, vcn: api.VirtualClusterName, chain: CellChain, level: CellLevel
    ) -> bool:
        """Evict one of ``vcn``'s own doomed-bad bindings at (chain, level)
        so a replayed real allocation can claim the virtual quota cell the
        advisory binding holds. Skips doomed cells hosting live guaranteed
        allocations (same priority guard as _try_unbind_doomed_bad_cell).
        Returns True if a binding was evicted."""
        doomed = self.vc_doomed_bad_cells.get(vcn, {}).get(chain)
        if doomed is None:
            return False
        pc = next(
            (
                c
                for c in doomed[level]
                if c.priority < MIN_GUARANTEED_PRIORITY
            ),
            None,
        )
        if pc is None:
            return False
        assert isinstance(pc, PhysicalCell)
        common.log.warning(
            "Evicting doomed binding %s -> %s (VC %s): the VC's replayed "
            "allocation needs the virtual quota cell",
            pc.virtual_cell.address, pc.address, vcn,
        )
        self._unbind_doomed_cell(pc)
        key = (chain, level)
        pending = self._pending_doomed()
        pending[key] = pending.get(key, 0) + 1
        return True

    def _evict_doomed_overlapping(
        self, anchor: PhysicalCell, vcn: api.VirtualClusterName
    ) -> None:
        """Evict doomed-bad advisory bindings overlapping the subtree
        ``anchor`` — the physical region a replayed pod's preassigned cell
        is about to claim. Both directions matter: a foreign doom at or
        above the anchor blocks the binding path, while a doom strictly
        inside it (any VC's) leaves the anchor split and un-allocatable.
        Real bindings are left alone (genuine conflicts degrade to lazy
        preemption, as before)."""
        cur: Optional[PhysicalCell] = anchor
        while cur is not None and cur.virtual_cell is None:
            cur = cur.parent  # type: ignore[assignment]
        if cur is not None:
            # Climb to the TOP of the binding chain: the doomed LISTING
            # lives at the quota level where the doomed bind happened,
            # while _set_bad_cell hangs advisory descendant bindings all
            # the way down to the leaves.
            while (
                cur.parent is not None
                and cur.parent.virtual_cell is not None
            ):
                cur = cur.parent  # type: ignore[assignment]
            if cur.virtual_cell.vc != vcn:
                # Same-VC bindings on the path are reused by the mapping.
                self._evict_doomed_binding(cur, avoid=anchor)
        stack: List[PhysicalCell] = [anchor]
        while stack:
            c = stack.pop()
            for child in c.children:
                assert isinstance(child, PhysicalCell)
                if child.virtual_cell is not None:
                    # Doomed (any VC): evict; a real binding is someone
                    # else's region — do not descend either way.
                    self._evict_doomed_binding(child, avoid=anchor)
                    continue
                stack.append(child)

    def _evict_doomed_binding(
        self, pc: PhysicalCell, avoid: Optional[PhysicalCell] = None
    ) -> None:
        """Remove a doomed-bad advisory binding from ``pc`` so a replayed
        real allocation can claim the region. No-op unless ``pc`` is in its
        VC's doomed list (a non-doomed binding is a true conflict, left for
        the mapping to reject into lazy preemption).

        The doom is immediately re-bound ("swapped") onto another bad free
        cell not overlapping ``avoid`` when one exists: leaving the
        shortfall unaddressed until the deferred check would transiently
        inflate allVCFreeCellNum at the evicted level, and the replayed
        pod's own safety checks then see phantom broken safety and
        lazy-preempt the group (found by the chaos harness
        restart-equivalence invariant)."""
        vc = pc.virtual_cell
        vcn = vc.vc
        doomed = self.vc_doomed_bad_cells.get(vcn, {}).get(pc.chain)
        if doomed is None or not doomed.contains(pc, pc.level):
            return
        if pc.priority >= MIN_GUARANTEED_PRIORITY:
            # The doomed cell hosts a live allocation of its own VC — that
            # is a genuine occupancy conflict, not an advisory binding;
            # leave it for the mapping to reject into lazy preemption.
            return
        common.log.warning(
            "Evicting doomed binding %s -> %s (VC %s): the cell overlaps a "
            "replayed real allocation",
            vc.address, pc.address, vcn,
        )
        chain, level = pc.chain, pc.level
        self._unbind_doomed_cell(pc)
        if not self._swap_doomed_binding(vcn, chain, level, pc, avoid):
            key = (chain, level)
            pending = self._pending_doomed()
            pending[key] = pending.get(key, 0) + 1

    def _swap_doomed_binding(
        self,
        vcn: api.VirtualClusterName,
        chain: CellChain,
        level: CellLevel,
        evicted: PhysicalCell,
        avoid: Optional[PhysicalCell],
    ) -> bool:
        """Re-bind an evicted doom onto a different bad free cell at the
        same (chain, level) — the choice the continuous timeline would have
        made, since there the real allocation existed before the doom. The
        replacement must not be the evicted cell itself nor overlap the
        region being replayed. Returns True when the doom was re-bound."""
        vc_free = self.vc_free_cell_num.get(vcn, {}).get(chain, {})
        if vc_free.get(level, 0) <= (
            self.total_left_cell_num[chain][level]
            - len(self.bad_free_cells[chain][level])
        ):
            return False  # shortfall no longer holds; nothing to re-doom
        preassigned = self.vc_schedulers[vcn].non_pinned_preassigned
        if chain not in preassigned:
            return False
        target = allocation.get_unbound_virtual_cell(preassigned[chain][level])
        if target is None:
            return False
        eligible = [
            c
            for c in self.bad_free_cells[chain][level]
            # Bad-free cells are unbound by construction (dooming
            # removes the cell from this list); the binding check is
            # defensive — clobbering an existing binding would corrupt
            # both VCs' doomed accounting.
            if c.virtual_cell is None  # type: ignore[union-attr]
            and not cell_equal(c, evicted)
            and (avoid is None or not _cells_overlap(c, avoid))
        ]
        pref = self.preferred_doomed.get((vcn, chain, level))
        candidate = next(
            (c for c in eligible if pref and c.address in pref),
            eligible[0] if eligible else None,
        )
        if candidate is None:
            return False
        assert isinstance(candidate, PhysicalCell)
        candidate.set_virtual_cell(target)
        target.set_physical_cell(candidate)
        common.log.warning(
            "Cell %s is doomed to be bad and bound to %s (VC %s, swapped "
            "from %s)", target.address, candidate.address, vcn,
            evicted.address,
        )
        self.vc_doomed_bad_cells[vcn][chain][level].append(candidate)
        self.all_vc_doomed_bad_cell_num[chain][level] = (
            self.all_vc_doomed_bad_cell_num[chain].get(level, 0) + 1
        )
        self._bump_doomed_epoch()
        self._allocate_preassigned_cell(candidate, vcn, True)
        return True

    def _unbind_bad_descendants(self, pc: PhysicalCell) -> None:
        """Clear the advisory bad-cell bindings under a cell whose own
        binding was just removed.

        ``_set_bad_cell`` binds a bad cell whenever its parent is bound, so
        a doomed-bound cell accumulates descendant bindings as nodes under
        it go bad. Unbinding only the top pair (as the reference's
        ``tryUnbindDoomedBadCell`` does via a single unbind) would leave
        those virtual children pointing at physical cells that no longer
        belong to their VC; a later dynamic bind of the preassigned cell
        then walks into the stale pointers and corrupts both VCs' cell
        state across doomed-bind/heal cycles (full-walk analog of the
        reference's unbindCell, cell_allocation.go:401-420)."""
        for child in pc.children:
            assert isinstance(child, PhysicalCell)
            if child.virtual_cell is not None:
                v = child.virtual_cell
                child.set_virtual_cell(None)
                v.set_physical_cell(None)
                common.log.info(
                    "Unbound bad descendant binding %s -> %s",
                    v.address, child.address,
                )
            self._unbind_bad_descendants(child)

    # -- leaf cell allocate / release ---------------------------------------

    def _allocate_leaf_cell(
        self,
        p_leaf: PhysicalCell,
        v_leaf: Optional[VirtualCell],
        p: CellPriority,
        vcn: api.VirtualClusterName,
    ) -> Tuple[bool, str]:
        """Create bindings, allocate the preassigned cell if newly bound, set
        priorities (reference: hived_algorithm.go:1294-1324)."""
        safety_ok, reason = True, ""
        if v_leaf is not None:
            allocation.set_cell_priority(v_leaf, p)
            allocation.update_used_leaf_cell_numbers(v_leaf, p, True)
            allocation.set_cell_priority(p_leaf, p)
            allocation.update_used_leaf_cell_numbers(p_leaf, p, True)
            pac = v_leaf.preassigned_cell
            preassigned_newly_bound = pac.physical_cell is None
            if p_leaf.virtual_cell is None:
                # The binding may already exist (e.g. the cell was bad).
                allocation.bind_cell(p_leaf, v_leaf)
            if preassigned_newly_bound:
                safety_ok, reason = self._allocate_preassigned_cell(
                    pac.physical_cell, vcn, False
                )
        else:
            allocation.set_cell_priority(p_leaf, OPPORTUNISTIC_PRIORITY)
            allocation.update_used_leaf_cell_numbers(
                p_leaf, OPPORTUNISTIC_PRIORITY, True
            )
            self._ot_cells.setdefault(vcn, {})[p_leaf.address] = p_leaf
        return safety_ok, reason

    def _release_leaf_cell(
        self,
        p_leaf: PhysicalCell,
        vcn: api.VirtualClusterName,
        opportunistic: bool = False,
    ) -> None:
        """(reference: hived_algorithm.go:1327-1353, with one deliberate
        fix: the branch must key off the GROUP's allocation mode, not off
        ``p_leaf.virtual_cell`` — a doomed-bad binding (possibly of ANOTHER
        VC) can be overlaid onto cells an opportunistic pod is using, and
        the reference would then walk the virtual branch and release the
        other VC's preassigned cell against this VC's counters (found by
        sequence fuzzing). Allocation already keys off the group's virtual
        placement; release now mirrors it."""
        v_leaf = None if opportunistic else p_leaf.virtual_cell
        if v_leaf is not None:
            allocation.update_used_leaf_cell_numbers(
                v_leaf, v_leaf.priority, False
            )
            allocation.set_cell_priority(v_leaf, FREE_PRIORITY)
            preassigned_physical = v_leaf.preassigned_cell.physical_cell
            if p_leaf.healthy:
                # Never unbind a bad cell: the binding keeps the failure
                # visible in the VC.
                allocation.unbind_cell(p_leaf)
            doomed = self.vc_doomed_bad_cells.get(vcn, {}).get(
                preassigned_physical.chain
            )
            is_doomed = doomed is not None and doomed.contains(
                preassigned_physical, preassigned_physical.level
            )
            if (
                not preassigned_physical.pinned
                and v_leaf.preassigned_cell.priority < MIN_GUARANTEED_PRIORITY
            ):
                if not is_doomed:
                    self._release_preassigned_cell(
                        preassigned_physical, vcn, False
                    )
                elif preassigned_physical.healthy:
                    # The cell was doomed bad but healed while its healthy
                    # part hosted this allocation (so setHealthyCell could
                    # not retire it — the cell was in use). Now the last use
                    # is gone and unbind_cell has destroyed the top binding:
                    # retire the doomed entry and release for real.
                    doomed.remove(
                        preassigned_physical, preassigned_physical.level
                    )
                    self.all_vc_doomed_bad_cell_num[
                        preassigned_physical.chain
                    ][preassigned_physical.level] -= 1
                    self._bump_doomed_epoch()
                    self._release_preassigned_cell(
                        preassigned_physical, vcn, False
                    )
                else:
                    # Still bad and doomed-listed: the doomed binding must
                    # survive the release. Usually a bound bad child stops
                    # the unbind walk early, but when the bad descendants
                    # were never bound (they went bad BEFORE the doomed
                    # binding existed, so _set_bad_cell had no bound parent
                    # to hang them under), the walk reaches the top and
                    # destroys the doomed binding — restore it. (Found by
                    # the restart-replay fuzzer; the reference has the same
                    # unguarded walk, cell_allocation.go:401-420.)
                    pac = v_leaf.preassigned_cell
                    if pac.physical_cell is None:
                        preassigned_physical.set_virtual_cell(pac)
                        pac.set_physical_cell(preassigned_physical)
        else:
            self._ot_cells.get(vcn, {}).pop(p_leaf.address, None)
        allocation.update_used_leaf_cell_numbers(p_leaf, p_leaf.priority, False)
        allocation.set_cell_priority(p_leaf, FREE_PRIORITY)

    # -- preassigned cell allocate / release (buddy split/merge) ------------

    def _allocate_preassigned_cell(
        self, c: PhysicalCell, vcn: api.VirtualClusterName, doomed_bad: bool
    ) -> Tuple[bool, str]:
        """Remove from the free list (splitting ancestors) and maintain the
        triple bookkeeping + doomed-bad-cell checks along every affected
        level (reference: hived_algorithm.go:1356-1427; the inline comments
        there explain each branch and are mirrored below)."""
        safety_ok, reason = True, ""
        chain, level = c.chain, c.level
        self.vc_free_cell_num[vcn].setdefault(chain, {}).setdefault(level, 0)
        self.vc_free_cell_num[vcn][chain][level] -= 1
        self.all_vc_free_cell_num.setdefault(chain, {}).setdefault(level, 0)
        self.all_vc_free_cell_num[chain][level] -= 1
        self.total_left_cell_num[chain][level] -= 1
        split_level_up_to = self._remove_cell_from_free_list(c)

        parent = c.parent
        for l in range(level + 1, split_level_up_to + 1):
            self.total_left_cell_num[chain][l] -= 1
            if (
                self.total_left_cell_num[chain][l]
                < self._effective_vc_free(chain, l)
            ):
                safety_ok = False
                reason = self._safety_reason(chain, l)
            assert isinstance(parent, PhysicalCell)
            if not parent.healthy:
                # Bad parent: neither vcFreeCellNum nor healthy-free count
                # changes; just remove it from the bad free cells.
                self.bad_free_cells[chain].remove(parent, l)
            else:
                # Healthy parent consumed: healthy free count decreases.
                self._try_bind_doomed_bad_cell(chain, l)
            parent = parent.parent
        if not c.healthy:
            self._allocate_bad_cell(c)
            if not doomed_bad:
                self._try_unbind_doomed_bad_cell(chain, level)
        else:
            self._try_bind_doomed_bad_cell(chain, level)
        num_to_reduce = len(c.children)
        for l in range(level - 1, LOWEST_LEVEL - 1, -1):
            self.total_left_cell_num[chain][l] -= num_to_reduce
            if (
                self.total_left_cell_num[chain][l]
                < self._effective_vc_free(chain, l)
            ):
                safety_ok = False
                reason = self._safety_reason(chain, l)
            if not doomed_bad:
                self._try_bind_doomed_bad_cell(chain, l)
            num_to_reduce *= len(self.full_cell_list[chain][l][0].children) if (
                l > LOWEST_LEVEL
            ) else 0
        return safety_ok, reason

    def _effective_vc_free(self, chain: CellChain, l: CellLevel) -> int:
        """allVCFreeCellNum discounted by pending doomed re-checks: quota
        freed by a mid-replay doom eviction is spoken for (it re-dooms when
        the replay completes), so the safety checks must not count it as
        free — otherwise the replayed group sees phantom broken safety and
        gets lazy-preempted out of its VC."""
        return self.all_vc_free_cell_num.get(chain, {}).get(
            l, 0
        ) - self._pending_doomed().get((chain, l), 0)

    def _safety_reason(self, chain: CellChain, l: CellLevel) -> str:
        """Safety-violation message. Uses .get throughout: total_left can be
        transiently negative while a nested doomed-bad-cell bind runs in the
        middle of an alloc/release loop (the reference tolerates this via
        Go's zero-value maps and ignores safetyOk for doomed binds)."""
        return (
            "Adding pod would lead to broken safety: cell type "
            f"{self.cell_types[chain].get(l)}, "
            f"{self.total_left_cell_num[chain].get(l, 0)} left, "
            f"{self.all_vc_free_cell_num.get(chain, {}).get(l, 0)} free "
            "cells in all VCs"
        )

    def _allocate_bad_cell(self, c: PhysicalCell) -> None:
        """(reference: hived_algorithm.go:1430-1448)"""
        if self.bad_free_cells[c.chain].contains(c, c.level):
            self.bad_free_cells[c.chain].remove(c, c.level)
        if c.virtual_cell is None:
            vc = allocation.get_unbound_virtual_cell(
                c.parent.virtual_cell.children
            )
            c.set_virtual_cell(vc)
            vc.set_physical_cell(c)
            common.log.info(
                "Virtual cell %s is bound to physical cell %s (bad)",
                vc.address, c.address,
            )
        for child in c.children:
            assert isinstance(child, PhysicalCell)
            if not child.healthy:
                self._allocate_bad_cell(child)

    def _release_preassigned_cell(
        self, c: PhysicalCell, vcn: api.VirtualClusterName, doomed_bad: bool
    ) -> None:
        """(reference: hived_algorithm.go:1451-1483)"""
        chain, level = c.chain, c.level
        self.vc_free_cell_num[vcn].setdefault(chain, {}).setdefault(level, 0)
        self.vc_free_cell_num[vcn][chain][level] += 1
        self.all_vc_free_cell_num.setdefault(chain, {}).setdefault(level, 0)
        self.all_vc_free_cell_num[chain][level] += 1
        self.total_left_cell_num[chain][level] += 1
        merge_level_up_to = self._add_cell_to_free_list(c)

        parent = c.parent
        for l in range(level + 1, merge_level_up_to + 1):
            self.total_left_cell_num[chain][l] += 1
            assert isinstance(parent, PhysicalCell)
            if not parent.healthy:
                self.bad_free_cells[chain][l].append(parent)
            else:
                self._try_unbind_doomed_bad_cell(chain, l)
            parent = parent.parent
        if not c.healthy:
            self._release_bad_cell(c)
            if not doomed_bad:
                self._try_bind_doomed_bad_cell(chain, level)
        else:
            self._try_unbind_doomed_bad_cell(chain, level)
        num_to_add = len(c.children)
        for l in range(level - 1, LOWEST_LEVEL - 1, -1):
            self.total_left_cell_num[chain][l] += num_to_add
            if not doomed_bad:
                self._try_unbind_doomed_bad_cell(chain, l)
            num_to_add *= len(self.full_cell_list[chain][l][0].children) if (
                l > LOWEST_LEVEL
            ) else 0

    def _release_bad_cell(self, c: PhysicalCell) -> None:
        """(reference: hived_algorithm.go:1486-1500)"""
        self.bad_free_cells[c.chain][c.level].append(c)
        if c.virtual_cell is not None:
            vc = c.virtual_cell
            c.set_virtual_cell(None)
            vc.set_physical_cell(None)
            common.log.info(
                "Virtual cell %s is unbound from physical cell %s",
                vc.address, c.address,
            )
        for child in c.children:
            assert isinstance(child, PhysicalCell)
            if not child.healthy:
                self._release_bad_cell(child)

    def _remove_cell_from_free_list(self, c: PhysicalCell) -> CellLevel:
        """Remove from the free list, splitting parents as needed; returns
        the highest level actually split
        (reference: hived_algorithm.go:1503-1527)."""
        chain = c.chain
        while True:
            terminate = False
            l = c.level
            parent = c.parent
            if parent is not None:
                if parent.split:
                    terminate = True
                else:
                    self.free_cell_list[chain][l].extend(parent.children)
                    parent.split = True
            else:
                terminate = True
            self.free_cell_list[chain].remove(c, l)
            if terminate:
                return l
            c = parent

    def _add_cell_to_free_list(self, c: PhysicalCell) -> CellLevel:
        """Add to the free list, merging buddies recursively; returns the
        highest level actually merged
        (reference: hived_algorithm.go:1530-1565)."""
        chain = c.chain
        while True:
            terminate = False
            l = c.level
            parent = c.parent
            if parent is not None:
                all_buddy_free = all(
                    cell_equal(buddy, c)
                    or self.free_cell_list[chain].contains(buddy, l)
                    for buddy in parent.children
                )
                if not all_buddy_free:
                    terminate = True
                else:
                    for buddy in parent.children:
                        if not cell_equal(buddy, c):
                            self.free_cell_list[chain].remove(buddy, l)
                    parent.split = False
            else:
                terminate = True
            if terminate:
                self.free_cell_list[chain][l].append(c)
                return l
            c = parent

    def configured_node_names(self) -> List[str]:
        """Sorted node names of every configured top-level cell — the
        fleet the config describes (standalone boot, benches, and lint
        all enumerate it)."""
        return sorted(
            {
                n
                for ccl in self.full_cell_list.values()
                for c in ccl[ccl.top_level]
                for n in c.nodes
            }
        )

    def free_slice_distribution(self) -> Dict[str, int]:
        """Schedulable-slice-size distribution: how many WHOLE free cells
        of each chip size the buddy hierarchy currently offers (the free
        list holds maximal free cells — a fragmented fleet shows mass at
        small sizes where a compact one shows whole cubes). Keys are chip
        counts as strings (JSON-stable), values cell counts. The sim
        tier's fragmentation metric (doc/hot-path.md "Warehouse-scale
        profile") and the defrag trend input for ROADMAP new-direction 3.
        Reads only free-list lengths; callers needing a consistent view
        against concurrent mutators hold the global order."""
        out: Dict[str, int] = {}
        for chain, ccl in self.free_cell_list.items():
            leaf_num = self.compiled.cell_level_to_leaf_num[chain]
            for level, cells in ccl.levels.items():
                n = len(cells)
                if n:
                    key = str(leaf_num[level])
                    out[key] = out.get(key, 0) + n
        return out

    # -- inspect API --------------------------------------------------------

    def get_all_affinity_groups(self) -> Dict:
        """(reference: hived_algorithm.go:298-309)"""
        return {"items": [g.to_status() for g in self.affinity_groups.values()]}

    def get_affinity_group(self, name: str) -> Dict:
        g = self.affinity_groups.get(name)
        if g is None:
            raise api.bad_request(
                f"Affinity group {name} does not exist since it is not "
                "allocated or preempting"
            )
        return g.to_status()

    def get_cluster_status(self) -> Dict:
        return {
            "physicalCluster": self.get_physical_cluster_status(),
            "virtualClusters": self.get_all_virtual_clusters_status(),
        }

    def get_physical_cluster_status(self) -> List[Dict]:
        """Mirrored statuses, the reference's approach
        (hived_algorithm.go:412-437) keyed on the per-chain mutation
        epochs: a chain whose epoch did not move since the last request
        serves its cached status list; only dirty chains re-walk their
        trees. Opportunistic-cell VC attribution changes always bump the
        owning leaf's chain (the allocate/release priority writes), so the
        per-chain key covers the ot map too. Returned dicts are shared and
        read-only by contract (the webserver JSON-encodes them; tests only
        assert on them)."""
        out: List[Dict] = []
        ot_vc_map: Optional[Dict[str, api.VirtualClusterName]] = None
        for chain in self.full_cell_list:
            cached = self._phys_status_cache.get(chain)
            if cached is not None and cached[0] == self.chain_epoch(chain):
                out.extend(cached[1])
                continue
            if ot_vc_map is None:
                # Lazy and shared across every dirty chain of this call —
                # the map walks all OT cells of all VCs once, not per chain.
                ot_vc_map = self._ot_cell_vc_by_address()
            out.extend(self.physical_chain_status(chain, ot_vc_map))
        return out

    def physical_chain_status(
        self,
        chain: CellChain,
        ot_vc_map: Optional[Dict[str, api.VirtualClusterName]] = None,
    ) -> List[Dict]:
        """One chain's mirrored top-cell status list, rebuilt only when the
        chain's mutation epoch moved. The framework serves scrapes through
        this per chain — an epoch-clean chain's mirror is read LOCK-FREE,
        and a dirty chain's rebuild takes only that chain's lock section
        instead of the whole-cluster global order (doc/observability.md).
        ``ot_vc_map`` lets a multi-chain caller share one OT-cell walk."""
        epoch = self.chain_epoch(chain)
        cached = self._phys_status_cache.get(chain)
        if cached is None or cached[0] != epoch:
            ccl = self.full_cell_list[chain]
            if ot_vc_map is None:
                ot_vc_map = self._ot_cell_vc_by_address()
            statuses = [
                self._physical_cell_status(
                    c,
                    leaf_type=self.chain_to_leaf_type.get(chain),
                    ot_vc_map=ot_vc_map,
                )
                for c in ccl[ccl.top_level]
                if isinstance(c, PhysicalCell)
            ]
            cached = self._phys_status_cache[chain] = (epoch, statuses)
        return cached[1]

    def get_all_virtual_clusters_status(self) -> Dict[str, List[Dict]]:
        return {vc: self.get_virtual_cluster_status(vc) for vc in self.vc_schedulers}

    def get_virtual_cluster_status(self, vcn: api.VirtualClusterName) -> List[Dict]:
        if vcn not in self.vc_schedulers:
            raise api.bad_request(f"VC {vcn} not found")
        # Mirror cache, keyed on the all-chain epoch total: a VC's status
        # reads its own chains' virtual trees plus opportunistic cells that
        # can live in ANY chain, so the conservative key is the sum (epochs
        # only grow — equal totals imply nothing changed anywhere).
        total = self.epoch_total()
        cached = self._vc_status_cache.get(vcn)
        if cached is not None and cached[0] == total:
            return cached[1]
        out = self._build_virtual_cluster_status(vcn)
        self._vc_status_cache[vcn] = (total, out)
        return out

    def _build_virtual_cluster_status(
        self, vcn: api.VirtualClusterName
    ) -> List[Dict]:
        vcs = self.vc_schedulers[vcn]
        out: List[Dict] = []
        for chain, ccl in vcs.non_pinned_preassigned.items():
            leaf_type = self.chain_to_leaf_type.get(chain)
            for level in sorted(ccl.levels):
                for c in ccl[level]:
                    assert isinstance(c, VirtualCell)
                    out.append(self._virtual_cell_status(c, leaf_type=leaf_type))
        for pid, ccl in vcs.pinned_cells.items():
            for c in ccl[ccl.top_level]:
                assert isinstance(c, VirtualCell)
                out.append(
                    self._virtual_cell_status(
                        c, leaf_type=self.chain_to_leaf_type.get(c.chain)
                    )
                )
        # Opportunistic cells used by this VC (reference: utils.go:419-436).
        for p_leaf in self._ot_cells.get(vcn, {}).values():
            ps = self._physical_cell_status(p_leaf, shallow=True)
            out.append(
                {
                    "leafCellType": self.chain_to_leaf_type.get(p_leaf.chain, ""),
                    "cellType": p_leaf.cell_type,
                    "cellAddress": p_leaf.address + "-opp",
                    "cellState": CellState.USED.value,
                    "cellHealthiness": (
                        api.CELL_HEALTHY if p_leaf.healthy else api.CELL_BAD
                    ),
                    "cellPriority": OPPORTUNISTIC_PRIORITY,
                    "physicalCell": ps,
                }
            )
        return out

    def _ot_cell_vc_by_address(self) -> Dict[str, api.VirtualClusterName]:
        """address -> VC for synthesized opportunistic virtual cells."""
        return {
            addr: vcn
            for vcn, ocs in self._ot_cells.items()
            for addr in ocs
        }

    def _physical_cell_status(
        self,
        c: PhysicalCell,
        leaf_type: Optional[str] = None,
        shallow: bool = False,
        ot_vc_map: Optional[Dict[str, api.VirtualClusterName]] = None,
    ) -> Dict:
        d: Dict = {
            "cellType": c.cell_type,
            "isNodeLevel": c.is_node_level,
            "cellAddress": c.address,
            "cellState": c.state.value,
            "cellHealthiness": api.CELL_HEALTHY if c.healthy else api.CELL_BAD,
            "cellPriority": c.priority,
        }
        if leaf_type:
            d["leafCellType"] = leaf_type
        if ot_vc_map is None:
            ot_vc_map = self._ot_cell_vc_by_address()
        if c.virtual_cell is not None:
            d["vc"] = c.virtual_cell.vc
        elif c.address in ot_vc_map:
            d["vc"] = ot_vc_map[c.address]
        if shallow:
            return d
        if c.virtual_cell is not None:
            d["virtualCell"] = self._virtual_cell_status(c.virtual_cell, shallow=True)
        if c.children:
            d["cellChildren"] = [
                self._physical_cell_status(child, ot_vc_map=ot_vc_map)
                for child in c.children
                if isinstance(child, PhysicalCell)
            ]
        return d

    def _virtual_cell_status(
        self,
        c: VirtualCell,
        leaf_type: Optional[str] = None,
        shallow: bool = False,
    ) -> Dict:
        d: Dict = {
            "cellType": c.cell_type,
            "isNodeLevel": c.is_node_level,
            "cellAddress": c.address,
            "cellState": c.state.value,
            "cellHealthiness": api.CELL_HEALTHY if c.healthy else api.CELL_BAD,
            "cellPriority": c.priority,
        }
        if leaf_type:
            d["leafCellType"] = leaf_type
        if shallow:
            return d
        if c.physical_cell is not None:
            d["physicalCell"] = self._physical_cell_status(
                c.physical_cell, shallow=True
            )
        if c.children:
            d["cellChildren"] = [
                self._virtual_cell_status(child)
                for child in c.children
                if isinstance(child, VirtualCell)
            ]
        return d
