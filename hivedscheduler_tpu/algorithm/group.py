"""Affinity groups (gangs) and their placements.

Python equivalent of the reference's ``pkg/algorithm/types.go``:
AlgoAffinityGroup (L133-214), groupPhysicalPlacement/groupVirtualPlacement
(L216-283), and the binding-path tree builder (L285-350).

An affinity group is the gang-scheduling unit: all pods of a group are
scheduled transactionally onto one cell chain, e.g. the 16 workers of a
v5p-64 Llama pretraining job.
"""

from __future__ import annotations

import enum
from typing import Any, Dict, List, Optional, Tuple

from ..api import types as api
from .cell import Cell, CellPriority, PhysicalCell, VirtualCell, cell_equal


class GroupState(str, enum.Enum):
    """(reference: algorithm/constants.go:60-71 and
    doc/design/state-machine.md "AG State Machine")"""

    # Allocated cells; all its cells are Used.
    ALLOCATED = "Allocated"
    # Preempting other groups; its cells are Reserving or Reserved.
    PREEMPTING = "Preempting"
    # Being preempted by other group(s); its cells are Used or Reserving.
    BEING_PREEMPTED = "BeingPreempted"


# placement: leaf_cell_num -> list over pods -> list of leaf cells per pod
Placement = Dict[int, List[List[Optional[Cell]]]]


class AffinityGroup:
    """Algorithm-internal gang state
    (reference: algorithm/types.go:133-214 ``AlgoAffinityGroup``)."""

    def __init__(
        self,
        spec: api.AffinityGroupSpec,
        vc: api.VirtualClusterName,
        lazy_preemption_enable: bool,
        priority: int,
        state: GroupState,
        init_placements: bool = True,
    ):
        self.name = spec.name
        self.vc = vc
        self.lazy_preemption_enable = lazy_preemption_enable
        # Whether binding to non-suggested nodes is acceptable (bad nodes
        # never are). Always False at group level, matching the reference
        # (types.go:139-141 is never assigned in newAlgoAffinityGroup): a
        # PREEMPTING group whose reservation falls outside the current
        # Preempting-phase candidate nodes must have its preemption canceled
        # and rescheduled (hived_algorithm.go:692-702) — with True here that
        # cancellation could never trigger and the preemptor would wait
        # forever on victims the default scheduler will never preempt.
        self.ignore_k8s_suggested_nodes = False
        self.priority = priority
        # Elastic gang plane (doc/fault-model.md "Elastic gang plane"):
        # total-pod-count bounds copied off the spec (0 = inelastic /
        # fixed), and the monotone resize generation matching the
        # resizeGeneration of the group-level bind info the placement was
        # built from. Bumped by every applied shrink/grow.
        self.min_members = getattr(spec, "min_members", 0)
        self.max_members = getattr(spec, "max_members", 0)
        self.resize_generation = 0
        # leaf_cell_num -> pod count
        self.total_pod_nums: Dict[int, int] = {}
        for m in spec.members:
            self.total_pod_nums[m.leaf_cell_number] = (
                self.total_pod_nums.get(m.leaf_cell_number, 0) + m.pod_number
            )
        # leaf_cell_num -> fixed-size slot list of allocated pods (pod objects)
        self.allocated_pods: Dict[int, List[Optional[Any]]] = {
            n: [None] * p for n, p in self.total_pod_nums.items()
        }
        self.preempting_pods: Dict[str, Any] = {}
        # Snapshot restore assigns complete placements wholesale
        # (init_placements=False skips building matrices it would discard).
        if init_placements:
            self.physical_placement: Placement = {
                n: [[None] * n for _ in range(p)]
                for n, p in self.total_pod_nums.items()
            }
            self.virtual_placement: Placement = {
                n: [[None] * n for _ in range(p)]
                for n, p in self.total_pod_nums.items()
            }
        else:
            self.physical_placement = {}
            self.virtual_placement = {}
        self.state = state
        self.lazy_preemption_status: Optional[Dict[str, Any]] = None
        # Memoized group-level bind info (core.generate_affinity_group_bind_info):
        # (member_bind_info_list, chain). The group's placements are fixed once
        # allocated, so every pod of the gang shares the identical group-level
        # record; only the per-pod (node, chip indices) selection differs.
        # Invalidated whenever the VIRTUAL placement changes (lazy preemption
        # and its revert change the preassigned cell types inside the record).
        self.bind_info_cache: Optional[Tuple[List[Any], str]] = None
        # Preempt-probe victims cache: (chain mutation epoch, victims,
        # overlapping preemptors) — repeated preempt probes of the same
        # PREEMPTING gang are O(1) while nothing in the gang's chain moved
        # (core._collect_victims_cached; doc/hot-path.md "Preempt-path
        # indexing"). Epoch-gated, so no explicit invalidation sites.
        self.victims_cache: Optional[Tuple[int, Any, Any]] = None
        # Physical-placement coordinate index: leaf address ->
        # (leaf_num, pod_index, leaf_index), built lazily by
        # find_leaf_coords. Physical placements never move once assigned
        # (slots only ever go from None to a cell during creation/replay),
        # so the index only needs rebuilding when it misses an address.
        self._leaf_coords: Optional[Dict[str, Tuple[int, int, int]]] = None

    @property
    def total_pods(self) -> int:
        return sum(self.total_pod_nums.values())

    def spec_dict(
        self, total_pod_nums: Optional[Dict[int, int]] = None
    ) -> Dict[str, Any]:
        """The gang's AffinityGroupSpec as a wire dict — the ONE place
        the (name, members, elastic bounds) serialization lives: snapshot
        group records, shrink-plan survivor patches, and resize re-syncs
        all consume it, and they must never disagree. ``total_pod_nums``
        overrides the member counts (a shrink plan serializes the POST-
        shrink shape before the matrices change)."""
        counts = (
            total_pod_nums
            if total_pod_nums is not None
            else self.total_pod_nums
        )
        d: Dict[str, Any] = {
            "name": self.name,
            "members": [
                {"podNumber": p, "leafCellNumber": n}
                for n, p in sorted(counts.items())
            ],
        }
        if self.min_members:
            d["minMembers"] = self.min_members
        if self.max_members:
            d["maxMembers"] = self.max_members
        return d

    def invalidate_placement_caches(self) -> None:
        """Drop every cache derived from the placement matrices. Resize
        (shrink/grow) is the one path where placements MOVE after
        assignment, so the lazily-built coordinate index and the memoized
        group bind info both go stale at once."""
        self._leaf_coords = None
        self.bind_info_cache = None
        self.victims_cache = None

    def find_leaf_coords(self, address: str) -> Optional[Tuple[int, int, int]]:
        """O(1) lookup of a physical leaf's position inside the group's
        placement — the indexed replacement for the O(placement) scan the
        reservation-state walks (core.retrieve_virtual_cell) used to pay
        per leaf, making preemption cancel/rollback O(placement²)."""
        coords = self._leaf_coords
        if coords is None or address not in coords:
            coords = {}
            for leaf_num, pod_placements in self.physical_placement.items():
                for pod_index, pod_placement in enumerate(pod_placements):
                    for leaf_index, leaf in enumerate(pod_placement):
                        if leaf is not None:
                            coords[leaf.address] = (
                                leaf_num, pod_index, leaf_index
                            )
            self._leaf_coords = coords
        return coords.get(address)

    def to_status(self) -> Dict[str, Any]:
        """Inspect DTO (reference: types.go:189-214 ``ToAffinityGroup``)."""
        status: Dict[str, Any] = {
            "metadata": {"name": self.name},
            "status": {
                "vc": self.vc,
                "priority": self.priority,
                "state": self.state.value,
                "minMembers": self.min_members,
                "maxMembers": self.max_members,
                "resizeGeneration": self.resize_generation,
                "lazyPreemptionStatus": self.lazy_preemption_status,
                "physicalPlacement": physical_placement_to_node_indices(
                    self.physical_placement
                )
                if self.physical_placement is not None
                else {},
                "virtualPlacement": virtual_placement_to_preassigned_map(
                    self.virtual_placement
                )
                if self.virtual_placement is not None
                else {},
                "allocatedPods": [
                    getattr(p, "uid", None)
                    for pods in self.allocated_pods.values()
                    for p in pods
                    if p is not None
                ],
                "preemptingPods": list(self.preempting_pods),
            },
        }
        return status


def physical_placement_to_node_indices(p: Placement) -> Dict[str, List[int]]:
    """node -> leaf cell (chip) indices (reference: types.go:222-238)."""
    out: Dict[str, List[int]] = {}
    for pod_placements in p.values():
        for pod_placement in pod_placements:
            for leaf in pod_placement:
                if leaf is None:
                    continue
                assert isinstance(leaf, PhysicalCell)
                out.setdefault(leaf.nodes[0], []).append(leaf.leaf_cell_indices[0])
    return out


def virtual_placement_to_preassigned_map(p: Placement) -> Dict[str, List[str]]:
    """preassigned cell address -> leaf cell addresses
    (reference: types.go:240-260)."""
    out: Dict[str, List[str]] = {}
    for pod_placements in p.values():
        for pod_placement in pod_placements:
            for leaf in pod_placement:
                if leaf is None:
                    continue
                assert isinstance(leaf, VirtualCell)
                out.setdefault(leaf.preassigned_cell.address, []).append(leaf.address)
    return out


def virtual_to_physical_placement(
    virtual: Placement,
    bindings: Dict[api.CellAddress, PhysicalCell],
    leaf_cell_nums: List[int],
) -> Placement:
    """Translate a virtual placement into the physical placement using the
    leaf bindings picked by allocation (reference: types.go:262-283)."""
    physical: Placement = {}
    for n in leaf_cell_nums:
        physical[n] = [
            [bindings[leaf.address] for leaf in pod_placement]
            for pod_placement in virtual[n]
        ]
    return physical


class BindingPathVertex:
    """One vertex in the tree of virtual cells that still need physical
    bindings (reference: types.go:344-350)."""

    __slots__ = ("cell", "children_to_bind")

    def __init__(self, cell: VirtualCell):
        self.cell = cell
        self.children_to_bind: List["BindingPathVertex"] = []


def build_binding_paths(
    virtual: Placement,
    leaf_cell_nums: List[int],
    bindings: Dict[api.CellAddress, PhysicalCell],
) -> Tuple[List[BindingPathVertex], List[List[BindingPathVertex]]]:
    """Collect all unbound ancestors of the placement's leaf cells into
    binding-path trees (reference: types.go:285-342 ``toBindingPaths``).

    Returns (preassigned roots to buddy-alloc, groups of non-preassigned
    subtree roots whose parents are already bound).
    """
    preassigned: List[BindingPathVertex] = []
    non_preassigned: List[List[BindingPathVertex]] = []
    all_vertices: Dict[api.CellAddress, BindingPathVertex] = {}

    for n in leaf_cell_nums:
        for pod_placement in virtual[n]:
            for leaf in pod_placement:
                assert isinstance(leaf, VirtualCell)
                if leaf.physical_cell is not None:
                    # Already bound (e.g. pinned cells): just record it.
                    bindings[leaf.address] = leaf.physical_cell
                    continue
                # Walk up collecting unbound, unvisited ancestors.
                path: List[VirtualCell] = []
                c: Optional[Cell] = leaf
                while c is not None:
                    vc = c
                    assert isinstance(vc, VirtualCell)
                    if vc.physical_cell is not None or vc.address in all_vertices:
                        break
                    path.append(vc)
                    c = c.parent
                if not path:
                    continue
                root = path[-1]
                root_vertex = BindingPathVertex(root)
                all_vertices[root.address] = root_vertex
                parent = root.parent
                if parent is None:
                    preassigned.append(root_vertex)
                elif parent.physical_cell is not None:  # type: ignore[union-attr]
                    # Parent bound: group with buddies sharing that parent so
                    # they are mapped together under it.
                    for group in non_preassigned:
                        if cell_equal(parent, group[0].cell.parent):
                            group.append(root_vertex)
                            break
                    else:
                        non_preassigned.append([root_vertex])
                else:
                    all_vertices[parent.address].children_to_bind.append(root_vertex)
                # Wire the rest of the path under the root (top-down).
                for vc in reversed(path[:-1]):
                    vertex = BindingPathVertex(vc)
                    all_vertices[vc.parent.address].children_to_bind.append(vertex)
                    all_vertices[vc.address] = vertex
    return preassigned, non_preassigned
