"""hivedscheduler_tpu: a TPU-native HiveD.

A from-scratch Kubernetes scheduler extender that gang-schedules multi-host
Cloud TPU workloads with topology-guaranteed virtual-cluster quotas, in the
spirit of HiveD (OSDI '20; reference: Global19/hivedscheduler).

Where the reference's cell hierarchy models GPU/PCIe/NVLink/IB topology, ours
models the Cloud TPU ICI torus (chip -> 4-chip TPU-VM host -> cube -> full
slice); its buddy allocator hands out contiguous ICI sub-slices; and at bind
time it injects the ``jax.distributed`` environment (coordinator address,
worker ids, visible chips) into scheduled pods.

Layer map (mirrors reference SURVEY.md section 1):
  - ``common``:    generic utilities (codecs, logging)
  - ``api``:       public config/annotation schema, constants, status DTOs
  - ``algorithm``: the scheduling core (cells, placement, buddy alloc,
                   preemption state machine, VC safety)
  - ``scheduler``: the K8s bridge (pod state machine, assume/force bind)
  - ``webserver``: HTTP extender + inspect API
  - ``tpu``:       TPU topology presets and the JAX distributed env contract
  - ``models``/``ops``/``parallel``: TPU-first JAX workloads scheduled by the
                   framework (the five BASELINE.json configs)
"""

__version__ = "0.5.0"
