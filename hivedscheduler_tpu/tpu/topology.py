"""TPU topology presets: cell-type chains modeling the ICI torus.

The reference encodes communication domains as cell levels (PCIe switch, CPU
socket, node, IB domain — example/config/design/hivedscheduler.yaml:46-135).
Here the levels are the ICI torus decomposition of a Cloud TPU slice:

    chip (1) -> [forged sub-host levels] -> host (TPU VM, the K8s node)
             -> host groups (ICI-contiguous sub-slices) -> full slice

Cross-slice traffic rides DCN, which is exactly "different top-level cells".
The "forged hierarchy" trick (reference design config comment at
example/config/design/hivedscheduler.yaml:78-84) lets VCs request sub-host
chip fractions (1 or 2 chips of a 4-chip host).

Conventions used throughout this repo:
  - ``v5e`` hosts have 4 chips (2x2); ``v5p`` hosts have 4 chips (2x2x1).
  - Slice names count chips: ``v5p-64`` = 64 chips = 16 hosts (one 4x4x4
    cube); ``v5e-16`` = 16 chips = 4 hosts.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..api import types as api


def chip_type(generation: str) -> str:
    return f"{generation}-chip"


def host_type(generation: str) -> str:
    return f"{generation}-host"


def slice_type(generation: str, num_chips: int) -> str:
    return f"{generation}-{num_chips}"


def make_cell_types(
    generation: str,
    chips_per_host: int = 4,
    slice_host_counts: Sequence[int] = (),
    forge_sub_host: bool = True,
) -> Dict[str, api.CellTypeSpec]:
    """Build the cellTypes map for one TPU generation.

    ``slice_host_counts`` lists the host-group sizes to expose as cells, in
    increasing powers of the previous size (each level must divide the next);
    e.g. ``(4, 16)`` for v5p yields ``v5p-16`` (4 hosts, one ICI plane) and
    ``v5p-64`` (16 hosts, the 4x4x4 cube).
    """
    types: Dict[str, api.CellTypeSpec] = {}
    child = chip_type(generation)
    # Forged sub-host hierarchy: chip -> 2-chip -> ... -> host, so VCs can own
    # chip fractions of a host (ICI-adjacent pairs on the 2x2 host mesh).
    n = 1
    if forge_sub_host:
        while n * 2 < chips_per_host:
            n *= 2
            name = f"{generation}-{n}-chip"
            types[name] = api.CellTypeSpec(
                child_cell_type=child, child_cell_number=2, is_node_level=False
            )
            child = name
        types[host_type(generation)] = api.CellTypeSpec(
            child_cell_type=child,
            child_cell_number=chips_per_host // max(n, 1),
            is_node_level=True,
        )
    else:
        types[host_type(generation)] = api.CellTypeSpec(
            child_cell_type=child,
            child_cell_number=chips_per_host,
            is_node_level=True,
        )
    prev_type = host_type(generation)
    prev_hosts = 1
    for hosts in slice_host_counts:
        if hosts % prev_hosts != 0:
            raise api.bad_request(
                f"slice host counts must nest: {hosts} not a multiple of {prev_hosts}"
            )
        name = slice_type(generation, hosts * chips_per_host)
        types[name] = api.CellTypeSpec(
            child_cell_type=prev_type,
            child_cell_number=hosts // prev_hosts,
            is_node_level=False,
        )
        prev_type, prev_hosts = name, hosts
    return types


def make_physical_cell(
    cell_type: str,
    node_names: Sequence[str],
    pinned_cell_id: str = "",
) -> api.PhysicalCellSpec:
    """Build a physicalCells entry for one slice: the node-level descendants
    get the given K8s node names as addresses (in ICI order: worker 0..N-1 of
    the slice), everything else is inferred by api.config defaulting."""

    def build(levels_of_nodes: List[List[str]]) -> api.PhysicalCellSpec:
        raise NotImplementedError

    spec = api.PhysicalCellSpec(cell_type=cell_type, pinned_cell_id=pinned_cell_id)
    # We only need to pre-populate down to node level; address inference fills
    # the rest. Walk the type name structure lazily: callers pass exactly the
    # node names of the slice in worker order, and we build a skeleton of
    # nested children whose fan-out is resolved later by defaulting. To keep
    # this simple and explicit we require the caller to nest via
    # make_slice_children below when the slice is multi-host.
    if len(node_names) == 1:
        spec.cell_address = node_names[0]
    else:
        spec.cell_children = _nest_hosts(list(node_names))
    return spec


def _nest_hosts(node_names: List[str]) -> List[api.PhysicalCellSpec]:
    """Nest host names under 4-way groups, mirroring make_cell_types'
    host-group fan-out (each slice level groups 4 of the previous)."""
    if len(node_names) <= 4:
        return [api.PhysicalCellSpec(cell_address=n) for n in node_names]
    assert len(node_names) % 4 == 0
    group = len(node_names) // 4
    return [
        api.PhysicalCellSpec(cell_children=_nest_hosts(node_names[i * group:(i + 1) * group]))
        for i in range(4)
    ]


def v5e_cell_types(max_hosts: int = 4) -> Dict[str, api.CellTypeSpec]:
    """v5e chains: chip -> 2-chip -> host(4) -> v5e-16 (4 hosts) [-> v5e-64]."""
    counts = [c for c in (4, 16) if c <= max_hosts]
    return make_cell_types("v5e", chips_per_host=4, slice_host_counts=counts)


def v5p_cell_types(max_hosts: int = 16) -> Dict[str, api.CellTypeSpec]:
    """v5p chains: chip -> 2-chip -> host(4) -> v5p-16 (4 hosts, ICI plane)
    -> v5p-64 (16 hosts, the 4x4x4 cube)."""
    counts = [c for c in (4, 16) if c <= max_hosts]
    return make_cell_types("v5p", chips_per_host=4, slice_host_counts=counts)
