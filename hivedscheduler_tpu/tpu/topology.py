"""TPU topology presets: cell-type chains modeling the ICI torus.

The reference encodes communication domains as cell levels (PCIe switch, CPU
socket, node, IB domain — example/config/design/hivedscheduler.yaml:46-135).
Here the levels are the ICI torus decomposition of a Cloud TPU slice:

    chip (1) -> [forged sub-host levels] -> host (TPU VM, the K8s node)
             -> host groups (ICI-contiguous sub-slices) -> full slice

Cross-slice traffic rides DCN, which is exactly "different top-level cells".
The "forged hierarchy" trick (reference design config comment at
example/config/design/hivedscheduler.yaml:78-84) lets VCs request sub-host
chip fractions (1 or 2 chips of a 4-chip host).

Conventions used throughout this repo:
  - ``v5e`` hosts have 4 chips (2x2); ``v5p`` hosts have 4 chips (2x2x1).
  - Slice names count chips: ``v5p-64`` = 64 chips = 16 hosts (one 4x4x4
    cube); ``v5e-16`` = 16 chips = 4 hosts.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..api import types as api


def chip_type(generation: str) -> str:
    return f"{generation}-chip"


def host_type(generation: str) -> str:
    return f"{generation}-host"


def slice_type(generation: str, num_chips: int) -> str:
    return f"{generation}-{num_chips}"


def make_cell_types(
    generation: str,
    chips_per_host: int = 4,
    slice_host_counts: Sequence[int] = (),
    forge_sub_host: bool = True,
) -> Dict[str, api.CellTypeSpec]:
    """Build the cellTypes map for one TPU generation.

    ``slice_host_counts`` lists the host-group sizes to expose as cells, in
    increasing powers of the previous size (each level must divide the next);
    e.g. ``(4, 16)`` for v5p yields ``v5p-16`` (4 hosts, one ICI plane) and
    ``v5p-64`` (16 hosts, the 4x4x4 cube).
    """
    types: Dict[str, api.CellTypeSpec] = {}
    child = chip_type(generation)
    # Forged sub-host hierarchy: chip -> 2-chip -> ... -> host, so VCs can own
    # chip fractions of a host (ICI-adjacent pairs on the 2x2 host mesh).
    n = 1
    if forge_sub_host and chips_per_host & (chips_per_host - 1) != 0:
        # Forging halves repeatedly; a non-power-of-2 host would silently
        # lose chips, so fall back to a flat host cell.
        forge_sub_host = False
    if forge_sub_host:
        while n * 2 < chips_per_host:
            n *= 2
            name = f"{generation}-{n}-chip"
            types[name] = api.CellTypeSpec(
                child_cell_type=child, child_cell_number=2, is_node_level=False
            )
            child = name
        types[host_type(generation)] = api.CellTypeSpec(
            child_cell_type=child,
            child_cell_number=chips_per_host // max(n, 1),
            is_node_level=True,
        )
    else:
        types[host_type(generation)] = api.CellTypeSpec(
            child_cell_type=child,
            child_cell_number=chips_per_host,
            is_node_level=True,
        )
    prev_type = host_type(generation)
    prev_hosts = 1
    for hosts in slice_host_counts:
        if hosts % prev_hosts != 0:
            raise api.bad_request(
                f"slice host counts must nest: {hosts} not a multiple of {prev_hosts}"
            )
        name = slice_type(generation, hosts * chips_per_host)
        types[name] = api.CellTypeSpec(
            child_cell_type=prev_type,
            child_cell_number=hosts // prev_hosts,
            is_node_level=False,
        )
        prev_type, prev_hosts = name, hosts
    return types


def make_physical_cell(
    cell_type: str,
    node_names: Sequence[str],
    cell_types: Dict[str, api.CellTypeSpec],
    pinned_cell_id: str = "",
) -> api.PhysicalCellSpec:
    """Build a physicalCells entry for one slice: the node-level descendants
    get the given K8s node names as addresses (in ICI order: worker 0..N-1 of
    the slice), everything else is inferred by api.config defaulting.

    ``cell_types`` is the map the cluster is declared with; the host nesting
    follows its fan-outs exactly (a mismatch between node_names and the
    declared host count is an error, never silently truncated)."""
    spec = api.PhysicalCellSpec(cell_type=cell_type, pinned_cell_id=pinned_cell_id)
    # Collect the multi-node fan-outs from cell_type down to the node level.
    fan_outs: List[int] = []
    ct = cell_type
    while ct in cell_types and not cell_types[ct].is_node_level:
        fan_outs.append(cell_types[ct].child_cell_number)
        ct = cell_types[ct].child_cell_type
    expected_hosts = 1
    for f in fan_outs:
        expected_hosts *= f
    if expected_hosts != len(node_names):
        raise api.bad_request(
            f"{cell_type} contains {expected_hosts} hosts but "
            f"{len(node_names)} node names were given"
        )
    if not fan_outs:
        spec.cell_address = node_names[0]
    else:
        spec.cell_children = _nest_hosts(list(node_names), fan_outs)
    return spec


def _nest_hosts(
    node_names: List[str], fan_outs: Sequence[int]
) -> List[api.PhysicalCellSpec]:
    """Nest host names following the declared per-level fan-outs."""
    fan = fan_outs[0]
    if len(fan_outs) == 1:
        assert fan == len(node_names)
        return [api.PhysicalCellSpec(cell_address=n) for n in node_names]
    group = len(node_names) // fan
    return [
        api.PhysicalCellSpec(
            cell_children=_nest_hosts(
                node_names[i * group:(i + 1) * group], fan_outs[1:]
            )
        )
        for i in range(fan)
    ]


def v5e_cell_types(max_hosts: int = 4) -> Dict[str, api.CellTypeSpec]:
    """v5e chains: chip -> 2-chip -> host(4) -> v5e-16 (4 hosts) [-> v5e-64]."""
    counts = [c for c in (4, 16) if c <= max_hosts]
    return make_cell_types("v5e", chips_per_host=4, slice_host_counts=counts)


def v5p_cell_types(max_hosts: int = 16) -> Dict[str, api.CellTypeSpec]:
    """v5p chains: chip -> 2-chip -> host(4) -> v5p-16 (4 hosts, ICI plane)
    -> v5p-64 (16 hosts, the 4x4x4 cube)."""
    counts = [c for c in (4, 16) if c <= max_hosts]
    return make_cell_types("v5p", chips_per_host=4, slice_host_counts=counts)


def v6e_cell_types(max_hosts: int = 64) -> Dict[str, api.CellTypeSpec]:
    """v6e (Trillium) chains: chip -> 2-chip -> host(4, 2x2) -> v6e-16
    (4 hosts) -> v6e-64 (16 hosts) -> v6e-256 (64 hosts, the full 16x16
    torus — Trillium's largest single ICI domain; beyond 256 chips is
    multislice over DCN, i.e. separate top-level cells here)."""
    counts = [c for c in (4, 16, 64) if c <= max_hosts]
    return make_cell_types("v6e", chips_per_host=4, slice_host_counts=counts)


def v4_cell_types(max_hosts: int = 16) -> Dict[str, api.CellTypeSpec]:
    """v4 chains: chip -> 2-chip -> host(4) -> v4-16 (4 hosts) -> v4-64
    (16 hosts, one 4x4x4 cube) — the legacy-fleet generation, same host
    shape as v5p."""
    counts = [c for c in (4, 16) if c <= max_hosts]
    return make_cell_types("v4", chips_per_host=4, slice_host_counts=counts)
