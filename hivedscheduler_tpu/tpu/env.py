"""The TPU / jax.distributed environment contract emitted at bind time.

The reference's device-isolation mechanism is one env var derived from one
annotation (``pod-leaf-cell-isolation`` -> ``NVIDIA_VISIBLE_DEVICES``,
reference: pkg/internal/utils.go:172-186, doc/user-manual.md:159-192). A JAX
multi-host TPU gang needs more: every worker must agree on the coordinator
address, the process count, and its own process id — and the assignment must
be consistent across the gang even though each pod is bound independently.

This module derives that whole block deterministically from the group's bind
info (which every binding pod carries in full, since it doubles as the crash
-recovery record): workers are ordered by (node name, first chip index), so
any pod of the gang — or the recovered scheduler — computes the identical
assignment with no coordination (SURVEY.md §7.4 hard part 5).

Containers lift the annotation into env vars via an init container or a
downward-API volume, the way the reference maps its isolation annotation to
``NVIDIA_VISIBLE_DEVICES`` (doc/user-manual.md:164-186).
"""

from __future__ import annotations

import functools
import re
from typing import Dict, List, Tuple

from ..api import types as api

# The port worker 0 serves jax.distributed coordination on. Any free port
# works as long as the whole gang agrees; this one is JAX's conventional
# default for `jax.distributed.initialize`.
COORDINATOR_PORT = 8476


def _natural_key(name: str) -> Tuple:
    """Sort key treating digit runs as numbers: w2 < w10 (plain string sort
    would give w0, w1, w10, ..., w15, w2 — physically wrong worker order for
    slices with >= 10 hosts)."""
    return tuple(
        int(tok) if tok.isdigit() else tok for tok in re.split(r"(\d+)", name)
    )


def _worker_order(info: api.PodBindInfo) -> List[Tuple[str, Tuple[int, ...]]]:
    """All pod placements of the gang as (node, chip indices), in the
    deterministic worker order: sorted by (natural node name, first chip
    index).

    Node names sort in ICI order when slices are declared with
    ``tpu.topology.make_physical_cell`` (worker 0..N-1 addresses); the
    natural sort keeps that true past 10 hosts. Within a node, the lowest
    chip index breaks ties between sub-host pods.
    """
    placements: Tuple[Tuple[str, Tuple[int, ...]], ...] = tuple(
        (
            placement.physical_node,
            tuple(placement.physical_leaf_cell_indices),
        )
        for member in info.affinity_group_bind_info
        for placement in member.pod_placements
    )
    return list(_sorted_worker_order(placements))


@functools.lru_cache(maxsize=4096)
def _sorted_worker_order(
    placements: Tuple[Tuple[str, Tuple[int, ...]], ...]
) -> Tuple[Tuple[str, Tuple[int, ...]], ...]:
    """Content-keyed memo of the natural sort: every pod of a gang carries
    the identical placement list, so the O(n log n) ordering runs once per
    gang instead of once per pod per filter round."""
    return tuple(
        sorted(
            placements,
            key=lambda p: (_natural_key(p[0]), p[1][0] if p[1] else -1),
        )
    )


def pod_tpu_env(info: api.PodBindInfo) -> Dict[str, str]:
    """The env block for the pod bound by ``info``.

    Keys:
      - ``TPU_VISIBLE_CHIPS``: this host's chip indices granted to the pod
        (the TPU analog of the reference's device isolation).
      - ``TPU_WORKER_ID`` / ``JAX_PROCESS_ID``: this pod's rank in the gang.
      - ``TPU_WORKER_HOSTNAMES``: all gang hostnames in worker order.
      - ``JAX_COORDINATOR_ADDRESS``: worker 0's host:port.
      - ``JAX_NUM_PROCESSES``: gang size.
    """
    order = _worker_order(info)
    me = (info.node, tuple(info.leaf_cell_isolation))
    try:
        worker_id = order.index(me)
    except ValueError:
        raise api.internal_error(
            f"Pod placement {me} not found in its own affinity group bind "
            f"info; cannot derive a TPU worker id"
        )
    hostnames = [node for node, _ in order]
    return {
        "TPU_VISIBLE_CHIPS": ",".join(str(i) for i in info.leaf_cell_isolation),
        "TPU_WORKER_ID": str(worker_id),
        "JAX_PROCESS_ID": str(worker_id),
        "TPU_WORKER_HOSTNAMES": ",".join(hostnames),
        "JAX_COORDINATOR_ADDRESS": f"{hostnames[0]}:{COORDINATOR_PORT}",
        "JAX_NUM_PROCESSES": str(len(order)),
    }
