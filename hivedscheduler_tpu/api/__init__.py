"""Public API surface: config schema, annotations, constants, status DTOs.

Equivalent of the reference's ``pkg/api`` package.
"""

from . import constants  # noqa: F401
from .config import Config, config_fingerprint, load_config  # noqa: F401
from .types import *  # noqa: F401,F403
