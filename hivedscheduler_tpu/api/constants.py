"""Public constants: annotation keys, resource gate, priorities, REST paths.

Python equivalent of the reference's ``pkg/api/constants.go:34-94``, with the
GPU-era names replaced by TPU-era ones.
"""

COMPONENT_NAME = "hivedscheduler-tpu"
GROUP_NAME = "hivedscheduler.tpu.io"

UNLIMITED_VALUE = -1

# To leverage this scheduler, at least one container in the Pod must set this
# extended-resource limit to a positive value
# (reference: api/constants.go:42-43 ``ResourceNamePodSchedulingEnable``).
RESOURCE_NAME_POD_SCHEDULING_ENABLE = GROUP_NAME + "/pod-scheduling-enable"

# The Pod declares what it wants via this annotation, in PodSchedulingSpec
# YAML format (reference: api/constants.go:46).
ANNOTATION_POD_SCHEDULING_SPEC = GROUP_NAME + "/pod-scheduling-spec"

# Written at bind: the chips of the node granted to this pod, as a
# comma-separated index list. The container maps it to TPU chip isolation
# (e.g. TPU_VISIBLE_CHIPS / TPU_CHIPS_PER_HOST_BOUNDS) the way the reference
# maps its analog to NVIDIA_VISIBLE_DEVICES
# (reference: api/constants.go:50, doc/user-manual.md:159-192).
ANNOTATION_POD_LEAF_CELL_ISOLATION = GROUP_NAME + "/pod-leaf-cell-isolation"

# Written at bind: full placement record used for crash recovery, in
# PodBindInfo YAML format (reference: api/constants.go:53-55).
ANNOTATION_POD_BIND_INFO = GROUP_NAME + "/pod-bind-info"

# Written at bind (TPU-specific, no reference analog): the jax.distributed
# environment block for this pod, in YAML map format. Containers lift it into
# env vars via an init container or fieldRef so jax.distributed.initialize()
# works out of the box. See tpu/env.py.
ANNOTATION_POD_TPU_ENV = GROUP_NAME + "/pod-tpu-env"

# Written when a pod's affinity group starts preempting (no reference
# analog): the reserved placement in PodBindInfo YAML format, patched onto
# the (still unbound) preemptor pod so a scheduler restart can replay the
# Reserving/Reserved reservation instead of losing it. Cleared when the
# preemption completes or is cancelled; superseded by the bind-info
# annotation once the pod binds (doc/fault-model.md "Preemption plane").
ANNOTATION_POD_PREEMPT_INFO = GROUP_NAME + "/pod-preempt-info"

# Node annotation (hardware health plane): comma-separated chip indices the
# device plane reports BAD on this node (e.g. "1,3"). Absent/empty = all
# chips healthy. Per-chip node conditions of type
# "<GROUP_NAME>/chip-<index>" with status "False" mean the same thing; the
# scheduler merges both sources. Chip badness composes with node badness —
# a chip is bad while either holds — and is damped by the same flap gate.
ANNOTATION_NODE_DEVICE_HEALTH = GROUP_NAME + "/device-health"

# Node annotation (maintenance plane): drain request. "*" (or "all"/"true")
# cordons every chip on the node; a comma-separated index list ("0,2")
# drains just those chips. Draining cells take no NEW placements; running
# gangs keep their cells. Lifted when the annotation clears or the node is
# deleted. Never damped — drains are deliberate operator actions.
ANNOTATION_NODE_DRAIN = GROUP_NAME + "/drain"

# Pod annotation (elastic gang plane, doc/fault-model.md): the
# defragmenter's drain handshake. Written onto every pod of a gang the
# defragmenter proposes to migrate (JSON: proposal generation, the
# fragment being compacted, the nodes the re-placement must avoid). The
# workload controller checkpoints, deletes, and resubmits the gang; the
# re-filtered placement compacts the buddy hierarchy. Cleared when a
# proposal is cancelled. Advisory end to end — a gang that never reacts
# simply keeps its cells.
ANNOTATION_POD_DEFRAG_MIGRATION = GROUP_NAME + "/defrag-migration"

# The scheduler-owned ConfigMap persisting the advisory doomed-bad-cell
# ledger (which bad cell each VC's unsatisfiable quota is pinned to), so a
# restart reconstructs the same advisory bindings instead of re-deriving
# arbitrary ones (doc/fault-model.md "Reconfiguration plane").
DOOMED_LEDGER_CONFIG_MAP_NAME = "hivedscheduler-doomed-ledger"
DOOMED_LEDGER_CONFIG_MAP_KEY = "ledger"

# The scheduler-owned ConfigMap family persisting periodic state snapshots
# (the durable projection for O(delta) recovery; doc/fault-model.md "HA and
# snapshot recovery plane"). The manifest ConfigMap carries the meta header
# (schema version, checksum, chunk count) plus the first body chunk;
# payloads past the 1 MiB ConfigMap ceiling spill into
# "<name>-<i>" chunk ConfigMaps. The manifest is written LAST so a crash
# mid-write leaves the previous snapshot's manifest (or a checksum
# mismatch, which recovery treats as "no snapshot").
SNAPSHOT_CONFIG_MAP_NAME = "hivedscheduler-snapshot"
SNAPSHOT_META_KEY = "meta"
SNAPSHOT_CHUNK_KEY = "chunk"

# The coordination.k8s.io Lease for active-standby leader election: the
# leader renews it every leaseRenewSeconds; a standby acquires it
# leaseDurationSeconds after the leader's last renewal and takes over
# (recovering via snapshot + delta replay). A deposed leader refuses bind
# writes (doc/fault-model.md "HA and snapshot recovery plane").
LEADER_LEASE_NAME = "hivedscheduler-leader"

# Priority space (reference: api/constants.go:58-62).
MAX_GUARANTEED_PRIORITY = 1000
MIN_GUARANTEED_PRIORITY = 0
OPPORTUNISTIC_PRIORITY = -1

# REST paths (reference: api/constants.go:72-94).
ROOT_PATH = "/"
VERSION_PATH = ROOT_PATH + "v1"

EXTENDER_PATH = VERSION_PATH + "/extender"
FILTER_PATH = EXTENDER_PATH + "/filter"
BIND_PATH = EXTENDER_PATH + "/bind"
PREEMPT_PATH = EXTENDER_PATH + "/preempt"

INSPECT_PATH = VERSION_PATH + "/inspect"
AFFINITY_GROUPS_PATH = INSPECT_PATH + "/affinitygroups/"
# The live advisory doomed-bad ledger plus its persistence epochs (what is
# in memory vs what has landed in the ConfigMap).
DOOMED_LEDGER_PATH = INSPECT_PATH + "/doomedledger"
CLUSTER_STATUS_PATH = INSPECT_PATH + "/clusterstatus"
PHYSICAL_CLUSTER_PATH = CLUSTER_STATUS_PATH + "/physicalcluster"
VIRTUAL_CLUSTERS_PATH = CLUSTER_STATUS_PATH + "/virtualclusters/"

# Pods whose recovery replay failed (corrupt bind-info annotation, cells
# absent from the current config) are parked here instead of crashing
# recovery; see doc/fault-model.md.
QUARANTINE_PATH = INSPECT_PATH + "/quarantine"

# The hardware health plane: applied bad nodes/chips, maintenance drains,
# flap-damper state (held transitions), and stranded gangs (groups holding
# bad or draining cells). See doc/fault-model.md "Hardware health plane".
HEALTH_PATH = INSPECT_PATH + "/health"

# The decision journal (scheduler observability plane,
# doc/observability.md): latest-N scheduling decisions with per-gate
# rejection reasons; append /<uid> or /<namespace>/<name> for the per-pod
# lookup ("why didn't my pod schedule", doc/user-manual.md).
DECISIONS_PATH = INSPECT_PATH + "/decisions"

# The sampled request-trace ring (spans: filter -> lock wait -> core
# schedule -> placement descent -> bind write -> recovery cycles).
TRACES_PATH = INSPECT_PATH + "/traces"

# The black-box plane's flight recorder (scheduler.recorder,
# doc/observability.md "The black-box plane"): the current recording
# window — every mutating verb in the sim trace vocabulary, anchored on
# a snapshot export. ?full=1 serves the whole dumpable recording, which
# `python -m hivedscheduler_tpu.sim --replay-recording FILE` replays
# into a deterministic incident repro.
FLIGHTRECORDER_PATH = INSPECT_PATH + "/flightrecorder"

# The shadow what-if plane (scheduler.whatif, doc/user-manual.md "When
# will my pod schedule?"): POST a gang spec (or queue: true for the whole
# waiting queue, or capacityTrace for capacity planning) and get a
# structured forecast — predicted wait, victim set, blocking gate,
# confidence horizon — computed on a snapshot-forked shadow core.
WHATIF_PATH = INSPECT_PATH + "/whatif"

# The HA / snapshot recovery plane: leadership (identity, leader state,
# lease holder), the last recovery's mode (snapshot+delta vs full replay)
# and delta counts, and snapshot persistence state. See doc/fault-model.md
# "HA and snapshot recovery plane".
HA_PATH = INSPECT_PATH + "/ha"

# Prometheus text exposition (top-level, the conventional scrape path —
# NOT under /v1/inspect): counters, gauges, fixed-bucket latency
# histograms, and per-chain lock-wait series, served from lock-free
# snapshots so a scrape never enters the chain-lock order.
PROMETHEUS_PATH = "/metrics"

# Probe endpoints (no reference analog; the reference relies on the informer
# WaitForCacheSync ordering alone). /healthz is liveness (process up);
# /readyz gates on recovery completion so K8s does not route extender
# traffic to a scheduler still replaying bound pods.
HEALTHZ_PATH = "/healthz"
READYZ_PATH = "/readyz"
