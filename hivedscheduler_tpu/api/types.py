"""Public API types: config schema, pod annotation schema, status DTOs.

Python equivalent of the reference's ``pkg/api/types.go`` (config spec at
L42-76, pod spec at L78-99, bind info at L101-118, inspect DTOs at L121-224),
re-expressed as dataclasses with explicit YAML (de)serialization instead of
struct tags. Cell types here name TPU slices (e.g. ``v5p-chip``,
``v5e-host``) rather than GPUs, but the schema is deliberately kept
wire-compatible so existing HiveD configs port mechanically.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from . import constants

# Type aliases for readability (reference: api/types.go:35-39).
CellType = str
CellAddress = str
PinnedCellId = str
VirtualClusterName = str


class WebServerError(Exception):
    """An error carrying an HTTP status code
    (reference: api/types.go:124-137)."""

    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message

    def __repr__(self) -> str:
        return f"WebServerError(code={self.code}, message={self.message!r})"


def bad_request(message: str) -> WebServerError:
    return WebServerError(400, message)


def not_found(message: str) -> WebServerError:
    return WebServerError(404, message)


def internal_error(message: str) -> WebServerError:
    return WebServerError(500, message)


###############################################################################
# Physical cluster definition (reference: api/types.go:42-62)
###############################################################################

@dataclass
class CellTypeSpec:
    """One node of the cell-type forest. A type absent from the cellTypes map
    is a leaf cell type: a single TPU chip
    (reference: api/types.go:47-51)."""

    child_cell_type: CellType = ""
    child_cell_number: int = 0
    is_node_level: bool = False

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "CellTypeSpec":
        return CellTypeSpec(
            child_cell_type=d.get("childCellType", "") or "",
            child_cell_number=int(d.get("childCellNumber", 0) or 0),
            is_node_level=bool(d.get("isNodeLevel", False)),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "childCellType": self.child_cell_type,
            "childCellNumber": self.child_cell_number,
            "isNodeLevel": self.is_node_level,
        }


@dataclass
class PhysicalCellSpec:
    """A physical cell instance; node-level cells carry K8s node names as
    their address, leaf cells carry chip indices
    (reference: api/types.go:54-60)."""

    cell_type: CellType = ""
    cell_address: CellAddress = ""
    pinned_cell_id: PinnedCellId = ""
    cell_children: List["PhysicalCellSpec"] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "PhysicalCellSpec":
        d = d or {}
        return PhysicalCellSpec(
            cell_type=str(d.get("cellType", "") or ""),
            cell_address=str(d.get("cellAddress", "") or ""),
            pinned_cell_id=str(d.get("pinnedCellId", "") or ""),
            cell_children=[
                PhysicalCellSpec.from_dict(c) for c in (d.get("cellChildren") or [])
            ],
        )

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "cellType": self.cell_type,
            "cellAddress": self.cell_address,
        }
        if self.pinned_cell_id:
            d["pinnedCellId"] = self.pinned_cell_id
        if self.cell_children:
            d["cellChildren"] = [c.to_dict() for c in self.cell_children]
        return d


@dataclass
class PhysicalClusterSpec:
    """(reference: api/types.go:42-45)"""

    cell_types: Dict[CellType, CellTypeSpec] = field(default_factory=dict)
    physical_cells: List[PhysicalCellSpec] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "PhysicalClusterSpec":
        d = d or {}
        return PhysicalClusterSpec(
            cell_types={
                str(k): CellTypeSpec.from_dict(v or {})
                for k, v in (d.get("cellTypes") or {}).items()
            },
            physical_cells=[
                PhysicalCellSpec.from_dict(c) for c in (d.get("physicalCells") or [])
            ],
        )


###############################################################################
# Virtual cluster definition (reference: api/types.go:64-76)
###############################################################################

@dataclass
class VirtualCellSpec:
    """A VC quota entry: N cells of a (fully-qualified, dot-separated) type
    within a chain (reference: api/types.go:69-72; the dotted path is split in
    algorithm/config.go:370-373)."""

    cell_number: int = 0
    cell_type: CellType = ""

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "VirtualCellSpec":
        return VirtualCellSpec(
            cell_number=int(d.get("cellNumber", 0) or 0),
            cell_type=str(d.get("cellType", "") or ""),
        )


@dataclass
class PinnedCellSpec:
    """(reference: api/types.go:74-76)"""

    pinned_cell_id: PinnedCellId = ""

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "PinnedCellSpec":
        return PinnedCellSpec(pinned_cell_id=str(d.get("pinnedCellId", "") or ""))


@dataclass
class VirtualClusterSpec:
    """(reference: api/types.go:64-67)"""

    virtual_cells: List[VirtualCellSpec] = field(default_factory=list)
    pinned_cells: List[PinnedCellSpec] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "VirtualClusterSpec":
        d = d or {}
        return VirtualClusterSpec(
            virtual_cells=[
                VirtualCellSpec.from_dict(c) for c in (d.get("virtualCells") or [])
            ],
            pinned_cells=[
                PinnedCellSpec.from_dict(c) for c in (d.get("pinnedCells") or [])
            ],
        )


###############################################################################
# Pod scheduling spec (the request annotation)
# (reference: api/types.go:78-99)
###############################################################################

@dataclass
class AffinityGroupMemberSpec:
    """(reference: api/types.go:96-99)"""

    pod_number: int = 0
    leaf_cell_number: int = 0

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "AffinityGroupMemberSpec":
        return AffinityGroupMemberSpec(
            pod_number=int(d.get("podNumber", 0) or 0),
            leaf_cell_number=int(d.get("leafCellNumber", 0) or 0),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {"podNumber": self.pod_number, "leafCellNumber": self.leaf_cell_number}


@dataclass
class AffinityGroupSpec:
    """The gang: a named set of members, each ``pod_number`` pods wanting
    ``leaf_cell_number`` chips (reference: api/types.go:90-94).

    Elastic bounds (doc/fault-model.md "Elastic gang plane"): ``minMembers``
    is the total-pod-count floor the gang may SHRINK to when its hardware
    degrades (0 = inelastic: the gang is evicted whole, the pre-elastic
    behavior); ``maxMembers`` is the ceiling an opportunistic gang may GROW
    to when idle capacity frees (0 = fixed size). Both count pods across
    all members, and both are optional — absent keys keep the spec
    wire-compatible with GPU-era HiveD configs."""

    name: str = ""
    members: List[AffinityGroupMemberSpec] = field(default_factory=list)
    min_members: int = 0
    max_members: int = 0

    @property
    def total_members(self) -> int:
        return sum(m.pod_number for m in self.members)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "AffinityGroupSpec":
        spec = AffinityGroupSpec(
            name=str(d.get("name", "") or ""),
            members=[
                AffinityGroupMemberSpec.from_dict(m) for m in (d.get("members") or [])
            ],
            min_members=int(d.get("minMembers", 0) or 0),
            max_members=int(d.get("maxMembers", 0) or 0),
        )
        spec.validate_bounds()
        return spec

    def validate_bounds(self) -> None:
        """Reject malformed elastic bounds (user error, HTTP 400). Absent
        (zero) bounds are always legal — the inelastic default."""
        total = self.total_members
        if self.min_members < 0:
            raise bad_request(
                f"affinityGroup {self.name}: minMembers must be >= 0 "
                f"(0 = inelastic), got {self.min_members}"
            )
        if self.min_members:
            if self.min_members > total:
                raise bad_request(
                    f"affinityGroup {self.name}: minMembers "
                    f"({self.min_members}) exceeds the declared member "
                    f"count ({total})"
                )
        if self.max_members:
            if self.max_members < total:
                raise bad_request(
                    f"affinityGroup {self.name}: maxMembers "
                    f"({self.max_members}) is below the declared member "
                    f"count ({total})"
                )

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "members": [m.to_dict() for m in self.members],
        }
        if self.min_members:
            d["minMembers"] = self.min_members
        if self.max_members:
            d["maxMembers"] = self.max_members
        return d


@dataclass
class PodSchedulingSpec:
    """What a pod asks for via the pod-scheduling-spec annotation
    (reference: api/types.go:78-88). ``leaf_cell_type`` names a TPU chip
    generation (e.g. ``v5p-chip``); ``leaf_cell_number`` is chips per pod
    (on multi-host slices: chips on this pod's host, normally 4)."""

    virtual_cluster: VirtualClusterName = ""
    priority: int = 0
    pinned_cell_id: PinnedCellId = ""
    leaf_cell_type: str = ""
    leaf_cell_number: int = 0
    gang_release_enable: bool = False
    lazy_preemption_enable: bool = False
    ignore_k8s_suggested_nodes: bool = True
    affinity_group: Optional[AffinityGroupSpec] = None

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "PodSchedulingSpec":
        ag = d.get("affinityGroup")
        return PodSchedulingSpec(
            virtual_cluster=str(d.get("virtualCluster", "") or ""),
            priority=int(d.get("priority", 0) or 0),
            pinned_cell_id=str(d.get("pinnedCellId", "") or ""),
            leaf_cell_type=str(d.get("leafCellType", "") or ""),
            leaf_cell_number=int(d.get("leafCellNumber", 0) or 0),
            gang_release_enable=bool(d.get("gangReleaseEnable", False)),
            lazy_preemption_enable=bool(d.get("lazyPreemptionEnable", False)),
            ignore_k8s_suggested_nodes=bool(d.get("ignoreK8sSuggestedNodes", True)),
            affinity_group=AffinityGroupSpec.from_dict(ag) if ag else None,
        )

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "virtualCluster": self.virtual_cluster,
            "priority": self.priority,
            "leafCellType": self.leaf_cell_type,
            "leafCellNumber": self.leaf_cell_number,
            "gangReleaseEnable": self.gang_release_enable,
            "lazyPreemptionEnable": self.lazy_preemption_enable,
            "ignoreK8sSuggestedNodes": self.ignore_k8s_suggested_nodes,
        }
        if self.pinned_cell_id:
            d["pinnedCellId"] = self.pinned_cell_id
        if self.affinity_group is not None:
            d["affinityGroup"] = self.affinity_group.to_dict()
        return d


###############################################################################
# Pod bind info (the recovery annotation)
# (reference: api/types.go:101-118)
###############################################################################

@dataclass
class PodPlacementInfo:
    """(reference: api/types.go:112-118)"""

    physical_node: str = ""
    physical_leaf_cell_indices: List[int] = field(default_factory=list)
    # Preassigned cell type per leaf cell; used to re-locate virtual cells when
    # replaying an allocated pod after restart (reference: api/types.go:115-117).
    preassigned_cell_types: List[CellType] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "PodPlacementInfo":
        return PodPlacementInfo(
            physical_node=str(d.get("physicalNode", "") or ""),
            physical_leaf_cell_indices=[
                int(i) for i in (d.get("physicalLeafCellIndices") or [])
            ],
            preassigned_cell_types=[
                str(t) for t in (d.get("preassignedCellTypes") or [])
            ],
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "physicalNode": self.physical_node,
            "physicalLeafCellIndices": list(self.physical_leaf_cell_indices),
            "preassignedCellTypes": list(self.preassigned_cell_types),
        }


@dataclass
class AffinityGroupMemberBindInfo:
    """(reference: api/types.go:108-110)"""

    pod_placements: List[PodPlacementInfo] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "AffinityGroupMemberBindInfo":
        return AffinityGroupMemberBindInfo(
            pod_placements=[
                PodPlacementInfo.from_dict(p) for p in (d.get("podPlacements") or [])
            ]
        )

    def to_dict(self) -> Dict[str, Any]:
        return {"podPlacements": [p.to_dict() for p in self.pod_placements]}


@dataclass
class PodBindInfo:
    """Written into the pod-bind-info annotation at bind; the scheduler's only
    persistent state (reference: api/types.go:101-106)."""

    node: str = ""
    leaf_cell_isolation: List[int] = field(default_factory=list)
    cell_chain: str = ""
    affinity_group_bind_info: List[AffinityGroupMemberBindInfo] = field(
        default_factory=list
    )
    # Elastic gang plane (doc/fault-model.md): monotone per-group resize
    # generation. Every shrink/grow rewrites the group-level record and
    # bumps it; recovery replay reconciles pods carrying different
    # generations of the same group deterministically (newest wins). 0 =
    # never resized — the key is omitted on the wire, so pre-elastic bind
    # infos round-trip untouched.
    resize_generation: int = 0

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "PodBindInfo":
        return PodBindInfo(
            node=str(d.get("node", "") or ""),
            leaf_cell_isolation=[int(i) for i in (d.get("leafCellIsolation") or [])],
            cell_chain=str(d.get("cellChain", "") or ""),
            affinity_group_bind_info=[
                AffinityGroupMemberBindInfo.from_dict(m)
                for m in (d.get("affinityGroupBindInfo") or [])
            ],
            resize_generation=int(d.get("resizeGeneration", 0) or 0),
        )

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "node": self.node,
            "leafCellIsolation": list(self.leaf_cell_isolation),
            "cellChain": self.cell_chain,
            "affinityGroupBindInfo": [
                m.to_dict() for m in self.affinity_group_bind_info
            ],
        }
        if self.resize_generation:
            d["resizeGeneration"] = self.resize_generation
        return d


###############################################################################
# Inspect API DTOs (reference: api/types.go:140-224). Plain dicts are used on
# the wire; these helpers build them.
###############################################################################

# Affinity group states surfaced by the inspect API
# (reference: algorithm/constants.go group states).
GROUP_STATE_ALLOCATED = "Allocated"
GROUP_STATE_PREEMPTING = "Preempting"
GROUP_STATE_BEING_PREEMPTED = "BeingPreempted"

CELL_HEALTHY = "Healthy"
CELL_BAD = "Bad"


def deep_copy_status(obj: Any) -> Any:
    """Inspect handlers must never leak internal mutable state
    (reference: api/types.go:227-273 deepCopy methods)."""
    return copy.deepcopy(obj)


__all__ = [
    "CellType",
    "CellAddress",
    "PinnedCellId",
    "VirtualClusterName",
    "WebServerError",
    "bad_request",
    "not_found",
    "internal_error",
    "CellTypeSpec",
    "PhysicalCellSpec",
    "PhysicalClusterSpec",
    "VirtualCellSpec",
    "PinnedCellSpec",
    "VirtualClusterSpec",
    "AffinityGroupMemberSpec",
    "AffinityGroupSpec",
    "PodSchedulingSpec",
    "PodPlacementInfo",
    "AffinityGroupMemberBindInfo",
    "PodBindInfo",
    "GROUP_STATE_ALLOCATED",
    "GROUP_STATE_PREEMPTING",
    "GROUP_STATE_BEING_PREEMPTED",
    "CELL_HEALTHY",
    "CELL_BAD",
    "deep_copy_status",
    "constants",
]
