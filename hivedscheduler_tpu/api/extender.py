"""K8s Scheduler Extender wire types.

The default scheduler speaks the scheduler-extender HTTP protocol; these are
the request/response DTOs for the filter/bind/preempt verbs, matching the
upstream wire format (capitalized JSON keys) the reference consumes via its
vendored ``k8s.io/kubernetes/pkg/scheduler/api`` package
(reference: pkg/webserver/webserver.go:167-240 decodes/encodes these).

Pods arrive as (a subset of) K8s Pod JSON; :func:`pod_from_k8s` projects that
onto our internal :class:`~hivedscheduler_tpu.scheduler.types.Pod` the way the
reference's ``internal.ToPod`` casts informer objects.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..scheduler.types import Pod


def pod_from_k8s(obj: Dict[str, Any]) -> Pod:
    """Project K8s Pod JSON onto the internal Pod model.

    Reads metadata.{name,namespace,uid,annotations}, spec.nodeName,
    status.phase, and the per-container extended-resource limits used by the
    scheduling-enable gate (reference: pkg/internal/utils.go:115-140).
    """
    metadata = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    limits: Dict[str, int] = {}
    for container in spec.get("containers") or []:
        resources = (container.get("resources") or {}).get("limits") or {}
        for name, quantity in resources.items():
            try:
                limits[name] = limits.get(name, 0) + int(quantity)
            except (TypeError, ValueError):
                continue
    return Pod(
        name=str(metadata.get("name", "") or ""),
        namespace=str(metadata.get("namespace") or "default"),
        uid=str(metadata.get("uid", "") or ""),
        annotations={
            str(k): str(v) for k, v in (metadata.get("annotations") or {}).items()
        },
        node_name=str(spec.get("nodeName", "") or ""),
        phase=str(status.get("phase") or "Pending"),
        resource_limits=limits,
    )


def pod_to_k8s(pod: Pod) -> Dict[str, Any]:
    """Inverse of :func:`pod_from_k8s` (round-trips the fields we model)."""
    return {
        "metadata": {
            "name": pod.name,
            "namespace": pod.namespace,
            "uid": pod.uid,
            "annotations": dict(pod.annotations),
        },
        "spec": {
            "nodeName": pod.node_name,
            "containers": [
                {
                    "resources": {
                        "limits": {k: v for k, v in pod.resource_limits.items()}
                    }
                }
            ],
        },
        "status": {"phase": pod.phase},
    }


@dataclass
class ExtenderArgs:
    """POST body of /v1/extender/filter."""

    pod: Pod
    node_names: List[str] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ExtenderArgs":
        return ExtenderArgs(
            pod=pod_from_k8s(d.get("Pod") or {}),
            node_names=[str(n) for n in (d.get("NodeNames") or [])],
        )

    def to_dict(self) -> Dict[str, Any]:
        return {"Pod": pod_to_k8s(self.pod), "NodeNames": list(self.node_names)}


@dataclass
class ExtenderFilterResult:
    """Response of /v1/extender/filter: either the nodes that fit, or a map
    node->reason of nodes that failed (the reference also abuses FailedNodes
    to surface wait reasons, scheduler.go:573-585)."""

    node_names: Optional[List[str]] = None
    failed_nodes: Dict[str, str] = field(default_factory=dict)
    error: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {
            "NodeNames": self.node_names,
            "FailedNodes": dict(self.failed_nodes),
            "Error": self.error,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ExtenderFilterResult":
        return ExtenderFilterResult(
            node_names=(
                [str(n) for n in d["NodeNames"]] if d.get("NodeNames") is not None
                else None
            ),
            failed_nodes={
                str(k): str(v) for k, v in (d.get("FailedNodes") or {}).items()
            },
            error=str(d.get("Error", "") or ""),
        )


@dataclass
class ExtenderBindingArgs:
    """POST body of /v1/extender/bind."""

    pod_name: str = ""
    pod_namespace: str = "default"
    pod_uid: str = ""
    node: str = ""

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ExtenderBindingArgs":
        return ExtenderBindingArgs(
            pod_name=str(d.get("PodName", "") or ""),
            pod_namespace=str(d.get("PodNamespace") or "default"),
            pod_uid=str(d.get("PodUID", "") or ""),
            node=str(d.get("Node", "") or ""),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "PodName": self.pod_name,
            "PodNamespace": self.pod_namespace,
            "PodUID": self.pod_uid,
            "Node": self.node,
        }


@dataclass
class ExtenderBindingResult:
    error: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {"Error": self.error}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ExtenderBindingResult":
        return ExtenderBindingResult(error=str(d.get("Error", "") or ""))


@dataclass
class MetaPod:
    uid: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {"UID": self.uid}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "MetaPod":
        return MetaPod(uid=str(d.get("UID", "") or ""))


@dataclass
class MetaVictims:
    pods: List[MetaPod] = field(default_factory=list)
    num_pdb_violations: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "Pods": [p.to_dict() for p in self.pods],
            "NumPDBViolations": self.num_pdb_violations,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "MetaVictims":
        return MetaVictims(
            pods=[MetaPod.from_dict(p) for p in (d.get("Pods") or [])],
            num_pdb_violations=int(d.get("NumPDBViolations") or 0),
        )


@dataclass
class ExtenderPreemptionArgs:
    """POST body of /v1/extender/preempt. The default scheduler proposes
    candidate victims per node; the extender answers with the victims it
    actually needs (reference: scheduler.go:629-721)."""

    pod: Pod = field(default_factory=lambda: Pod(name=""))
    node_name_to_meta_victims: Dict[str, MetaVictims] = field(default_factory=dict)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ExtenderPreemptionArgs":
        return ExtenderPreemptionArgs(
            pod=pod_from_k8s(d.get("Pod") or {}),
            node_name_to_meta_victims={
                str(node): MetaVictims.from_dict(v)
                for node, v in (d.get("NodeNameToMetaVictims") or {}).items()
            },
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "Pod": pod_to_k8s(self.pod),
            "NodeNameToMetaVictims": {
                node: v.to_dict()
                for node, v in self.node_name_to_meta_victims.items()
            },
        }


@dataclass
class ExtenderPreemptionResult:
    node_name_to_meta_victims: Dict[str, MetaVictims] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "NodeNameToMetaVictims": {
                node: v.to_dict()
                for node, v in self.node_name_to_meta_victims.items()
            }
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ExtenderPreemptionResult":
        return ExtenderPreemptionResult(
            node_name_to_meta_victims={
                str(node): MetaVictims.from_dict(v)
                for node, v in (d.get("NodeNameToMetaVictims") or {}).items()
            }
        )
