"""Scheduler config: load, default, and infer full physical cell addresses.

Python equivalent of the reference's ``pkg/api/config.go``: the Config schema
(L39-85), pointer-based defaulting (L87-118), and the recursive physical-cell
address inference (L120-167). Reconfiguration follows the reference's
restart-based model (``WatchConfig`` exits the process on change,
api/config.go:202-217): we expose :func:`config_fingerprint` so a supervisor
(or our webserver loop) can detect change and exit for the work-preserving
restart path.
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, field
from typing import Dict, Optional

from .. import common
from . import types as api


@dataclass
class Config:
    """(reference: api/config.go:39-85)"""

    kube_apiserver_address: Optional[str] = None
    kube_config_file_path: Optional[str] = None
    # Default ":9096" (reference: api/config.go:100-101).
    webserver_address: str = ":9096"
    # After this many failed bind attempts, force-bind directly
    # (reference: api/config.go:100-102, default 3).
    force_pod_bind_threshold: int = 3
    # FIFO-vs-throughput knob (reference: api/config.go:71-77, default 0).
    waiting_pod_scheduling_block_ms: int = 0
    # Per-request deadline budget for the extender handlers (no reference
    # analog): caps the RetryingKubeClient backoff schedule so a stuck bind
    # cannot hold an HTTP worker for the full retry budget
    # (doc/fault-model.md). 0 disables the cap.
    request_deadline_seconds: float = 30.0
    # Hardware health plane (doc/fault-model.md "Hardware health plane").
    # Flap damping: once a node/chip health target has flapped
    # `health_flap_threshold` times within `health_flap_window` health
    # ticks, further transitions are HELD until `health_flap_hold` quiet
    # ticks pass, then the latest desired state applies (a settled
    # transition is never lost). Event-clocked — one tick per informer
    # relist / watch-cycle end (health_tick), NOT per observation, so the
    # window is cluster-size-independent and chaos schedules stay
    # deterministic. Threshold 0 disables damping.
    health_flap_threshold: int = 3
    health_flap_window: int = 8
    health_flap_hold: int = 4
    # Stranded-gang remediation policy: when True, gangs holding bad or
    # draining cells are lazily evicted (their pods deleted through the
    # kube client) once the underlying health transition has settled;
    # when False (default) they are only surfaced (/v1/inspect/health,
    # strandedGroupCount).
    stranded_gang_eviction: bool = False
    # Elastic gang plane (doc/fault-model.md "Elastic gang plane"). When
    # stranded remediation is armed (stranded_gang_eviction) and a stranded
    # gang declares a minMembers bound, elastic_gang_shrink releases exactly
    # the stranded members' cells (annotation rewrite + targeted eviction)
    # instead of deleting the whole gang. True by default: shrink is
    # strictly less destructive than the eviction it replaces, and it only
    # ever applies to gangs that opted in via minMembers.
    elastic_gang_shrink: bool = True
    # Background defragmenter (off by default): every
    # defrag_interval_ticks health ticks, scan the buddy free lists for
    # mergeable fragments and propose checkpoint-coordinated migrations of
    # the blocking gangs, at most defrag_max_migrations_per_cycle per
    # cycle (the rate limit; migrations are advisory until the workload
    # controller completes the drain handshake).
    defrag_enable: bool = False
    defrag_interval_ticks: int = 8
    defrag_max_migrations_per_cycle: int = 1
    # Wall-clock settling floor for the flap damper (doc/fault-model.md
    # "Hardware health plane"): when > 0, a held transition whose target
    # stayed quiet for this many wall-clock seconds settles even without
    # `health_flap_hold` event ticks — a quiet cluster (no informer
    # relist/watch-cycle traffic) settles promptly. 0 (default) keeps the
    # event clock exclusively authoritative, which chaos schedules need
    # for determinism.
    health_flap_hold_seconds: float = 0.0
    # Observability plane (doc/observability.md): bounded ring sizes for
    # the decision journal (/v1/inspect/decisions — always on) and the
    # sampled trace ring (/v1/inspect/traces; the sampling RATE is the
    # HIVED_TRACE_SAMPLE env knob, not config — it must be flippable on a
    # live process without a config rollout).
    decision_journal_capacity: int = 512
    trace_ring_capacity: int = 256
    # Pending-pod plane (doc/hot-path.md "Pending-pod plane"): bound on
    # the negative-filter (WAIT) cache — distinct waiting spec identities
    # whose rejection certificates are kept so an unchanged re-filter is
    # answered by one version-vector compare instead of a placement
    # descent. 0 disables the cache (as does the HIVED_WAIT_CACHE=0 env
    # hatch, which needs no config rollout).
    wait_cache_capacity: int = 4096
    # Black-box plane (doc/observability.md "The black-box plane"): the
    # live invariant auditor's event-clock cadence — every N mutating
    # verbs the chaos invariants run over the live core under a brief
    # global section (0 disables; HIVED_LIVE_AUDIT=0 and
    # HIVED_AUDIT_INTERVAL_TICKS are the no-rollout env hatches) — and
    # the flight recorder's bounded verb-ring capacity per window
    # (0 disables; HIVED_FLIGHT_RECORDER=0 likewise).
    audit_interval_ticks: int = 256
    flight_recorder_capacity: int = 2048
    # HA / snapshot recovery plane (doc/fault-model.md "HA and snapshot
    # recovery plane"). snapshot_interval_seconds > 0 arms the background
    # snapshot flusher (HivedScheduler.start_snapshot_flusher) that
    # serializes the durable projection to the scheduler-owned ConfigMap
    # family every interval; 0 (default) disables periodic snapshots
    # (recovery then always replays annotations — the pre-snapshot
    # behavior). The Lease knobs govern active-standby failover: the
    # leader renews the coordination.k8s.io Lease every
    # lease_renew_seconds and is deposed lease_duration_seconds after its
    # last successful renewal.
    snapshot_interval_seconds: float = 0.0
    # Durable-state plane v2 (doc/fault-model.md). The flusher's export
    # gate skips while preempt churn is live; past
    # snapshot_max_staleness_seconds a refused flush arms a forced retry
    # at the next quiet point (0 disables the override). The store knobs
    # select where chunks persist: "configmap" (default, the PR 7 chunk
    # family) or "file" (the object-store backend, scheduler.store —
    # write-new-then-flip manifest pointer under snapshot_store_path, no
    # 1MiB cap, generation GC keeping the last
    # snapshot_store_gc_generations). snapshot_scrub_interval_beats > 0
    # arms the continuous integrity scrubber (scheduler.scrub) every that
    # many flusher beats; HIVED_SNAPSHOT_SCRUB=0 is the no-rollout hatch.
    snapshot_max_staleness_seconds: float = 0.0
    snapshot_store_backend: str = "configmap"
    snapshot_store_path: str = ""
    snapshot_store_gc_generations: int = 3
    snapshot_scrub_interval_beats: int = 4
    lease_duration_seconds: float = 15.0
    lease_renew_seconds: float = 5.0
    # Multi-process scheduling core (doc/hot-path.md "The multi-process
    # contract"): > 0 shards the core by chain family into that many
    # worker processes behind the webserver; 0 (default) serves the
    # in-process sharded scheduler exactly as before. The
    # HIVED_PROC_SHARDS env knob overrides at launch.
    proc_shards: int = 0
    # Shard supervision plane (doc/fault-model.md "Shard supervision
    # plane", proc shards only): the heartbeat cadence of the
    # liveness/resurrection pass (0 disables the thread; detection via
    # pipe EOF / verb deadlines still works), and the restart-storm
    # bounds — resurrection attempt N backs off
    # min(cap, base * 2^(N-1)) seconds, and the circuit breaker degrades
    # the shard to "down" after max consecutive failures.
    shard_supervision_interval_seconds: float = 5.0
    shard_max_resurrection_failures: int = 3
    shard_resurrection_backoff_seconds: float = 1.0
    shard_resurrection_backoff_cap_seconds: float = 30.0
    # Control-plane weather plane (doc/fault-model.md "Control-plane
    # weather plane"): the apiserver outage detector's sliding
    # failure-rate window per verb class, the consecutive-failure count
    # that escalates to blackout, the consecutive-success count that
    # clears back, and the bound on the write-behind intent journal that
    # absorbs durable writes during a blackout (overflow drops OLDEST,
    # latest-wins per object key).
    weather_window: int = 32
    weather_blackout_after: int = 8
    weather_clear_after: int = 3
    intent_journal_capacity: int = 512
    physical_cluster: api.PhysicalClusterSpec = field(
        default_factory=api.PhysicalClusterSpec
    )
    virtual_clusters: Dict[api.VirtualClusterName, api.VirtualClusterSpec] = field(
        default_factory=dict
    )

    @staticmethod
    def from_dict(d: dict) -> "Config":
        fpbt = d.get("forcePodBindThreshold")
        wait_ms = d.get("waitingPodSchedulingBlockMilliSec")
        deadline_s = d.get("requestDeadlineSeconds")
        flap_t = d.get("healthFlapThreshold")
        flap_w = d.get("healthFlapWindow")
        flap_h = d.get("healthFlapHold")
        flap_hs = d.get("healthFlapHoldSeconds")
        dj_cap = d.get("decisionJournalCapacity")
        tr_cap = d.get("traceRingCapacity")
        wc_cap = d.get("waitCacheCapacity")
        snap_s = d.get("snapshotIntervalSeconds")
        snap_stale = d.get("snapshotMaxStalenessSeconds")
        store_be = d.get("snapshotStoreBackend")
        store_path = d.get("snapshotStorePath")
        store_gc = d.get("snapshotStoreGcGenerations")
        scrub_b = d.get("snapshotScrubIntervalBeats")
        lease_d = d.get("leaseDurationSeconds")
        lease_r = d.get("leaseRenewSeconds")
        procs = d.get("procShards")
        sup_s = d.get("shardSupervisionIntervalSeconds")
        sup_f = d.get("shardMaxResurrectionFailures")
        sup_b = d.get("shardResurrectionBackoffSeconds")
        sup_c = d.get("shardResurrectionBackoffCapSeconds")
        defrag_t = d.get("defragIntervalTicks")
        defrag_m = d.get("defragMaxMigrationsPerCycle")
        audit_t = d.get("auditIntervalTicks")
        fr_cap = d.get("flightRecorderCapacity")
        wx_win = d.get("weatherWindow")
        wx_black = d.get("weatherBlackoutAfter")
        wx_clear = d.get("weatherClearAfter")
        ij_cap = d.get("intentJournalCapacity")
        c = Config(
            kube_apiserver_address=d.get("kubeApiServerAddress"),
            kube_config_file_path=d.get("kubeConfigFilePath"),
            webserver_address=d.get("webServerAddress") or ":9096",
            # Explicit 0 must survive defaulting (reference preserves it via
            # pointer-nil defaulting, api/config.go:100-102).
            force_pod_bind_threshold=3 if fpbt is None else int(fpbt),
            waiting_pod_scheduling_block_ms=0 if wait_ms is None else int(wait_ms),
            request_deadline_seconds=(
                30.0 if deadline_s is None else float(deadline_s)
            ),
            health_flap_threshold=3 if flap_t is None else int(flap_t),
            health_flap_window=8 if flap_w is None else int(flap_w),
            health_flap_hold=4 if flap_h is None else int(flap_h),
            health_flap_hold_seconds=(
                0.0 if flap_hs is None else float(flap_hs)
            ),
            stranded_gang_eviction=bool(d.get("strandedGangEviction", False)),
            elastic_gang_shrink=bool(d.get("elasticGangShrink", True)),
            defrag_enable=bool(d.get("defragEnable", False)),
            defrag_interval_ticks=8 if defrag_t is None else int(defrag_t),
            defrag_max_migrations_per_cycle=(
                1 if defrag_m is None else int(defrag_m)
            ),
            decision_journal_capacity=(
                512 if dj_cap is None else int(dj_cap)
            ),
            trace_ring_capacity=256 if tr_cap is None else int(tr_cap),
            wait_cache_capacity=4096 if wc_cap is None else int(wc_cap),
            audit_interval_ticks=256 if audit_t is None else int(audit_t),
            flight_recorder_capacity=(
                2048 if fr_cap is None else int(fr_cap)
            ),
            snapshot_interval_seconds=(
                0.0 if snap_s is None else float(snap_s)
            ),
            snapshot_max_staleness_seconds=(
                0.0 if snap_stale is None else float(snap_stale)
            ),
            snapshot_store_backend=(
                "configmap" if store_be is None else str(store_be)
            ),
            snapshot_store_path=(
                "" if store_path is None else str(store_path)
            ),
            snapshot_store_gc_generations=(
                3 if store_gc is None else int(store_gc)
            ),
            snapshot_scrub_interval_beats=(
                4 if scrub_b is None else int(scrub_b)
            ),
            lease_duration_seconds=(
                15.0 if lease_d is None else float(lease_d)
            ),
            lease_renew_seconds=5.0 if lease_r is None else float(lease_r),
            proc_shards=0 if procs is None else int(procs),
            shard_supervision_interval_seconds=(
                5.0 if sup_s is None else float(sup_s)
            ),
            shard_max_resurrection_failures=(
                3 if sup_f is None else int(sup_f)
            ),
            shard_resurrection_backoff_seconds=(
                1.0 if sup_b is None else float(sup_b)
            ),
            shard_resurrection_backoff_cap_seconds=(
                30.0 if sup_c is None else float(sup_c)
            ),
            weather_window=32 if wx_win is None else int(wx_win),
            weather_blackout_after=(
                8 if wx_black is None else int(wx_black)
            ),
            weather_clear_after=3 if wx_clear is None else int(wx_clear),
            intent_journal_capacity=(
                512 if ij_cap is None else int(ij_cap)
            ),
            physical_cluster=api.PhysicalClusterSpec.from_dict(
                d.get("physicalCluster")
            ),
            virtual_clusters={
                str(name): api.VirtualClusterSpec.from_dict(spec)
                for name, spec in (d.get("virtualClusters") or {}).items()
            },
        )
        default_physical_cells(c.physical_cluster)
        return c


def load_config(path: Optional[str] = None) -> Config:
    """Read the YAML config file; path defaults to ``$CONFIG`` then
    ``./hivedscheduler.yaml`` (reference: api/constants.go:65,
    api/config.go:188-200)."""
    path = path or os.environ.get("CONFIG", "./hivedscheduler.yaml")
    with open(path) as f:
        raw = common.from_yaml(f.read()) or {}
    return Config.from_dict(raw)


def config_fingerprint(path: str) -> str:
    """Content hash used by the restart-based reconfiguration loop
    (reference semantics: api/config.go:202-217 exits on content change)."""
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def default_physical_cells(pc: api.PhysicalClusterSpec) -> None:
    """Fill in every omitted cellType/cellAddress in the physical cell specs
    (reference: api/config.go:120-133 ``defaultingPhysicalCells``)."""
    for idx, spec in enumerate(pc.physical_cells):
        if spec.cell_type not in pc.cell_types:
            raise api.bad_request(
                f"physicalCells contains unknown cellType: {spec.cell_type}"
            )
        _infer_cell_spec(spec, pc.cell_types, spec.cell_type, idx, "")


def _infer_cell_spec(
    spec: api.PhysicalCellSpec,
    cell_types: Dict[api.CellType, api.CellTypeSpec],
    cell_type: api.CellType,
    default_address: int,
    address_prefix: str,
) -> None:
    """Recursive address inference (reference: api/config.go:134-167):

    - omitted ``cellType`` inherits from the parent's child type;
    - omitted ``cellAddress`` defaults to the cell's index-derived position;
    - node-level types reset the running index so leaf addresses restart at 0
      within each node (chip indices are per-host on TPU VMs);
    - provided addresses are still prefixed with the parent path so every cell
      gets a full, unique address.
    """
    if not spec.cell_type:
        spec.cell_type = cell_type
    if not spec.cell_address:
        spec.cell_address = address_prefix + str(default_address)
    else:
        spec.cell_address = address_prefix + spec.cell_address

    ct = cell_types.get(cell_type)
    if ct is None:
        # Leaf cell type: a single TPU chip, no children to infer.
        return
    if ct.is_node_level:
        default_address = 0
    if ct.child_cell_number > 0 and not spec.cell_children:
        spec.cell_children = [
            api.PhysicalCellSpec() for _ in range(ct.child_cell_number)
        ]
    for i, child in enumerate(spec.cell_children):
        _infer_cell_spec(
            child,
            cell_types,
            ct.child_cell_type,
            default_address * ct.child_cell_number + i,
            spec.cell_address + "/",
        )
