"""HTTP web server: the scheduler-extender endpoints + the inspect REST API.

Python equivalent of the reference's ``pkg/webserver/webserver.go`` (L46-300):
JSON decode/validate of extender args, dispatch to the framework's routines,
inspect handlers with deep-copied status, and error→HTTP mapping (the
reference recovers webserver panics and maps WebServerError to its code,
webserver.go:136-165; everything else becomes a 500).

Uses the stdlib ThreadingHTTPServer — the request handlers themselves
serialize on the framework's scheduler lock, matching the reference's
concurrency contract (scheduler.go:104-108).
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

from .. import common
from ..api import constants, extender as ei, types as api
from ..scheduler import kube as kube_mod, wire as wire_mod
from ..scheduler.framework import HivedScheduler
from . import prometheus

# Latency metrics + the per-phase filter breakdown (lockWait / coreSchedule /
# leafCellSearch), the per-chain lock-wait split (lockWaitByChain — the
# sharded scheduler lock, doc/hot-path.md "The lock-sharding contract"),
# and the concurrent-core counters (gangAdmissionBatchedCount /
# preemptProbeIncrementalCount); served from the same inspect tree as the
# cluster-status endpoints. The inspect status endpoints below serve
# MIRRORED per-chain status objects (rebuilt only for chains whose
# mutation epoch moved), so a scrape under load no longer holds the lock
# for a full-tree walk.
METRICS_PATH = constants.INSPECT_PATH + "/metrics"


class WebServer:
    """(reference: webserver/webserver.go:46-91)"""

    def __init__(self, scheduler: HivedScheduler, address: Optional[str] = None):
        self.scheduler = scheduler
        addr = address if address is not None else scheduler.config.webserver_address
        host, _, port = addr.rpartition(":")
        self.host = host or "0.0.0.0"
        self.port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------ #
    # Lifecycle (reference: webserver.go:93-134 AsyncRun)
    # ------------------------------------------------------------------ #

    def start(self) -> None:
        handler = _make_handler(self.scheduler)
        self._httpd = ThreadingHTTPServer((self.host, self.port), handler)
        # Report the actually-bound port (port 0 picks a free one — used by
        # the tests and the simulator).
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        common.log.info(
            "%s webserver listening on %s:%d",
            constants.COMPONENT_NAME, self.host, self.port,
        )

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


def _make_handler(scheduler: HivedScheduler):
    class Handler(BaseHTTPRequestHandler):
        # HTTP/1.1 keep-alive: the default scheduler reuses its extender
        # connection (Go net/http does); HTTP/1.0's close-per-request would
        # add a TCP setup to every filter call. Every reply sets
        # Content-Length, which 1.1 requires.
        protocol_version = "HTTP/1.1"
        # TCP_NODELAY: a request/response protocol on a keep-alive
        # connection is the textbook Nagle + delayed-ACK interaction —
        # without it each small write can stall ~40-200 ms waiting for the
        # peer's ACK (measured: wire p50 inflated 3.9 ms -> 174 ms on a
        # delayed-ACK kernel). Go's net/http (the reference's server and
        # the kube-scheduler client) sets it by default.
        disable_nagle_algorithm = True

        # Silence per-request stderr lines; structured logging happens in the
        # routines themselves.
        def log_message(self, fmt, *args):  # noqa: N802
            common.log.debug("webserver: " + fmt, *args)

        # -------------------------------------------------------------- #
        # Plumbing
        # -------------------------------------------------------------- #

        def _drain_body(self) -> bytes:
            """Read the full request body. MUST run before any reply on a
            POST: with HTTP/1.1 keep-alive, unread body bytes stay in the
            stream and the NEXT request on the connection is parsed
            starting at them (found by review: a 404 on an unknown path
            desynced every subsequent request of the connection)."""
            length = int(self.headers.get("Content-Length") or 0)
            return self.rfile.read(length) if length > 0 else b""

        def _parse_json(self, body: bytes) -> Dict:
            if not body:
                raise api.bad_request("Empty request body")
            try:
                return json.loads(body)
            except json.JSONDecodeError as e:
                raise api.bad_request(f"Failed to unmarshal request body: {e}")

        def _reply(self, code: int, payload: Dict) -> None:
            data = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _reply_raw(self, data: bytes) -> None:
            """200 with pre-encoded filter bytes: JSON from the legacy
            path, a wire frame when the request was one (the content
            type tells the client which decoder to reach for)."""
            self.send_response(200)
            self.send_header(
                "Content-Type",
                wire_mod.CONTENT_TYPE
                if wire_mod.is_wire(data)
                else "application/json",
            )
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _reply_error(self, e: Exception) -> None:
            """(reference: webserver.go:136-165 panic→HTTP mapping)"""
            if isinstance(e, api.WebServerError):
                self._reply(e.code, {"code": e.code, "message": e.message})
            else:
                common.log.exception("webserver handler error")
                self._reply(500, {"code": 500, "message": str(e)})

        # -------------------------------------------------------------- #
        # Extender verbs (reference: webserver.go:167-240)
        # -------------------------------------------------------------- #

        def do_POST(self) -> None:  # noqa: N802
            path = self.path.rstrip("/") or "/"
            body = self._drain_body()  # always, before any reply (keep-alive)
            # Arm this worker thread's deadline budget: kube writes issued
            # while serving the request (bind, preempt-info checkpoint)
            # refuse backoff sleeps that would cross it, so a stuck
            # apiserver cannot hold the worker for the full retry schedule
            # (requestDeadlineExceededCount counts early give-ups).
            budget = scheduler.config.request_deadline_seconds
            if budget > 0:
                kube_mod.set_request_deadline(budget)
            try:
                if path == constants.FILTER_PATH:
                    # Binary extender frames (scheduler.wire): a client
                    # that sent a wire frame gets a wire-framed reply (the
                    # raw JSON result bytes as one BYTES payload); a
                    # version-byte mismatch maps to HTTP 415 so the client
                    # re-sends legacy JSON and latches wire off — the
                    # lossless cross-version fallback.
                    wire_body = wire_mod.is_wire(body)
                    raw = getattr(scheduler, "filter_raw", None)
                    if raw is not None:
                        # Multi-process frontend (scheduler.shards): the
                        # filter body is routed and forwarded as raw
                        # bytes; decode/encode happen in the worker so
                        # this thread's GIL share stays O(1) per call.
                        try:
                            data = raw(body)
                        except wire_mod.WireVersionError as e:
                            raise api.WebServerError(
                                415, f"wire version mismatch: {e}"
                            )
                        self._reply_raw(data)
                        return
                    if wire_body:
                        try:
                            d = wire_mod.loads(
                                body, kind=wire_mod.KIND_OBJ
                            )
                        except wire_mod.WireVersionError as e:
                            raise api.WebServerError(
                                415, f"wire version mismatch: {e}"
                            )
                        except wire_mod.WireError as e:
                            raise api.bad_request(
                                f"Failed to unmarshal wire frame: {e}"
                            )
                        args = ei.ExtenderArgs.from_dict(d)
                    else:
                        args = ei.ExtenderArgs.from_dict(
                            self._parse_json(body)
                        )
                    # Errors inside filter must be reported in-band in the
                    # Error field so the default scheduler sees them
                    # (reference: serveFilterPath recovers to
                    # ExtenderFilterResult{Error}).
                    try:
                        result = scheduler.filter_routine(args)
                    except api.WebServerError as e:
                        result = ei.ExtenderFilterResult(error=e.message)
                    if wire_body:
                        # One TAG_JSON payload: the encoder json.dumps's
                        # it at C speed and the client's json_passthrough
                        # slices the JSON bytes back out without a frame
                        # walk.
                        self._reply_raw(wire_mod.dumps(
                            wire_mod.Json(result.to_dict())
                        ))
                    else:
                        self._reply(200, result.to_dict())
                elif path == constants.BIND_PATH:
                    args2 = ei.ExtenderBindingArgs.from_dict(
                        self._parse_json(body)
                    )
                    try:
                        result2 = scheduler.bind_routine(args2)
                    except api.WebServerError as e:
                        result2 = ei.ExtenderBindingResult(error=e.message)
                    self._reply(200, result2.to_dict())
                elif path == constants.PREEMPT_PATH:
                    args3 = ei.ExtenderPreemptionArgs.from_dict(
                        self._parse_json(body)
                    )
                    # Preempt has no in-band Error field; protocol errors map
                    # to HTTP status codes.
                    result3 = scheduler.preempt_routine(args3)
                    self._reply(200, result3.to_dict())
                elif path == constants.WHATIF_PATH:
                    # Shadow what-if plane (scheduler.whatif): forecasts
                    # run on a snapshot fork, never on live state (the
                    # read-only audit raises otherwise); a transient
                    # projection maps to 503 — retry.
                    payload = scheduler.whatif_routine(
                        self._parse_json(body)
                    )
                    self._reply(200, payload)
                else:
                    raise api.not_found(f"Cannot found resource: {self.path}")
            except Exception as e:  # noqa: BLE001
                self._reply_error(e)
            finally:
                kube_mod.clear_request_deadline()

        # -------------------------------------------------------------- #
        # Inspect API (reference: webserver.go:242-300)
        # -------------------------------------------------------------- #

        def _reply_text(self, code: int, body: str) -> None:
            data = body.encode()
            self.send_response(code)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self) -> None:  # noqa: N802
            try:
                split = urllib.parse.urlsplit(self.path)
                if split.path == constants.PROMETHEUS_PATH:
                    # Prometheus text exposition: served from the
                    # LOCK-FREE metrics snapshot — a scrape never enters
                    # the chain-lock order (doc/observability.md).
                    self._reply_text(
                        200,
                        prometheus.render(scheduler.get_metrics()),
                    )
                    return
                payload = self._route_get(split.path, split.query)
                self._reply(200, payload)
            except Exception as e:  # noqa: BLE001
                self._reply_error(e)

        def _route_get(self, path: str, query: str = ""):
            agp = constants.AFFINITY_GROUPS_PATH
            vcp = constants.VIRTUAL_CLUSTERS_PATH
            dcp = constants.DECISIONS_PATH
            if path == constants.HEALTHZ_PATH:
                # Liveness: the process serves HTTP. (Readiness is separate:
                # a recovering scheduler is alive but must not get traffic.)
                return {"status": "ok"}
            if path == constants.READYZ_PATH:
                # Readiness = leadership AND recovery completion: a warm
                # standby (or a deposed leader) is alive but must receive
                # no extender traffic — K8s routes to the active leader
                # only (doc/fault-model.md "HA and snapshot recovery
                # plane").
                if not scheduler.is_leader():
                    raise api.WebServerError(
                        503, "standby: not the leader (lease held elsewhere)"
                    )
                if not scheduler.is_ready():
                    raise api.WebServerError(
                        503, "recovering: initial cluster replay in progress"
                    )
                return {"status": "ready"}
            if path == constants.HA_PATH:
                return scheduler.get_ha()
            if path == constants.QUARANTINE_PATH:
                return scheduler.get_quarantine()
            if path == dcp or path == dcp + "/":
                # ?verdict= / ?gate= slice the journal server-side
                # (?verdict=wait&gate=vcQuota), composing with ?n=.
                return scheduler.get_decisions(
                    _query_n(query),
                    _query_str(query, "verdict"),
                    _query_str(query, "gate"),
                )
            if path.startswith(dcp + "/"):
                # Per-pod lookup: uid, or namespace/name (may contain "/").
                return scheduler.get_decision(path[len(dcp) + 1:])
            if path == constants.TRACES_PATH:
                return scheduler.get_traces(_query_n(query))
            if path == constants.FLIGHTRECORDER_PATH:
                # The black-box flight recorder: summary by default,
                # ?full=1 for the whole replayable recording.
                return scheduler.get_flightrecorder(
                    _query_str(query, "full") == "1"
                )
            if path == constants.DOOMED_LEDGER_PATH:
                return scheduler.get_doomed_ledger()
            if path == constants.HEALTH_PATH:
                return scheduler.get_health()
            if path == agp or path == agp.rstrip("/"):
                return scheduler.get_all_affinity_groups()
            if path.startswith(agp):
                name = path[len(agp):].strip("/")
                return scheduler.get_affinity_group(name)
            if path == constants.PHYSICAL_CLUSTER_PATH:
                return scheduler.get_physical_cluster_status()
            if path == vcp or path == vcp.rstrip("/"):
                return scheduler.get_all_virtual_clusters_status()
            if path.startswith(vcp):
                name = path[len(vcp):].strip("/")
                return scheduler.get_virtual_cluster_status(name)
            if path == constants.CLUSTER_STATUS_PATH:
                return scheduler.get_cluster_status()
            if path == METRICS_PATH:
                return scheduler.get_metrics()
            if path == constants.VERSION_PATH or path == constants.ROOT_PATH:
                return {
                    "component": constants.COMPONENT_NAME,
                    "version": _version(),
                }
            raise api.not_found(f"Cannot found resource: {path}")

    return Handler


def _query_str(query: str, key: str) -> Optional[str]:
    """One string query parameter (the ?verdict= / ?gate= / ?full=
    knobs); absent or malformed degrades to None — a diagnostic read
    never errors on its own query string."""
    try:
        values = urllib.parse.parse_qs(query or "").get(key)
        return str(values[0]) if values else None
    except (ValueError, TypeError, IndexError):
        return None


def _query_n(query: str) -> Optional[int]:
    """The latest-N knob (?n=) of the ring endpoints; malformed values
    degrade to "everything" rather than erroring a diagnostic read."""
    try:
        values = urllib.parse.parse_qs(query or "").get("n")
        return int(values[0]) if values else None
    except (ValueError, TypeError):
        return None


def _version() -> str:
    from .. import __version__

    return __version__
