"""Prometheus text exposition (format version 0.0.4) for the scheduler.

Renders ``HivedScheduler.get_metrics()`` — which is LOCK-FREE by contract
(it never enters the chain-lock order; see framework.get_metrics) — into
the text format Prometheus scrapes at ``/metrics``:

- every JSON counter/gauge as a ``hived_*`` metric (the REGISTRY below is
  the single authoritative key→name mapping);
- the fixed-bucket latency histograms (filter / preempt verb / bind write
  / recovery replay) as conventional ``_bucket``/``_sum``/``_count``
  families;
- the per-chain lock-wait breakdown and the per-phase accumulators as
  labeled series.

The registry is deliberately explicit rather than reflective: the golden
metrics-schema test (tests/test_observability.py) asserts BOTH directions
— every registry entry appears in doc/observability.md, and every numeric
key ``get_metrics`` emits is either registered or consciously excluded —
so a counter added in code without documentation (or vice versa) fails CI
instead of silently drifting.
"""

from __future__ import annotations

from typing import Dict, List

PREFIX = "hived_"

# snapshot key -> (metric name, TYPE, HELP). Counters are monotonic since
# process start; gauges are instantaneous.
COUNTERS: Dict[str, tuple] = {
    "filterCount": ("hived_filter_requests_total", "filter verb calls"),
    "bindCount": ("hived_filter_bind_total", "filter calls ending in an assume-bind"),
    "preemptCount": ("hived_filter_preempt_total", "filter calls proposing preemption"),
    "waitCount": ("hived_filter_wait_total", "filter calls ending in a wait"),
    "fastWaitCount": ("hived_filter_fast_waits_total", "filter calls answered from the negative-filter (WAIT) cache with one version-vector compare"),
    "bindRetryCount": ("hived_bind_retries_total", "bind kube-write retries"),
    "bindGiveUpCount": ("hived_bind_give_ups_total", "bind writes that exhausted retries"),
    "bindTerminalFailureCount": ("hived_bind_terminal_failures_total", "bind writes failed terminally (404/409)"),
    "quarantineCount": ("hived_quarantines_total", "bound pods quarantined during recovery replay"),
    "requestDeadlineExceededCount": ("hived_request_deadline_exceeded_total", "kube retry rounds cut short by the request deadline"),
    "doomedLedgerPersistCount": ("hived_doomed_ledger_persists_total", "successful doomed-ledger ConfigMap writes"),
    "doomedLedgerPersistFailureCount": ("hived_doomed_ledger_persist_failures_total", "failed doomed-ledger ConfigMap writes"),
    "doomedLedgerCoalescedCount": ("hived_doomed_ledger_coalesced_total", "doomed-epoch bumps coalesced into one ledger write"),
    "preemptionRecoveredCount": ("hived_preemptions_recovered_total", "preempting groups replayed at restart"),
    "preemptionCancelledOnRecoveryCount": ("hived_preemptions_cancelled_on_recovery_total", "preemption reservations cancelled at restart"),
    "healthTransitionCount": ("hived_health_transitions_total", "health transitions applied to the core"),
    "healthDampedCount": ("hived_health_damped_total", "health observations held by the flap damper"),
    "healthSettledCount": ("hived_health_settled_total", "held health transitions later settled"),
    "nodeEventNoopCount": ("hived_node_event_noops_total", "node update events skipped by the unchanged-projection fast path"),
    "strandedEvictionCount": ("hived_stranded_evictions_total", "pods evicted by stranded-gang remediation"),
    "gangAdmissionBatchedCount": ("hived_gang_admissions_batched_total", "pods admitted through the decode-free gang admission path"),
    "preemptProbeIncrementalCount": ("hived_preempt_probes_incremental_total", "preempt probes served from the epoch-gated victims cache"),
    "traceSampledCount": ("hived_traces_sampled_total", "requests sampled into the trace ring"),
    "mappingRetryCount": ("hived_mapping_retries_total", "guaranteed schedules that succeeded after retrying past a failed virtual-to-physical mapping"),
    "snapshotPersistCount": ("hived_snapshot_persists_total", "successful snapshot ConfigMap writes"),
    "snapshotPersistFailureCount": ("hived_snapshot_persist_failures_total", "failed snapshot ConfigMap writes"),
    "snapshotFallbackCount": ("hived_snapshot_fallbacks_total", "recoveries that fell back from an unusable snapshot to full annotation replay"),
    "snapshotSectionFallbackCount": ("hived_snapshot_section_fallbacks_total", "chain-family sections refused at recovery (checksum or doom-gate) whose chains replayed from annotations while healthy sections restored wholesale (durable-state plane v2)"),
    "scrubRunCount": ("hived_scrub_runs_total", "integrity-scrub passes over the durable snapshot (event-clocked on flusher/standby beats at snapshotScrubIntervalBeats)"),
    "scrubDivergenceCount": ("hived_scrub_divergences_total", "scrub passes that found the durable envelope diverged from live state (unusable, corrupt sections, or doomed-set drift; counted + journaled under _scrub + black-box bundle dumped — should stay 0)"),
    "scrubRepairCount": ("hived_scrub_repairs_total", "scrub divergences repaired (leader: durable snapshot rewritten from the live projection; standby: pre-applied projection discarded and re-prefetched)"),
    "deposedBindRefusedCount": ("hived_deposed_bind_refusals_total", "bind writes refused because this process no longer holds the leader lease"),
    "gangShrinkCount": ("hived_gang_shrinks_total", "stranded gangs shrunk in place instead of evicted (elastic gang plane)"),
    "gangShrinkAbortCount": ("hived_gang_shrink_aborts_total", "shrinks aborted and rolled back (survivor annotation patch failed or the gang changed mid-flight)"),
    "gangGrowCount": ("hived_gang_grows_total", "opportunistic gangs grown into idle capacity"),
    "defragProposalCount": ("hived_defrag_proposals_total", "defragmenter migration proposals issued (drain handshake started)"),
    "defragMigrationCount": ("hived_defrag_migrations_total", "defragmenter migrations completed (gang re-placed off its fragment)"),
    "defragCancelCount": ("hived_defrag_cancels_total", "defragmenter proposals cancelled, their advisory reservation released"),
    "whatifForecastCount": ("hived_whatif_forecasts_total", "what-if forecast requests served (shadow what-if plane)"),
    "whatifForecastGangCount": ("hived_whatif_forecast_gangs_total", "per-gang forecasts produced across all what-if requests"),
    "whatifForkCount": ("hived_whatif_forks_total", "shadow scheduler forks built from the live projection"),
    "whatifAuditViolationCount": ("hived_whatif_audit_violations_total", "shadow-forecast threads caught attempting a LIVE-state mutation by the read-only-fork audit (should stay 0)"),
    "auditRunCount": ("hived_audit_runs_total", "live invariant-auditor passes over the live core (black-box plane, event-clocked at auditIntervalTicks)"),
    "auditViolationCount": ("hived_audit_violations_total", "live-audit invariant violations (counted + journaled + black-box bundle dumped; the scheduler keeps serving — should stay 0)"),
    "flightRecorderEventCount": ("hived_flightrecorder_events_total", "mutating verbs captured by the flight recorder since process start"),
    "flightRecorderReanchorCount": ("hived_flightrecorder_reanchors_total", "flight-recorder windows re-anchored on a fresh snapshot export (ring wrap or post-recovery)"),
    "deltaSuggestedResyncCount": ("hived_delta_suggested_resyncs_total", "delta-encoded suggested-set frames a worker refused (base mismatch or integrity check) and the frontend resynced with a full list (one wire plane; should stay near 0)"),
    "shardRestartCount": ("hived_shard_restarts_total", "shard workers hot-resurrected by the supervision plane (crash/hang detected, worker respawned and recovered from its partition slot)"),
    "shardDegradedWaitCount": ("hived_shard_degraded_waits_total", "filter requests answered WAIT with the shardDown gate because their owning shard was down or resurrecting"),
    "shardDownFastWaitCount": ("hived_shard_down_fast_waits_total", "degraded shardDown WAITs answered from the frontend fast-WAIT cache with one epoch compare instead of a decision-journal write (self-invalidated by resurrection's epoch bump)"),
    "intentJournaledCount": ("hived_intent_journaled_total", "durable writes absorbed into the write-behind intent journal because their retry budget exhausted during an apiserver blackout (control-plane weather plane)"),
    "intentSupersededCount": ("hived_intent_superseded_total", "journaled intents replaced latest-wins by a newer intent for the same object before draining"),
    "intentCoalescedCount": ("hived_intent_coalesced_total", "annotation-patch intents merge-coalesced into an already-journaled patch for the same pod"),
    "intentDrainedCount": ("hived_intent_drained_total", "journaled intents successfully written through after the weather cleared and leadership was re-confirmed"),
    "intentDroppedCount": ("hived_intent_dropped_total", "oldest journaled intents dropped because the bounded journal overflowed (should stay 0; raise intentJournalCapacity)"),
    "intentDiscardedCount": ("hived_intent_discarded_total", "journaled intents discarded by the superseded-leader fence (another lease holder observed; the new leader owns the durable state)"),
    "outageBindRefusedCount": ("hived_outage_bind_refusals_total", "bind writes refused retriably (503 apiserverOutage) because the apiserver weather was blackout"),
    "outageWaitCount": ("hived_outage_waits_total", "filter requests answered WAIT with the apiserverOutage gate during an apiserver blackout (served off the in-memory projection)"),
}

GAUGES: Dict[str, tuple] = {
    "quarantinedPodCount": ("hived_quarantined_pods", "bound pods currently quarantined"),
    "strandedGroupCount": ("hived_stranded_groups", "gangs currently holding bad or draining cells"),
    "badNodeCount": ("hived_bad_nodes", "nodes currently marked bad"),
    "badChipCount": ("hived_bad_chips", "chips currently marked bad (device-health plane)"),
    "drainingChipCount": ("hived_draining_chips", "chips currently draining (maintenance plane)"),
    "healthPendingCount": ("hived_health_pending_transitions", "health transitions currently held by the flap damper"),
    "ready": ("hived_ready", "1 once recovery completed (readyz), else 0"),
    "leader": ("hived_leader", "1 while this process holds (or needs no) leader lease, else 0"),
    "snapshotImportedPodCount": ("hived_snapshot_imported_pods", "bound pods bulk-imported from the snapshot at the last recovery"),
    "snapshotDeltaPodCount": ("hived_snapshot_delta_pods", "pods replayed or released as deltas past the snapshot at the last recovery"),
    "snapshotAgeSeconds": ("hived_snapshot_age_seconds", "seconds since the last durable snapshot flush landed (-1 before the first flush; alert on this against snapshotMaxStalenessSeconds)"),
    "whatifForkPodCount": ("hived_whatif_fork_pods", "pods restored into the most recent shadow fork"),
    "whatifForkAgeSeconds": ("hived_whatif_fork_age_seconds", "seconds since the most recent shadow fork was built (forecast staleness; -1 before the first fork)"),
    "whatifForecastSeconds": ("hived_whatif_forecast_seconds", "wall seconds of the most recent what-if forecast (fork + replay)"),
    "apiserverWeather": ("hived_apiserver_weather", "apiserver weather verdict: 0 clear, 1 brownout (elevated failure rate), 2 blackout (durable writes journaled, binds refused retriably)"),
    "apiserverWeatherEpoch": ("hived_apiserver_weather_epoch", "monotone weather-transition epoch (bumped on every overall state change; apiserverOutage WAIT certificates pin it)"),
    "intentJournalDepth": ("hived_intent_journal_depth", "intents currently parked in the write-behind journal awaiting drain"),
}

# get_metrics keys -> histogram family names.
HISTOGRAMS: Dict[str, tuple] = {
    "filter": ("hived_filter_latency_seconds", "filter verb end-to-end latency"),
    "preempt": ("hived_preempt_latency_seconds", "preempt verb end-to-end latency"),
    "bind": ("hived_bind_write_latency_seconds", "bind kube write latency (incl. retry backoff)"),
    "recoveryReplay": ("hived_recovery_replay_latency_seconds", "per-pod recovery replay latency"),
}

# Labeled series rendered from structured snapshot values.
LABELED: Dict[str, str] = {
    "hived_lock_wait_seconds_total": "per-chain lock wait (chain label; '*global*' aggregates global-mode holders)",
    "hived_lock_acquisitions_total": "per-chain lock acquisitions (chain label)",
    "hived_phase_seconds_total": "per-phase accumulated time (phase label: lockWait, coreSchedule, leafCellSearch)",
    "hived_phase_ops_total": "per-phase operation count (phase label)",
    "hived_boot_phase_seconds": "boot wall seconds per phase (phase label: compile, healthInit, nodeAdd, fingerprint, recovery) — a gauge of the LAST boot, so standby cold-start is observable, not inferred",
    "hived_build_info": "constant-1 gauge whose labels identify the running deploy: snapshotSchema, configFingerprint (12-hex prefix), shards, and the hatch states (lazyVc, waitCache, nodeEventFastpath, liveAudit, flightRecorder)",
    "hived_wire_bytes_total": "per-codec internal-transport bytes (codec label: binary, pickle, json) — shard pipe/ring frames plus the frontend's HTTP filter envelope; zeros in a single-process deploy (one wire plane)",
    "hived_shard_up": "per-shard liveness gauge (shard label): 1 while the worker is up, 0 while it is resurrecting or degraded to down (shard supervision plane; absent in a single-process deploy)",
}

# JSON-snapshot keys that are deliberately NOT exported to Prometheus:
# derived presentation values (windowed percentiles — Prometheus derives
# quantiles from the histograms), structured sub-objects rendered as
# labeled/histogram families above, and non-numeric mode flags.
EXCLUDED_KEYS = {
    "filterLatencyP50Ms",   # windowed percentile; use the histogram
    "filterLatencyP99Ms",   # windowed percentile; use the histogram
    "phases",               # rendered as hived_phase_* labeled series
    "lockWaitByChain",      # rendered as hived_lock_* labeled series
    "latencyHistograms",    # rendered as hived_*_latency_seconds
    "lockSharding",         # string mode flag ("chains"/"global")
    "recoveryMode",         # string mode flag ("none"/"full"/"snapshot+delta"/"snapshot+partial")
    "bootPhaseSeconds",     # rendered as the hived_boot_phase_seconds gauge
    "buildInfo",            # rendered as the hived_build_info labeled gauge
    "wireBytesTotal",       # rendered as the hived_wire_bytes_total labeled counter
    "shardWire",            # JSON-only transport detail (frame histogram)
    "shardUp",              # rendered as the hived_shard_up labeled gauge
    "shardsDown",           # JSON-only attribution list (non-numeric)
}


def metric_names() -> List[str]:
    """Every family name this renderer can emit (the code-side truth the
    golden schema test diffs against doc/observability.md)."""
    names = [name for name, _ in COUNTERS.values()]
    names += [name for name, _ in GAUGES.values()]
    names += [name for name, _ in HISTOGRAMS.values()]
    names += list(LABELED)
    return sorted(names)


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _fmt(value: float) -> str:
    # Integers render bare; floats keep full precision minus trailing noise.
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, int):
        return str(value)
    return repr(float(value))


def render(snapshot: Dict) -> str:
    """The text exposition body for one ``get_metrics()`` snapshot."""
    lines: List[str] = []

    def header(name: str, mtype: str, help_text: str) -> None:
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")

    for key, (name, help_text) in COUNTERS.items():
        if key not in snapshot:
            continue
        header(name, "counter", help_text)
        lines.append(f"{name} {_fmt(snapshot[key])}")

    for key, (name, help_text) in GAUGES.items():
        if key not in snapshot:
            continue
        header(name, "gauge", help_text)
        lines.append(f"{name} {_fmt(snapshot[key])}")

    for key, (name, help_text) in HISTOGRAMS.items():
        hist = snapshot.get("latencyHistograms", {}).get(key)
        if hist is None:
            continue
        header(name, "histogram", help_text)
        for le, cum in hist["buckets"]:
            lines.append(f'{name}_bucket{{le="{_fmt(le)}"}} {cum}')
        lines.append(f'{name}_bucket{{le="+Inf"}} {hist["count"]}')
        lines.append(f"{name}_sum {_fmt(hist['sum'])}")
        lines.append(f"{name}_count {hist['count']}")

    # Headers are emitted even with no samples yet, so the families are
    # discoverable on a fresh scheduler.
    waits = snapshot.get("lockWaitByChain", {})
    header(
        "hived_lock_wait_seconds_total", "counter",
        LABELED["hived_lock_wait_seconds_total"],
    )
    for chain, entry in sorted(waits.items()):
        lines.append(
            'hived_lock_wait_seconds_total{chain="%s"} %s'
            % (_escape_label(chain), _fmt(entry["totalMs"] / 1e3))
        )
    header(
        "hived_lock_acquisitions_total", "counter",
        LABELED["hived_lock_acquisitions_total"],
    )
    for chain, entry in sorted(waits.items()):
        lines.append(
            'hived_lock_acquisitions_total{chain="%s"} %s'
            % (_escape_label(chain), _fmt(entry["count"]))
        )

    wire = snapshot.get("wireBytesTotal")
    if wire is not None:
        header(
            "hived_wire_bytes_total", "counter",
            LABELED["hived_wire_bytes_total"],
        )
        for codec, total in sorted(wire.items()):
            lines.append(
                'hived_wire_bytes_total{codec="%s"} %s'
                % (_escape_label(codec), _fmt(int(total)))
            )

    build = snapshot.get("buildInfo")
    if build:
        header("hived_build_info", "gauge", LABELED["hived_build_info"])
        labels = ",".join(
            f'{k}="{_escape_label(v)}"' for k, v in sorted(build.items())
        )
        lines.append("hived_build_info{%s} 1" % labels)

    # Header always (family discoverability, like the lock series); rows
    # only under proc shards — a single process has no shards to gauge.
    header("hived_shard_up", "gauge", LABELED["hived_shard_up"])
    for sid, up in sorted(
        (snapshot.get("shardUp") or {}).items(), key=lambda kv: int(kv[0])
    ):
        lines.append(
            'hived_shard_up{shard="%s"} %s'
            % (_escape_label(sid), _fmt(int(up)))
        )

    boot = snapshot.get("bootPhaseSeconds", {})
    header(
        "hived_boot_phase_seconds", "gauge",
        LABELED["hived_boot_phase_seconds"],
    )
    for phase, seconds in sorted(boot.items()):
        lines.append(
            'hived_boot_phase_seconds{phase="%s"} %s'
            % (_escape_label(phase), _fmt(float(seconds)))
        )

    phases = snapshot.get("phases", {})
    header(
        "hived_phase_seconds_total", "counter",
        LABELED["hived_phase_seconds_total"],
    )
    for phase, entry in sorted(phases.items()):
        lines.append(
            'hived_phase_seconds_total{phase="%s"} %s'
            % (_escape_label(phase), _fmt(entry["totalMs"] / 1e3))
        )
    header(
        "hived_phase_ops_total", "counter",
        LABELED["hived_phase_ops_total"],
    )
    for phase, entry in sorted(phases.items()):
        lines.append(
            'hived_phase_ops_total{phase="%s"} %s'
            % (_escape_label(phase), _fmt(entry["count"]))
        )

    return "\n".join(lines) + "\n"
