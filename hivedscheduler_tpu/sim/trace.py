"""Seeded, replayable arrival traces: a PURE function of (seed, shape).

``generate_trace(seed, shape)`` reads nothing but its arguments — no env,
no clock, no global state — so the same (seed, shape) produces the same
trace byte for byte, on any host, under any ``HIVED_PROC_SHARDS`` setting
(tests/test_sim_smoke.py asserts both). That is what makes a warehouse
trace an *instrument*: a perf number at 10k hosts is only a trend point if
the exact same load can be replayed against the next optimization.

Shape vocabulary:

- **Arrival pattern** — ``diurnal`` (sinusoidal day curve), ``burst``
  (steady floor + concentrated storm windows), ``steady``.
- **Gang ladder** — the mixed sizes of BASELINE.json's config ladder:
  single-chip singletons, single-host jobs, v5e-16 4-pod gangs, v5p-16
  gangs, whole v5p-64 16-pod gangs, across both VCs.
- **Preemption pressure** — ``opportunistic_fraction`` of arrivals run at
  OPPORTUNISTIC priority; guaranteed arrivals are split across two
  priority tiers (0 and 5) so intra-VC preemption and the per-priority
  view slots both get exercised.
- **Fault injection** — the chaos event vocabulary (tests/chaos.py):
  ``node_flip`` (unready/ready), ``chip_fault``/``chip_heal``
  (device-health annotation), ``drain_toggle`` (maintenance drains).
  Faults reference a node INDEX into the sorted configured node list, so
  the trace stays fleet-agnostic until the driver resolves it.

Every event carries a monotonically increasing ``seq`` so ordering is
total even at equal timestamps; times are rounded to milliseconds so the
JSON form is stable.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass
from random import Random
from typing import Dict, List

SCHEMA_VERSION = 1

# (vc, leaf_type, n_pods, chips, weight, label): BASELINE.json's config
# ladder as gang shapes — from the single-chip request up to the whole
# v5p-64 gang with intra-VC preemption (labels name the ladder rung).
GANG_LADDER = (
    ("research", "v5e-chip", 1, 1, 3.0, "single-chip"),
    ("research", "v5e-chip", 1, 2, 2.0, "sub-host"),
    ("research", "v5e-chip", 1, 4, 3.0, "single-host"),
    ("research", "v5e-chip", 4, 4, 2.0, "v5e-16-gang"),
    ("prod", "v5e-chip", 4, 4, 2.0, "v5e-16-gang-prod"),
    ("research", "v5p-chip", 4, 4, 2.0, "v5p-16-gang"),
    ("prod", "v5p-chip", 16, 4, 1.0, "v5p-64-gang"),
)

# Guaranteed arrivals split across two tiers (intra-VC preemption
# pressure); opportunistic arrivals take OPPORTUNISTIC priority (-1).
GUARANTEED_PRIORITIES = (0, 0, 0, 5)

FAULT_EVENTS = ("node_flip", "chip_fault", "drain_toggle")


@dataclass(frozen=True)
class TraceShape:
    """Everything that shapes a trace besides the seed. Immutable and
    JSON-round-trippable: the trace embeds it, so a trace file is
    self-describing and the (seed, shape) -> bytes purity is testable."""

    hosts: int = 5184
    gangs: int = 400
    duration_s: float = 3600.0
    pattern: str = "diurnal"  # diurnal | burst | steady
    diurnal_amplitude: float = 0.8
    burst_storms: int = 4
    burst_fraction: float = 0.4
    opportunistic_fraction: float = 0.3
    mean_runtime_s: float = 600.0
    fault_events: int = 30

    def to_dict(self) -> Dict:
        return {
            "hosts": self.hosts,
            "gangs": self.gangs,
            "durationS": self.duration_s,
            "pattern": self.pattern,
            "diurnalAmplitude": self.diurnal_amplitude,
            "burstStorms": self.burst_storms,
            "burstFraction": self.burst_fraction,
            "opportunisticFraction": self.opportunistic_fraction,
            "meanRuntimeS": self.mean_runtime_s,
            "faultEvents": self.fault_events,
        }

    @staticmethod
    def from_dict(d: Dict) -> "TraceShape":
        return TraceShape(
            hosts=int(d.get("hosts", 5184)),
            gangs=int(d.get("gangs", 400)),
            duration_s=float(d.get("durationS", 3600.0)),
            pattern=str(d.get("pattern", "diurnal")),
            diurnal_amplitude=float(d.get("diurnalAmplitude", 0.8)),
            burst_storms=int(d.get("burstStorms", 4)),
            burst_fraction=float(d.get("burstFraction", 0.4)),
            opportunistic_fraction=float(
                d.get("opportunisticFraction", 0.3)
            ),
            mean_runtime_s=float(d.get("meanRuntimeS", 600.0)),
            fault_events=int(d.get("faultEvents", 30)),
        )


def _arrival_times(rnd: Random, shape: TraceShape) -> List[float]:
    """Sorted arrival times over [0, duration) under the shape's pattern.
    Deterministic: only ``rnd`` supplies randomness."""
    d = shape.duration_s
    n = shape.gangs
    times: List[float] = []
    if shape.pattern == "diurnal":
        # Rejection-sample against the day curve
        # rate(t) = 1 + A*sin(2*pi*(t/d - 0.25)): trough at t=0 ("3am"),
        # peak mid-trace. Bounded acceptance keeps this exact.
        a = max(0.0, min(1.0, shape.diurnal_amplitude))
        while len(times) < n:
            t = rnd.random() * d
            rate = 1.0 + a * math.sin(2.0 * math.pi * (t / d - 0.25))
            if rnd.random() * (1.0 + a) <= rate:
                times.append(t)
    elif shape.pattern == "burst":
        storms = max(1, shape.burst_storms)
        storm_len = d / (storms * 10.0)  # each storm is 10% of its slot
        n_burst = int(n * max(0.0, min(1.0, shape.burst_fraction)))
        starts = [d * (k + 0.45) / storms for k in range(storms)]
        for i in range(n_burst):
            s = starts[i % storms]
            times.append(s + rnd.random() * storm_len)
        for _ in range(n - n_burst):
            times.append(rnd.random() * d)
    else:  # steady
        for _ in range(n):
            times.append(rnd.random() * d)
    times.sort()
    return times


def _pick_weighted(rnd: Random, ladder) -> tuple:
    total = sum(e[4] for e in ladder)
    roll = rnd.random() * total
    acc = 0.0
    for entry in ladder:
        acc += entry[4]
        if roll <= acc:
            return entry
    return ladder[-1]


def generate_trace(seed: int, shape: TraceShape) -> Dict:
    """The trace: submit events (gang shape + priority + runtime) and
    fault events (chaos vocabulary, node-index addressed), sorted by
    (time, seq). Pure in (seed, shape)."""
    rnd = Random(seed)
    events: List[Dict] = []
    seq = 0
    for i, t in enumerate(_arrival_times(rnd, shape)):
        vc, leaf_type, n_pods, chips, _w, label = _pick_weighted(
            rnd, GANG_LADDER
        )
        if rnd.random() < shape.opportunistic_fraction:
            priority = -1
        else:
            priority = GUARANTEED_PRIORITIES[
                rnd.randrange(len(GUARANTEED_PRIORITIES))
            ]
        runtime = rnd.expovariate(1.0 / shape.mean_runtime_s)
        # Floor: a gang that departs before its own submit processes is
        # pure churn noise; 1% of the mean keeps the tail shaped.
        runtime = max(shape.mean_runtime_s * 0.01, runtime)
        events.append(
            {
                "t": round(t, 3),
                "seq": seq,
                "kind": "submit",
                "gang": {
                    "name": f"g{i}",
                    "vc": vc,
                    "leafType": leaf_type,
                    "pods": n_pods,
                    "chips": chips,
                    "priority": priority,
                    "ladder": label,
                    "runtimeS": round(runtime, 3),
                },
            }
        )
        seq += 1
    # Fault injection: node-index addressed so the trace needs no fleet.
    flips: List[Dict] = []
    for _ in range(max(0, shape.fault_events)):
        t = rnd.random() * shape.duration_s
        node_index = rnd.randrange(max(1, shape.hosts))
        kind = FAULT_EVENTS[rnd.randrange(len(FAULT_EVENTS))]
        ev: Dict = {
            "t": round(t, 3),
            "seq": seq,
            "kind": kind,
            "nodeIndex": node_index,
        }
        if kind == "chip_fault":
            ev["chip"] = rnd.randrange(4)
            # Every fault heals later in trace time (chaos vocabulary's
            # chip_heal), so fleet capacity trends back.
            heal_t = min(
                shape.duration_s, t + rnd.random() * shape.duration_s / 4
            )
            events.append(ev)
            seq += 1
            ev = {
                "t": round(heal_t, 3),
                "seq": seq,
                "kind": "chip_heal",
                "nodeIndex": node_index,
                "chip": ev["chip"],
            }
        elif kind == "node_flip":
            flips.append(ev)  # "to" assigned below, in REPLAY order
        elif kind == "drain_toggle":
            ev["on"] = rnd.random() < 0.5
        events.append(ev)
        seq += 1
    # Assign node_flip directions per node in REPLAY (time) order, not
    # generation order: flips alternate down/up starting from down, so a
    # node is never "healed" before it broke and any odd tail leaves at
    # most the final down (capacity bleed is bounded to the last flip).
    by_node: Dict[int, List[Dict]] = {}
    for ev in flips:
        by_node.setdefault(ev["nodeIndex"], []).append(ev)
    for evs in by_node.values():
        evs.sort(key=lambda e: (e["t"], e["seq"]))
        for i, ev in enumerate(evs):
            ev["to"] = "down" if i % 2 == 0 else "up"
    events.sort(key=lambda e: (e["t"], e["seq"]))
    return {
        "version": SCHEMA_VERSION,
        "seed": seed,
        "shape": shape.to_dict(),
        "events": events,
    }


def trace_json(trace: Dict) -> bytes:
    """Canonical byte form (sorted keys, no whitespace): the unit of the
    bit-identical-replay guarantee."""
    return json.dumps(
        trace, sort_keys=True, separators=(",", ":")
    ).encode()


def load_trace(path: str) -> Dict:
    with open(path) as f:
        return json.load(f)
