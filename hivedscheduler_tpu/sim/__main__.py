"""CLI for the warehouse-scale sim tier.

Generate a seeded trace and replay it time-compressed through the real
scheduler::

    python -m hivedscheduler_tpu.sim --hosts 10368 --seed 0 --gangs 800

Write the trace for later replay (bit-identical from the same seed)::

    python -m hivedscheduler_tpu.sim --hosts 5184 --write-trace t.json
    python -m hivedscheduler_tpu.sim --trace t.json --out report.json

``--mode shards --shards N`` drives the multi-process frontend
(``procShards``); ``--json`` emits the full report instead of the text
summary.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

from .. import common
from .driver import run_trace
from .report import render_text
from .trace import TraceShape, generate_trace, load_trace, trace_json


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hivedscheduler_tpu.sim",
        description="Trace-driven warehouse-scale scheduler simulation",
    )
    # Default resolved per mode: trace generation uses 5184; recording
    # replay distinguishes "flag given" from "use the recording's stamp".
    ap.add_argument("--hosts", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--gangs", type=int, default=400)
    ap.add_argument(
        "--pattern", choices=("diurnal", "burst", "steady"),
        default="diurnal",
    )
    ap.add_argument("--duration", type=float, default=3600.0,
                    help="trace-time span in seconds")
    ap.add_argument("--opportunistic", type=float, default=0.3,
                    help="fraction of arrivals at OPPORTUNISTIC priority")
    ap.add_argument("--faults", type=int, default=30)
    ap.add_argument("--mean-runtime", type=float, default=600.0)
    ap.add_argument("--mode", choices=("inproc", "shards"),
                    default="inproc")
    ap.add_argument("--defrag", action="store_true",
                    help="arm the background defragmenter and execute "
                    "its checkpoint-coordinated migrations during the "
                    "replay (inproc mode; A/B against the same seed "
                    "without the flag)")
    ap.add_argument("--shards", type=int, default=2)
    ap.add_argument("--transport", choices=("proc", "local"),
                    default="proc")
    ap.add_argument("--trace", help="replay this trace file instead of "
                    "generating one")
    ap.add_argument("--replay-recording", metavar="FILE",
                    help="deterministic incident replay (black-box "
                    "plane): restore a flight recording's anchor "
                    "through the what-if fork path and re-drive its "
                    "verb window through TraceDriver, comparing the "
                    "replayed placement fingerprint against the live "
                    "run's (exit 1 on divergence). The fleet config is "
                    "rebuilt from the recording's host stamp (--hosts "
                    "overrides)")
    ap.add_argument("--write-trace", help="write the generated trace "
                    "here and exit (no replay)")
    ap.add_argument("--out", help="write the JSON report here")
    ap.add_argument("--json", action="store_true",
                    help="print the full JSON report")
    ap.add_argument("--verbose", action="store_true",
                    help="scheduler INFO logs (quiet by default: a 10k-"
                    "host trace logs millions of placement lines)")
    args = ap.parse_args(argv)

    common.init_logging(
        logging.INFO if args.verbose else logging.ERROR
    )
    if args.replay_recording:
        return _replay_recording_main(args)
    if args.trace:
        trace = load_trace(args.trace)
    else:
        shape = TraceShape(
            hosts=args.hosts if args.hosts is not None else 5184,
            gangs=args.gangs,
            duration_s=args.duration,
            pattern=args.pattern,
            opportunistic_fraction=args.opportunistic,
            fault_events=args.faults,
            mean_runtime_s=args.mean_runtime,
        )
        trace = generate_trace(args.seed, shape)
    if args.write_trace:
        with open(args.write_trace, "wb") as f:
            f.write(trace_json(trace))
        print(f"trace written: {args.write_trace} "
              f"({len(trace['events'])} events)")
        return 0

    report = run_trace(
        trace,
        mode=args.mode,
        n_shards=args.shards,
        transport=args.transport,
        defrag=args.defrag,
    )
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        print(render_text(report))
    return 0


def _replay_recording_main(args) -> int:
    """--replay-recording: capture -> dump -> replay -> fingerprint
    compare (doc/user-manual.md "Reproducing a production incident from
    a flight recording")."""
    from ..scheduler.recorder import replay_recording
    from .driver import build_fleet_config

    with open(args.replay_recording) as f:
        recording = json.load(f)
    if recording.get("kind") != "flightRecording":
        print("not a flight recording (expected kind=flightRecording)",
              file=sys.stderr)
        return 2
    # An explicitly-passed --hosts OVERRIDES the recording's stamp (the
    # flag's contract); otherwise the stamp wins, and a stamp-less
    # recording (frontend capture) requires the flag rather than
    # silently replaying against the default fleet and failing the
    # config-fingerprint gate with a confusing mismatch.
    if args.hosts is not None:
        hosts = args.hosts
    elif recording.get("hosts"):
        hosts = recording["hosts"]
    else:
        print("recording carries no host stamp; pass --hosts N matching "
              "the capturing fleet", file=sys.stderr)
        return 2
    config, actual_hosts = build_fleet_config(int(hosts))
    result = replay_recording(recording, config)
    payload = dict(result, hosts=actual_hosts)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
    if args.json:
        print(json.dumps(payload, sort_keys=True))
    else:
        ev = result["events"]
        print(f"replayed {sum(v for k, v in ev.items() if not k.startswith('_'))} "
              f"events ({ev.get('_skipped', 0)} skipped, "
              f"{ev.get('_errors', 0)} protocol errors) at {actual_hosts} hosts")
        print(f"live    fingerprint: {result['liveFingerprint']}")
        print(f"replay  fingerprint: {result['replayFingerprint']}")
        print("IDENTICAL — deterministic repro"
              if result["identical"]
              else "DIVERGED — anchor/config mismatch or nondeterminism")
    return 0 if result["identical"] else 1


if __name__ == "__main__":
    sys.exit(main())
