"""Per-trace report: tail latency + scheduling-quality metrics.

One dict, JSON-serializable, with four metric families the warehouse
tier exists to trend:

- **latency** — wall-clock cost of the scheduler per gang schedule
  attempt (p50/p95/p99/max) and sustained pods/s through the filter path;
- **fragmentation** — the schedulable-slice-size distribution
  (driver.fragmentation_snapshot) sampled across trace time, summarized
  as the end-state distribution plus the largest schedulable slice;
- **preemption** — preemption events and preempted pods, normalized per
  bound guaranteed gang;
- **quota satisfaction** — bound/submitted for guaranteed gangs, plus
  the TRACE-time queueing delay distribution (submit -> bound).
"""

from __future__ import annotations

import statistics
from typing import Dict, List, Optional


def _pct(sorted_vals: List[float], p: float) -> float:
    """bench.py's `_percentiles` convention (sorted[min(n-1, int(p*n))]),
    so the sim tier's tails are directly comparable with every bench
    stage's in the same BENCH artifact."""
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1, int(p * len(sorted_vals)))]


def latency_summary(lat_ms: List[float]) -> Dict:
    s = sorted(lat_ms)
    return {
        "samples": len(s),
        "p50Ms": round(statistics.median(s), 3) if s else 0.0,
        "p95Ms": round(_pct(s, 0.95), 3),
        "p99Ms": round(_pct(s, 0.99), 3),
        "maxMs": round(s[-1], 3) if s else 0.0,
    }


def frag_summary(frag_series: List[Dict]) -> Optional[Dict]:
    """End-state slice distribution + the largest schedulable slice at
    each sample (the defrag trend metric of ROADMAP new-direction 3)."""
    if not frag_series:
        return None
    largest = [
        max((int(k) for k in s["freeSlices"]), default=0)
        for s in frag_series
    ]
    end = frag_series[-1]["freeSlices"]
    total_free = sum(int(k) * v for k, v in end.items())
    return {
        "samples": len(frag_series),
        "endFreeSlices": end,
        "endFreeChips": total_free,
        "largestFreeSliceChips": largest[-1] if largest else 0,
        "largestFreeSliceSeries": largest,
        "series": frag_series,
    }


def build_report(
    trace: Dict,
    lat_ms: List[float],
    wall_s: float,
    counts: Dict,
    wait_times_s: List[float],
    frag_series: List[Dict],
    metrics: Dict,
    mode: str,
    pending: Optional[Dict] = None,
) -> Dict:
    waits = sorted(wait_times_s)
    bound_g = counts["boundGuaranteed"]
    sub_g = counts["submittedGuaranteed"]
    report = {
        "schemaVersion": 1,
        "seed": trace.get("seed"),
        "shape": trace.get("shape"),
        "mode": mode,
        "events": len(trace.get("events", [])),
        "wallS": round(wall_s, 3),
        "counts": counts,
        "latency": latency_summary(lat_ms),
        "podsPerSec": round(counts["podsBound"] / wall_s, 1)
        if wall_s > 0
        else 0.0,
        "preemption": {
            "events": counts["preemptionEvents"],
            "preemptedPods": counts["preemptedPods"],
            "ratePerBoundGuaranteed": round(
                counts["preemptionEvents"] / bound_g, 4
            )
            if bound_g
            else 0.0,
        },
        "quotaSatisfaction": {
            "submittedGuaranteed": sub_g,
            "boundGuaranteed": bound_g,
            "fraction": round(bound_g / sub_g, 4) if sub_g else 1.0,
            "queueWaitP50S": round(statistics.median(waits), 3)
            if waits
            else 0.0,
            "queueWaitP99S": round(_pct(waits, 0.99), 3),
        },
        "fragmentation": frag_summary(frag_series),
        # Pending-pod plane (doc/hot-path.md "Pending-pod plane"): the
        # waiting-queue depth trend (max + end-of-trace), retry-wake
        # costs, and the wait-cache hit ratio. NOT part of the placement
        # fingerprint: wake attempt totals are a property of the retry
        # mode, and the fingerprint must be bit-identical across
        # indexed / FIFO-hatch / cache-off replays of one trace.
        "pendingPlane": pending or {},
        # The scheduler's own counters for cross-checks (preemptCount,
        # nodeEventNoopCount, filter histogram...).
        "schedulerMetrics": {
            k: metrics.get(k)
            for k in (
                "filterCount",
                "bindCount",
                "preemptCount",
                "waitCount",
                "healthTransitionCount",
                "nodeEventNoopCount",
                "fastWaitCount",
                "filterLatencyP50Ms",
                "filterLatencyP99Ms",
            )
        },
    }
    return report


def placement_fingerprint(report: Dict) -> Dict:
    """The run-invariant slice of a report: everything that must be
    IDENTICAL when the same trace replays (wall-clock latencies excluded
    by construction). The replay-determinism test diffs this."""
    return {
        "counts": report["counts"],
        "preemption": report["preemption"],
        "quotaSatisfaction": report["quotaSatisfaction"],
        "fragmentation": report["fragmentation"],
        "binds": report["schedulerMetrics"]["bindCount"],
    }


def render_text(report: Dict) -> str:
    """A human-readable one-screen summary for the CLI."""
    lines = []
    shape = report.get("shape") or {}
    lines.append(
        f"trace seed={report['seed']} pattern={shape.get('pattern')} "
        f"hosts={report.get('hosts', shape.get('hosts'))} "
        f"gangs={shape.get('gangs')} mode={report['mode']}"
    )
    c = report["counts"]
    lat = report["latency"]
    lines.append(
        f"  schedule latency: p50={lat['p50Ms']}ms p95={lat['p95Ms']}ms "
        f"p99={lat['p99Ms']}ms max={lat['maxMs']}ms "
        f"({report['podsPerSec']} pods/s, wall {report['wallS']}s)"
    )
    q = report["quotaSatisfaction"]
    lines.append(
        f"  quota satisfaction: {q['boundGuaranteed']}/"
        f"{q['submittedGuaranteed']} guaranteed bound "
        f"({q['fraction']:.1%}); queue wait p50={q['queueWaitP50S']}s "
        f"p99={q['queueWaitP99S']}s"
    )
    p = report["preemption"]
    lines.append(
        f"  preemption: {p['events']} events, {p['preemptedPods']} pods "
        f"({p['ratePerBoundGuaranteed']}/bound-guaranteed-gang)"
    )
    frag = report["fragmentation"]
    if frag:
        lines.append(
            f"  fragmentation: end free {frag['endFreeChips']} chips, "
            f"largest slice {frag['largestFreeSliceChips']} chips, "
            f"distribution {frag['endFreeSlices']}"
        )
    lines.append(
        f"  gangs: {c['boundGangs']}/{c['submitted']} bound, "
        f"{c['waitingAtEnd']} waiting, {c['liveAtEnd']} live at end, "
        f"{c['faultsApplied']} faults applied"
    )
    pend = report.get("pendingPlane") or {}
    if pend.get("wakeEvents"):
        lines.append(
            f"  pending plane ({pend.get('retryMode')}): waiting max "
            f"{pend.get('waitingMax')}, {pend.get('wakeEvents')} wakes, "
            f"{pend.get('wakeAttempts')} attempts "
            f"({pend.get('wakeSkipped')} skipped by the index), "
            f"wait-cache hit ratio {pend.get('waitCacheHitRatio')}"
        )
    if c.get("defragProposals") or c.get("defragMigrations"):
        lines.append(
            f"  defrag: {c['defragProposals']} proposals, "
            f"{c['defragMigrations']} migrations executed"
        )
    return "\n".join(lines)
