"""Warehouse-scale trace-driven simulation tier (ROADMAP new-direction 4).

The instrument that turns "as fast as the hardware allows" into trend
lines instead of spot numbers: seeded, replayable arrival traces shaped by
BASELINE.json's config ladder (diurnal load, burst storms, mixed gang
sizes, preemption pressure, node-fault injection reusing the chaos event
vocabulary), driven time-compressed through the REAL scheduler — the same
filter/preempt/delete verbs the HTTP extender serves — at 5k/10k/50k
hosts, reporting tail latency plus scheduling-quality metrics
(fragmentation, preemption rate, quota satisfaction) per trace.

- :mod:`.trace`  — trace generation, a pure function of (seed, shape)
- :mod:`.fleet`  — fleet config builder (shared with bench.py)
- :mod:`.driver` — time-compressed replay through the real scheduler
- :mod:`.report` — per-trace report assembly and rendering

CLI: ``python -m hivedscheduler_tpu.sim --hosts 10368 --seed 0``.
"""

from .trace import TraceShape, generate_trace, trace_json  # noqa: F401
from .driver import TraceDriver, build_fleet_config  # noqa: F401
