"""Bench/sim fleet builder: the mixed v5p/v5e fleet at any scale.

Owned here (not in bench.py) so the sim tier and the bench driver share
one fleet shape: ``build_config`` is the 432-host-quantum fleet every
measured table in doc/hot-path.md uses (cubes=16/slices=40/solos=16), and
``fleet_config_for_hosts`` scales it continuously to the 5k/10k/50k-host
targets of the warehouse-scale trace runs. bench.py re-exports
``build_config``/``make_pod`` for its stages and for existing callers.
"""

from __future__ import annotations

from typing import Tuple

from ..api import constants
from ..api.config import Config
from ..scheduler.types import Pod
from ..tpu import topology

# The 432-host reference fleet is cubes=16, slices=40, solos=16
# (doc/hot-path.md measured tables); scaling keeps those proportions.
BASE_HOSTS = 432
BASE_CUBES, BASE_SLICES, BASE_SOLOS = 16, 40, 16


def build_config(cubes: int = 4, slices: int = 8, solos: int = 8) -> Config:
    """The bench fleet: ``cubes`` v5p-64 cubes (16 hosts each), ``slices``
    v5e-16 slices (4 hosts each), ``solos`` standalone v5e hosts. Defaults
    give the 104-host default load; the 432-host fleet variant
    (doc/hot-path.md measured tables) is cubes=16, slices=40, solos=16.
    VC quota scales with the fleet so the gang mix always fits."""
    cell_types = {}
    cell_types.update(topology.v5p_cell_types(max_hosts=16))
    cell_types.update(topology.v5e_cell_types(max_hosts=4))
    physical = []
    for cube in range(cubes):
        physical.append(
            topology.make_physical_cell(
                "v5p-64",
                [f"v5p-c{cube}-w{i}" for i in range(16)],
                cell_types,
            ).to_dict()
        )
    for s in range(slices):
        physical.append(
            topology.make_physical_cell(
                "v5e-16", [f"v5e-s{s}-w{i}" for i in range(4)], cell_types
            ).to_dict()
        )
    for h in range(solos):
        physical.append(
            topology.make_physical_cell(
                "v5e-host", [f"v5e-solo-{h}"], cell_types
            ).to_dict()
        )
    return Config.from_dict(
        {
            "physicalCluster": {
                "cellTypes": {
                    n: {
                        "childCellType": s.child_cell_type,
                        "childCellNumber": s.child_cell_number,
                        "isNodeLevel": s.is_node_level,
                    }
                    for n, s in cell_types.items()
                },
                "physicalCells": physical,
            },
            "virtualClusters": {
                "prod": {
                    "virtualCells": [
                        {"cellType": "v5p-64", "cellNumber": cubes // 2},
                        {"cellType": "v5e-16", "cellNumber": slices // 2},
                    ]
                },
                "research": {
                    "virtualCells": [
                        {"cellType": "v5p-64.v5p-16", "cellNumber": 2 * cubes},
                        {"cellType": "v5e-16", "cellNumber": slices // 2},
                        {"cellType": "v5e-host", "cellNumber": solos},
                    ]
                },
            },
        }
    )


def fleet_dims_for_hosts(hosts: int) -> Tuple[int, int, int]:
    """(cubes, slices, solos) approximating a host-count target with the
    reference fleet's proportions. Floors keep the two VCs constructible
    (prod needs cubes//2 >= 1 and slices//2 >= 1)."""
    f = max(1, int(hosts)) / BASE_HOSTS
    cubes = max(2, round(BASE_CUBES * f))
    slices = max(2, round(BASE_SLICES * f))
    solos = max(1, round(BASE_SOLOS * f))
    return cubes, slices, solos


def fleet_hosts(cubes: int, slices: int, solos: int) -> int:
    return 16 * cubes + 4 * slices + solos


def make_pod(
    name, uid, vc, priority, leaf_type, leaf_num, group,
    ignore_suggested: bool = True,
) -> Pod:
    import yaml

    spec = {
        "virtualCluster": vc,
        "priority": priority,
        "leafCellType": leaf_type,
        "leafCellNumber": leaf_num,
        "affinityGroup": group,
    }
    if not ignore_suggested:
        # The defrag migration re-filter steers via the suggested set.
        spec["ignoreK8sSuggestedNodes"] = False
    return Pod(
        name=name,
        uid=uid,
        annotations={constants.ANNOTATION_POD_SCHEDULING_SPEC: yaml.safe_dump(spec)},
        resource_limits={constants.RESOURCE_NAME_POD_SCHEDULING_ENABLE: 1},
    )
