"""Time-compressed trace replay through the REAL scheduler.

No mocks: the driver speaks the verbs the HTTP extender serves —
``filter_routine`` (with assume-bind), ``preempt_routine`` (commit +
victim delete + re-filter, the production preemption protocol),
``delete_pod`` (departures and victim kills), ``update_node`` (the chaos
fault vocabulary) — against either the in-process ``HivedScheduler`` or
the multi-process ``ShardedScheduler`` frontend (``mode="shards"``,
doc/hot-path.md "The multi-process contract").

Time compression: trace time is a logical clock. Events replay in trace
order with zero sleeps; the *scheduler's* cost is measured in wall time
per gang schedule, while queueing delay (submit → bound) is measured in
TRACE time — so a 1-hour diurnal trace at 10k hosts runs in seconds yet
reports both "how slow is the scheduler" (tail latency) and "how well
does it schedule" (fragmentation, preemption rate, quota satisfaction).

Determinism: placements are a pure function of (config, trace) — the
preempt RNG is seeded from the trace seed, and placement itself is
state-pure (doc/hot-path.md "State-pure sorted view") — so two runs of
one trace produce identical binds, preemptions, and fragmentation
(tests/test_sim_smoke.py asserts it). Wall-clock latencies are the only
run-varying output.
"""

from __future__ import annotations

import heapq
import os
import random
import time
from collections import OrderedDict
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .. import common
from ..algorithm import compiler
from ..api import constants, extender as ei
from ..api.config import Config
from ..scheduler.framework import HivedScheduler, NullKubeClient
from ..scheduler.types import Node, Pod, apply_node_fault_event
from . import fleet
from .trace import TraceShape

# Pending-pod plane (doc/hot-path.md "Pending-pod plane"): the waiting
# queue is ELIGIBILITY-INDEXED — waiters are grouped by chain family and
# a retry wake attempts, in FIFO order, only the waiters whose family's
# state may have CHANGED since their last attempt. Change tracking is a
# dirty-family set fed by every state-changing action the driver
# performs or triggers — departures and preemption kills (family-
# scoped), binds (a fresh bind is a fresh victim for a waiting
# preemptor; family-scoped), and faults of ANY kind plus defrag health
# ticks (ALL families: a capacity removal can shift a placement onto
# occupied cells and surface victims, and the scheduler's flap-damper
# settle sweep piggybacks on any node observation and may apply a HELD
# transition for an unrelated node) — drained at each wake. Chains in different families share no cells, so a waiter
# whose family is clean would re-read exactly the state its last failed
# attempt read and fail identically, with no side effects and no RNG
# draw; skipping it is a deletion of a provable no-op from the FIFO
# rescan's attempt sequence (the admission-equivalence argument
# tests/test_sim_smoke.py proves differentially at identical seeds).
# Over-waking is always safe — the FIFO reference attempts everyone —
# so every unknown degrades to "wake all", never to a missed wake.
# This retires the old RETRY_BUDGET_PER_EVENT=8 stopgap and its
# starvation caveat: no waiter is ever dropped from a wake it is
# eligible for. HIVED_SIM_FIFO_RETRY=1 restores the budget-free FIFO
# rescan of EVERY waiter on every capacity-freeing event — the
# differential's reference mode, and the regime where the
# scheduler-side wait cache does the same pruning one layer down (each
# unchanged re-filter answers from its certificate).
FIFO_RETRY_ENV = "HIVED_SIM_FIFO_RETRY"

# Sentinel family meaning "unknown — treat as every family".
ALL_FAMILIES = -1


def _leaf_family_map(config: Config) -> Dict[str, int]:
    """Leaf type -> chain-family index from the compiled spec metadata —
    the same connected-components partition the shards RoutingTable uses
    (compiler.chain_families; one leaf SKU never spans two families by
    construction). Derivation failure degrades to an empty map = every
    wake is global (the FIFO behavior), never an error — but logged, so
    a silently-disabled index is diagnosable."""
    pc = config.physical_cluster
    try:
        fams = compiler.chain_families(pc.cell_types, pc.physical_cells)
        elements = compiler.build_cell_chains(pc.cell_types)
        leaf_family: Dict[str, int] = {}
        for i, fam in enumerate(fams):
            for chain in fam:
                ce = elements.get(chain)
                if ce is not None:
                    leaf_family.setdefault(str(ce.leaf_cell_type), i)
        return leaf_family
    except Exception as e:  # noqa: BLE001 — degrade to global wakes
        common.log.warning(
            "chain-family derivation failed; retry wakes degrade to "
            "global (eligibility index off): %s", e,
        )
        return {}


class _WaitQueue:
    """FIFO-ordered waiting gangs with the eligibility index. ``eligible``
    preserves global FIFO order within any wake, so the indexed mode's
    attempt sequence is the FIFO rescan's with provably-no-op attempts
    deleted."""

    def __init__(self, leaf_family: Dict[str, int], fifo: bool):
        self.fifo = fifo
        self._leaf_family = leaf_family
        self._order: "OrderedDict[str, _Gang]" = OrderedDict()
        self.waiting_max = 0
        self.wake_events = 0
        self.wake_attempts = 0
        self.wake_skipped = 0

    def __len__(self) -> int:
        return len(self._order)

    def family(self, gang: "_Gang") -> int:
        """The gang's chain-family index; -1 = unknown leaf type (always
        eligible — conservative)."""
        return self._leaf_family.get(gang.leaf_type, -1)

    def key(self, gang: "_Gang") -> Tuple[int, int, str]:
        """The waiter's index key: (chain family, gang chips, VC). Only
        the FAMILY component gates eligibility — a gang-size gate
        ("enough free chips in the family") is unsound for guaranteed
        waiters (preemption can succeed with zero free capacity), and a
        VC gate is unsound because physical capacity is shared across
        VCs; either would break admission equivalence. Size and VC make
        the queue's composition observable (key_counts)."""
        return (self.family(gang), gang.n_pods * gang.chips, gang.vc)

    def key_counts(self) -> Dict[str, int]:
        """Waiting-queue composition by index key, for the report."""
        out: Dict[str, int] = {}
        for g in self._order.values():
            fam, chips, vc = self.key(g)
            k = f"family{fam}:{chips}ch:{vc}"
            out[k] = out.get(k, 0) + 1
        return out

    def add(self, gang: "_Gang") -> None:
        self._order[gang.name] = gang
        if len(self._order) > self.waiting_max:
            self.waiting_max = len(self._order)

    def remove(self, name: str) -> None:
        self._order.pop(name, None)

    def eligible(
        self, families: Optional[FrozenSet[int]]
    ) -> List["_Gang"]:
        """Waiters to attempt for one wake, FIFO order. ``families=None``
        (or the FIFO hatch) wakes everyone; otherwise only waiters whose
        chain family the event touched (plus unknown-family waiters)."""
        gangs = list(self._order.values())
        if self.fifo or families is None:
            return gangs
        out = []
        for g in gangs:
            f = self.family(g)
            if f < 0 or f in families:
                out.append(g)
            else:
                self.wake_skipped += 1
        return out


def build_fleet_config(hosts: int) -> Tuple[Config, int]:
    """A bench-proportioned fleet approximating ``hosts``; returns the
    config and the exact host count."""
    cubes, slices, solos = fleet.fleet_dims_for_hosts(hosts)
    return (
        fleet.build_config(cubes, slices, solos),
        fleet.fleet_hosts(cubes, slices, solos),
    )


class _Gang:
    __slots__ = (
        "name", "vc", "leaf_type", "n_pods", "chips", "priority",
        "runtime_s", "submit_t", "pods", "bound", "bound_t", "ladder",
    )

    def __init__(self, spec: Dict, submit_t: float):
        self.name = spec["name"]
        self.vc = spec["vc"]
        self.leaf_type = spec["leafType"]
        self.n_pods = int(spec["pods"])
        self.chips = int(spec["chips"])
        self.priority = int(spec["priority"])
        self.runtime_s = float(spec["runtimeS"])
        self.ladder = spec.get("ladder", "")
        self.submit_t = submit_t
        self.pods: List[Pod] = []
        self.bound: List[Pod] = []
        self.bound_t: Optional[float] = None

    @property
    def guaranteed(self) -> bool:
        return self.priority >= 0

    def make_pods(self, ignore_suggested: bool = True) -> List[Pod]:
        group = {
            "name": self.name,
            "members": [
                {"podNumber": self.n_pods, "leafCellNumber": self.chips}
            ],
        }
        self.pods = [
            fleet.make_pod(
                f"{self.name}-{i}", f"{self.name}-u{i}", self.vc,
                self.priority, self.leaf_type, self.chips, group,
                ignore_suggested=ignore_suggested,
            )
            for i in range(self.n_pods)
        ]
        return self.pods


def fragmentation_snapshot(core) -> Dict[str, int]:
    """The sim tier's fragmentation metric: the core's schedulable-
    slice-size distribution (HivedCore.free_slice_distribution)."""
    return core.free_slice_distribution()


class TraceDriver:
    """Replays one trace against one scheduler instance."""

    def __init__(
        self,
        config: Config,
        mode: str = "inproc",
        n_shards: int = 2,
        transport: str = "proc",
        frag_samples: int = 8,
        scheduler=None,
        fifo_retry: Optional[bool] = None,
        prepare_nodes: bool = True,
        whatif_at: Optional[float] = None,
        whatif_verify: bool = False,
    ):
        self.mode = mode
        self.frag_samples = frag_samples
        # Shadow what-if plane (scheduler.whatif, HIVED_BENCH_WHATIF):
        # whatif_at is a trace-time FRACTION; when the replay clock
        # crosses it, the current waiting queue is forecast against the
        # known departure horizon on a snapshot fork and the result kept
        # in self.whatif_sample (forecast-vs-actual is scored after the
        # run from gang_bound_t). whatif_verify additionally runs the
        # forecast twice on independent forks and records equality.
        self._whatif_at = whatif_at
        self._whatif_verify = whatif_verify
        self.whatif_sample: Optional[Dict] = None
        # gang name -> trace time it bound (the forecast's ground truth).
        self.gang_bound_t: Dict[str, float] = {}
        # Retry-wake mode (doc/hot-path.md "Pending-pod plane"): indexed
        # by default; True (or HIVED_SIM_FIFO_RETRY=1) restores the FIFO
        # rescan of every waiter per capacity-freeing event.
        self.fifo_retry = (
            os.environ.get(FIFO_RETRY_ENV, "").strip() == "1"
            if fifo_retry is None
            else bool(fifo_retry)
        )
        self._leaf_family = _leaf_family_map(config)
        # Families whose state may have changed since the last retry
        # wake (reset per run; fed by every state-changing driver
        # action, drained by retry_waiting).
        self._dirty_families: Set[int] = set()
        if scheduler is not None:
            # Pre-built subject (hack/sim_server.py's HTTP-wire adapter):
            # anything exposing the HivedScheduler verb surface — possibly
            # a ShardedScheduler, which has configured_node_names() on the
            # frontend and no single .core. Informer verbs may run
            # in-process; filter/preempt may cross a wire.
            self.sched = scheduler
            self.core = getattr(scheduler, "core", None)
            names = getattr(scheduler, "configured_node_names", None)
            self.nodes = sorted(
                names() if names is not None
                else scheduler.core.configured_node_names()
            )
        elif mode == "shards":
            from ..scheduler.shards import ShardedScheduler

            self.sched = ShardedScheduler(
                config,
                kube_client=NullKubeClient(),
                n_shards=n_shards,
                transport=transport,
                auto_admit=True,
            )
            self.core = None  # per-shard cores live behind the frontend
            self.nodes = sorted(self.sched.configured_node_names())
        else:
            self.sched = HivedScheduler(
                config, kube_client=NullKubeClient(), auto_admit=True
            )
            self.core = self.sched.core
            self.nodes = sorted(self.core.configured_node_names())
        self._node_cache: Dict[str, Node] = {}
        if prepare_nodes:
            for n in self.nodes:
                node = Node(name=n)
                self._node_cache[n] = node
                self.sched.add_node(node)
        else:
            # RESTORED-subject mode (a what-if shadow fork): the
            # projection restore already carries the exact health state
            # — re-adding every node as healthy would wipe it, and the
            # fault verbs' node cache must mirror the restored health
            # (a fresh-healthy baseline would HEAL restored badness on
            # the first fault event; scheduler.whatif).
            from ..scheduler.whatif import restored_node_baseline

            for n in self.nodes:
                self._node_cache[n] = (
                    restored_node_baseline(self.core, n)
                    if self.core is not None
                    else Node(name=n)
                )

    def _bound_pod(self, uid: str) -> Pod:
        """The assume-bound pod object for one scheduled uid, any mode
        and transport."""
        if self.core is not None:
            return self.sched.pod_schedule_statuses[uid].pod
        found = self.sched.get_status_pod(uid)
        return found[0]

    def close(self) -> None:
        close = getattr(self.sched, "close", None)
        if close is not None:
            close()

    def _mark_dirty_gang(self, gang: "_Gang") -> None:
        self._dirty_families.add(
            self._leaf_family.get(gang.leaf_type, ALL_FAMILIES)
        )

    # -- fault vocabulary (chaos events, resolved by node index) ------- #

    def _apply_fault(self, ev: Dict) -> None:
        name = self.nodes[ev["nodeIndex"] % len(self.nodes)]
        # EVERY fault kind dirties EVERY family: (a) a capacity REMOVAL
        # can also change a waiter's next attempt (a shifted placement
        # can surface preemption victims), so removals mark even though
        # they never trigger a wake; (b) the node event below runs the
        # scheduler's flap-damper settle sweep, which can apply a HELD
        # transition for any OTHER node — including one in a family this
        # fault never touched — so node-scoped marking would under-wake
        # and break the FIFO admission equivalence. Fault events are
        # rare next to departures (which stay family-scoped), so the
        # index keeps its selectivity where the volume is.
        self._dirty_families.add(ALL_FAMILIES)
        old = self._node_cache[name]
        # One shared fault vocabulary with the what-if horizon replay
        # (scheduler.types.apply_node_fault_event).
        new = apply_node_fault_event(old, ev)
        if new is None:
            return
        self._node_cache[name] = new
        self.sched.update_node(old, new)

    # -- the scheduling protocol (what the extender does) -------------- #

    def _filter_gang(
        self, gang: _Gang, nodes: Optional[List[str]] = None
    ) -> bool:
        """Filter every pod of the gang; on full success the gang is live
        (assume-bound). On partial failure the placed pods are deleted —
        the framework's partial-gang release. ``nodes`` narrows the
        suggested set (the defrag migration steer)."""
        bound: List[Pod] = []
        for p in gang.pods:
            r = self.sched.filter_routine(
                ei.ExtenderArgs(pod=p, node_names=nodes or self.nodes)
            )
            if not r.node_names:
                for q in gang.pods:
                    self.sched.delete_pod(q)
                return False
            bound.append(self._bound_pod(p.uid))
        gang.bound = bound
        return True

    # -- the defragmenter's workload-controller half ------------------- #

    def _defrag_pulse(self, live: Dict[str, "_Gang"]) -> Tuple[int, int]:
        """One defrag beat (inproc mode only): advance the event clock
        (runs a cycle when the interval allows), then play the workload
        controller for every proposal — checkpoint (implicit), delete the
        gang, re-filter it onto the compacting placement (suggested set
        minus the fragment's nodes), cancel-on-fail releasing the
        reservation. Returns (proposals, migrations)."""
        sched = self.sched
        if getattr(sched, "defrag", None) is None or self.core is None:
            return 0, 0
        # health_tick runs the damper's settle sweep (held transitions
        # for ANY node may apply) and defrag churn deletes/re-places
        # whole gangs: both touch arbitrary families — mark them all
        # (defrag pulses are per frag-sample, rare; over-waking is safe).
        self._dirty_families.add(ALL_FAMILIES)
        sched.health_tick()
        proposals = sched.take_defrag_proposals()
        migrated = 0
        for prop in proposals:
            gang = live.get(prop["group"])
            if gang is None:
                sched.defrag.report_migration(
                    prop["group"], ok=False, reason="gang departed"
                )
                continue
            avoid = set(prop["avoidNodes"])
            target = [n for n in self.nodes if n not in avoid]
            for p in gang.bound:
                sched.delete_pod(p)
            gang.make_pods(ignore_suggested=False)
            if self._filter_gang(gang, nodes=target):
                sched.defrag.report_migration(prop["group"], ok=True)
                migrated += 1
                continue
            # Cancel-on-fail: release the reservation and put the gang
            # back wherever it fits (its original cells are still free).
            gang.make_pods()
            if not self._filter_gang(gang):
                live.pop(prop["group"], None)
            sched.defrag.report_migration(
                prop["group"], ok=False,
                reason="re-filter found no compacting placement",
            )
        return len(proposals), migrated

    def _try_preempt(self, gang: _Gang, live: Dict[str, "_Gang"]) -> int:
        """The production preemption protocol for the gang's first pod:
        probe/commit via preempt_routine; if victims are proposed, kill
        them (their whole gangs, as the eviction would) and report how
        many pods died. The caller re-filters afterwards."""
        pod = gang.pods[0]
        result = self.sched.preempt_routine(
            ei.ExtenderPreemptionArgs(
                pod=pod,
                node_name_to_meta_victims={
                    n: ei.MetaVictims() for n in self.nodes
                },
            )
        )
        victims = {
            mp.uid
            for mv in result.node_name_to_meta_victims.values()
            for mp in mv.pods
        }
        if not victims:
            return 0
        killed = 0
        for gname in list(live):
            g = live[gname]
            if any(p.uid in victims for p in g.bound):
                for p in g.bound:
                    self.sched.delete_pod(p)
                killed += len(g.bound)
                del live[gname]
                self._mark_dirty_gang(g)
        return killed

    def _take_whatif_sample(self, now: float, departures, waiting) -> None:
        """Mid-trace what-if forecast of the whole waiting queue (inproc
        subjects only — the plane forks the in-process scheduler)."""
        if self.core is None:
            return
        from ..scheduler import whatif as whatif_mod

        self.whatif_sample = whatif_mod.sim_sample(
            self,
            now,
            list(departures),
            list(waiting._order.values()),
            verify_deterministic=self._whatif_verify,
        )
        self.whatif_sample["waitingCount"] = len(waiting)

    def retry_storm(self, rounds: int = 3) -> Dict:
        """Extender-style pending retries over the end-of-trace waiting
        queue (call after ``run``): the K8s default scheduler re-filters
        every pending pod on its backoff REGARDLESS of cluster events —
        the exact repeated-rejection regime the negative-filter cache
        exists for (doc/hot-path.md "Pending-pod plane"). Sweeps the
        still-waiting gangs ``rounds`` times with nothing changed and
        reports the re-filter cost. An UNMEASURED quiesce pre-pass first
        removes (and releases) any waiter the trace's final wake left
        schedulable, so every measured call is a true repeated
        rejection — bind handling and teardown never pollute the
        recorded throughput or percentiles."""
        gangs = list(getattr(self, "last_waiting", []) or [])
        # One pod object per gang for the whole storm (the default
        # scheduler retries the same pod object too); building pods is
        # driver bookkeeping, not re-filter cost — keep it out of the
        # measured region.
        probes = {g.name: g.make_pods()[0] for g in gangs}
        for gang in list(gangs):  # quiesce (unmeasured)
            pod = probes[gang.name]
            r = self.sched.filter_routine(
                ei.ExtenderArgs(pod=pod, node_names=self.nodes)
            )
            if r.node_names:
                self.sched.delete_pod(pod)
                gangs.remove(gang)
        n_waiters = len(gangs)
        lat_ms: List[float] = []
        steady_ms: List[float] = []  # rounds 2+: the repeated rejections
        attempts = 0
        t0 = time.perf_counter()
        for rnd in range(max(0, rounds)):
            for gang in list(gangs):
                pod = probes[gang.name]
                t1 = time.perf_counter()
                r = self.sched.filter_routine(
                    ei.ExtenderArgs(pod=pod, node_names=self.nodes)
                )
                dt = (time.perf_counter() - t1) * 1e3
                if r.node_names:
                    # Cannot happen post-quiesce (measured WAITs mutate
                    # nothing), but never let an assume-bind leak into
                    # the stats or the state if it somehow does.
                    self.sched.delete_pod(pod)
                    gangs.remove(gang)
                    continue
                lat_ms.append(dt)
                if rnd > 0:
                    steady_ms.append(dt)
                attempts += 1
        wall_s = time.perf_counter() - t0
        lat_ms.sort()
        steady_ms.sort()
        # report._pct: the one percentile convention every stage of a
        # BENCH artifact shares.
        from .report import _pct

        return {
            "rounds": rounds,
            "waiters": n_waiters,
            "attempts": attempts,
            "wallS": round(wall_s, 4),
            "refilterPerSec": round(attempts / wall_s, 1)
            if wall_s > 0
            else 0.0,
            "p50Ms": round(_pct(lat_ms, 0.50), 4),
            "p99Ms": round(_pct(lat_ms, 0.99), 4),
            # Rounds 2+ only — each waiter's first sweep attempt may be
            # a legitimate cold re-filter (the trace's final events
            # changed its chains); the steady tail is the
            # repeated-rejection cost the plane exists to cut.
            "steadyP50Ms": round(_pct(steady_ms, 0.50), 4),
            "steadyP99Ms": round(_pct(steady_ms, 0.99), 4),
        }

    # -- flight-recording replay (the black-box plane) ----------------- #

    def replay_recording(self, recording: Dict) -> Dict:
        """Re-drive a flight-recorder window's verbs against this
        driver's subject (scheduler.recorder: the subject was restored to
        the window's anchor through the what-if fork path). Placement is
        a pure function of (state, verb order, preempt RNG), so the
        subject's own recorder captures a bind stream that fingerprints
        identically to the live window's — the deterministic incident
        repro. Returns per-kind event counts plus ``_skipped`` (events
        the replay had no target for) and ``_errors`` (verbs that raised
        the same protocol errors the live run saw)."""
        from ..scheduler.recorder import (
            _pod_from_payload,
            _rng_state_from_json,
        )

        sched = self.sched
        pods = {
            int(k): v for k, v in (recording.get("pods") or {}).items()
        }
        node_lists = {
            int(k): [str(n) for n in v]
            for k, v in (recording.get("nodeLists") or {}).items()
        }
        counts: Dict[str, int] = {}
        skipped = errors = 0
        for ev in recording.get("events") or []:
            kind = str(ev.get("kind") or "")
            counts[kind] = counts.get(kind, 0) + 1
            try:
                if kind == "filter":
                    pod = _pod_from_payload(pods[ev["pod"]])
                    # Key-presence, not truthiness: a recorded EMPTY
                    # suggested set is a real input (the buddy-fit
                    # rejection scenario) and must not replay as the
                    # whole fleet.
                    ref = ev.get("nodes")
                    nodes = (
                        node_lists[ref] if ref in node_lists
                        else self.nodes
                    )
                    sched.filter_routine(
                        ei.ExtenderArgs(pod=pod, node_names=nodes)
                    )
                elif kind == "preempt":
                    pod = _pod_from_payload(pods[ev["pod"]])
                    cand = node_lists.get(ev.get("nodes")) or []
                    sched.preempt_routine(
                        ei.ExtenderPreemptionArgs(
                            pod=pod,
                            node_name_to_meta_victims={
                                n: ei.MetaVictims() for n in cand
                            },
                        )
                    )
                elif kind == "bind":
                    sched.bind_routine(
                        ei.ExtenderBindingArgs(
                            pod_name=ev["podName"],
                            pod_namespace=(
                                ev.get("namespace") or "default"
                            ),
                            pod_uid=ev["uid"],
                            node=ev["node"],
                        )
                    )
                elif kind == "pod_add":
                    sched.add_pod(_pod_from_payload(pods[ev["pod"]]))
                elif kind == "pod_update":
                    sched.update_pod(
                        _pod_from_payload(pods[ev["old"]]),
                        _pod_from_payload(pods[ev["pod"]]),
                    )
                elif kind == "pod_delete":
                    status = sched.pod_schedule_statuses.get(ev["uid"])
                    if status is not None:
                        sched.delete_pod(status.pod)
                    else:
                        skipped += 1
                elif kind == "node_add":
                    sched.add_node(Node(
                        name=ev["node"],
                        ready=bool(ev.get("ready", True)),
                        annotations=dict(ev.get("annotations") or {}),
                    ))
                elif kind == "node_state":
                    new = Node(
                        name=ev["node"],
                        ready=bool(ev.get("ready", True)),
                        annotations=dict(ev.get("annotations") or {}),
                    )
                    old = sched.nodes.get(ev["node"]) or Node(
                        name=ev["node"]
                    )
                    sched.update_node(old, new)
                elif kind == "node_delete":
                    node = sched.nodes.get(ev["node"])
                    if node is not None:
                        sched.delete_node(node)
                    else:
                        skipped += 1
                elif kind == "health_tick":
                    sched.health_tick()
                elif kind == "settle_health":
                    sched.settle_health_now()
                elif kind == "settle_health_wall":
                    # Wall-floor settles replay as force-settles: the
                    # recorded position IS the time the floor expired.
                    sched.settle_health_now()
                elif kind == "defrag_cycle":
                    sched.run_defrag_cycle_now()
                elif kind == "defrag_take":
                    sched.take_defrag_proposals()
                elif kind == "defrag_report":
                    if getattr(sched, "defrag", None) is not None:
                        sched.defrag.report_migration(
                            str(ev.get("group") or ""),
                            ok=bool(ev.get("ok")),
                            reason=str(ev.get("reason") or ""),
                        )
                elif kind == "seed_rng":
                    state = _rng_state_from_json(ev.get("state"))
                    if state is not None and self.core is not None:
                        rng = self.core.preempt_rng
                        if rng is None:
                            # A fresh core carries no RNG until seeded;
                            # the recorded state IS the seeding.
                            rng = self.core.preempt_rng = random.Random()
                        rng.setstate(state)
                else:
                    skipped += 1
            except Exception as e:  # noqa: BLE001
                # Protocol errors replay as protocol errors (the live run
                # recorded them too); anything else is counted, logged,
                # and must not abort the repro mid-window.
                errors += 1
                common.log.debug(
                    "replay verb %s raised (recorded outcome stands): %s",
                    kind, e,
                )
        counts["_skipped"] = skipped
        counts["_errors"] = errors
        return counts

    # -- replay -------------------------------------------------------- #

    def run(self, trace: Dict) -> Dict:
        shape = TraceShape.from_dict(trace["shape"])
        # Deterministic preempt victim-node picks, keyed to the trace:
        # the sharded frontend seeds every worker, a single-core subject
        # (in-process or behind the wire adapter) seeds its core.
        seed = int(trace.get("seed", 0))
        seeder = getattr(self.sched, "seed_preempt_rng", None)
        if seeder is not None:
            seeder(seed)
        elif self.core is not None:
            self.core.preempt_rng = random.Random(seed)
            recorder = getattr(self.sched, "recorder", None)
            if recorder is not None:
                # The flight recorder anchors on the preempt-RNG state:
                # reseeding bypasses the verb stream, so tell it (replay
                # reinstates the exact state; scheduler.recorder).
                recorder.note_rng_state(self.core.preempt_rng)

        live: Dict[str, _Gang] = {}
        waiting = _WaitQueue(self._leaf_family, self.fifo_retry)
        self._dirty_families = set()
        wake_wall_s = 0.0
        departures: List[Tuple[float, int, str]] = []  # (t, seq, gang)
        dep_seq = 0
        lat_ms: List[float] = []
        submitted = bound_gangs = 0
        submitted_guaranteed = bound_guaranteed = 0
        preemption_events = preempted_pods = 0
        pods_bound = 0
        wait_times: List[float] = []
        frag_series: List[Dict] = []
        frag_at = [
            shape.duration_s * (k + 1) / max(1, self.frag_samples)
            for k in range(self.frag_samples)
        ]
        frag_i = 0
        faults_applied = 0
        defrag_proposals = defrag_migrations = 0
        t_wall0 = time.perf_counter()

        def depart_until(t: float) -> int:
            """Process departures through trace time ``t``, dirtying each
            departed gang's family; returns how many gangs freed (the
            wake trigger)."""
            nonlocal pods_bound
            freed = 0
            while departures and departures[0][0] <= t:
                _, _, gname = heapq.heappop(departures)
                g = live.pop(gname, None)
                if g is None:
                    continue  # already preempted away
                for p in g.bound:
                    self.sched.delete_pod(p)
                self._mark_dirty_gang(g)
                freed += 1
            return freed

        def try_schedule(gang: _Gang, now: float) -> bool:
            nonlocal bound_gangs, bound_guaranteed, pods_bound
            nonlocal preemption_events, preempted_pods, dep_seq
            t0 = time.perf_counter()
            ok = self._filter_gang(gang)
            if not ok and gang.guaranteed:
                gang.make_pods()  # fresh pods: the failed set was deleted
                killed = self._try_preempt(gang, live)
                if killed:
                    preemption_events += 1
                    preempted_pods += killed
                    ok = self._filter_gang(gang)
                if not ok:
                    # Release any reservation the probe committed (the
                    # extender's cancel: preempt with no candidates), so
                    # a waiting gang never parks capacity it cannot use.
                    self.sched.preempt_routine(
                        ei.ExtenderPreemptionArgs(
                            pod=gang.pods[0],
                            node_name_to_meta_victims={},
                        )
                    )
                    for q in gang.pods:
                        self.sched.delete_pod(q)
            lat_ms.append((time.perf_counter() - t0) * 1e3)
            if not ok:
                return False
            gang.bound_t = now
            self.gang_bound_t[gang.name] = now
            # A fresh bind is a fresh potential preemption victim: dirty
            # the family so earlier-FIFO guaranteed waiters re-attempt at
            # the next wake (exactly what the FIFO rescan gives them).
            self._mark_dirty_gang(gang)
            live[gang.name] = gang
            heapq.heappush(
                departures, (now + gang.runtime_s, dep_seq, gang.name)
            )
            dep_seq += 1
            bound_gangs += 1
            pods_bound += len(gang.bound)
            if gang.guaranteed:
                bound_guaranteed += 1
            wait_times.append(now - gang.submit_t)
            return True

        def retry_waiting(now: float) -> None:
            """One retry wake: drain the dirty-family set and attempt the
            eligible waiters in FIFO order (the FIFO hatch attempts
            everyone; an ALL_FAMILIES mark means the same). Marks
            generated DURING the wake — binds, preemption kills — stay
            for the NEXT wake, which is when the FIFO rescan's
            position-earlier waiters get to react to them too. No budget:
            the budget stopgap is retired; the eligibility index (and,
            one layer down, the scheduler's wait cache) is what bounds
            the cost now."""
            nonlocal wake_wall_s
            if not len(waiting):
                return
            fams = self._dirty_families
            self._dirty_families = set()
            families = (
                None if ALL_FAMILIES in fams else frozenset(fams)
            )
            waiting.wake_events += 1
            t0 = time.perf_counter()
            for gang in waiting.eligible(families):
                waiting.wake_attempts += 1
                gang.make_pods()
                if try_schedule(gang, now):
                    waiting.remove(gang.name)
            wake_wall_s += time.perf_counter() - t0

        whatif_t = (
            shape.duration_s * self._whatif_at
            if self._whatif_at is not None
            else None
        )
        for ev in trace["events"]:
            t = float(ev["t"])
            if (
                whatif_t is not None
                and self.whatif_sample is None
                and t >= whatif_t
            ):
                # Sample BEFORE this event applies, with the departure
                # heap untouched: unprocessed departures at t <= now
                # replay on the fork at relative t=0, so the fork sees
                # exactly the state+horizon the live replay will. The
                # sample never mutates live state (audit-enforced) and
                # never triggers wakes — the A/B fingerprint equality
                # with a whatif-free replay is asserted by the bench.
                self._take_whatif_sample(whatif_t, departures, waiting)
            while frag_i < len(frag_at) and frag_at[frag_i] <= t:
                # Defrag beat first, so the sample reflects the compacted
                # state this beat achieved (the A/B's measured quantity).
                dp, dm = self._defrag_pulse(live)
                defrag_proposals += dp
                defrag_migrations += dm
                if dm:
                    # Defrag migrations re-place whole gangs: global wake
                    # (identical in both retry modes by construction).
                    retry_waiting(frag_at[frag_i])
                if self.core is not None:
                    frag_series.append(
                        {
                            "t": frag_at[frag_i],
                            "freeSlices": fragmentation_snapshot(
                                self.core
                            ),
                        }
                    )
                frag_i += 1
            if depart_until(t):
                retry_waiting(t)
            kind = ev["kind"]
            if kind == "submit":
                gang = _Gang(ev["gang"], t)
                gang.make_pods()
                submitted += 1
                if gang.guaranteed:
                    submitted_guaranteed += 1
                if not try_schedule(gang, t):
                    waiting.add(gang)
            else:
                self._apply_fault(ev)
                faults_applied += 1
                # Same wake TRIGGERS as ever (capacity-freeing kinds);
                # the fault itself already dirtied its node's families,
                # capacity-removing kinds included — those are drained
                # by whichever wake comes next.
                if kind in ("chip_heal", "node_flip", "drain_toggle"):
                    retry_waiting(t)
        # Trace end: drain remaining departures, give waiters one last
        # chance at the emptying fleet (quota satisfaction is judged on
        # the whole trace, not on a cutoff artifact).
        end_t = shape.duration_s
        if depart_until(end_t):
            retry_waiting(end_t)
        while frag_i < len(frag_at):
            dp, dm = self._defrag_pulse(live)
            defrag_proposals += dp
            defrag_migrations += dm
            if self.core is not None:
                frag_series.append(
                    {
                        "t": frag_at[frag_i],
                        "freeSlices": fragmentation_snapshot(self.core),
                    }
                )
            frag_i += 1
        wall_s = time.perf_counter() - t_wall0
        # Kept for retry_storm (the extender-style pending-retry sweep
        # bench_pending drives after the replay).
        self.last_waiting: List[_Gang] = list(waiting._order.values())
        metrics = self.sched.get_metrics()
        fast_waits = int(metrics.get("fastWaitCount", 0) or 0)
        wait_calls = int(metrics.get("waitCount", 0) or 0)
        # Pending-pod plane observability (doc/hot-path.md): wake-side
        # costs and the wait-cache hit ratio. Deliberately OUTSIDE the
        # counts dict — wake attempt totals are a property of the retry
        # MODE, and the placement fingerprint (report.py) must stay
        # bit-identical across indexed / FIFO / cache-off replays of one
        # trace (the admission-equivalence contract).
        pending = {
            "retryMode": "fifo" if self.fifo_retry else "indexed",
            "waitingMax": waiting.waiting_max,
            "waitingAtEnd": len(waiting),
            "waitingByKey": waiting.key_counts(),
            "wakeEvents": waiting.wake_events,
            "wakeAttempts": waiting.wake_attempts,
            "wakeSkipped": waiting.wake_skipped,
            "wakeWallS": round(wake_wall_s, 3),
            "fastWaitCount": fast_waits,
            "waitCacheHitRatio": (
                round(fast_waits / wait_calls, 4) if wait_calls else 0.0
            ),
        }

        from .report import build_report

        return build_report(
            trace=trace,
            lat_ms=lat_ms,
            wall_s=wall_s,
            counts={
                "submitted": submitted,
                "boundGangs": bound_gangs,
                "podsBound": pods_bound,
                "submittedGuaranteed": submitted_guaranteed,
                "boundGuaranteed": bound_guaranteed,
                "preemptionEvents": preemption_events,
                "preemptedPods": preempted_pods,
                "waitingAtEnd": len(waiting),
                "liveAtEnd": len(live),
                "faultsApplied": faults_applied,
                "defragProposals": defrag_proposals,
                "defragMigrations": defrag_migrations,
            },
            wait_times_s=wait_times,
            frag_series=frag_series,
            metrics=metrics,
            mode=self.mode,
            pending=pending,
        )


def run_trace(
    trace: Dict,
    mode: str = "inproc",
    n_shards: int = 2,
    transport: str = "proc",
    hosts: Optional[int] = None,
    defrag: bool = False,
    frag_samples: int = 8,
    fifo_retry: Optional[bool] = None,
    wait_cache: Optional[bool] = None,
    retry_storm_rounds: int = 0,
) -> Dict:
    """Build the fleet the trace's shape names (or ``hosts`` override),
    replay, and return the report. ``defrag=True`` arms the background
    defragmenter (inproc mode) and drives its checkpoint-coordinated
    migrations at every fragmentation sample point — the A/B switch of
    the ``HIVED_BENCH_DEFRAG`` stage. ``fifo_retry``/``wait_cache`` are
    the pending-pod-plane A/B switches (HIVED_BENCH_PENDING,
    doc/hot-path.md "Pending-pod plane"): FIFO-rescan retry wakes instead
    of the eligibility index, and the scheduler-side negative-filter
    cache off (wait_cache=False travels via the config knob, so it
    reaches shard workers too)."""
    shape = TraceShape.from_dict(trace["shape"])
    config, actual_hosts = build_fleet_config(
        hosts if hosts is not None else shape.hosts
    )
    if defrag:
        config.defrag_enable = True
        config.defrag_interval_ticks = 1
        config.defrag_max_migrations_per_cycle = 2
    if wait_cache is not None and not wait_cache:
        config.wait_cache_capacity = 0
    driver = TraceDriver(
        config, mode=mode, n_shards=n_shards, transport=transport,
        frag_samples=frag_samples, fifo_retry=fifo_retry,
    )
    recorder = getattr(driver.sched, "recorder", None)
    if recorder is not None:
        # Stamp the fleet size so --replay-recording can rebuild the
        # identical bench config without a flag (scheduler.recorder).
        recorder.hosts = actual_hosts
    try:
        report = driver.run(trace)
        if retry_storm_rounds > 0:
            # Attached OUTSIDE the placement fingerprint (pendingPlane
            # is excluded from it): the storm is a measurement sweep,
            # not part of the replayed trace.
            report["pendingPlane"]["retryStorm"] = driver.retry_storm(
                rounds=retry_storm_rounds
            )
    finally:
        driver.close()
    report["hosts"] = actual_hosts
    return report
