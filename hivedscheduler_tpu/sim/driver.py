"""Time-compressed trace replay through the REAL scheduler.

No mocks: the driver speaks the verbs the HTTP extender serves —
``filter_routine`` (with assume-bind), ``preempt_routine`` (commit +
victim delete + re-filter, the production preemption protocol),
``delete_pod`` (departures and victim kills), ``update_node`` (the chaos
fault vocabulary) — against either the in-process ``HivedScheduler`` or
the multi-process ``ShardedScheduler`` frontend (``mode="shards"``,
doc/hot-path.md "The multi-process contract").

Time compression: trace time is a logical clock. Events replay in trace
order with zero sleeps; the *scheduler's* cost is measured in wall time
per gang schedule, while queueing delay (submit → bound) is measured in
TRACE time — so a 1-hour diurnal trace at 10k hosts runs in seconds yet
reports both "how slow is the scheduler" (tail latency) and "how well
does it schedule" (fragmentation, preemption rate, quota satisfaction).

Determinism: placements are a pure function of (config, trace) — the
preempt RNG is seeded from the trace seed, and placement itself is
state-pure (doc/hot-path.md "State-pure sorted view") — so two runs of
one trace produce identical binds, preemptions, and fragmentation
(tests/test_sim_smoke.py asserts it). Wall-clock latencies are the only
run-varying output.
"""

from __future__ import annotations

import heapq
import random
import time
from typing import Dict, List, Optional, Set, Tuple

from ..api import constants, extender as ei
from ..api.config import Config
from ..scheduler.framework import HivedScheduler, NullKubeClient
from ..scheduler.types import Node, Pod
from . import fleet
from .trace import TraceShape

# Waiting-queue retry budget per capacity-freeing event: bounds the
# worst-case O(waiting * events) replay cost while keeping the FIFO
# fairness the reference's block knob approximates.
RETRY_BUDGET_PER_EVENT = 8


def build_fleet_config(hosts: int) -> Tuple[Config, int]:
    """A bench-proportioned fleet approximating ``hosts``; returns the
    config and the exact host count."""
    cubes, slices, solos = fleet.fleet_dims_for_hosts(hosts)
    return (
        fleet.build_config(cubes, slices, solos),
        fleet.fleet_hosts(cubes, slices, solos),
    )


class _Gang:
    __slots__ = (
        "name", "vc", "leaf_type", "n_pods", "chips", "priority",
        "runtime_s", "submit_t", "pods", "bound", "bound_t", "ladder",
    )

    def __init__(self, spec: Dict, submit_t: float):
        self.name = spec["name"]
        self.vc = spec["vc"]
        self.leaf_type = spec["leafType"]
        self.n_pods = int(spec["pods"])
        self.chips = int(spec["chips"])
        self.priority = int(spec["priority"])
        self.runtime_s = float(spec["runtimeS"])
        self.ladder = spec.get("ladder", "")
        self.submit_t = submit_t
        self.pods: List[Pod] = []
        self.bound: List[Pod] = []
        self.bound_t: Optional[float] = None

    @property
    def guaranteed(self) -> bool:
        return self.priority >= 0

    def make_pods(self, ignore_suggested: bool = True) -> List[Pod]:
        group = {
            "name": self.name,
            "members": [
                {"podNumber": self.n_pods, "leafCellNumber": self.chips}
            ],
        }
        self.pods = [
            fleet.make_pod(
                f"{self.name}-{i}", f"{self.name}-u{i}", self.vc,
                self.priority, self.leaf_type, self.chips, group,
                ignore_suggested=ignore_suggested,
            )
            for i in range(self.n_pods)
        ]
        return self.pods


def fragmentation_snapshot(core) -> Dict[str, int]:
    """The sim tier's fragmentation metric: the core's schedulable-
    slice-size distribution (HivedCore.free_slice_distribution)."""
    return core.free_slice_distribution()


class TraceDriver:
    """Replays one trace against one scheduler instance."""

    def __init__(
        self,
        config: Config,
        mode: str = "inproc",
        n_shards: int = 2,
        transport: str = "proc",
        frag_samples: int = 8,
        scheduler=None,
    ):
        self.mode = mode
        self.frag_samples = frag_samples
        if scheduler is not None:
            # Pre-built subject (hack/sim_server.py's HTTP-wire adapter):
            # anything exposing the HivedScheduler verb surface — possibly
            # a ShardedScheduler, which has configured_node_names() on the
            # frontend and no single .core. Informer verbs may run
            # in-process; filter/preempt may cross a wire.
            self.sched = scheduler
            self.core = getattr(scheduler, "core", None)
            names = getattr(scheduler, "configured_node_names", None)
            self.nodes = sorted(
                names() if names is not None
                else scheduler.core.configured_node_names()
            )
        elif mode == "shards":
            from ..scheduler.shards import ShardedScheduler

            self.sched = ShardedScheduler(
                config,
                kube_client=NullKubeClient(),
                n_shards=n_shards,
                transport=transport,
                auto_admit=True,
            )
            self.core = None  # per-shard cores live behind the frontend
            self.nodes = sorted(self.sched.configured_node_names())
        else:
            self.sched = HivedScheduler(
                config, kube_client=NullKubeClient(), auto_admit=True
            )
            self.core = self.sched.core
            self.nodes = sorted(self.core.configured_node_names())
        self._node_cache: Dict[str, Node] = {}
        for n in self.nodes:
            node = Node(name=n)
            self._node_cache[n] = node
            self.sched.add_node(node)

    def _bound_pod(self, uid: str) -> Pod:
        """The assume-bound pod object for one scheduled uid, any mode
        and transport."""
        if self.core is not None:
            return self.sched.pod_schedule_statuses[uid].pod
        found = self.sched.get_status_pod(uid)
        return found[0]

    def close(self) -> None:
        close = getattr(self.sched, "close", None)
        if close is not None:
            close()

    # -- fault vocabulary (chaos events, resolved by node index) ------- #

    def _apply_fault(self, ev: Dict) -> None:
        name = self.nodes[ev["nodeIndex"] % len(self.nodes)]
        old = self._node_cache[name]
        annotations = dict(old.annotations)
        ready = old.ready
        kind = ev["kind"]
        if kind == "node_flip":
            ready = ev.get("to", "down") == "up"
        elif kind in ("chip_fault", "chip_heal"):
            bad: Set[str] = set(
                x
                for x in annotations.get(
                    constants.ANNOTATION_NODE_DEVICE_HEALTH, ""
                ).split(",")
                if x
            )
            chip = str(ev.get("chip", 0))
            if kind == "chip_fault":
                bad.add(chip)
            else:
                bad.discard(chip)
            if bad:
                annotations[constants.ANNOTATION_NODE_DEVICE_HEALTH] = (
                    ",".join(sorted(bad))
                )
            else:
                annotations.pop(
                    constants.ANNOTATION_NODE_DEVICE_HEALTH, None
                )
        elif kind == "drain_toggle":
            if ev.get("on"):
                annotations[constants.ANNOTATION_NODE_DRAIN] = "*"
            else:
                annotations.pop(constants.ANNOTATION_NODE_DRAIN, None)
        new = Node(name=name, ready=ready, annotations=annotations)
        self._node_cache[name] = new
        self.sched.update_node(old, new)

    # -- the scheduling protocol (what the extender does) -------------- #

    def _filter_gang(
        self, gang: _Gang, nodes: Optional[List[str]] = None
    ) -> bool:
        """Filter every pod of the gang; on full success the gang is live
        (assume-bound). On partial failure the placed pods are deleted —
        the framework's partial-gang release. ``nodes`` narrows the
        suggested set (the defrag migration steer)."""
        bound: List[Pod] = []
        for p in gang.pods:
            r = self.sched.filter_routine(
                ei.ExtenderArgs(pod=p, node_names=nodes or self.nodes)
            )
            if not r.node_names:
                for q in gang.pods:
                    self.sched.delete_pod(q)
                return False
            bound.append(self._bound_pod(p.uid))
        gang.bound = bound
        return True

    # -- the defragmenter's workload-controller half ------------------- #

    def _defrag_pulse(self, live: Dict[str, "_Gang"]) -> Tuple[int, int]:
        """One defrag beat (inproc mode only): advance the event clock
        (runs a cycle when the interval allows), then play the workload
        controller for every proposal — checkpoint (implicit), delete the
        gang, re-filter it onto the compacting placement (suggested set
        minus the fragment's nodes), cancel-on-fail releasing the
        reservation. Returns (proposals, migrations)."""
        sched = self.sched
        if getattr(sched, "defrag", None) is None or self.core is None:
            return 0, 0
        sched.health_tick()
        proposals = sched.take_defrag_proposals()
        migrated = 0
        for prop in proposals:
            gang = live.get(prop["group"])
            if gang is None:
                sched.defrag.report_migration(
                    prop["group"], ok=False, reason="gang departed"
                )
                continue
            avoid = set(prop["avoidNodes"])
            target = [n for n in self.nodes if n not in avoid]
            for p in gang.bound:
                sched.delete_pod(p)
            gang.make_pods(ignore_suggested=False)
            if self._filter_gang(gang, nodes=target):
                sched.defrag.report_migration(prop["group"], ok=True)
                migrated += 1
                continue
            # Cancel-on-fail: release the reservation and put the gang
            # back wherever it fits (its original cells are still free).
            gang.make_pods()
            if not self._filter_gang(gang):
                live.pop(prop["group"], None)
            sched.defrag.report_migration(
                prop["group"], ok=False,
                reason="re-filter found no compacting placement",
            )
        return len(proposals), migrated

    def _try_preempt(self, gang: _Gang, live: Dict[str, "_Gang"]) -> int:
        """The production preemption protocol for the gang's first pod:
        probe/commit via preempt_routine; if victims are proposed, kill
        them (their whole gangs, as the eviction would) and report how
        many pods died. The caller re-filters afterwards."""
        pod = gang.pods[0]
        result = self.sched.preempt_routine(
            ei.ExtenderPreemptionArgs(
                pod=pod,
                node_name_to_meta_victims={
                    n: ei.MetaVictims() for n in self.nodes
                },
            )
        )
        victims = {
            mp.uid
            for mv in result.node_name_to_meta_victims.values()
            for mp in mv.pods
        }
        if not victims:
            return 0
        killed = 0
        for gname in list(live):
            g = live[gname]
            if any(p.uid in victims for p in g.bound):
                for p in g.bound:
                    self.sched.delete_pod(p)
                killed += len(g.bound)
                del live[gname]
        return killed

    # -- replay -------------------------------------------------------- #

    def run(self, trace: Dict) -> Dict:
        shape = TraceShape.from_dict(trace["shape"])
        # Deterministic preempt victim-node picks, keyed to the trace:
        # the sharded frontend seeds every worker, a single-core subject
        # (in-process or behind the wire adapter) seeds its core.
        seed = int(trace.get("seed", 0))
        seeder = getattr(self.sched, "seed_preempt_rng", None)
        if seeder is not None:
            seeder(seed)
        elif self.core is not None:
            self.core.preempt_rng = random.Random(seed)

        live: Dict[str, _Gang] = {}
        waiting: List[_Gang] = []
        departures: List[Tuple[float, int, str]] = []  # (t, seq, gang)
        dep_seq = 0
        lat_ms: List[float] = []
        submitted = bound_gangs = 0
        submitted_guaranteed = bound_guaranteed = 0
        preemption_events = preempted_pods = 0
        pods_bound = 0
        wait_times: List[float] = []
        frag_series: List[Dict] = []
        frag_at = [
            shape.duration_s * (k + 1) / max(1, self.frag_samples)
            for k in range(self.frag_samples)
        ]
        frag_i = 0
        faults_applied = 0
        defrag_proposals = defrag_migrations = 0
        t_wall0 = time.perf_counter()

        def depart_until(t: float) -> int:
            nonlocal pods_bound
            freed = 0
            while departures and departures[0][0] <= t:
                _, _, gname = heapq.heappop(departures)
                g = live.pop(gname, None)
                if g is None:
                    continue  # already preempted away
                for p in g.bound:
                    self.sched.delete_pod(p)
                freed += 1
            return freed

        def try_schedule(gang: _Gang, now: float) -> bool:
            nonlocal bound_gangs, bound_guaranteed, pods_bound
            nonlocal preemption_events, preempted_pods, dep_seq
            t0 = time.perf_counter()
            ok = self._filter_gang(gang)
            if not ok and gang.guaranteed:
                gang.make_pods()  # fresh pods: the failed set was deleted
                killed = self._try_preempt(gang, live)
                if killed:
                    preemption_events += 1
                    preempted_pods += killed
                    ok = self._filter_gang(gang)
                if not ok:
                    # Release any reservation the probe committed (the
                    # extender's cancel: preempt with no candidates), so
                    # a waiting gang never parks capacity it cannot use.
                    self.sched.preempt_routine(
                        ei.ExtenderPreemptionArgs(
                            pod=gang.pods[0],
                            node_name_to_meta_victims={},
                        )
                    )
                    for q in gang.pods:
                        self.sched.delete_pod(q)
            lat_ms.append((time.perf_counter() - t0) * 1e3)
            if not ok:
                return False
            gang.bound_t = now
            live[gang.name] = gang
            heapq.heappush(
                departures, (now + gang.runtime_s, dep_seq, gang.name)
            )
            dep_seq += 1
            bound_gangs += 1
            pods_bound += len(gang.bound)
            if gang.guaranteed:
                bound_guaranteed += 1
            wait_times.append(now - gang.submit_t)
            return True

        def retry_waiting(now: float) -> None:
            budget = RETRY_BUDGET_PER_EVENT
            i = 0
            while i < len(waiting) and budget > 0:
                gang = waiting[i]
                gang.make_pods()
                budget -= 1
                if try_schedule(gang, now):
                    waiting.pop(i)
                else:
                    i += 1

        for ev in trace["events"]:
            t = float(ev["t"])
            while frag_i < len(frag_at) and frag_at[frag_i] <= t:
                # Defrag beat first, so the sample reflects the compacted
                # state this beat achieved (the A/B's measured quantity).
                dp, dm = self._defrag_pulse(live)
                defrag_proposals += dp
                defrag_migrations += dm
                if dm:
                    retry_waiting(frag_at[frag_i])
                if self.core is not None:
                    frag_series.append(
                        {
                            "t": frag_at[frag_i],
                            "freeSlices": fragmentation_snapshot(
                                self.core
                            ),
                        }
                    )
                frag_i += 1
            if depart_until(t):
                retry_waiting(t)
            kind = ev["kind"]
            if kind == "submit":
                gang = _Gang(ev["gang"], t)
                gang.make_pods()
                submitted += 1
                if gang.guaranteed:
                    submitted_guaranteed += 1
                if not try_schedule(gang, t):
                    waiting.append(gang)
            else:
                self._apply_fault(ev)
                faults_applied += 1
                if kind in ("chip_heal", "node_flip", "drain_toggle"):
                    retry_waiting(t)
        # Trace end: drain remaining departures, give waiters one last
        # chance at the emptying fleet (quota satisfaction is judged on
        # the whole trace, not on a cutoff artifact).
        end_t = shape.duration_s
        if depart_until(end_t):
            retry_waiting(end_t)
        while frag_i < len(frag_at):
            dp, dm = self._defrag_pulse(live)
            defrag_proposals += dp
            defrag_migrations += dm
            if self.core is not None:
                frag_series.append(
                    {
                        "t": frag_at[frag_i],
                        "freeSlices": fragmentation_snapshot(self.core),
                    }
                )
            frag_i += 1
        wall_s = time.perf_counter() - t_wall0

        from .report import build_report

        return build_report(
            trace=trace,
            lat_ms=lat_ms,
            wall_s=wall_s,
            counts={
                "submitted": submitted,
                "boundGangs": bound_gangs,
                "podsBound": pods_bound,
                "submittedGuaranteed": submitted_guaranteed,
                "boundGuaranteed": bound_guaranteed,
                "preemptionEvents": preemption_events,
                "preemptedPods": preempted_pods,
                "waitingAtEnd": len(waiting),
                "liveAtEnd": len(live),
                "faultsApplied": faults_applied,
                "defragProposals": defrag_proposals,
                "defragMigrations": defrag_migrations,
            },
            wait_times_s=wait_times,
            frag_series=frag_series,
            metrics=self.sched.get_metrics(),
            mode=self.mode,
        )


def run_trace(
    trace: Dict,
    mode: str = "inproc",
    n_shards: int = 2,
    transport: str = "proc",
    hosts: Optional[int] = None,
    defrag: bool = False,
    frag_samples: int = 8,
) -> Dict:
    """Build the fleet the trace's shape names (or ``hosts`` override),
    replay, and return the report. ``defrag=True`` arms the background
    defragmenter (inproc mode) and drives its checkpoint-coordinated
    migrations at every fragmentation sample point — the A/B switch of
    the ``HIVED_BENCH_DEFRAG`` stage."""
    shape = TraceShape.from_dict(trace["shape"])
    config, actual_hosts = build_fleet_config(
        hosts if hosts is not None else shape.hosts
    )
    if defrag:
        config.defrag_enable = True
        config.defrag_interval_ticks = 1
        config.defrag_max_migrations_per_cycle = 2
    driver = TraceDriver(
        config, mode=mode, n_shards=n_shards, transport=transport,
        frag_samples=frag_samples,
    )
    try:
        report = driver.run(trace)
    finally:
        driver.close()
    report["hosts"] = actual_hosts
    return report
