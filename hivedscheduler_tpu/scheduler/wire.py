"""One wire for everything: the versioned compact binary frame format
every internal hop rides (doc/hot-path.md "One wire").

Three measured ledger rows pointed at the same bottleneck — serialization
(parallel-compile pickle-back, pod-dict-sized ring pickles, O(fleet) JSON
suggested-node lists) — so this module is the single codec those hops now
share: the shards duplex pipe + ShmRing frames, the parallel-compile
hand-back, the snapshot fork/anchor hops, and the sim server's HTTP wire.

Frame layout (golden-pinned by tests/test_wire.py):

    MAGIC(1) VERSION(1) KIND(1) VARINT(payload bytes) PAYLOAD

``MAGIC`` (0xA7) collides with neither pickle (protocol >= 2 starts with
0x80) nor JSON (``{``/``[``/whitespace), so every receiving hop sniffs the
first byte and falls back to its legacy codec losslessly — the
``HIVED_WIRE=0`` hatch simply stops producing frames, and mixed traffic
decodes fine during the transition. A version-byte mismatch raises
``WireVersionError`` (the caller re-sends legacy or refuses), and the
payload-length varint makes truncation a mechanical ``WireTruncatedError``
instead of a misdecode.

The PAYLOAD is one tagged value. Scalars are struct-packed (zigzag-free
dual-tag varints for ints, big-endian f64 for floats); strings are
interned per frame (first occurrence carries the bytes, repeats are a
varint back-reference — node/chain/VC names repeat heavily in cell and
snapshot frames); two bulk fast paths keep the hot frames at C speed:

- ``STRLIST``: an all-string list (the suggested-node list) is one
  NUL-joined blob — ``str.join``/``str.split`` instead of per-element
  tag dispatch;
- ``JSON``: a dict wrapped in ``wire.Json`` (caller-asserted JSON-safe:
  string keys all the way down, JSON value types only — true for every
  k8s-born pod dict and every ``to_dict()`` result) is one ``json.dumps``
  blob — the C encoder does the element walk.

Anything the tagged model cannot express raises ``WireEncodeError`` and
the transport falls back to pickle for that frame (counted per codec in
``wireBytesTotal``); decode correctness never depends on the fallback
being rare.

Pure data transformation — no locks, no I/O, no imports from the rest of
the package — so both the scheduler layer and the algorithm layer (the
compile hand-back) can use it without an import cycle.
"""

from __future__ import annotations

import json
import os
import struct
from typing import Any, Dict, Optional

MAGIC = 0xA7
VERSION = 1

# Frame kinds: caller semantics, pinned by the golden wire-format test.
KIND_OBJ = 1       # generic scheduler object (pipe / ring frames)
KIND_SNAPSHOT = 2  # snapshot-body envelope (fork / anchor hops)
KIND_CELLS = 3     # struct-packed compile hand-back (columnar cells)
KIND_DELTA = 4     # delta-encoded suggested set

# HTTP content type for binary extender frames (hack/sim_server.py).
CONTENT_TYPE = "application/x-hived-wire"

# The one knob: HIVED_WIRE=0 stops every hop from PRODUCING frames
# (receivers still sniff, so mixed traffic during a rollout decodes).
WIRE_ENV = "HIVED_WIRE"


def enabled() -> bool:
    """The legacy hatch, read fresh per call site so tests and the A/B
    bench can flip it per stage: HIVED_WIRE=0 reverts every producer to
    its legacy codec; receivers keep sniffing either way."""
    return os.environ.get(WIRE_ENV, "1").strip() != "0"


class WireError(Exception):
    """Base for every wire codec error."""


class WireEncodeError(WireError):
    """Value not expressible in the tagged model — fall back to pickle."""


class WireDecodeError(WireError):
    """Frame is not decodable as the running wire format."""


class WireVersionError(WireDecodeError):
    """Frame carries a different format version — refuse, never guess."""


class WireTruncatedError(WireDecodeError):
    """Frame shorter than its own length header (cut mid-transport)."""


class Json(dict):
    """Marker subclass: this dict is JSON-born (string keys all the way
    down, JSON value types only), so the encoder may serialize it as one
    C-speed ``json.dumps`` blob instead of element-wise. The contract is
    caller-asserted; a dict that turns out not to be JSON-encodable is
    transparently re-encoded element-wise."""

    __slots__ = ()


# --------------------------------------------------------------------- #
# Value tags (pinned by the golden fixtures — renumbering is a VERSION
# bump, not an edit)
# --------------------------------------------------------------------- #

_T_NONE = 0
_T_TRUE = 1
_T_FALSE = 2
_T_POSINT = 3   # varint n
_T_NEGINT = 4   # varint (-1 - n)
_T_FLOAT = 5    # 8-byte big-endian double
_T_STR = 6      # varint len + utf8; registers the next intern index
_T_REF = 7      # varint intern index (string back-reference)
_T_BYTES = 8    # varint len + raw
_T_LIST = 9     # varint n + values
_T_TUPLE = 10   # varint n + values
_T_DICT = 11    # varint n + (key value) pairs
_T_JSON = 12    # varint len + json utf8 (decodes to a plain dict)
_T_STRLIST = 13  # varint n + varint len + NUL-joined utf8

_pack_f64 = struct.Struct(">d").pack
_unpack_f64 = struct.Struct(">d").unpack_from


def _w_varint(out: bytearray, n: int) -> None:
    while n > 0x7F:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)


def _r_varint(buf: bytes, pos: int):
    shift = 0
    n = 0
    while True:
        b = buf[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7


def _w_value(obj: Any, out: bytearray, interns: Dict[str, int]) -> None:
    t = type(obj)
    if t is str:
        idx = interns.get(obj)
        if idx is not None:
            out.append(_T_REF)
            _w_varint(out, idx)
        else:
            interns[obj] = len(interns)
            b = obj.encode()
            out.append(_T_STR)
            _w_varint(out, len(b))
            out += b
    elif t is int:
        if obj >= 0:
            out.append(_T_POSINT)
            _w_varint(out, obj)
        else:
            out.append(_T_NEGINT)
            _w_varint(out, -1 - obj)
    elif obj is None:
        out.append(_T_NONE)
    elif t is bool:
        out.append(_T_TRUE if obj else _T_FALSE)
    elif t is float:
        out.append(_T_FLOAT)
        out += _pack_f64(obj)
    elif t is Json:
        try:
            b = json.dumps(obj, separators=(",", ":")).encode()
        except (TypeError, ValueError):
            # Caller over-promised; the element-wise path is always safe.
            out.append(_T_DICT)
            _w_varint(out, len(obj))
            for k, v in obj.items():
                _w_value(k, out, interns)
                _w_value(v, out, interns)
        else:
            out.append(_T_JSON)
            _w_varint(out, len(b))
            out += b
    elif t is dict:
        out.append(_T_DICT)
        _w_varint(out, len(obj))
        for k, v in obj.items():
            _w_value(k, out, interns)
            _w_value(v, out, interns)
    elif t is list:
        if obj and all(
            type(x) is str and "\x00" not in x for x in obj
        ):
            # Suggested-node-list fast path: one C-level join; decode is
            # one C-level split. No interning — the names are unique.
            b = "\x00".join(obj).encode()
            out.append(_T_STRLIST)
            _w_varint(out, len(obj))
            _w_varint(out, len(b))
            out += b
        else:
            out.append(_T_LIST)
            _w_varint(out, len(obj))
            for v in obj:
                _w_value(v, out, interns)
    elif t is tuple:
        out.append(_T_TUPLE)
        _w_varint(out, len(obj))
        for v in obj:
            _w_value(v, out, interns)
    elif t is bytes:
        out.append(_T_BYTES)
        _w_varint(out, len(obj))
        out += obj
    elif t is bytearray or t is memoryview:
        b = bytes(obj)
        out.append(_T_BYTES)
        _w_varint(out, len(b))
        out += b
    else:
        # Subclasses land here on purpose: round-tripping them as their
        # base type would silently change the object's type.
        raise WireEncodeError(
            f"type {t.__module__}.{t.__name__} is not wire-encodable"
        )


def _r_value(buf: bytes, pos: int, strings: list):
    tag = buf[pos]
    pos += 1
    if tag == _T_STR:
        n, pos = _r_varint(buf, pos)
        end = pos + n
        if end > len(buf):
            raise WireTruncatedError("string runs past frame end")
        s = buf[pos:end].decode()
        strings.append(s)
        return s, end
    if tag == _T_REF:
        n, pos = _r_varint(buf, pos)
        try:
            return strings[n], pos
        except IndexError:
            raise WireDecodeError(f"intern reference {n} out of range")
    if tag == _T_POSINT:
        return _r_varint(buf, pos)
    if tag == _T_NEGINT:
        n, pos = _r_varint(buf, pos)
        return -1 - n, pos
    if tag == _T_NONE:
        return None, pos
    if tag == _T_TRUE:
        return True, pos
    if tag == _T_FALSE:
        return False, pos
    if tag == _T_FLOAT:
        if pos + 8 > len(buf):
            raise WireTruncatedError("float runs past frame end")
        return _unpack_f64(buf, pos)[0], pos + 8
    if tag == _T_JSON:
        n, pos = _r_varint(buf, pos)
        end = pos + n
        if end > len(buf):
            raise WireTruncatedError("json blob runs past frame end")
        try:
            return json.loads(buf[pos:end]), end
        except ValueError as e:
            raise WireDecodeError(f"json blob undecodable: {e}")
    if tag == _T_DICT:
        n, pos = _r_varint(buf, pos)
        d = {}
        for _ in range(n):
            k, pos = _r_value(buf, pos, strings)
            v, pos = _r_value(buf, pos, strings)
            d[k] = v
        return d, pos
    if tag == _T_LIST:
        n, pos = _r_varint(buf, pos)
        lst = []
        append = lst.append
        for _ in range(n):
            v, pos = _r_value(buf, pos, strings)
            append(v)
        return lst, pos
    if tag == _T_STRLIST:
        n, pos = _r_varint(buf, pos)
        blen, pos = _r_varint(buf, pos)
        end = pos + blen
        if end > len(buf):
            raise WireTruncatedError("string list runs past frame end")
        lst = buf[pos:end].decode().split("\x00")
        if len(lst) != n:
            raise WireDecodeError(
                f"string list count mismatch: header {n}, got {len(lst)}"
            )
        return lst, end
    if tag == _T_TUPLE:
        n, pos = _r_varint(buf, pos)
        items = []
        append = items.append
        for _ in range(n):
            v, pos = _r_value(buf, pos, strings)
            append(v)
        return tuple(items), pos
    if tag == _T_BYTES:
        n, pos = _r_varint(buf, pos)
        end = pos + n
        if end > len(buf):
            raise WireTruncatedError("bytes run past frame end")
        return buf[pos:end], end
    raise WireDecodeError(f"unknown value tag {tag}")


# --------------------------------------------------------------------- #
# Frames
# --------------------------------------------------------------------- #


def dumps(obj: Any, kind: int = KIND_OBJ) -> bytes:
    """Encode one value into a self-delimiting wire frame. Raises
    ``WireEncodeError`` (and produces nothing) when the value is not
    expressible — callers fall back to their legacy codec per frame."""
    out = bytearray()
    _w_value(obj, out, {})
    head = bytearray((MAGIC, VERSION, kind))
    _w_varint(head, len(out))
    head += out
    return bytes(head)


def is_wire(buf) -> bool:
    """First-byte sniff: True when ``buf`` can only be a wire frame (of
    ANY version — version errors must surface, not fall back)."""
    return len(buf) >= 4 and buf[0] == MAGIC


def frame_kind(buf) -> int:
    """The KIND byte of a validated frame header."""
    if not is_wire(buf):
        raise WireDecodeError("not a wire frame")
    return buf[2]


def loads(buf, kind: Optional[int] = None) -> Any:
    """Decode one frame. The validation ladder is mechanical: magic,
    version (refusal, not fallback), optional kind pin, payload length
    (truncation), then the tagged payload with no trailing bytes."""
    if isinstance(buf, (bytearray, memoryview)):
        buf = bytes(buf)
    if not isinstance(buf, bytes) or len(buf) < 4 or buf[0] != MAGIC:
        raise WireDecodeError("not a wire frame")
    if buf[1] != VERSION:
        raise WireVersionError(
            f"wire version {buf[1]}, running {VERSION}"
        )
    if kind is not None and buf[2] != kind:
        raise WireDecodeError(
            f"frame kind {buf[2]}, expected {kind}"
        )
    try:
        paylen, pos = _r_varint(buf, 3)
    except IndexError:
        raise WireTruncatedError("frame cut inside the length header")
    if len(buf) - pos != paylen:
        raise WireTruncatedError(
            f"payload length mismatch: header says {paylen} bytes, "
            f"got {len(buf) - pos}"
        )
    try:
        val, end = _r_value(buf, pos, [])
    except (IndexError, struct.error):
        raise WireTruncatedError("frame cut inside the payload")
    if end != len(buf):
        raise WireDecodeError(f"{len(buf) - end} trailing bytes")
    return val


def json_passthrough(buf) -> Optional[bytes]:
    """Zero-copy reply path: when a frame's payload is exactly one JSON
    blob (a ``wire.Json`` reply), return the raw JSON bytes — the HTTP
    layer can write them verbatim, skipping the decode + ``json.dumps``
    re-encode the legacy pickle path pays. Returns None for any other
    shape (caller falls back to ``loads``)."""
    if isinstance(buf, (bytearray, memoryview)):
        buf = bytes(buf)
    if (
        not isinstance(buf, bytes)
        or len(buf) < 5
        or buf[0] != MAGIC
        or buf[1] != VERSION
    ):
        return None
    try:
        paylen, pos = _r_varint(buf, 3)
    except IndexError:
        return None
    if len(buf) - pos != paylen or buf[pos] != _T_JSON:
        return None
    try:
        blen, bpos = _r_varint(buf, pos + 1)
    except IndexError:
        return None
    if bpos + blen != len(buf):
        return None
    return buf[bpos:]


def frame_size_bucket(n: int) -> int:
    """Power-of-two size bucket for the bytes-per-frame histogram the
    bench stages record (bucket k covers [2^(k-1), 2^k) bytes)."""
    return n.bit_length()
