"""Active-standby HA: lease-based leader election for the scheduler.

The reference runs a single scheduler process with no leader election
(PAPER.md) — a crash is a full scheduling blackout until the replacement
finishes replaying every bound pod. This module adds the warm-standby
half of the recovery plane (doc/fault-model.md "HA and snapshot recovery
plane"):

- :class:`LeaderElector` drives a ``coordination.k8s.io`` Lease through
  any :class:`~.framework.KubeClient` (production: the REST client in
  ``scheduler.kube``; tests: an in-memory fake). The holder renews every
  ``renew_s``; anyone else may acquire once ``renewTime +
  leaseDurationSeconds`` has passed. Acquisition goes through the
  optimistic ``resourceVersion`` precondition, so two standbys racing for
  an expired lease cannot both win.

- **Self-deposal at expiry**: ``is_leader()`` is a pure local check —
  held AND the local clock has not passed the last successful renewal
  plus the lease duration. A leader that cannot reach the apiserver stops
  claiming leadership the moment its lease would have expired for
  everyone else, WITHOUT needing to observe the new holder. That is the
  fencing half of the split-brain argument: the old leader refuses bind
  writes (framework.bind_routine) strictly before a standby can have
  acquired the lease.

- :class:`StandbyLoop` is the production driver: hold off while another
  process leads (optionally prefetching snapshots so takeover starts
  warm), run recovery on acquiry, then keep renewing. ``/readyz`` stays
  503 the whole standby phase (webserver gates on leadership AND recovery
  completion), so K8s never routes extender traffic to the standby.

Clocks are injectable (``clock=``) so the chaos harness drives failovers
deterministically; production uses ``time.monotonic``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from .. import common


class LeaderElector:
    """One participant in the Lease protocol. ``try_acquire_or_renew`` is a
    single synchronous step (testable without threads); ``run`` loops it.

    The elector only needs two client methods — ``read_lease()`` and
    ``write_lease(spec, resource_version=)`` (see framework.KubeClient) —
    and the Lease spec shape it reads/writes is the K8s one:
    holderIdentity, leaseDurationSeconds, acquireTime, renewTime,
    leaseTransitions. ``renewTime``/``acquireTime`` are numbers in the
    elector's OWN clock domain; the REST client translates to/from
    MicroTime strings (kube.KubeAPIClient)."""

    def __init__(
        self,
        client,
        identity: str,
        duration_s: float = 15.0,
        renew_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.client = client
        self.identity = identity
        self.duration_s = float(duration_s)
        self.renew_s = float(renew_s)
        self.clock = clock
        # Local expiry of OUR leadership: last successful renewal + the
        # lease duration. None = not the leader. This is the only state
        # is_leader() reads, so the check is lock-free and O(1).
        self._held_until: Optional[float] = None
        self.observed_holder = ""
        # Times leadership changed hands TO this elector (mirrors the
        # Lease's leaseTransitions for this participant's acquisitions).
        self.transition_count = 0
        # Lease weather semantics (doc/fault-model.md "Control-plane
        # weather plane"): the last step's verdict about WHY leadership
        # is (or is not) progressing — "ok", "unreachable" (cannot renew:
        # the apiserver did not answer; leadership decays by local expiry
        # only), or "superseded" (another holder observed: definite
        # deposition — the intent-journal discard fence keys on this
        # distinction, framework._definitely_superseded).
        self.lease_weather = "ok"
        self.cannot_renew_count = 0
        self.superseded_count = 0
        # Warm resumptions: renew succeeded with OUR identity still on
        # the lease after a local expiry — leadership resumes without the
        # cold-takeover recovery (StandbyLoop consumes the flag).
        self.own_reacquire_count = 0
        self._own_resumption = False

    # ---------------- the protocol step ---------------- #

    def is_leader(self) -> bool:
        held = self._held_until
        return held is not None and self.clock() < held

    def try_acquire_or_renew(self) -> bool:
        """One election step: renew our lease, or acquire a free/expired
        one. Returns the (possibly unchanged) leadership verdict. Failures
        never raise — a read/write error leaves the local state alone, and
        self-deposal at expiry still happens via is_leader()."""
        now = self.clock()
        try:
            cur = self.client.read_lease()
        except Exception as e:  # noqa: BLE001
            self.lease_weather = "unreachable"
            self.cannot_renew_count += 1
            common.log.warning(
                "leader lease read failed (leadership unchanged until "
                "local expiry): %s", e,
            )
            return self.is_leader()
        spec: Dict = {}
        resource_version = None
        if cur:
            spec = dict(cur.get("spec") or {})
            resource_version = cur.get("resourceVersion")
        holder = str(spec.get("holderIdentity") or "")
        self.observed_holder = holder
        try:
            renew_time = float(spec.get("renewTime") or 0.0)
            duration = float(
                spec.get("leaseDurationSeconds") or self.duration_s
            )
        except (TypeError, ValueError):
            renew_time, duration = 0.0, self.duration_s
        if holder and holder != self.identity and now < renew_time + duration:
            # Someone else holds an unexpired lease. If we thought we were
            # the leader, we have been superseded (e.g. clock trouble) —
            # depose immediately rather than waiting for local expiry.
            if self._held_until is not None:
                # Definite supersession (vs a plain standby beat, which
                # is healthy "ok" weather: the apiserver answered).
                self.lease_weather = "superseded"
                self.superseded_count += 1
                common.log.warning(
                    "leader lease now held by %s; deposing", holder,
                )
                self._held_until = None
            else:
                self.lease_weather = "ok"
            return False
        transitions = int(spec.get("leaseTransitions") or 0)
        acquiring = holder != self.identity
        new_spec = {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": int(self.duration_s),
            "acquireTime": (
                now if acquiring else spec.get("acquireTime", now)
            ),
            "renewTime": now,
            "leaseTransitions": transitions + (1 if acquiring else 0),
        }
        # Was our leadership LOCALLY expired going into this step? (A
        # stale _held_until float, not None — None means never-held or
        # definitively deposed.) If the write below lands while our own
        # identity is still on the lease, this is a warm resumption: no
        # standby can have acquired in between (the optimistic
        # resourceVersion precondition would have failed us), so the
        # in-memory projection is still the cluster truth and the
        # cold-takeover recovery is unnecessary.
        resuming_own = (
            self._held_until is not None
            and now >= self._held_until
            and not acquiring
        )
        try:
            self.client.write_lease(
                new_spec, resource_version=resource_version
            )
        except Exception as e:  # noqa: BLE001
            # Lost the optimistic write (another standby won) or transport
            # trouble: keep whatever leadership the last successful
            # renewal bought — it self-expires.
            self.lease_weather = "unreachable"
            self.cannot_renew_count += 1
            common.log.warning(
                "leader lease write failed (leadership unchanged until "
                "local expiry): %s", e,
            )
            return self.is_leader()
        self.lease_weather = "ok"
        if self._held_until is None:
            self.transition_count += 1
            common.log.warning(
                "acquired leader lease as %s (transitions=%d)",
                self.identity, new_spec["leaseTransitions"],
            )
        elif resuming_own:
            self.own_reacquire_count += 1
            self._own_resumption = True
            common.log.warning(
                "re-acquired own leader lease as %s after local expiry "
                "(warm resumption, no cold takeover)", self.identity,
            )
        self._held_until = now + self.duration_s
        self.observed_holder = self.identity
        return True

    def consume_own_resumption(self) -> bool:
        """Return-and-clear the warm-resumption flag. StandbyLoop calls
        this on every not-leading→leading edge: True means the leadership
        gap was OUR lease all along (local expiry, nobody else acquired),
        so the cold-takeover recovery callback must be skipped — the
        in-memory projection never stopped being the cluster truth."""
        flag = self._own_resumption
        self._own_resumption = False
        return flag

    def step_down(self) -> None:
        """Voluntarily release leadership (graceful shutdown): zero the
        renewTime so a standby acquires immediately instead of waiting a
        full lease duration. The release is read-verify-write under the
        optimistic precondition — a late step_down (our lease expired and
        another elector already acquired) must NOT blank the new holder's
        lease, which would let a third elector acquire while the new
        holder still considers itself leader."""
        if self._held_until is None:
            return
        self._held_until = None
        try:
            cur = self.client.read_lease()
            if not cur:
                return
            spec = dict(cur.get("spec") or {})
            if str(spec.get("holderIdentity") or "") != self.identity:
                return  # superseded already: nothing of ours to release
            self.client.write_lease(
                {
                    "holderIdentity": "",
                    "leaseDurationSeconds": int(self.duration_s),
                    "renewTime": 0.0,
                    "leaseTransitions": int(
                        spec.get("leaseTransitions") or 0
                    ),
                },
                resource_version=cur.get("resourceVersion"),
            )
        except Exception as e:  # noqa: BLE001
            common.log.warning("lease release write failed: %s", e)


class StandbyLoop:
    """The active-standby driver: hold off while another process leads,
    take over on lease expiry, keep renewing afterwards.

    ``on_started_leading`` runs ONCE, synchronously, at the moment of
    acquisition and before the loop resumes renewing — this is where the
    caller runs recovery (snapshot + delta replay) and starts its
    informer; ``/readyz`` turns 200 only after it returns (the framework
    gates readiness on recovery completion AND leadership).
    ``on_stopped_leading`` fires if leadership is ever lost afterwards —
    the safest production response is to exit and let the supervisor
    restart the process into standby (the framework independently fences
    bind writes either way).

    While standing by, each idle beat invokes ``on_standby_beat`` (e.g.
    prefetch the latest snapshot chunks so takeover starts warm)."""

    def __init__(
        self,
        elector: LeaderElector,
        on_started_leading: Callable[[], None],
        on_stopped_leading: Optional[Callable[[], None]] = None,
        on_standby_beat: Optional[Callable[[], None]] = None,
        sleep: Optional[Callable[[float], None]] = None,
    ) -> None:
        self.elector = elector
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.on_standby_beat = on_standby_beat
        self._stop = threading.Event()
        self._sleep = sleep or self._stop.wait
        self._thread: Optional[threading.Thread] = None
        self.was_leading = False

    def step(self) -> bool:
        """One beat of the loop (synchronous, test-friendly): election
        step, transition callbacks, standby prefetch. Returns leadership."""
        leading = self.elector.try_acquire_or_renew()
        if leading and not self.was_leading:
            self.was_leading = True
            consume = getattr(
                self.elector, "consume_own_resumption", None
            )
            if consume is not None and consume():
                # Own-lease warm resumption: the apiserver blackout
                # outlasted the lease locally, but our identity was still
                # on the Lease when it healed — nobody else led in
                # between, so the projection is intact and the cold
                # recovery (snapshot + replay) is skipped.
                common.log.warning(
                    "resuming own leadership warm (no cold takeover)",
                )
            else:
                self.on_started_leading()
        elif not leading:
            if self.was_leading:
                self.was_leading = False
                common.log.error(
                    "leadership lost (lease expired or superseded)",
                )
                if self.on_stopped_leading is not None:
                    self.on_stopped_leading()
            if self.on_standby_beat is not None:
                try:
                    self.on_standby_beat()
                except Exception:  # noqa: BLE001
                    common.log.exception("standby beat failed")
        return leading

    def run(self) -> None:
        while not self._stop.is_set():
            try:
                self.step()
            except Exception:  # noqa: BLE001
                common.log.exception("leader election step failed")
            self._sleep(self.elector.renew_s)

    def start(self) -> None:
        t = threading.Thread(
            target=self.run, name="hived-leader-elector", daemon=True
        )
        self._thread = t
        t.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
