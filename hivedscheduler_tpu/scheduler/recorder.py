"""Production flight recorder: a bounded verb ring with deterministic
incident replay (the black-box plane, doc/observability.md).

The recorder captures every MUTATING verb the scheduler serves — filter
(with its bind/preempt/wait outcome), the preempt lifecycle, bind writes,
pod add/update/delete, node add/state/delete (health and drain events),
health-clock ticks, and the defragmenter's controller verbs — as events
in the sim trace vocabulary (``{t, seq, kind, ...}``; node events carry
the trace tier's ``nodeIndex`` addressing alongside the name). Each
recording **window** is anchored on a PR-7 snapshot export
(``export_fork_body`` — the same walk the what-if plane forks from) plus
the preempt-RNG state, so the window is self-contained: *anchor state +
recorded verbs = a deterministic repro*.

Replay (``python -m hivedscheduler_tpu.sim --replay-recording FILE``)
restores the anchor through the what-if fork path
(``_import_snapshot_state`` on a fresh scheduler, exactly like
``whatif.build_fork``) and re-drives the window's verbs through
:class:`~..sim.driver.TraceDriver` — placement is a pure function of
(state, verb order, preempt RNG), so the replay's bind stream is
fingerprint-identical to the live run's (tests/test_flight_recorder.py
asserts it at the 432-host bench fleet).

Window management: when the ring reaches capacity the recorder
**re-anchors** — takes a fresh snapshot export (whose state subsumes every
recorded event) and starts an empty window. A transient projection
(preemption in flight — ``export_fork_body`` returns None) defers the
re-anchor; past a 2x hard cap the oldest events are dropped and the
window is marked ``truncated`` (served for diagnosis, refused for
replay). Under ``procShards`` the recorder captures at the FRONTEND
(pre-routing), so one stream covers all shards; frontend windows anchor
only at boot (``pristine``) — a merged mid-run anchor across shard
projections is a recorded follow-on.

Overhead: one dict build + list append per verb, no locks shared with
the scheduling path beyond the GIL; gated by the interleaved bench A/B
(``HIVED_BENCH_AUDIT=1``) against the PR-6 <=3% filter-p50 budget.
``HIVED_FLIGHT_RECORDER=0`` (or ``flightRecorderCapacity: 0``) disables.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Callable, Dict, List, Optional, Tuple

from .. import common
from ..api import constants, extender as ei
from . import snapshot as snapshot_mod, wire as wire_mod
from .types import Node, Pod

FLIGHT_RECORDER_ENV = "HIVED_FLIGHT_RECORDER"


def filter_outcome(result) -> str:
    """THE wire-visible outcome classification of an
    ExtenderFilterResult — the framework recorder, the shards frontend's
    recorder, and its trace attrs all share this one implementation
    (taxonomy changes happen here, once)."""
    if result is None:
        return "error"
    if result.node_names:
        return "bind"
    if result.failed_nodes and set(result.failed_nodes) != {
        constants.COMPONENT_NAME
    }:
        return "preempt"
    return "wait"


def record_preempt_result(rec, pod: Pod, args, result) -> None:
    """THE preempt-verb capture both frontends share: victim uids off
    the result, outcome = preempt / none (probe found nothing — the
    free-resource and wait shapes are indistinguishable on the wire) /
    error (the verb raised)."""
    victims = (
        [
            mp.uid
            for mv in result.node_name_to_meta_victims.values()
            for mp in mv.pods
        ]
        if result is not None
        else None
    )
    rec.record_preempt(
        pod,
        list(args.node_name_to_meta_victims.keys()),
        "preempt" if victims else (
            "none" if result is not None else "error"
        ),
        victims=victims,
    )

RECORDING_VERSION = 1

# Fault kinds whose capacity effect the sim tier treats as a retry-wake
# trigger; recorded on node_state events purely as diagnostic context
# (verb-level replay re-derives behavior from the verbs themselves).
_WAKE_KINDS = ("chip_heal", "node_flip", "drain_toggle")


def _json_rng_state(state) -> Optional[List]:
    """random.Random.getstate() -> a JSON-stable [version, [ints], gauss]
    triple (and back via _rng_state_from_json)."""
    if state is None:
        return None
    try:
        version, internal, gauss = state
        return [int(version), [int(x) for x in internal], gauss]
    except (TypeError, ValueError):
        return None


def _rng_state_from_json(data) -> Optional[Tuple]:
    if not data:
        return None
    try:
        version, internal, gauss = data
        return (int(version), tuple(int(x) for x in internal), gauss)
    except (TypeError, ValueError):
        return None


def _pod_payload(pod: Pod) -> Dict:
    # The annotation/limit dicts are SHARED, not copied: pod objects are
    # replaced (never mutated) on every lifecycle change, so the payload
    # stays a faithful call-time snapshot without two dict copies per
    # recorded pod on the filter hot path. The one in-place mutation in
    # the codebase — the preempt-info checkpoint stamped onto a
    # preemptor pod — touches an annotation the filter replay never
    # reads (it only matters to recovery), so sharing is repro-safe.
    return {
        "name": pod.name,
        "namespace": pod.namespace,
        "uid": pod.uid,
        "annotations": pod.annotations,
        "resourceLimits": pod.resource_limits,
        "node": pod.node_name or "",
        "phase": pod.phase or "",
    }


def _pod_from_payload(payload: Dict) -> Pod:
    return Pod(
        name=payload["name"],
        namespace=payload.get("namespace") or "default",
        uid=payload["uid"],
        annotations=dict(payload.get("annotations") or {}),
        node_name=payload.get("node") or None,
        phase=payload.get("phase") or "Pending",
        resource_limits={
            str(k): int(v)
            for k, v in (payload.get("resourceLimits") or {}).items()
        },
    )


class FlightRecorder:
    """One scheduler's black box. ``exporter`` is the anchor source
    (``export_fork_body``; None = frontend capture, pristine anchors
    only); ``rng_state_fn`` snapshots the preempt RNG at (re)anchor."""

    def __init__(
        self,
        capacity: int = 2048,
        exporter: Optional[Callable[[], Optional[Dict]]] = None,
        rng_state_fn: Optional[Callable[[], object]] = None,
        config_fingerprint: str = "",
        granularity: str = "framework",
        hosts: Optional[int] = None,
    ):
        self.capacity = max(16, int(capacity))
        self.exporter = exporter
        self.rng_state_fn = rng_state_fn
        self.config_fingerprint = config_fingerprint
        self.granularity = granularity
        self.hosts = hosts
        self.events: List[Dict] = []
        self._seq = 0
        self.total_events = 0
        self.dropped_events = 0
        self.reanchor_count = 0
        self.truncated = False
        self._need_reanchor = False
        # Anchor of the CURRENT window. Pristine = "replay from a fresh
        # scheduler" (valid until the first re-anchor).
        self.anchor: Dict = {"pristine": True, "body": None,
                             "rngState": None, "seq": 0}
        if rng_state_fn is not None:
            try:
                self.anchor["rngState"] = _json_rng_state(rng_state_fn())
            except Exception:  # noqa: BLE001
                pass
        # Pod payload registry: events reference payloads by ref so a
        # gang's spec annotation is stored once per distinct content, not
        # per re-filter. uid -> (last pod object, last payload, ref) for
        # the identity/equality fast path.
        self._pods: Dict[int, Dict] = {}
        self._pod_memo: Dict[str, Tuple[object, Dict, int]] = {}
        self._pod_ref_seq = 0
        # Suggested-node-list registry: identity-memoized first (callers
        # reusing one list object — the sim driver, filter_fast's memo —
        # hit in O(1)), content-keyed second (fresh per-request lists pay
        # one tuple hash). BOTH memos are bounded and clear wholesale:
        # refs are monotonic and never reused, so forgetting dedup state
        # only costs a re-registration, never a wrong reference. Each
        # identity entry holds a strong ref to its list (the id cannot
        # recycle while the entry lives), capped at a handful.
        self._node_lists: Dict[int, List[str]] = {}
        self._nodes_by_id: Dict[int, Tuple[object, int]] = {}
        self._nodes_by_key: Dict[Tuple, int] = {}
        self._nodes_ref_seq = 0
        # Node-index addressing (the sim trace vocabulary): lazily built
        # from the first node event's scheduler-provided sorted list.
        self._node_index: Dict[str, int] = {}

    # ------------------------------------------------------------------ #
    # registries
    # ------------------------------------------------------------------ #

    def _pod_ref(self, pod: Pod) -> int:
        memo = self._pod_memo.get(pod.uid)
        if memo is not None:
            obj, payload, ref = memo
            if obj is pod:
                return ref
            fresh = _pod_payload(pod)
            if fresh == payload:
                self._pod_memo[pod.uid] = (pod, payload, ref)
                return ref
            self._pod_ref_seq += 1
            ref = self._pod_ref_seq
            self._pods[ref] = fresh
            self._pod_memo[pod.uid] = (pod, fresh, ref)
            self._prune_pods()
            return ref
        payload = _pod_payload(pod)
        self._pod_ref_seq += 1
        ref = self._pod_ref_seq
        self._pods[ref] = payload
        self._pod_memo[pod.uid] = (pod, payload, ref)
        self._prune_pods()
        return ref

    def _prune_pods(self) -> None:
        """Drop pod payloads (and memo pins) no live window event
        references. The re-anchor path clears these wholesale, but a
        frontend recorder (exporter=None) never re-anchors — a
        long-lived frontend must not accrete one payload per pod
        lifetime forever (the same discipline as _prune_node_lists)."""
        if len(self._pods) <= max(4096, 2 * self.capacity):
            return
        live = {ev.get("pod") for ev in self.events}
        live |= {ev.get("old") for ev in self.events}
        self._pods = {r: p for r, p in self._pods.items() if r in live}
        self._pod_memo = {
            uid: entry
            for uid, entry in self._pod_memo.items()
            if entry[2] in self._pods
        }

    def _nodes_ref(self, node_names) -> int:
        hit = self._nodes_by_id.get(id(node_names))
        if hit is not None and hit[0] is node_names:
            return hit[1]
        key = tuple(node_names)
        ref = self._nodes_by_key.get(key)
        if ref is None:
            if len(self._nodes_by_key) > 64:
                self._nodes_by_key.clear()
            self._nodes_ref_seq += 1
            ref = self._nodes_ref_seq
            self._node_lists[ref] = [str(n) for n in key]
            self._nodes_by_key[key] = ref
            self._prune_node_lists()
        if len(self._nodes_by_id) > 8:
            self._nodes_by_id.clear()
        self._nodes_by_id[id(node_names)] = (node_names, ref)
        return ref

    def _prune_node_lists(self) -> None:
        """Drop list payloads no live window event references (distinct
        content is rare — the filter_fast premise — but a long-lived
        frontend must not accrete payloads forever)."""
        if len(self._node_lists) <= 4096:
            return
        live = {ev.get("nodes") for ev in self.events}
        self._node_lists = {
            r: v for r, v in self._node_lists.items() if r in live
        }
        self._nodes_by_key.clear()
        self._nodes_by_id.clear()

    def set_node_universe(self, names) -> None:
        """The sorted configured node list, for trace-vocabulary
        nodeIndex addressing on node events."""
        self._node_index = {str(n): i for i, n in enumerate(sorted(names))}

    # ------------------------------------------------------------------ #
    # window management
    # ------------------------------------------------------------------ #

    def force_reanchor(self) -> None:
        """State was rewritten outside the verb stream (recovery,
        snapshot restore): the current window no longer replays. The next
        recorded verb re-anchors instead of appending."""
        self._need_reanchor = True

    def note_rng_state(self, rng) -> None:
        """The preempt RNG was (re)seeded (the sim driver / shard seeding
        path). Pre-window it updates the anchor; mid-window it records a
        seed event the replay re-applies."""
        state = _json_rng_state(rng.getstate())
        if not self.events and not self._need_reanchor:
            self.anchor["rngState"] = state
        else:
            self._append({"kind": "seed_rng", "state": state})

    def _try_anchor(self) -> bool:
        if self.exporter is None:
            return False
        try:
            body = self.exporter()
        except Exception:  # noqa: BLE001 — recording must never raise
            common.log.exception("flight-recorder anchor export failed")
            return False
        if body is None:
            return False  # transient projection: defer
        rng_state = None
        if self.rng_state_fn is not None:
            try:
                rng_state = _json_rng_state(self.rng_state_fn())
            except Exception:  # noqa: BLE001
                pass
        self.anchor = {
            "pristine": False,
            "body": body,
            "rngState": rng_state,
            "seq": self._seq,
        }
        # Anchor-at-rest compression (scheduler.wire): the window holds
        # its anchor for the whole recording lifetime, and the packed
        # KIND_SNAPSHOT frame is ~4.7x smaller than the live body dict's
        # JSON (measured at 91k cells). Stored alongside the frame, the
        # fingerprint lets recording() run the same validation ladder the
        # HA pre-apply uses. Pack failure keeps the dict — recording must
        # never lose an anchor to a codec edge.
        if wire_mod.enabled():
            try:
                self.anchor["bodyWire"] = snapshot_mod.encode_body_wire(
                    body, str(self.config_fingerprint), 0
                )
                self.anchor["body"] = None
            except Exception:  # noqa: BLE001
                self.anchor.pop("bodyWire", None)
                self.anchor["body"] = body
        self.events = []
        self._pods = {}
        self._pod_memo = {}
        self._node_lists = {}
        self._nodes_by_key.clear()
        self._nodes_by_id.clear()
        self.truncated = False
        self.reanchor_count += 1
        return True

    def _append(self, ev: Dict) -> None:
        if self._need_reanchor:
            if self._try_anchor():
                self._need_reanchor = False
                # The triggering verb's effects are inside the fresh
                # anchor — appending it too would double-apply on replay.
                return
            # Cannot anchor (frontend, or transient): the window is torn
            # until an anchor lands; keep the tail for diagnosis.
            self.truncated = True
        self._seq += 1
        self.total_events += 1
        ev["seq"] = self._seq
        ev["t"] = float(self._seq)
        self.events.append(ev)
        if len(self.events) >= self.capacity:
            if not self._try_anchor() and len(self.events) >= 2 * self.capacity:
                drop = len(self.events) - 2 * self.capacity + 1
                del self.events[:drop]
                self.dropped_events += drop
                self.truncated = True

    # ------------------------------------------------------------------ #
    # verb hooks (called by the framework / frontend, outside locks)
    # ------------------------------------------------------------------ #

    def record_filter(self, pod: Pod, node_names, outcome: str,
                      node: str = "", leaf_cells=None,
                      error: str = "") -> None:
        ev: Dict = {
            "kind": "filter",
            "pod": self._pod_ref(pod),
            "uid": pod.uid,
            "nodes": self._nodes_ref(node_names),
            "outcome": outcome,
        }
        if node:
            ev["node"] = node
        if leaf_cells:
            # The raw isolation annotation string (framework capture) or
            # a list (tests); the fingerprint treats it as opaque.
            ev["leafCells"] = leaf_cells
        if error:
            ev["error"] = error[:200]
        self._append(ev)

    def record_filter_wire(self, request: Dict, outcome: str,
                           node: str = "") -> None:
        """filter_raw capture from the already-decoded request dict —
        the raw hot path must not rebuild dataclasses per call. The memo
        is keyed by uid + annotation-dict equality, so a re-filtered pod
        (the retry-storm regime) costs one small dict compare; full pod
        construction runs only on first sight or a changed spec."""
        pod_d = request.get("Pod") or {}
        md = pod_d.get("metadata") or {}
        uid = str(md.get("uid") or "")
        ann = md.get("annotations") or {}
        memo = self._pod_memo.get(uid)
        if memo is not None and memo[1].get("annotations") == ann:
            ref = memo[2]
        else:
            ref = self._pod_ref(ei.pod_from_k8s(pod_d))
        ev: Dict = {
            "kind": "filter",
            "pod": ref,
            "uid": uid,
            "nodes": self._nodes_ref(request.get("NodeNames") or []),
            "outcome": outcome,
        }
        if node:
            ev["node"] = node
        self._append(ev)

    def record_preempt(self, pod: Pod, candidate_nodes, outcome: str,
                       victims=None) -> None:
        ev: Dict = {
            "kind": "preempt",
            "pod": self._pod_ref(pod),
            "uid": pod.uid,
            "nodes": self._nodes_ref(list(candidate_nodes)),
            "outcome": outcome,
        }
        if victims:
            ev["victims"] = sorted(victims)
        self._append(ev)

    def record_bind(self, pod_name: str, namespace: str, uid: str,
                    node: str, ok: bool) -> None:
        self._append({
            "kind": "bind", "uid": uid, "podName": pod_name,
            "namespace": namespace, "node": node, "ok": bool(ok),
        })

    def record_pod_event(self, kind: str, pod: Pod) -> None:
        """kind in pod_add / pod_delete."""
        ev: Dict = {"kind": kind, "uid": pod.uid}
        if kind != "pod_delete":
            ev["pod"] = self._pod_ref(pod)
        self._append(ev)

    def record_pod_update(self, old: Pod, new: Pod) -> None:
        """One event carrying both sides (replay re-issues
        update_pod(old, new) — the framework's uid-change and
        bound-transition semantics re-derive from the pair)."""
        self._append({
            "kind": "pod_update",
            "uid": new.uid,
            "old": self._pod_ref(old),
            "pod": self._pod_ref(new),
        })

    def record_node_event(self, kind: str, node: Node,
                          fault: str = "") -> None:
        """kind in node_add / node_state / node_delete; ``fault`` is the
        chaos-vocabulary kind derived from the projection diff
        (node_flip / chip_fault / chip_heal / drain_toggle)."""
        ev: Dict = {
            "kind": kind,
            "node": node.name,
            "nodeIndex": self._node_index.get(node.name, -1),
        }
        if kind != "node_delete":
            ev["ready"] = bool(node.ready)
            if node.annotations:
                ev["annotations"] = dict(node.annotations)
        if fault:
            ev["fault"] = fault
            ev["wake"] = fault in _WAKE_KINDS
        self._append(ev)

    def record_marker(self, kind: str, **fields) -> None:
        """Clock/defrag verbs: health_tick, settle_health, defrag_cycle,
        defrag_take, defrag_report."""
        ev = {"kind": kind}
        ev.update(fields)
        self._append(ev)

    # ------------------------------------------------------------------ #
    # serving / dumping
    # ------------------------------------------------------------------ #

    def _anchor_for_dump(self) -> Dict:
        """The anchor in its EXTERNAL shape (a plain ``body`` dict): the
        recording/dump format predates the wire codec and stays
        byte-compatible, so a wire-packed anchor-at-rest is unpacked here
        through the same validation ladder the HA pre-apply uses. An
        undecodable frame (impossible same-process, but recording must
        never raise) dumps as a torn anchor with the refusal reason."""
        buf = self.anchor.get("bodyWire")
        if buf is None:
            return self.anchor
        anchor = {k: v for k, v in self.anchor.items() if k != "bodyWire"}
        body, reason = snapshot_mod.decode_body_wire(
            buf, str(self.config_fingerprint)
        )
        if body is None:
            anchor["bodyError"] = reason
        anchor["body"] = body
        return anchor

    def recording(self) -> Dict:
        """The full dumpable window (the unit --replay-recording
        consumes)."""
        return {
            "version": RECORDING_VERSION,
            "kind": "flightRecording",
            "configFingerprint": self.config_fingerprint,
            "granularity": self.granularity,
            "hosts": self.hosts,
            "truncated": self.truncated,
            "anchor": self._anchor_for_dump(),
            "events": list(self.events),
            "pods": {str(ref): p for ref, p in self._pods.items()},
            "nodeLists": {
                str(ref): names for ref, names in self._node_lists.items()
            },
            "meta": {
                "capacity": self.capacity,
                "windowEvents": len(self.events),
                "totalEvents": self.total_events,
                "droppedEvents": self.dropped_events,
                "reanchors": self.reanchor_count,
            },
        }

    def summary(self) -> Dict:
        """The cheap inspect payload (?full=1 serves the recording)."""
        kinds: Dict[str, int] = {}
        for ev in self.events:
            kinds[ev["kind"]] = kinds.get(ev["kind"], 0) + 1
        return {
            "granularity": self.granularity,
            "truncated": self.truncated,
            "anchorPristine": bool(self.anchor.get("pristine")),
            "anchorSeq": self.anchor.get("seq", 0),
            "windowEvents": len(self.events),
            "totalEvents": self.total_events,
            "droppedEvents": self.dropped_events,
            "reanchors": self.reanchor_count,
            "capacity": self.capacity,
            "eventKinds": kinds,
            "fingerprint": events_fingerprint(
                self.events, self.granularity
            ),
        }

    def dump(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.recording(), f, separators=(",", ":"))
        return path

    def metrics_snapshot(self) -> Dict:
        return {
            "flightRecorderEventCount": self.total_events,
            "flightRecorderReanchorCount": self.reanchor_count,
        }


# --------------------------------------------------------------------- #
# Replay: anchor restore (the what-if fork path) + verb re-drive
# --------------------------------------------------------------------- #


def recording_fingerprint(recording: Dict,
                          granularity: Optional[str] = None) -> str:
    """The placement fingerprint of a recording window: the ordered
    stream of scheduling OUTCOMES — every filter bind (pod -> node, plus
    chip isolation when the capture layer had it) and every preempt
    victim set. Two windows with equal fingerprints placed identically in
    the same order. ``granularity`` lets a replay (which always captures
    at the framework layer, chips included) fingerprint itself at a
    frontend-captured recording's coarser (pod, node) granularity."""
    return events_fingerprint(
        recording.get("events") or [],
        granularity or recording.get("granularity") or "framework",
    )


def events_fingerprint(events: List[Dict], gran: str) -> str:
    """recording_fingerprint over a live event list (the summary path
    must not copy the whole window just to hash its bind stream)."""
    items: List = []
    for ev in events:
        kind = ev.get("kind")
        if kind == "filter" and ev.get("outcome") == "bind":
            item = ["bind", ev.get("uid"), ev.get("node")]
            if gran == "framework":
                # Opaque isolation token (the raw annotation string, or
                # a list from test-built events) — normalized to str so
                # both shapes compare stably.
                iso = ev.get("leafCells")
                item.append(
                    ",".join(str(x) for x in iso)
                    if isinstance(iso, (list, tuple))
                    else str(iso or "")
                )
            items.append(item)
        elif kind == "preempt" and ev.get("victims"):
            items.append(["preempt", ev.get("uid"),
                          list(ev.get("victims"))])
    blob = json.dumps(items, separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()


def build_replay_subject(recording: Dict, config):
    """A scheduler restored to the recording's anchor, through the
    what-if fork path (whatif.build_fork minus the live scheduler):
    fresh instance, ``_import_snapshot_state`` of the anchor body, RNG
    state reinstated. The subject carries its OWN fresh flight recorder
    (capacity = the window) so the replay's bind stream fingerprints."""
    from .framework import HivedScheduler, NullKubeClient

    if recording.get("truncated"):
        raise ValueError(
            "recording window is truncated (events were dropped while the "
            "projection stayed transient); it documents the incident but "
            "cannot replay deterministically"
        )
    sched = HivedScheduler(
        config,
        kube_client=NullKubeClient(),
        auto_admit=True,
        global_lock=True,
        trace_sample=0.0,
        force_bind_executor=lambda fn: None,
        flight_recorder=False,
        live_audit=False,
    )
    fp = getattr(sched, "_config_fingerprint", "")
    want = recording.get("configFingerprint") or ""
    if want and fp and want != fp:
        raise ValueError(
            f"recording was captured under config fingerprint "
            f"{want[:12]}..., replay config is {fp[:12]}... — placements "
            f"would not be comparable"
        )
    anchor = recording.get("anchor") or {}
    if not anchor.get("pristine"):
        body = anchor.get("body")
        if body is None:
            raise ValueError("recording anchor carries no snapshot body")
        sched._import_snapshot_state(body, live_names=None)
        with sched._lock:
            sched._snapshot_pending.clear()
            sched._snapshot_claims.clear()
    state = _rng_state_from_json(anchor.get("rngState"))
    if state is not None:
        import random as _random

        if sched.core.preempt_rng is None:
            sched.core.preempt_rng = _random.Random()
        sched.core.preempt_rng.setstate(state)
    # The replay's own black box: same capacity, framework granularity.
    replay_rec = FlightRecorder(
        capacity=max(64, len(recording.get("events") or []) + 16),
        exporter=None,
        config_fingerprint=fp,
        granularity="framework",
    )
    replay_rec.set_node_universe(sched.core.configured_node_names())
    sched.recorder = replay_rec
    return sched


def replay_recording(recording: Dict, config) -> Dict:
    """Restore the anchor and replay the window through TraceDriver
    (``TraceDriver.replay_recording``); returns the comparison report:
    live vs replayed fingerprints, per-kind counts, divergence flag."""
    from ..sim.driver import TraceDriver

    subject = build_replay_subject(recording, config)
    driver = TraceDriver(config, scheduler=subject, prepare_nodes=False)
    counts = driver.replay_recording(recording)
    live_fp = recording_fingerprint(recording)
    gran = recording.get("granularity") or "framework"
    replay_fp = recording_fingerprint(
        subject.recorder.recording(), granularity=gran
    )
    return {
        "liveFingerprint": live_fp,
        "replayFingerprint": replay_fp,
        "identical": live_fp == replay_fp,
        "granularity": gran,
        "anchorPristine": bool(
            (recording.get("anchor") or {}).get("pristine")
        ),
        "events": counts,
    }
