"""The hardware health plane: chip-granular health inputs + flap damping.

TPUs fail and get maintained at finer granularity than nodes: Cloud TPU
surfaces per-chip faults and advance maintenance notices, while the node
object stays Ready. This module turns those signals into the core's
chip-granular health primitives (doc/fault-model.md "Hardware health
plane"):

- :func:`device_bad_chips` parses the device-health annotation and the
  per-chip node conditions into the set of BAD chip indices on a node;
- :func:`drain_chip_indices` parses the drain annotation into the set of
  DRAINING chip indices (no new placements; running gangs keep cells);
- :class:`FlapDamper` is the hysteresis gate health transitions pass
  through before being applied, so a flapping node settles instead of
  storming doom-bind/retire churn and doomed-ledger rewrites.

The damper is **event-clocked**: time is a counter of explicit ticks
(`HivedScheduler.health_tick` — one per informer relist / watch-cycle end,
or one per harness event), never the wall clock, so chaos schedules replay
deterministically from their seed. Observations do NOT advance the clock:
a per-observation clock would scale the window with cluster size and turn
damping off exactly on large fleets. Semantics:

- the FIRST observation of a target always applies (recovery replays the
  current cluster state through the damper with no delay);
- a transition applies immediately unless the target has already flapped
  ``threshold`` times within the last ``window`` clock ticks — then the
  desired state is HELD (pending) and kept up to date as further flips
  arrive;
- once ``hold`` ticks pass with no further flip, the LATEST desired state
  applies ("a settled transition is never lost");
- a flip back to the applied state simply clears the pending hold (there
  is nothing left to settle).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Deque, Dict, List, Optional, Set, Tuple

from ..api import constants
from .types import Node

# A damper target: ("node", node_name) or ("chip", node_name, chip_index).
Target = Tuple

_CHIP_CONDITION_PREFIX = constants.GROUP_NAME + "/chip-"

_DRAIN_ALL = ("*", "all", "true")


def _parse_indices(value: str) -> Set[int]:
    out: Set[int] = set()
    for part in value.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            out.add(int(part))
        except ValueError:
            continue  # operator typo: ignore the token, keep the rest
    return out


def device_bad_chips(node: Node) -> Set[int]:
    """Chip indices reported bad on this node: the device-health annotation
    (comma-separated indices) merged with per-chip node conditions
    (``<group>/chip-<i>`` status False)."""
    bad = _parse_indices(
        node.annotations.get(constants.ANNOTATION_NODE_DEVICE_HEALTH, "")
    )
    for ctype, ok in node.conditions.items():
        if not ok and ctype.startswith(_CHIP_CONDITION_PREFIX):
            try:
                bad.add(int(ctype[len(_CHIP_CONDITION_PREFIX):]))
            except ValueError:
                continue
    return bad


def drain_chip_indices(node: Node, all_chips: Set[int]) -> Set[int]:
    """Chip indices the drain annotation cordons on this node: the whole
    node ("*"/"all"/"true") or a comma-separated index list; absent/empty
    means no drain."""
    value = node.annotations.get(constants.ANNOTATION_NODE_DRAIN, "").strip()
    if not value:
        return set()
    if value.lower() in _DRAIN_ALL:
        return set(all_chips)
    # Clamp to chips the config actually places on the node (an index for
    # hardware we do not manage is a no-op, not an error — and a node the
    # config does not manage at all has nothing to drain).
    return _parse_indices(value) & all_chips


class _TargetRecord:
    __slots__ = ("applied", "pending", "stamps", "last_flip", "last_flip_wall")

    def __init__(self, applied: bool):
        self.applied = applied
        self.pending: Optional[bool] = None
        self.stamps: Deque[int] = deque()
        self.last_flip = -(1 << 30)
        self.last_flip_wall = 0.0


class FlapDamper:
    """Per-target hysteresis for health transitions (see module docstring).
    threshold <= 0 disables damping entirely (every observation applies).

    ``hold_seconds`` is the optional WALL-CLOCK settling floor (ROADMAP
    "wall-clock damping tier"): when > 0, a held target that stayed quiet
    for that many wall seconds settles at the next :meth:`settled` call
    even if fewer than ``hold`` event ticks passed — so a quiet cluster
    (whose only event-clock ticks are informer relist/watch-cycle ends,
    minutes apart) settles promptly. The event clock stays authoritative
    when ``hold_seconds`` is 0 (the default, and what chaos schedules use:
    the wall clock is nondeterministic). ``now_fn`` is injectable for
    tests."""

    def __init__(
        self,
        threshold: int,
        window: int,
        hold: int,
        hold_seconds: float = 0.0,
        now_fn=time.monotonic,
    ):
        self.threshold = threshold
        self.window = max(1, window)
        self.hold = max(1, hold)
        self.hold_seconds = hold_seconds
        self._now = now_fn
        self._records: Dict[Target, _TargetRecord] = {}
        # Targets whose record currently holds a pending transition —
        # settled()/pending_count() run per informer tick and per metrics
        # scrape, so they must be O(pending), not O(all targets) (an
        # all-records walk per node event made recovery O(nodes^2)).
        self._pending: Dict[Target, None] = {}

    def observe(self, target: Target, desired: bool, clock: int) -> bool:
        """Record a desired health state for a target at ``clock``. Returns
        True when the transition should be applied NOW; False when it is a
        no-op or held for settling (collect via :meth:`settled`)."""
        rec = self._records.get(target)
        if rec is None:
            # First sighting always applies: recovery replays the current
            # cluster state with zero delay, and a brand-new node cannot
            # have flapped yet.
            self._records[target] = _TargetRecord(desired)
            return True
        if desired == rec.applied:
            # Flapped back before the hold expired: nothing to settle.
            if rec.pending is not None:
                rec.pending = None
                self._pending.pop(target, None)
            return False
        if rec.pending is not None and desired == rec.pending:
            # A REPEATED identical observation of a held target (kubelet
            # heartbeats, relist re-deliveries) is not a flip: re-stamping
            # it would extend the hold forever and a genuinely-bad node
            # would never settle bad.
            return False
        rec.stamps.append(clock)
        rec.last_flip = clock
        rec.last_flip_wall = self._now()
        while rec.stamps and rec.stamps[0] <= clock - self.window:
            rec.stamps.popleft()
        if self.threshold > 0 and len(rec.stamps) >= self.threshold:
            rec.pending = desired
            self._pending[target] = None
            return False
        rec.applied = desired
        return True

    def settled(self, clock: int) -> List[Tuple[Target, bool]]:
        """Held transitions whose targets stayed quiet for ``hold`` ticks —
        or, when the wall-clock floor is armed, for ``hold_seconds`` of
        wall time: their latest desired state is promoted to applied and
        returned for the caller to enact."""
        if not self._pending:
            return []
        out: List[Tuple[Target, bool]] = []
        now_wall = self._now() if self.hold_seconds > 0 else 0.0
        for target in list(self._pending):
            rec = self._records.get(target)
            if rec is None or rec.pending is None:
                self._pending.pop(target, None)
                continue
            quiet_ticks = clock - rec.last_flip >= self.hold
            quiet_wall = (
                self.hold_seconds > 0
                and now_wall - rec.last_flip_wall >= self.hold_seconds
            )
            if quiet_ticks or quiet_wall:
                rec.applied = rec.pending
                rec.pending = None
                self._pending.pop(target, None)
                out.append((target, rec.applied))
        return out

    def force_settle(self) -> List[Tuple[Target, bool]]:
        """Promote every held transition immediately (teardown / projection
        paths that need the damper drained deterministically)."""
        out: List[Tuple[Target, bool]] = []
        for target in list(self._pending):
            rec = self._records.get(target)
            if rec is not None and rec.pending is not None:
                rec.applied = rec.pending
                rec.pending = None
                out.append((target, rec.applied))
            self._pending.pop(target, None)
        return out

    def pending_count(self) -> int:
        # len() alone: atomic under the GIL, safe against concurrent
        # observers for the lock-free metrics scrape.
        return len(self._pending)

    def reset(self) -> None:
        """Drop every record and pending hold. Called when the core's
        health state is wholesale-replaced (snapshot restore, or the
        virgin-core rebuild when a pre-applied standby's snapshot turns
        out unusable at takeover): the applied-state memory describes the
        projection being discarded, and keeping it would swallow the node
        replay's re-observations as no-op non-flips (found by the
        hot-standby discard test)."""
        self._records.clear()
        self._pending.clear()

    def forget_node(self, node_name: str) -> None:
        """Drop every record touching a node (node deleted: its flap
        history dies with it)."""
        for target in [
            t for t in self._records if t[1] == node_name
        ]:
            del self._records[target]
            self._pending.pop(target, None)

    def snapshot(self) -> List[Dict]:
        """Inspect view: the currently-held transitions."""
        out: List[Dict] = []
        for target, rec in sorted(self._records.items(), key=str):
            if rec.pending is None:
                continue
            entry: Dict = {
                "target": (
                    f"node:{target[1]}"
                    if target[0] == "node"
                    else f"chip:{target[1]}:{target[2]}"
                ),
                "applied": "healthy" if rec.applied else "bad",
                "pending": "healthy" if rec.pending else "bad",
                "lastFlipClock": rec.last_flip,
            }
            out.append(entry)
        return out
