"""The structured decision journal: why every pod landed where it did.

HiveD's core promise is *explainable* sharing — a gang lands (or waits)
because of VC quota, buddy-level topology, hardware health, and priority
gates. This module records one :class:`DecisionRecord` per scheduling
attempt (filter or preempt verb), containing:

- the candidate cell chains considered, and the **per-gate rejection
  reason** for every chain that turned the pod down (quota, chip health,
  drains, buddy-level fit, suggested-node constraints);
- the lock scope the attempt ran under (the narrowed chain set, or
  ``"global"`` — the untyped-pod narrowing satellite records its chosen
  set here);
- the final verdict: a placement (node + chip indices), a preemption
  (victim pod list), a wait (reason), an insisted previous bind, or a
  protocol error.

Served at ``/v1/inspect/decisions`` (latest-N ring + per-pod lookup) and
dumped per-seed when a chaos-harness invariant fails (tests/chaos.py).

Threading: a record is created and mutated by exactly one request thread
(it rides a thread-local "current record" so the core's inner gates can
enrich it without signature plumbing — the same pattern as
``tracing.use``). Only ``commit`` touches shared state, under a private
micro-lock that is never part of the chain-lock order.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

DEFAULT_CAPACITY = 512

# Gate names (doc/observability.md "Decision records"): the stages of the
# scheduling funnel a chain can reject a pod at.
GATE_VC_QUOTA = "vcQuota"          # intra-VC placement found no room
GATE_CHIP_HEALTH = "chipHealth"    # bad chips made the capacity unusable
GATE_DRAINING = "draining"         # maintenance drains cordoned the chips
GATE_SUGGESTED = "suggestedNodes"  # K8s suggested-node set excluded the fit
GATE_BUDDY_FIT = "buddyFit"        # virtual→physical buddy mapping failed
                                   # (fragmentation, doomed-bad bindings)
GATE_CAPACITY = "capacity"         # plain insufficient physical capacity
GATE_SHARD_DOWN = "shardDown"      # owning shard worker down/resurrecting
                                   # (frontend-journaled degraded-mode WAIT;
                                   # doc/fault-model.md "Shard supervision
                                   # plane")
GATE_APISERVER_OUTAGE = "apiserverOutage"  # apiserver blackout: durable
                                   # writes impossible, filter answers off
                                   # the projection (doc/fault-model.md
                                   # "Control-plane weather plane")
# (Requests rejected before scheduling — unknown VC, SKU the VC lacks,
# over-sized gang — surface as verdict "error", not a per-chain gate.)


def classify_reason(reason: str) -> str:
    """Map a scheduler failure-reason string to its gate. The strings are
    produced by a closed set of sites (placement._find_nodes_for_pods,
    intra_vc.IntraVCScheduler.schedule, core._schedule_guaranteed_group);
    the golden decision tests pin one scenario per gate so a reworded
    reason that breaks classification fails loudly."""
    r = reason or ""
    if "draining node" in r:
        return GATE_DRAINING
    if "Mapping the virtual placement" in r:
        return GATE_BUDDY_FIT
    if "bad node" in r:
        return GATE_CHIP_HEALTH
    if "non-suggested node" in r:
        return GATE_SUGGESTED
    if "when scheduling in VC" in r:
        return GATE_VC_QUOTA
    return GATE_CAPACITY


class DecisionRecord:
    """One scheduling attempt, mutated by its request thread only."""

    __slots__ = (
        "seq", "trace_id", "pod_key", "pod_uid", "group", "vc", "priority",
        "leaf_cell_type", "leaf_cell_number", "phase", "lock_chains",
        "chains_considered", "attempts", "verdict", "node", "leaf_cells",
        "victims", "wait_reason", "certificate", "error", "notes",
        "wall_time",
    )

    def __init__(self, seq: int, pod_key: str, pod_uid: str, phase: str,
                 trace_id: Optional[int] = None):
        self.seq = seq
        self.trace_id = trace_id
        self.pod_key = pod_key
        self.pod_uid = pod_uid
        self.phase = phase
        self.group = ""
        self.vc = ""
        self.priority: Optional[int] = None
        self.leaf_cell_type = ""
        self.leaf_cell_number: Optional[int] = None
        self.lock_chains: Optional[object] = None  # list of chains | "global"
        self.chains_considered: List[str] = []
        self.attempts: List[Dict] = []
        self.verdict = ""
        self.node = ""
        self.leaf_cells: List[int] = []
        self.victims: List[Dict] = []
        self.wait_reason = ""
        self.certificate: Optional[Dict] = None
        self.error = ""
        self.notes: List[str] = []
        self.wall_time = time.time()

    # -- enrichment (called from the core's gates) ---------------------- #

    def set_spec(self, spec) -> None:
        """Copy the identifying fields off a decoded PodSchedulingSpec."""
        try:
            self.vc = str(spec.virtual_cluster)
            self.priority = spec.priority
            self.leaf_cell_type = str(spec.leaf_cell_type or "")
            self.leaf_cell_number = spec.leaf_cell_number
            if spec.affinity_group is not None:
                self.group = spec.affinity_group.name
        except Exception:  # noqa: BLE001 — diagnostics must never raise
            pass

    def consider_chain(self, chain) -> None:
        c = str(chain)
        if c not in self.chains_considered:
            self.chains_considered.append(c)

    def reject(self, target, reason: str, gate: Optional[str] = None) -> None:
        """One gate turning the pod down on one chain (or pinned cell)."""
        self.attempts.append(
            {
                "target": str(target),
                "gate": gate or classify_reason(reason),
                "reason": reason,
            }
        )

    def note(self, message: str) -> None:
        self.notes.append(message)

    # -- verdicts -------------------------------------------------------- #

    def verdict_bind(self, node: str, leaf_cells: List[int]) -> None:
        self.verdict = "bind"
        self.node = node
        self.leaf_cells = list(leaf_cells)

    def verdict_insist(self, node: str) -> None:
        self.verdict = "insist-bind"
        self.node = node

    def verdict_preempt(self, victim_pods) -> None:
        self.verdict = "preempt"
        self.victims = [
            {"pod": v.key, "uid": v.uid, "node": v.node_name}
            for v in victim_pods
        ]

    def verdict_wait(
        self, reason: str, certificate: Optional[Dict] = None
    ) -> None:
        """A WAIT verdict, optionally carrying its rejection certificate
        (the failed gate + the version vector the attempt read —
        doc/hot-path.md "Pending-pod plane"): the "what must change for
        this pod to schedule" record the what-if plane consumes, and the
        key the negative-filter cache revalidates re-filters against."""
        self.verdict = "wait"
        self.wait_reason = reason
        self.certificate = certificate

    def verdict_error(self, message: str) -> None:
        self.verdict = "error"
        self.error = message

    def to_dict(self) -> Dict:
        d: Dict = {
            "seq": self.seq,
            "pod": self.pod_key,
            "uid": self.pod_uid,
            "phase": self.phase,
            "group": self.group,
            "vc": self.vc,
            "priority": self.priority,
            "leafCellType": self.leaf_cell_type,
            "leafCellNumber": self.leaf_cell_number,
            "lockChains": self.lock_chains,
            "chainsConsidered": self.chains_considered,
            "rejections": self.attempts,
            "verdict": self.verdict,
            "wallTime": round(self.wall_time, 3),
        }
        if self.trace_id is not None:
            d["traceId"] = self.trace_id
        if self.node:
            d["node"] = self.node
        if self.leaf_cells:
            d["leafCells"] = self.leaf_cells
        if self.victims:
            d["victims"] = self.victims
        if self.wait_reason:
            d["waitReason"] = self.wait_reason
        if self.certificate is not None:
            d["certificate"] = self.certificate
        if self.error:
            d["error"] = self.error
        if self.notes:
            d["notes"] = self.notes
        return d


class DecisionJournal:
    """Bounded ring of committed decision records plus a per-pod index of
    each pod's LATEST decision (the lookup the "why didn't my pod
    schedule" walkthrough uses, doc/user-manual.md)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(1, capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        # uid -> latest committed record dict; bounded at 4× the ring so
        # a long-lived cluster's dead pods cannot grow it forever, while a
        # pod's last decision outlives its ring slot by a good margin.
        self._by_uid: "OrderedDict[str, Dict]" = OrderedDict()
        self._lock = threading.Lock()
        self._seq = itertools.count(1)
        self._local = threading.local()

    # -- record lifecycle ------------------------------------------------ #

    def begin(self, pod_key: str, pod_uid: str, phase: str,
              trace_id: Optional[int] = None) -> DecisionRecord:
        rec = DecisionRecord(
            next(self._seq), pod_key, pod_uid, phase, trace_id
        )
        self._local.rec = rec
        return rec

    def current(self) -> Optional[DecisionRecord]:
        """The request thread's in-flight record (None outside a recorded
        attempt — e.g. bare-core probes in tests and benches)."""
        return getattr(self._local, "rec", None)

    def commit(self, rec: DecisionRecord) -> None:
        if getattr(self._local, "rec", None) is rec:
            self._local.rec = None
        d = rec.to_dict()
        with self._lock:
            self._ring.append(d)
            self._by_uid[rec.pod_uid] = d
            self._by_uid.move_to_end(rec.pod_uid)
            while len(self._by_uid) > 4 * self.capacity:
                self._by_uid.popitem(last=False)

    # -- reads (lock only the journal's own micro-lock) ------------------ #

    def snapshot(self, n: Optional[int] = None) -> List[Dict]:
        with self._lock:
            items = list(self._ring)
        if n is not None and n >= 0:
            # n=0 means zero items; the bare [-0:] slice cannot say that.
            items = items[-n:] if n > 0 else []
        return items

    def stamp_predicted_wait(
        self,
        uid: str,
        predicted_wait_s: Optional[float],
        horizon_s: Optional[float] = None,
    ) -> bool:
        """Stamp a what-if forecast onto a pod's latest WAIT record
        (scheduler.whatif, doc/observability.md "Decision records"):
        ``predictedWaitS`` is the promised ETA in seconds (None = blocked
        beyond the forecast's confidence horizon, carried alongside as
        ``predictedWaitHorizonS``). Only WAIT verdicts are stamped — a
        pod that bound since the forecast keeps its bind record clean.
        The mutation is visible through every shared read of the record
        (ring snapshots share the dicts), which is the point: the
        journal's WAIT answer now carries its ETA."""
        with self._lock:
            rec = self._by_uid.get(uid)
            if rec is None or rec.get("verdict") != "wait":
                return False
            rec["predictedWaitS"] = predicted_wait_s
            if horizon_s is not None:
                rec["predictedWaitHorizonS"] = round(horizon_s, 3)
            return True

    def stamp_predicted_wait_groups(
        self,
        by_group: Dict[str, Optional[float]],
        horizon_s: Optional[float] = None,
    ) -> int:
        """Gang-wide batch stamp: every pod whose LATEST record is a
        WAIT for a group in ``by_group`` gets its forecast. ONE journal
        scan for the whole batch — the sharded frontend stamps its
        MERGED queue forecast into each shard's journal with this (a
        sweep-registered gang's shard-local verdict can contradict the
        merged one, so shards never stamp their own queue-mode answers),
        and a deep queue must not turn that into gangs × journal scans
        under the lock."""
        if not by_group:
            return 0
        n = 0
        with self._lock:
            for rec in self._by_uid.values():
                group = rec.get("group")
                if group in by_group and rec.get("verdict") == "wait":
                    rec["predictedWaitS"] = by_group[group]
                    if horizon_s is not None:
                        rec["predictedWaitHorizonS"] = round(horizon_s, 3)
                    n += 1
        return n

    def lookup(self, key: str) -> Optional[Dict]:
        """Latest decision for a pod, by uid or by pod key
        (``namespace/name``)."""
        with self._lock:
            rec = self._by_uid.get(key)
            if rec is not None:
                return rec
            for d in reversed(self._by_uid.values()):
                if d.get("pod") == key:
                    return d
        return None
