"""Continuous durable-state integrity scrubber (doc/fault-model.md
"Durable-state plane v2").

PR 7's validation ladder runs at RECOVERY — a corrupted snapshot is only
discovered at the worst possible moment, mid-failover, when the fallback
(full annotation replay) is most expensive. The scrubber moves that
discovery to steady state, in the :class:`~.audit.LiveAuditor` mold:
event-clocked (it rides the snapshot flusher's beats — never its own
thread or wall clock), always-on in production, and degrading gracefully
on divergence (count + journal + black-box artifact + repair — NEVER an
assert into the serving path).

Leader beats re-read the durable envelope end to end and re-run the
validation ladder against LIVE state: per-section sha256 checksums, the
config fingerprint rung, and the doomed-cell gate vs the in-memory ledger
(decode carries the first two; the scrubber adds the third). A divergence
means the durable copy would degrade — or doom — the next failover, so
the repair is simply a rewrite from the live projection
(``flush_snapshot_now``), which is always authoritative on the leader.

Standby beats are the anti-entropy half: a HOT standby pre-applies the
projection into its own core (``prefetch_snapshot(apply=True)``), and a
bit of rot there would silently ship into the next takeover. The scrubber
fingerprints the pre-applied projection against the durable envelope's
core sections; on mismatch it discards the pre-apply wholesale and
re-prefetches from durable state (durable wins — the standby's copy is
the derived one).

``HIVED_SNAPSHOT_SCRUB=0`` is the emergency hatch: it disables scrubbing
at construction without touching config. Cadence comes from
``snapshotScrubIntervalBeats`` (every Nth flusher beat).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Dict, Optional

from .. import common
from . import snapshot as snapshot_mod
from .audit import AUDIT_ARTIFACT_DIR_ENV

SCRUB_ENABLE_ENV = "HIVED_SNAPSHOT_SCRUB"


def projection_fingerprint(core_body: Dict) -> str:
    """Order-insensitive fingerprint of a core projection body. Used for
    the standby anti-entropy compare: the durable envelope's merged core
    sections vs the standby's own ``export_projection()`` must hash
    identically or the pre-apply has rotted."""
    return hashlib.sha256(
        json.dumps(core_body, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()


class SnapshotScrubber:
    """Event-clocked integrity scrubber over the durable snapshot plane.

    Thread-safety: ``tick`` is called from the flusher thread (leader)
    or the standby beat loop (standby) — one caller at a time by
    construction; counters ride the GIL."""

    def __init__(self, sched, interval_beats: int = 4):
        self.sched = sched
        self.interval_beats = max(1, int(interval_beats))
        self.enabled = os.environ.get(SCRUB_ENABLE_ENV, "").strip() != "0"
        self.beats = 0
        self.scrub_runs = 0
        self.divergence_count = 0
        self.repair_count = 0
        self.last_divergence: str = ""
        self.last_artifact: str = ""

    # -- the event clock ------------------------------------------------ #

    def tick(self) -> None:
        """One flusher/standby beat completed."""
        if not self.enabled:
            return
        self.beats += 1
        if self.beats % self.interval_beats == 0:
            self.scrub_now(f"cadence beat={self.beats}")

    def scrub_now(self, ctx: str = "manual") -> bool:
        """One scrub pass. Returns True when durable state verified clean
        (or there was nothing to verify). A divergence is counted,
        journaled, dumped, and REPAIRED — never raised; any crash of the
        scrub itself logs and counts as a run, never a divergence (the
        scrubber must not invent corruption)."""
        sched = self.sched
        if getattr(sched, "_in_recovery", False):
            return True  # a half-replayed view has no authoritative side
        self.scrub_runs += 1
        try:
            if sched.is_leader():
                return self._scrub_leader(ctx)
            return self._scrub_standby(ctx)
        except Exception as e:  # noqa: BLE001
            common.log.warning(
                "snapshot scrub pass crashed (not counted as a "
                "divergence): %s", e,
            )
            return True

    # -- leader: durable envelope vs live ledger ------------------------ #

    def _scrub_leader(self, ctx: str) -> bool:
        sched = self.sched
        try:
            chunks = sched.kube_client.load_snapshot()
        except Exception as e:  # noqa: BLE001
            # A store/apiserver outage is the weather plane's problem
            # (vane + journal), not corruption.
            common.log.debug("scrub read failed (weather, not rot): %s", e)
            return True
        if not chunks:
            return True  # nothing persisted yet — first boot
        snap, reason = snapshot_mod.decode(
            chunks, sched._config_fingerprint, None
        )
        if snap is None:
            return self._diverged(
                ctx, f"durable envelope unusable: {reason}", repair=True
            )
        corrupt = snap.get("_corrupt") or {}
        if corrupt.get("sections") or corrupt.get("chains"):
            return self._diverged(
                ctx,
                "corrupt sections in durable envelope: "
                f"sections={sorted(corrupt.get('sections') or [])} "
                f"chains={sorted(corrupt.get('chains') or [])}",
                repair=True,
            )
        # The doom gate, scrubbed ahead of failover: durable dooms must
        # match the live ledger. A mismatch here can be flush lag (a doom
        # landed after the last flush) — still worth repairing NOW rather
        # than at takeover, where it would force a fallback.
        snap_dooms = sched._core_dooms(snap.get("core") or {})
        live_dooms = sched._ledger_dooms()
        if snap_dooms != live_dooms:
            return self._diverged(
                ctx,
                "durable doomed set diverges from live ledger: "
                f"snapshot-only={sorted(snap_dooms - live_dooms)[:8]} "
                f"ledger-only={sorted(live_dooms - snap_dooms)[:8]}",
                repair=True,
            )
        return True

    # -- standby: pre-applied projection vs durable (anti-entropy) ------ #

    def _scrub_standby(self, ctx: str) -> bool:
        sched = self.sched
        if sched._preapplied_chunks is None:
            return True  # cold/warm standby — nothing pre-applied to rot
        try:
            chunks = sched.kube_client.load_snapshot()
        except Exception as e:  # noqa: BLE001
            common.log.debug("standby scrub read failed: %s", e)
            return True
        if not chunks or chunks != sched._preapplied_chunks:
            # The pre-apply lags the durable stream; the next prefetch
            # beat reconciles. Only a SAME-family mismatch is rot.
            return True
        snap, reason = snapshot_mod.decode(
            chunks, sched._config_fingerprint, None
        )
        if snap is None:
            return True  # prefetch/recovery ladders own this case
        if sched._preapplied_replay is not None:
            # PARTIAL pre-apply: the live core deliberately holds only
            # the healthy families (demoted chains sit in bootstrap
            # state, their hosts forced bad), so the wholesale
            # projection compare below would read the scoping itself as
            # rot. The takeover gate re-validates the scope against the
            # real ledger; the leader-side section scrub owns the
            # durable bytes.
            return True
        durable_fp = projection_fingerprint(snap.get("core") or {})
        with sched._lock:
            live_fp = projection_fingerprint(sched.core.export_projection())
        if durable_fp == live_fp:
            return True
        diverged = self._diverged(
            ctx,
            "hot-standby pre-applied projection diverges from durable "
            f"envelope (durable {durable_fp[:12]} vs pre-applied "
            f"{live_fp[:12]}); discarding pre-apply and re-prefetching",
            repair=False,
        )
        # Durable wins: drop the rotted pre-apply and rebuild it from the
        # envelope we just verified section-clean.
        try:
            sched.discard_preapplied_state()
            sched._prefetched_snapshot = None
            sched.prefetch_snapshot(apply=True)
            self.repair_count += 1
        except Exception:  # noqa: BLE001 — repair is best-effort
            common.log.exception("standby scrub re-prefetch failed")
        return diverged

    # -- divergence plumbing -------------------------------------------- #

    def _diverged(self, ctx: str, detail: str, repair: bool) -> bool:
        self.divergence_count += 1
        self.last_divergence = detail[:2000]
        common.log.error(
            "SNAPSHOT SCRUB DIVERGENCE #%d (%s): %s — scheduler keeps "
            "serving; black-box bundle dumping",
            self.divergence_count, ctx, self.last_divergence,
        )
        self._journal(ctx, detail)
        try:
            self.last_artifact = self.dump_artifact(ctx, detail)
        except Exception:  # noqa: BLE001 — the dump must never raise
            common.log.exception("scrub artifact dump failed")
        if repair:
            try:
                if self.sched.flush_snapshot_now():
                    self.repair_count += 1
                    common.log.warning(
                        "scrub repaired durable snapshot by rewriting from "
                        "the live projection"
                    )
            except Exception:  # noqa: BLE001 — repair is best-effort
                common.log.exception("scrub repair flush failed")
        return False

    def _journal(self, ctx: str, detail: str) -> None:
        """A divergence is a decision too: one journal record under the
        synthetic pod key ``_scrub`` so ``/v1/inspect/decisions`` shows
        it inline with the scheduling stream."""
        try:
            rec = self.sched.decisions.begin("_scrub", "_scrub", "scrub")
            rec.verdict_error(f"durable-state divergence ({ctx}): "
                              f"{detail[:500]}")
            self.sched.decisions.commit(rec)
        except Exception:  # noqa: BLE001 — diagnostics must never raise
            pass

    def dump_artifact(self, ctx: str, detail: str) -> str:
        """The black-box bundle, co-located with the audit bundles under
        HIVED_AUDIT_ARTIFACT_DIR (default $TMPDIR/hived-audit)."""
        import tempfile

        out_dir = os.environ.get(AUDIT_ARTIFACT_DIR_ENV) or os.path.join(
            tempfile.gettempdir(), "hived-audit"
        )
        os.makedirs(out_dir, exist_ok=True)
        sched = self.sched
        recorder = getattr(sched, "recorder", None)
        payload = {
            "context": ctx,
            "divergence": detail,
            "divergenceCount": self.divergence_count,
            "scrubRuns": self.scrub_runs,
            "wallTime": time.time(),
            "decisions": sched.decisions.snapshot(),
            "traces": sched.tracer.snapshot(),
            "metrics": sched.get_metrics(),
            "flightRecording": (
                recorder.recording() if recorder is not None else None
            ),
        }
        path = os.path.join(
            out_dir,
            f"scrub-divergence-{self.divergence_count}-{os.getpid()}.json",
        )
        with open(path, "w") as f:
            json.dump(payload, f, indent=1, default=str)
        common.log.error("black-box bundle dumped to %s", path)
        return path

    def metrics_snapshot(self) -> Dict:
        return {
            "scrubRunCount": self.scrub_runs,
            "scrubDivergenceCount": self.divergence_count,
            "scrubRepairCount": self.repair_count,
        }
