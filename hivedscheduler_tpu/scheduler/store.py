"""Pluggable snapshot stores (doc/fault-model.md "Durable-state plane v2").

PR 7's durable envelope lived in a ConfigMap chunk family — ~1 MiB per
object, apiserver-coupled, and etcd-priced per flush. At the 50k-host
north star the projection outgrows that: this module extracts the
persistence seam as a :class:`SnapshotStore` interface (``persist`` a
chunk list / ``load`` it back) with two implementations:

* the ConfigMap chunk family stays the DEFAULT and needs no store object
  at all — ``RetryingKubeClient`` keeps routing to the apiserver when its
  ``snapshot_store`` is None (the zero-regression path);
* :class:`FileSnapshotStore` is the object-store backend: a
  filesystem/S3-shaped layout (a POSIX directory stands in for a bucket —
  an NFS/GCS-fuse mount in production, a tmpdir in tests) with
  write-new-then-flip atomicity and generation GC.

Atomicity contract (the part the chaos ``torn_chunk`` events attack): a
``persist`` writes every chunk of a NEW generation directory first, fsyncs
them, and only then flips the single ``MANIFEST`` pointer via the POSIX
``os.replace`` rename — readers follow the pointer, so they observe either
the previous complete generation or the new complete generation, never a
mix. A crash or torn write before the flip leaves orphan files the next
GC sweeps; a torn MANIFEST write is impossible by the rename's atomicity.
GC keeps the last ``keep_generations`` generations (point-in-time rollback
for operators) and never touches the current one.

Failure model: every OSError is wrapped in :class:`StoreUnavailableError`,
which carries ``kube_retryable = True`` so ``is_retryable_kube_error``
classifies a store outage exactly like an apiserver 5xx — capped retries
feeding the weather vane, and once the vane reads blackout the manifest
write parks in the PR 18 intent journal instead of raising (zero errors
surfaced to the flusher; the journal drains when the store heals).
"""

from __future__ import annotations

import json
import os
import shutil
from typing import List, Optional

from .. import common

MANIFEST_NAME = "MANIFEST"
GENERATION_PREFIX = "gen-"
CHUNK_PREFIX = "chunk-"


class StoreUnavailableError(OSError):
    """The backing store is unreachable (mount gone, bucket 5xx, disk
    full). ``kube_retryable`` makes the shared classifier treat it as a
    transient control-plane failure: retries with backoff, then the
    write-behind intent journal under blackout — never a raised error on
    the flusher path."""

    kube_retryable = True


class SnapshotStore:
    """Where the durable snapshot envelope lives. Implementations must be
    atomic at the chunk-list granularity: ``load`` returns either a
    complete previously-persisted list or None (nothing persisted yet) —
    torn writes must be invisible (the PR 7 validation ladder is the
    second line of defense, not the first)."""

    name = "abstract"

    def persist(self, chunks: List[str]) -> None:
        raise NotImplementedError

    def load(self) -> Optional[List[str]]:
        raise NotImplementedError


class FileSnapshotStore(SnapshotStore):
    """Filesystem/S3-shaped object store::

        root/
          MANIFEST              # {"generation": N, "chunks": k} — the pointer
          gen-00000042/chunk-0000 ... chunk-<k-1>

    No 1 MiB cap (chunking is kept only so the envelope format is
    identical across backends), no apiserver round-trips, and the flip is
    one ``os.replace``."""

    name = "file"

    def __init__(self, root: str, keep_generations: int = 3) -> None:
        if not root:
            raise ValueError("FileSnapshotStore requires a root path")
        self.root = root
        self.keep_generations = max(1, int(keep_generations))
        # Test/ops visibility, not golden metrics.
        self.persist_count = 0
        self.gc_removed_count = 0

    # ------------------------------------------------------------------ #
    # helpers
    # ------------------------------------------------------------------ #

    def _manifest_path(self) -> str:
        return os.path.join(self.root, MANIFEST_NAME)

    def _gen_dir(self, gen: int) -> str:
        return os.path.join(self.root, f"{GENERATION_PREFIX}{gen:08d}")

    def _read_manifest(self) -> Optional[dict]:
        try:
            with open(self._manifest_path(), "r", encoding="utf-8") as f:
                manifest = json.load(f)
        except FileNotFoundError:
            return None
        except ValueError:
            # A corrupt pointer is indistinguishable from no pointer:
            # the next persist writes a fresh generation and flips over
            # it; load treats the store as empty (recovery falls back).
            common.log.warning(
                "snapshot store manifest unreadable at %s; treating the "
                "store as empty", self._manifest_path(),
            )
            return None
        if not (
            isinstance(manifest, dict)
            and isinstance(manifest.get("generation"), int)
            and isinstance(manifest.get("chunks"), int)
        ):
            return None
        return manifest

    def _generations_on_disk(self) -> List[int]:
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return []
        gens = []
        for n in names:
            if n.startswith(GENERATION_PREFIX):
                try:
                    gens.append(int(n[len(GENERATION_PREFIX):]))
                except ValueError:
                    continue
        return sorted(gens)

    # ------------------------------------------------------------------ #
    # SnapshotStore
    # ------------------------------------------------------------------ #

    def persist(self, chunks: List[str]) -> None:
        try:
            os.makedirs(self.root, exist_ok=True)
            manifest = self._read_manifest()
            on_disk = self._generations_on_disk()
            current = max(
                [manifest["generation"]] if manifest else [0] + on_disk
            )
            gen = current + 1
            gen_dir = self._gen_dir(gen)
            os.makedirs(gen_dir, exist_ok=True)
            for i, chunk in enumerate(chunks):
                path = os.path.join(gen_dir, f"{CHUNK_PREFIX}{i:04d}")
                with open(path, "w", encoding="utf-8") as f:
                    f.write(chunk)
                    f.flush()
                    os.fsync(f.fileno())
            # The commit point: write the new pointer beside the old one,
            # fsync it, then atomically rename over MANIFEST. Readers see
            # the old complete generation until this instant.
            tmp = self._manifest_path() + f".tmp-{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"generation": gen, "chunks": len(chunks)}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._manifest_path())
            self.persist_count += 1
            self._gc(gen)
        except OSError as e:
            if isinstance(e, StoreUnavailableError):
                raise
            raise StoreUnavailableError(
                f"snapshot store write failed under {self.root}: {e}"
            ) from e

    def load(self) -> Optional[List[str]]:
        try:
            manifest = self._read_manifest()
            if manifest is None:
                return None
            gen_dir = self._gen_dir(manifest["generation"])
            chunks: List[str] = []
            for i in range(manifest["chunks"]):
                path = os.path.join(gen_dir, f"{CHUNK_PREFIX}{i:04d}")
                try:
                    with open(path, "r", encoding="utf-8") as f:
                        chunks.append(f.read())
                except FileNotFoundError:
                    # Torn family (GC raced a reader, or bit-level loss):
                    # return what exists — the validation ladder demotes
                    # the missing sections and recovery degrades in
                    # proportion, exactly the ConfigMap-backend contract.
                    break
            return chunks or None
        except OSError as e:
            if isinstance(e, StoreUnavailableError):
                raise
            raise StoreUnavailableError(
                f"snapshot store read failed under {self.root}: {e}"
            ) from e

    # ------------------------------------------------------------------ #
    # GC
    # ------------------------------------------------------------------ #

    def _gc(self, current: int) -> None:
        """Keep the last ``keep_generations`` generations (the current one
        always included); best-effort — a GC failure never fails the
        persist that triggered it (the flip already landed)."""
        floor = current - self.keep_generations + 1
        for gen in self._generations_on_disk():
            if gen >= floor:
                continue
            try:
                shutil.rmtree(self._gen_dir(gen))
                self.gc_removed_count += 1
            except OSError as e:
                common.log.warning(
                    "snapshot store GC could not remove generation %d: %s",
                    gen, e,
                )


def make_snapshot_store(config) -> Optional[SnapshotStore]:
    """Operator wiring (``__main__``): the configured backend, or None for
    the default ConfigMap chunk family (RetryingKubeClient then routes
    snapshot persistence to the apiserver exactly as before)."""
    backend = getattr(config, "snapshot_store_backend", "configmap")
    if backend in ("", "configmap"):
        return None
    if backend == "file":
        return FileSnapshotStore(
            config.snapshot_store_path,
            keep_generations=config.snapshot_store_gc_generations,
        )
    raise ValueError(f"unknown snapshotStoreBackend {backend!r}")
